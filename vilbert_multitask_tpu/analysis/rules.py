"""The vmtlint ruleset: this codebase's real failure modes, as AST checks.

Every rule here traces back to a measured incident or advisor finding:
VMT101 is the round-2 1GB-per-forward host transfer, VMT104 is the
`serve_soak.py` negative-latency timestamp bug, VMT107 is the silent
worker-loop swallow class, etc. Rules are deliberately narrow — a lint
that cries wolf gets disabled; one that encodes the repo's actual
post-mortems gets kept.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from vilbert_multitask_tpu.analysis.context import (
    ModuleContext,
    _is_static_expr,
    _literal_int_tuple,
    is_literal,
    static_names_in,
)
from vilbert_multitask_tpu.analysis.core import Finding, Rule

# --------------------------------------------------------------------- 101
HOST_TRANSFER_CALLS = {
    "jax.device_get": "fetches device buffers to host",
    "numpy.asarray": "materializes a host array from a traced value",
    "numpy.array": "materializes a host array from a traced value",
}
HOST_TRANSFER_METHODS = {"item", "tolist"}
HOST_SCALAR_BUILTINS = {"float", "int", "bool"}


class HostTransferInJit(Rule):
    """np.*/.item()/float()/device_get reachable inside a jit boundary.

    Inside a traced function these either fail at trace time or — worse —
    silently execute per call on concrete inputs, re-shipping host bytes
    every forward (the round-2 23.7 s p50). numpy calls whose args are all
    literals are allowed: they fold to compile-time constants.
    """

    id = "VMT101"
    name = "host-transfer-in-jit"
    severity = "error"
    description = ("host-transfer call (np.asarray/np.array/.item()/"
                   ".tolist()/float()/jax.device_get) inside a "
                   "jit/pjit-compiled function, or in a helper reached "
                   "from one through the call graph")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        seen: Set[int] = set()
        # Lexical jit bodies first, then helpers the project call graph
        # proves reachable from some jit body (possibly in another
        # module) — those inherit traced context wholesale.
        sources: List[Tuple] = [(info, None) for info in ctx.jit_bodies]
        if ctx.project is not None:
            sources += ctx.project.traced_helpers(ctx)
        for info, witness in sources:
            body = info.body
            # Trace-time-static names (static_argnames/nums params, shape
            # tuple unpacks): host math on them is a compile-time constant
            # — the kernel idiom ``scale=1/float(np.sqrt(D))`` is fine.
            static = static_names_in(info)
            scope = body.body if isinstance(body.body, list) else [body.body]
            for stmt in scope:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or id(node) in seen:
                        continue
                    seen.add(id(node))
                    f = self._check_call(ctx, node, static)
                    if f is not None:
                        if witness:
                            f.message += (f" [in a helper reached from "
                                          f"{witness}]")
                        yield f

    def _check_call(self, ctx: ModuleContext, call: ast.Call,
                    static: Set[str]) -> Optional[Finding]:
        resolved = ctx.resolve(call.func)
        args_static = all(_is_static_expr(a, static) for a in call.args)
        if resolved in HOST_TRANSFER_CALLS:
            return self.finding(
                ctx, call, f"`{resolved}` inside a jitted function "
                f"{HOST_TRANSFER_CALLS[resolved]} — every call pays a "
                f"device→host→device round trip; use jnp or hoist out of "
                f"the jit boundary")
        if resolved.startswith("numpy.") and not args_static:
            return self.finding(
                ctx, call, f"`{resolved}` on a non-static value inside a "
                f"jitted function runs on host per call (tracer leak or "
                f"silent host transfer); use the jax.numpy equivalent")
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in HOST_TRANSFER_METHODS):
            return self.finding(
                ctx, call, f"`.{call.func.attr}()` inside a jitted function "
                f"forces a host transfer per call; return the array and "
                f"convert outside the jit")
        if (isinstance(call.func, ast.Name)
                and call.func.id in HOST_SCALAR_BUILTINS
                and call.args and not args_static):
            return self.finding(
                ctx, call, f"`{call.func.id}()` on a traced value inside a "
                f"jitted function forces a concrete host scalar "
                f"(ConcretizationError at best, per-call sync at worst)")
        return None


# --------------------------------------------------------------------- 102
class RecompileTrigger(Rule):
    """jit cache defeats: a fresh jitted callable per loop iteration, or an
    unhashable literal passed as a static argument.

    ``jax.jit(f)`` keys its compile cache on the wrapped callable's
    identity — building it inside a loop recompiles every iteration.
    A list/dict/set passed for a ``static_argnums`` slot raises
    "unhashable static arguments" at call time.
    """

    id = "VMT102"
    name = "recompile-trigger"
    severity = "error"
    description = ("jax.jit created inside a loop, or an unhashable "
                   "literal passed as a static argument")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call) and ctx.is_jit_entry(node.func)
                    and ctx.in_loop(node, stop_at_function=False)):
                yield self.finding(
                    ctx, node, "jax.jit inside a loop builds a fresh "
                    "callable each iteration — the compile cache keys on "
                    "callable identity, so every iteration recompiles; "
                    "hoist the jitted function out of the loop")
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if (ctx.is_jit_entry(deco)
                            and ctx.in_loop(node, stop_at_function=False)):
                        yield self.finding(
                            ctx, node, f"jit-decorated `{node.name}` is "
                            f"defined inside a loop — each iteration "
                            f"creates and compiles a new callable")
        yield from self._unhashable_statics(ctx)
        yield from self._jit_in_traced_helper(ctx)

    def _jit_in_traced_helper(self, ctx: ModuleContext) -> Iterator[Finding]:
        """A helper reached from a jit body that builds a fresh jitted
        callable: the inner callable is recreated every outer trace, so
        its compile cache never hits."""
        if ctx.project is None:
            return
        for info, witness in ctx.project.traced_helpers(ctx):
            for node in ast.walk(info.body):
                if (isinstance(node, ast.Call)
                        and ctx.is_jit_entry(node.func)
                        and not ctx.in_loop(node, stop_at_function=False)):
                    yield self.finding(
                        ctx, node, f"jax.jit built inside "
                        f"`{getattr(info.body, 'name', '<lambda>')}`, "
                        f"which is reached from {witness} — the callable "
                        f"(and its compile cache entry) is recreated on "
                        f"every call; hoist the jitted function out")

    def _unhashable_statics(self, ctx: ModuleContext) -> Iterator[Finding]:
        # static positions per locally-jitted name, from the jit call site.
        static_pos: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.is_jit_entry(node.func)):
                continue
            for kw in node.keywords:
                if kw.arg != "static_argnums":
                    continue
                pos = _literal_int_tuple(kw.value)
                parent = ctx.parent(node)
                if pos and isinstance(parent, ast.Assign):
                    for t in parent.targets:
                        if isinstance(t, ast.Name):
                            static_pos[t.id] = pos
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in static_pos):
                continue
            for i in static_pos[node.func.id]:
                if i < len(node.args) and isinstance(
                        node.args[i], (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        ctx, node.args[i],
                        f"unhashable literal passed to static arg {i} of "
                        f"jitted `{node.func.id}` — static argument values "
                        f"must be hashable (use a tuple)")


# --------------------------------------------------------------------- 103
class DonatedBufferReuse(Rule):
    """Reading a buffer after passing it to a donate_argnums call.

    Donation hands the input's device memory to XLA for the output; the
    Python reference still exists but the buffer is deleted — touching it
    raises, or on some backends silently reads garbage. The common shape:
    ``loss = step(state, batch)`` in a loop without rebinding ``state``.
    """

    id = "VMT103"
    name = "donated-buffer-reuse"
    severity = "error"
    description = ("variable used again after being passed in a "
                   "donate_argnums position")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # Names that donate when called: lexically-jitted bindings in this
        # module, widened by the project graph to imported jitted
        # functions and wrappers whose params are transitively donated
        # (donated-buffer escape across call edges).
        donors: Dict[str, Tuple[int, ...]] = {}
        if ctx.project is not None:
            donors.update(ctx.project.local_donors(ctx))
        donors.update(ctx.jit_bound_names)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_block(ctx, node.body, donors)

    def _donating_calls(self, ctx: ModuleContext, stmt: ast.stmt,
                        donors: Dict[str, Tuple[int, ...]]
                        ) -> Iterator[Tuple[ast.Call, List[str]]]:
        for node in ast.walk(stmt):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            donate = donors.get(node.func.id)
            if not donate:
                continue
            names = [node.args[i].id for i in donate
                     if i < len(node.args)
                     and isinstance(node.args[i], ast.Name)]
            if names:
                yield node, names

    @staticmethod
    def _bound_names(stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                out.add(node.id)
        return out

    def _check_block(self, ctx: ModuleContext, block: List[ast.stmt],
                     donors: Dict[str, Tuple[int, ...]]
                     ) -> Iterator[Finding]:
        donated: Dict[str, int] = {}  # name -> line it was donated on
        for stmt in block:
            # Reads happen before this statement's own (re)bindings.
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in donated):
                    yield self.finding(
                        ctx, node, f"`{node.id}` was donated to a "
                        f"donate_argnums call on line {donated[node.id]}; "
                        f"its device buffer no longer exists — rebind the "
                        f"result or drop the donation")
                    donated.pop(node.id)
            for call, names in self._donating_calls(ctx, stmt, donors):
                for n in names:
                    donated[n] = call.lineno
            for n in self._bound_names(stmt):
                donated.pop(n, None)
            # Loop bodies: a donation inside whose name is never rebound in
            # the body is read again by the call itself next iteration.
            if isinstance(stmt, (ast.For, ast.While)):
                rebound = set()
                for inner in stmt.body:
                    rebound |= self._bound_names(inner)
                for inner in stmt.body:
                    for call, names in self._donating_calls(ctx, inner,
                                                            donors):
                        for n in names:
                            if n not in rebound:
                                yield self.finding(
                                    ctx, call, f"`{n}` is donated inside "
                                    f"this loop but never rebound in the "
                                    f"loop body — the next iteration reads "
                                    f"a deleted buffer; assign the call's "
                                    f"result back to `{n}`")


# --------------------------------------------------------------------- 104
BLOCKING_CALLS = {"jax.block_until_ready", "jax.device_get",
                  "jax.effects_barrier"}
# Calls that enqueue async device work. Deliberately a list, not "jax.*":
# jax.devices()/default_backend()/config.update() etc. are host-side and
# blocking — flagging a timed backend-init span would be a false positive.
_DISPATCH_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.",
                      "jax.scipy.")
_DISPATCH_CALLS = {"jax.device_put"}
_SUBMIT_NAME_RE = re.compile(r"(submit|start|begin|t_?0|sent)", re.I)
_IO_METHODS = {"getresponse", "recv", "urlopen", "readinto"}


class BenchTimingHazard(Rule):
    """Timing spans that measure the wrong thing.

    (a) a ``time.perf_counter()`` span around async JAX dispatches with no
    ``block_until_ready``/``device_get`` inside the measured region times
    only the dispatch, not the work; (b) a submit/start timestamp captured
    *after* the blocking I/O it claims to measure — the exact
    ``serve_soak.py:148`` bug that produced negative latency samples.
    """

    id = "VMT104"
    name = "bench-timing-hazard"
    severity = "error"
    description = ("perf_counter span around device dispatches without "
                   "block_until_ready, or a submit timestamp captured "
                   "after the measured I/O")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_spans(ctx, node.body)
            if isinstance(node, (ast.For, ast.While)):
                yield from self._check_spans(ctx, node.body)
                yield from self._late_submit_stamp(ctx, node.body)

    # -- (a) unblocked device span ---------------------------------------
    def _is_perf_counter(self, ctx: ModuleContext, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and ctx.resolve(node.func) in
                ("time.perf_counter", "time.monotonic", "time.time"))

    def _span_ends(self, ctx: ModuleContext, stmt: ast.stmt
                   ) -> Set[str]:
        """Names t for which this statement computes ``perf_counter() - t``."""
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and self._is_perf_counter(ctx, node.left)
                    and isinstance(node.right, ast.Name)):
                out.add(node.right.id)
        return out

    def _device_dispatch(self, ctx: ModuleContext, stmt: ast.stmt
                         ) -> Optional[ast.Call]:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if (resolved.startswith(_DISPATCH_PREFIXES)
                    or resolved in _DISPATCH_CALLS
                    or ctx.jitted_call_name(node)):
                return node
        return None

    def _has_blocker(self, ctx: ModuleContext, stmt: ast.stmt) -> bool:
        return any(isinstance(n, ast.Call)
                   and ctx.resolve(n.func) in BLOCKING_CALLS
                   for n in ast.walk(stmt))

    def _check_spans(self, ctx: ModuleContext, block: List[ast.stmt]
                     ) -> Iterator[Finding]:
        open_spans: Dict[str, int] = {}  # timer var -> stmt index
        for i, stmt in enumerate(block):
            for t in self._span_ends(ctx, stmt):
                if t not in open_spans:
                    continue
                span = block[open_spans.pop(t):i]
                dispatch = next(
                    (d for s in span
                     if (d := self._device_dispatch(ctx, s)) is not None),
                    None)
                if dispatch is not None and not any(
                        self._has_blocker(ctx, s) for s in span):
                    yield self.finding(
                        ctx, dispatch, "timed region dispatches JAX work "
                        "but never blocks on it — jax dispatch is async, "
                        "so the span measures launch overhead, not "
                        "compute; add jax.block_until_ready(...) inside "
                        "the measured region")
            if (isinstance(stmt, ast.Assign)
                    and self._is_perf_counter(ctx, stmt.value)):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        open_spans[target.id] = i

    # -- (b) submit stamp after the measured I/O -------------------------
    def _late_submit_stamp(self, ctx: ModuleContext, block: List[ast.stmt]
                           ) -> Iterator[Finding]:
        io_seen = False
        for stmt in block:
            if not io_seen:
                io_seen = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _IO_METHODS
                    for n in ast.walk(stmt))
                continue
            if not isinstance(stmt, ast.Assign):
                continue
            for node in ast.walk(stmt):
                if not self._is_perf_counter(ctx, node):
                    continue
                for target in stmt.targets:
                    base = target
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if (isinstance(base, ast.Name)
                            and _SUBMIT_NAME_RE.search(base.id)):
                        yield self.finding(
                            ctx, stmt, f"submit/start timestamp "
                            f"`{base.id}` is captured AFTER blocking I/O "
                            f"in this loop — the measured span excludes "
                            f"the request and can go negative; capture "
                            f"the timestamp before the I/O call")


# --------------------------------------------------------------------- 105
class StrayPrint(Rule):
    """print/jax.debug.print/breakpoint left in library code.

    Serving and training hot paths log through ``logging`` or structured
    stderr writes; a bare print in library code is debug debris (and
    ``jax.debug.print`` inside a jit inserts a host callback into the
    compiled program). CLI entrypoints (``main``/``__main__`` blocks) and
    prints with an explicit ``file=`` are the user interface — exempt.
    """

    id = "VMT105"
    name = "stray-print"
    severity = "warning"
    description = "bare print()/jax.debug.print/breakpoint() in library code"
    library_only = True

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved == "breakpoint":
                yield self.finding(ctx, node,
                                   "breakpoint() left in library code")
            elif resolved == "jax.debug.print":
                yield self.finding(
                    ctx, node, "jax.debug.print in library code — inside "
                    "a jit this compiles a host callback into the "
                    "program; remove before shipping")
            elif (resolved == "print" and not ctx.in_main_block(node)
                    and not any(kw.arg == "file" for kw in node.keywords)):
                yield self.finding(
                    ctx, node, "bare print() in library code — use "
                    "logging (or print(..., file=sys.stderr) for "
                    "deliberate diagnostics)")


# --------------------------------------------------------------------- 106
class SqliteThreadSharing(Rule):
    """A sqlite3 connection stored for cross-call reuse without a lock.

    sqlite connections are not thread-safe; the serve tier runs HTTP,
    worker, and push threads against the same databases. The repo pattern
    is connection-per-call (serve/db.py, serve/queue.py) — a connection
    parked on ``self``/module scope, or ``check_same_thread=False``,
    without a ``threading.Lock`` in the same class is a data race.
    """

    id = "VMT106"
    name = "sqlite-thread-sharing"
    severity = "error"
    description = ("sqlite3.connect result shared across threads without "
                   "a lock")

    @staticmethod
    def _has_lock(cls_node: ast.ClassDef, ctx: ModuleContext) -> bool:
        return any(
            isinstance(n, ast.Call) and ctx.resolve(n.func) in
            ("threading.Lock", "threading.RLock")
            for n in ast.walk(cls_node))

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.resolve(node.func) == "sqlite3.connect"):
                continue
            cross_thread = any(
                kw.arg == "check_same_thread"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords)
            parent = ctx.parent(node)
            stored = (isinstance(parent, ast.Assign) and any(
                isinstance(t, ast.Attribute) or (
                    isinstance(t, ast.Name)
                    and ctx.enclosing_function(node) is None)
                for t in parent.targets))
            if not (stored or cross_thread):
                continue
            cls = next((a for a in ctx.ancestors(node)
                        if isinstance(a, ast.ClassDef)), None)
            if cls is not None and self._has_lock(cls, ctx):
                continue
            where = ("with check_same_thread=False" if cross_thread
                     else "on shared state")
            yield self.finding(
                ctx, node, f"sqlite3 connection stored {where} without a "
                f"threading.Lock — sqlite connections are not "
                f"thread-safe; open a connection per call (the "
                f"serve/db.py pattern) or guard every use with a lock")


# --------------------------------------------------------------------- 107
class SwallowedException(Rule):
    """``except:``/``except Exception:`` whose body only passes.

    In a worker/queue hot loop this turns a poisoned job or a dying
    backend into silent job loss. Narrow exception types are fine;
    ``__del__``/``__exit__`` teardown (where raising is worse) is exempt.

    CFG-aware since the proto tier landed: a ``pass`` handler whose
    continuation still *does* something — reaches any call or a valued
    return before falling off the function or looping back — is a
    deliberate "degrade and carry on" recovery path, not a swallow. The
    walk stops at the try body's own statements and at the enclosing
    loop's header, so "reaches work" means work *after* the handler, not
    the next iteration's re-attempt. All-``continue`` handlers keep
    firing unconditionally (their continuation is by definition the next
    iteration).
    """

    id = "VMT107"
    name = "swallowed-exception"
    severity = "warning"
    description = "broad except clause that silently discards the error"

    _TEARDOWN = {"__del__", "__exit__", "__aexit__"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or ctx.resolve(node.type) in (
                "Exception", "BaseException")
            trivial = all(
                isinstance(s, (ast.Pass, ast.Continue)) for s in node.body)
            if not (broad and trivial):
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and fn.name in self._TEARDOWN:
                continue
            if all(isinstance(s, ast.Pass) for s in node.body) \
                    and fn is not None \
                    and self._continuation_works(ctx, fn, node):
                continue
            caught = ("bare except" if node.type is None
                      else f"except {ctx.resolve(node.type)}")
            yield self.finding(
                ctx, node, f"{caught} swallows every error with "
                f"`{'pass' if isinstance(node.body[0], ast.Pass) else 'continue'}`"
                f" — in a hot loop this silently drops jobs; catch the "
                f"specific exception or at least log it")

    @staticmethod
    def _continuation_works(ctx: ModuleContext, fn: ast.AST,
                            handler: ast.ExceptHandler) -> bool:
        """True when the path leaving ``handler`` still reaches a call
        or a valued return inside ``fn`` — without re-entering the try
        body or crossing the enclosing loop's header."""
        from vilbert_multitask_tpu.analysis.cfg import (
            build_cfg, iter_event_nodes)
        try:
            cfg = build_cfg(fn)
        except RecursionError:  # pragma: no cover
            return False
        tries = [a for a in ctx.ancestors(handler)
                 if isinstance(a, ast.Try) and handler in a.handlers]
        if not tries:
            return False
        body_ids = {id(n) for stmt in tries[0].body
                    for n in ast.walk(stmt)}
        loop = next((a for a in ctx.ancestors(tries[0])
                     if isinstance(a, (ast.While, ast.For))
                     and ctx.enclosing_function(a) is fn), None)
        loop_head_ids: Set[int] = set()
        if loop is not None:
            if isinstance(loop, ast.While):
                loop_head_ids.add(id(loop.test))
            else:
                loop_head_ids.update((id(loop.iter), id(loop.target)))
        start = next((blk for blk in cfg.blocks
                      if any(e is handler.body[-1] for e in blk.events)),
                     None)
        if start is None:
            return False
        seen = {start.id}
        frontier = [start]
        first = True
        while frontier:
            blk = frontier.pop()
            for event in blk.events:
                if first and blk is start:
                    # Skip events up to and including the handler body.
                    continue
                if id(event) in body_ids or id(event) in loop_head_ids:
                    break
                if isinstance(event, ast.Return) \
                        and event.value is not None:
                    return True
                if any(isinstance(n, ast.Call)
                       for n in iter_event_nodes(event)):
                    return True
            else:
                for succ in blk.succs:
                    if succ.id not in seen:
                        seen.add(succ.id)
                        frontier.append(succ)
            first = False
        return False


# --------------------------------------------------------------------- 108
_NP_CONSTRUCTORS = ("numpy.array", "numpy.zeros", "numpy.ones",
                    "numpy.empty", "numpy.full", "numpy.arange",
                    "numpy.linspace", "numpy.eye")
_MUTATING_METHODS = {"fill", "sort", "put", "resize", "partition",
                     "setfield", "itemset"}


class ModuleLevelNumpyMutation(Rule):
    """Functions mutating module-level numpy arrays in place.

    A module-global ndarray mutated from functions is shared mutable state
    that is invisible to jit tracing (baked in as a constant at trace
    time, stale forever after) and unsafe under the serving threads.
    """

    id = "VMT108"
    name = "module-numpy-mutation"
    severity = "warning"
    description = "in-place mutation of a module-level numpy array"

    def _module_arrays(self, ctx: ModuleContext) -> Set[str]:
        out: Set[str] = set()
        for stmt in ctx.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)):
                continue
            if ctx.resolve(stmt.value.func) in _NP_CONSTRUCTORS:
                out.update(t.id for t in stmt.targets
                           if isinstance(t, ast.Name))
        return out

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        arrays = self._module_arrays(ctx)
        if not arrays:
            return
        for node in ast.walk(ctx.tree):
            if ctx.enclosing_function(node) is None:
                continue
            hit: Optional[str] = None
            if (isinstance(node, (ast.Assign, ast.AugAssign))):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Name) and base.id in arrays \
                            and (isinstance(t, ast.Subscript)
                                 or isinstance(node, ast.AugAssign)):
                        hit = base.id
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATING_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in arrays):
                hit = node.func.value.id
            if hit is not None:
                yield self.finding(
                    ctx, node, f"module-level numpy array `{hit}` is "
                    f"mutated in place — jit traces bake it in as a "
                    f"stale constant and the serving threads race on it; "
                    f"pass state explicitly or make it immutable")


# --------------------------------------------------------------------- 109
class WallClockDuration(Rule):
    """``time.time()`` used in duration arithmetic.

    The wall clock steps under NTP slew/adjustment, so a latency computed
    from it can jump or go negative; monotonic ``time.perf_counter()`` is
    the duration clock everywhere in this repo (the obs tracer refuses
    wall clock entirely). Legitimate wall-clock subtraction exists —
    uptime reporting, deadline math against persisted cross-process
    timestamps — and is suppressed inline with a justification.
    """

    id = "VMT109"
    name = "wallclock-duration"
    severity = "error"
    description = ("time.time() used to compute a duration/latency — "
                   "use monotonic time.perf_counter()")

    def _is_walltime(self, ctx: ModuleContext, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and ctx.resolve(node.func) == "time.time")

    def _anchors(self, ctx: ModuleContext
                 ) -> Tuple[Set[Tuple[int, str]], Set[str]]:
        """Targets assigned from time.time(): plain names scoped to their
        enclosing function (id(fn) or 0 at module level), attribute
        targets (``self._t0 = time.time()``) module-wide by source text."""
        names: Set[Tuple[int, str]] = set()
        attrs: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and self._is_walltime(ctx, node.value)):
                continue
            fn = ctx.enclosing_function(node)
            scope = id(fn) if fn is not None else 0
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add((scope, t.id))
                elif isinstance(t, ast.Attribute):
                    attrs.add(ast.unparse(t))
        return names, attrs

    def _matches(self, ctx: ModuleContext, operand: ast.AST, scope: int,
                 names: Set[Tuple[int, str]], attrs: Set[str]) -> bool:
        if self._is_walltime(ctx, operand):
            return True
        if isinstance(operand, ast.Name):
            return (scope, operand.id) in names
        if isinstance(operand, ast.Attribute):
            return ast.unparse(operand) in attrs
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        names, attrs = self._anchors(ctx)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            fn = ctx.enclosing_function(node)
            scope = id(fn) if fn is not None else 0
            if (self._matches(ctx, node.left, scope, names, attrs)
                    or self._matches(ctx, node.right, scope, names, attrs)):
                yield self.finding(
                    ctx, node, "duration computed from the wall clock "
                    "(time.time()) — NTP slew makes it jump or go "
                    "negative; measure spans with the monotonic "
                    "time.perf_counter() (or suppress with a "
                    "justification if this really is calendar math)")


# --------------------------------------------------------------------- 110
_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition")
_INIT_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}
# self.field.<method>() calls that mutate the container in place.
_CONTAINER_MUTATORS = {"append", "extend", "insert", "add", "remove",
                       "discard", "pop", "popitem", "clear", "update",
                       "setdefault", "appendleft", "popleft"}


class _ClassLockAnalysis:
    """Per-class lock-discipline facts: which fields the lock guards, and
    which accesses happen outside it."""

    def __init__(self, ctx: ModuleContext, cls: ast.ClassDef):
        self.ctx = ctx
        self.cls = cls
        self.methods: Dict[str, ast.AST] = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.locks: Set[str] = self._find_locks()
        # (field, node, method, lexically_guarded, is_write)
        self.accesses: List[Tuple[str, ast.AST, str, bool, bool]] = []
        self.locked_only: Set[str] = set()
        if self.locks:
            self._collect_accesses()
            self._infer_locked_only()

    def _find_locks(self) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(self.cls):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and self.ctx.resolve(node.value.func) in _LOCK_CTORS):
                out.update(t.attr for t in node.targets
                           if isinstance(t, ast.Attribute)
                           and isinstance(t.value, ast.Name)
                           and t.value.id == "self")
        return out

    def _lexically_guarded(self, node: ast.AST) -> bool:
        """Inside ``with self.<lock>:`` — stopping at function boundaries,
        because a nested def inside a with-block escapes the lock."""
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    e = item.context_expr
                    if (isinstance(e, ast.Attribute)
                            and isinstance(e.value, ast.Name)
                            and e.value.id == "self"
                            and e.attr in self.locks):
                        return True
        return False

    def _is_write(self, node: ast.Attribute) -> bool:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = self.ctx.parent(node)
        if (isinstance(parent, ast.Subscript) and parent.value is node
                and isinstance(parent.ctx, (ast.Store, ast.Del))):
            return True  # self.d[k] = v
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and isinstance(parent.ctx, (ast.Store, ast.Del))):
            return True  # self.obj.attr = v
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _CONTAINER_MUTATORS):
            gp = self.ctx.parent(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                return True  # self.d.clear() / self.xs.append(...)
        return False

    def _collect_accesses(self) -> None:
        for mname, method in self.methods.items():
            for node in ast.walk(method):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr not in self.locks):
                    continue
                self.accesses.append((
                    node.attr, node, mname,
                    self._lexically_guarded(node), self._is_write(node)))

    def _infer_locked_only(self) -> None:
        """Private methods whose every intra-class call site holds the
        lock are themselves lock-guarded (the ``_degrade_to_xla`` /
        ``Histogram._get_series`` pattern). Fixed point so helpers called
        only from locked helpers qualify. __init__ call sites count as
        guarded — construction is single-threaded."""
        sites: Dict[str, List[Tuple[str, bool]]] = {}
        for mname, method in self.methods.items():
            for node in ast.walk(method):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in self.methods):
                    continue
                sites.setdefault(node.func.attr, []).append(
                    (mname, self._lexically_guarded(node)))
        changed = True
        while changed:
            changed = False
            for m, callers in sites.items():
                if (m in self.locked_only or not m.startswith("_")
                        or m.startswith("__")):
                    continue
                if all(guarded or c in self.locked_only
                       or c in _INIT_METHODS for c, guarded in callers):
                    self.locked_only.add(m)
                    changed = True

    def guarded_fields(self) -> Set[str]:
        """Fields the lock demonstrably protects: written at least once
        under it (lexically or in a locked-only method), outside
        construction. Read-only-under-lock fields don't qualify — that
        pattern is usually immutability, not lock discipline."""
        return {field for field, _n, m, guarded, write in self.accesses
                if write and m not in _INIT_METHODS
                and (guarded or m in self.locked_only)}

    def unguarded_writes(self, guarded: Set[str]
                         ) -> Iterator[Tuple[str, ast.AST, str]]:
        for field, node, m, lex, write in self.accesses:
            if (write and field in guarded and m not in _INIT_METHODS
                    and not lex and m not in self.locked_only):
                yield field, node, m


class LockDisciplineRace(Rule):
    """A lock-guarded field written without the lock in a class that runs
    on threads.

    Per class: infer the guarded-field set (fields written under ``with
    self.<lock>`` or inside methods only ever called with the lock held),
    then flag writes that skip the lock — but only when the project call
    graph shows the class actually executes on a thread (a
    ``Thread(target=...)``, executor ``submit``/``map``, HTTP handler
    verb, or anything call-reachable from one). Unguarded *reads* are not
    flagged: lock-free reads of a generation counter or stats snapshot
    are a deliberate, benign pattern in this codebase.
    """

    id = "VMT110"
    name = "unlocked-shared-field"
    severity = "error"
    description = ("field written without the lock that guards its other "
                   "writes, in a class reachable from a thread entry "
                   "point")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _ClassLockAnalysis(ctx, node)
            if not info.locks:
                continue
            guarded = info.guarded_fields()
            if not guarded:
                continue
            witness = ctx.project.thread_witness(ctx, node)
            if witness is None:
                continue
            lock = sorted(info.locks)[0]
            for field, acc, method in info.unguarded_writes(guarded):
                yield self.finding(
                    ctx, acc, f"`self.{field}` is written in "
                    f"`{node.name}.{method}` without `self.{lock}`, but "
                    f"its other writes hold the lock; `{node.name}` runs "
                    f"on threads ({witness}) — this is a data race: take "
                    f"the lock here or suppress with a justification")


# --------------------------------------------------------------------- 111
class PartitionSpecAxisMismatch(Rule):
    """PartitionSpec axis name matching no declared mesh axis.

    Collects every mesh axis declared anywhere in the project — string
    constants in ``jax.sharding.Mesh(...)`` axis arguments and in
    ``axis_names`` assignments/defaults/keywords (``parallel/mesh.py``,
    ``config.py``) — then validates the constant-string axes of every
    ``PartitionSpec(...)`` call against that set. A typo'd axis fails at
    runtime only on the multi-host path that actually builds the mesh;
    statically it's just a string comparison. Variable axis arguments are
    skipped; a project declaring no axes is silent.
    """

    id = "VMT111"
    name = "partition-spec-axis"
    severity = "error"
    description = ("PartitionSpec uses an axis name not declared by any "
                   "mesh in the project")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        from vilbert_multitask_tpu.analysis.graph import module_mesh_axes

        declared = (ctx.project.mesh_axes() if ctx.project is not None
                    else module_mesh_axes(ctx))
        if not declared:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and ctx.resolve(node.func)
                    == "jax.sharding.PartitionSpec"):
                continue
            for arg in node.args:
                for const in ast.walk(arg):
                    if (isinstance(const, ast.Constant)
                            and isinstance(const.value, str)
                            and const.value not in declared):
                        yield self.finding(
                            ctx, const, f"PartitionSpec axis "
                            f"`{const.value}` is not declared by any mesh "
                            f"in the project (declared: "
                            f"{', '.join(sorted(declared))}) — a typo'd "
                            f"axis only fails at runtime on the mesh "
                            f"path")


# --------------------------------------------------------------------- 112
class LayeringViolation(Rule):
    """Import that breaks a declared layering contract.

    Contracts live in ``[tool.vmtlint.layers]`` in pyproject.toml as
    ``forbid = ["pkg.models -> pkg.serve", ...]`` — dotted module-prefix
    pairs meaning "modules under the left prefix must not import modules
    under the right". Checked against every import in the module,
    including lazy function-level ones (a lazy import still couples the
    layers at runtime).
    """

    id = "VMT112"
    name = "layering-violation"
    severity = "error"
    description = ("import forbidden by a [tool.vmtlint.layers] contract")

    @staticmethod
    def _under(name: str, prefix: str) -> bool:
        return name == prefix or name.startswith(prefix + ".")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        project = ctx.project
        if project is None or not project.layers:
            return
        mod = project.module(ctx)
        if mod is None:
            return
        seen: Set[Tuple[int, str]] = set()
        for src, dst in project.layers:
            if not self._under(mod.name, src):
                continue
            for imp in mod.imports:
                if not any(self._under(t, dst) for t in imp.targets()):
                    continue
                key = (getattr(imp.node, "lineno", 0), dst)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, imp.node, f"import of `{imp.targets()[-1]}` "
                    f"breaks the layering contract `{src} -> {dst}` "
                    f"declared in [tool.vmtlint.layers] — this layer "
                    f"must not depend on that one")


# --------------------------------------------------------------------- 113
_TRANSFER_EFFECTS = {
    "jax.device_put": "uploads host bytes to the device",
    "jax.device_get": "pulls device buffers back to the host",
    "jax.block_until_ready": "stalls the host on device completion",
}


class PerRowTransferInLoop(Rule):
    """Host<->device transfer inside a Python loop on the engine hot path.

    The per-dispatch cost anatomy (bench ``roundtrip_ms``) showed each
    host<->device round trip on a tunneled backend costs milliseconds; a
    transfer issued once PER LOOP ITERATION in code reachable from the
    serving entry points (``run``/``run_many``/``predict``) multiplies
    that by the batch — the exact shape the O(1)-leaf row slab removed
    from the rows path (one fused device_put per forward, index gathers
    for cached rows). Flags both direct ``jax.device_put``/``device_get``/
    ``block_until_ready`` calls and calls to project functions the call
    graph proves perform one transitively, but only inside ``for``/
    ``while`` bodies of hot-path functions (comprehensions are not loops
    here: they are the repo's idiom for building ONE fused transfer).
    Deliberate per-chunk transfers (run_many's pipelined dispatch/drain)
    carry baseline justifications rather than suppressions — the finding
    stays visible as the cost it is.
    """

    id = "VMT113"
    name = "per-row-transfer-in-loop"
    severity = "error"
    description = ("host<->device transfer (direct or through a project "
                   "call) inside a loop in a function reachable from the "
                   "engine serving entry points")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        mod = ctx.project.module(ctx)
        if mod is None:
            return
        cg = ctx.project.callgraph
        for fn, hot in ctx.project.hot_path_functions(ctx):
            for call in cg.own_call_nodes(fn):
                if not ctx.in_loop(call):
                    continue
                resolved = ctx.resolve(call.func)
                if resolved in _TRANSFER_EFFECTS:
                    yield self.finding(
                        ctx, call, f"`{resolved}` inside a loop on the "
                        f"engine hot path ({hot}) "
                        f"{_TRANSFER_EFFECTS[resolved]} once per iteration "
                        f"— hoist it out, batch the rows into one fused "
                        f"transfer, or keep the data device-resident")
                    continue
                target = cg.resolve_callable(mod, call.func, fn.scope,
                                             fn.cls_scope)
                witness = ctx.project.transfer_witness(target)
                if witness:
                    yield self.finding(
                        ctx, call, f"`{target}` performs a host<->device "
                        f"transfer ({witness}) and is called inside a loop "
                        f"on the engine hot path ({hot}) — each iteration "
                        f"pays a transfer round trip; batch the transfers "
                        f"or justify the pipelining in the baseline")


# --------------------------------------------------------------------- 114
# Substrings that mark a sleep delay as jittered/randomized. ``backoff_s``
# is the blessed helper: resilience.RetryPolicy.backoff_s is full-jitter
# by construction.
_JITTER_MARKERS = ("random", "uniform", "jitter", "expovariate",
                   "backoff_s")


class NakedRetryLoop(Rule):
    """An unbounded retry loop: catch + un-jittered sleep, no attempt cap.

    The exact shape ``resilience.RetryPolicy`` exists to replace (and that
    ``serve/remote.py`` used to hand-roll): ``while True`` around a try/
    except with a constant or deterministic-exponential ``time.sleep`` —
    every process that observed the same failure sleeps the same schedule
    and retries in lockstep (thundering herd), and nothing ever gives up,
    so a dead dependency pins the loop forever. A bounded ``for`` over
    attempts is structurally capped and stays clean; so does any delay
    expression that visibly randomizes (random/uniform/jitter/expovariate
    or the RetryPolicy ``backoff_s`` helper). Poll loops with a real exit
    condition (``while not stop.is_set()``) are not retry loops and are
    never flagged.
    """

    id = "VMT114"
    name = "naked-retry-loop"
    severity = "error"
    description = ("unbounded `while True` loop catching an exception and "
                   "time.sleep-ing a constant/un-jittered delay — retries "
                   "in lockstep forever; use resilience.RetryPolicy "
                   "(bounded attempts + full jitter)")

    @staticmethod
    def _is_unbounded(loop: ast.While) -> bool:
        return (isinstance(loop.test, ast.Constant)
                and bool(loop.test.value))

    def _jittered(self, ctx: ModuleContext, delay: ast.AST) -> bool:
        for node in ast.walk(delay):
            text = ""
            if isinstance(node, (ast.Name, ast.Attribute)):
                text = ctx.resolve(node)
            elif isinstance(node, ast.Call):
                text = ctx.resolve(node.func)
            if text and any(m in text.lower() for m in _JITTER_MARKERS):
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not (isinstance(loop, ast.While)
                    and self._is_unbounded(loop)):
                continue
            catches = any(
                isinstance(n, ast.ExceptHandler)
                for stmt in loop.body for n in ast.walk(stmt))
            if not catches:
                continue
            for stmt in loop.body:
                for node in ast.walk(stmt):
                    if not (isinstance(node, ast.Call)
                            and ctx.resolve(node.func) == "time.sleep"
                            and node.args):
                        continue
                    # Sleeps inside a NESTED bounded loop belong to that
                    # loop, not this retry loop.
                    owner = next(
                        (a for a in ctx.ancestors(node)
                         if isinstance(a, (ast.For, ast.While))), None)
                    if owner is not loop and not (
                            isinstance(owner, ast.While)
                            and self._is_unbounded(owner)):
                        continue
                    if self._jittered(ctx, node.args[0]):
                        continue
                    yield self.finding(
                        ctx, node, "un-jittered time.sleep in an unbounded "
                        "`while True` retry loop — every worker that saw "
                        "the failure retries on the same schedule, forever; "
                        "use resilience.RetryPolicy.call (bounded attempts, "
                        "full jitter, process retry budget)")


# --------------------------------------------------------------------- 115
# The always-on telemetry planes: modules under these path segments run
# for the life of the serving process, so a buffer that only ever grows
# there is a slow memory leak with a pager attached.
_OBS_PLANE_RE = re.compile(r"(^|[\\/])(obs|serve)[\\/]")
_BUFFER_GROWERS = {"append", "appendleft", "extend", "extendleft", "insert"}
_BUFFER_REMOVERS = {"pop", "popleft", "popitem", "remove", "clear"}


class UnboundedObsBuffer(Rule):
    """A telemetry buffer on the obs/serve planes that only ever grows.

    Every long-lived collector in this repo is bounded by construction —
    histogram reservoirs and trace rings are ``deque(maxlen=...)``, the
    time-series store is a ring, the flight recorder rotates its bundles.
    A module-level or instance list (or a deque built WITHOUT ``maxlen``)
    that functions append to, with no removal/truncation anywhere in the
    module, breaks that contract: it grows for the life of the serving
    process. Growth guarded by a ``len(...)`` check (the reservoir idiom)
    or paired with any ``pop``/``clear``/slice-truncation is bounded and
    stays clean.
    """

    id = "VMT115"
    name = "unbounded-obs-buffer"
    severity = "error"
    description = ("append to a module-level/instance list or maxlen-less "
                   "deque on the obs/serve planes with no removal or "
                   "truncation in the module — the buffer grows for the "
                   "process lifetime; use deque(maxlen=...) or trim it")

    def _is_unbounded_ctor(self, ctx: ModuleContext,
                           value: ast.AST) -> bool:
        """Empty list / list() / deque(...) without a bound."""
        if isinstance(value, ast.List) and not value.elts:
            return True
        if not isinstance(value, ast.Call):
            return False
        resolved = ctx.resolve(value.func)
        if resolved == "list" and not value.args:
            return True
        if resolved.endswith("deque"):
            # deque(iterable, maxlen) — a second positional IS the bound.
            if len(value.args) >= 2:
                return False
            return not any(k.arg == "maxlen" for k in value.keywords)
        return False

    def _candidates(self, ctx: ModuleContext
                    ) -> Tuple[Dict[str, ast.AST], Dict[str, ast.AST]]:
        """Unbounded buffer initializers: module-level names and
        ``self.<attr>`` assignments (attr keyed by name module-wide)."""
        names: Dict[str, ast.AST] = {}
        attrs: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if not self._is_unbounded_ctor(ctx, value):
                continue
            for t in targets:
                if (isinstance(t, ast.Name)
                        and ctx.enclosing_function(node) is None):
                    names[t.id] = node
                elif (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attrs[t.attr] = node
        return names, attrs

    @staticmethod
    def _base(expr: ast.AST) -> Optional[Tuple[str, str]]:
        """Classify a buffer expression: ("name", x) or ("attr", x)."""
        if isinstance(expr, ast.Name):
            return ("name", expr.id)
        if isinstance(expr, ast.Attribute):
            return ("attr", expr.attr)
        return None

    def _removals(self, ctx: ModuleContext) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        for node in ast.walk(ctx.tree):
            # x.pop()/x.clear()/... and del x[...] both shrink the buffer.
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BUFFER_REMOVERS):
                key = self._base(node.func.value)
                if key:
                    out.add(key)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        key = self._base(t.value)
                        if key:
                            out.add(key)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    # x[:] = ... overwrites in place; x = x[-n:] truncates.
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.slice, ast.Slice)):
                        key = self._base(t.value)
                        if key:
                            out.add(key)
                if (isinstance(node.value, ast.Subscript)
                        and isinstance(node.value.slice, ast.Slice)):
                    key = self._base(node.value.value)
                    if key:
                        out.add(key)
        return out

    def _len_guarded(self, ctx: ModuleContext, call: ast.Call,
                     buf_text: str) -> bool:
        """Growth under ``if len(<buf>) < cap:`` is the reservoir idiom."""
        for anc in ctx.ancestors(call):
            if not isinstance(anc, (ast.If, ast.While)):
                continue
            for n in ast.walk(anc.test):
                if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                        and n.func.id == "len" and n.args
                        and ast.unparse(n.args[0]) == buf_text):
                    return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _OBS_PLANE_RE.search(ctx.rel_path):
            return
        names, attrs = self._candidates(ctx)
        if not names and not attrs:
            return
        removed = self._removals(ctx)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BUFFER_GROWERS):
                continue
            key = self._base(node.func.value)
            if key is None or key in removed:
                continue
            kind, name = key
            if kind == "name":
                # Import-time table building is static data, not a leak;
                # only growth from inside a function accretes per event.
                if (name not in names
                        or ctx.enclosing_function(node) is None):
                    continue
            elif name not in attrs:
                continue
            if self._len_guarded(ctx, node, ast.unparse(node.func.value)):
                continue
            where = ("module-level list" if kind == "name"
                     else f"instance buffer `self.{name}`")
            yield self.finding(
                ctx, node, f"`.{node.func.attr}` grows {where} `{name}` "
                f"on the obs/serve plane with no removal or truncation "
                f"anywhere in the module — it accretes for the process "
                f"lifetime; bound it (deque(maxlen=...), rotation, or an "
                f"explicit trim)")


# --------------------------------------------------------------------- 116
# Calls that block the calling thread outright. sqlite3.connect covers the
# serving plane's I/O idiom (every DB op opens a per-call connection, so
# the connect call IS the disk touch); the jax entries pin the thread on
# device round trips (same effects table as VMT113).
_BLOCKING_DIRECT = {
    "time.sleep": "sleeps the thread outright",
    "sqlite3.connect": "performs SQLite disk I/O",
    "jax.device_put": "uploads host bytes to the device",
    "jax.device_get": "pulls device buffers back to the host",
    "jax.block_until_ready": "stalls the host on device completion",
}
# The serving plane only: the engine's deliberate device_put under its
# input-cache lock (slab insert) is the documented exception — serialized
# uploads ARE its contract — so this rule scopes to serve/.
_SCHED_PLANE_RE = re.compile(r"(^|[\\/])serve[\\/]")


class BlockingCallUnderSchedulerLock(Rule):
    """A blocking call reachable while a serving-plane lock is held.

    The continuous-batching scheduler's condvar guards the ready list the
    intake pool and dispatch loop share; the worker's inflight lock sits
    on every claim/finish. A device dispatch, ``device_get``, SQLite open,
    or ``time.sleep`` executed with such a lock held turns that one slow
    call into a convoy: every intake thread and the dispatcher pile up on
    the lock for the duration (the latency anatomy's execute window,
    spent inside a mutex). Reuses VMT110's per-class lock inference —
    calls flagged when lexically inside ``with self.<lock>:`` or in a
    method the fixed point proves only ever runs with the lock held — and
    VMT113's call-graph witnesses for project calls that transfer
    transitively. ``Condition.wait`` stays clean (it releases the lock);
    so does everything outside serve/ (the engine's slab insert
    deliberately serializes uploads under its cache lock).
    """

    id = "VMT116"
    name = "blocking-call-under-scheduler-lock"
    severity = "error"
    description = ("device dispatch, device_get, SQLite I/O, or time.sleep "
                   "reachable while holding a serving-plane lock in a "
                   "threaded class — the lock convoy stalls every sharer "
                   "for the call's duration")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None or not _SCHED_PLANE_RE.search(ctx.rel_path):
            return
        mod = ctx.project.module(ctx)
        if mod is None:
            return
        cg = ctx.project.callgraph
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            info = _ClassLockAnalysis(ctx, cls)
            if not info.locks:
                continue
            # Single-threaded classes can't convoy — same witness bar as
            # VMT110.
            witness = ctx.project.thread_witness(ctx, cls)
            if witness is None:
                continue
            lock = sorted(info.locks)[0]
            for mname, method in info.methods.items():
                if mname in _INIT_METHODS:
                    continue
                locked_method = mname in info.locked_only
                held = (f"`{cls.name}.{mname}` only ever runs with "
                        f"`self.{lock}` held" if locked_method
                        else f"inside `with self.{lock}:`")
                for call in ast.walk(method):
                    if not isinstance(call, ast.Call):
                        continue
                    # Nested defs escape the lock (they run later, on
                    # whatever thread calls them).
                    if ctx.enclosing_function(call) is not method:
                        continue
                    if not (locked_method
                            or info._lexically_guarded(call)):
                        continue
                    resolved = ctx.resolve(call.func)
                    if resolved in _BLOCKING_DIRECT:
                        yield self.finding(
                            ctx, call, f"`{resolved}` "
                            f"{_BLOCKING_DIRECT[resolved]} while "
                            f"{held}; `{cls.name}` runs on threads "
                            f"({witness}) — every sharer convoys on the "
                            f"lock for the call's duration; move the "
                            f"blocking work outside the critical section")
                        continue
                    fn = cg.by_node.get(id(method))
                    if fn is None:
                        continue
                    target = cg.resolve_callable(mod, call.func, fn.scope,
                                                 fn.cls_scope)
                    tw = ctx.project.transfer_witness(target)
                    if tw:
                        yield self.finding(
                            ctx, call, f"`{target}` performs a "
                            f"host<->device transfer ({tw}) while {held}; "
                            f"`{cls.name}` runs on threads ({witness}) — "
                            f"the device round trip convoys every sharer "
                            f"on the lock; dispatch outside the critical "
                            f"section")


_POOL_MODULE_RE = re.compile(r"(^|[\\/])pool\.py$")


class ReplicaAffinityLeak(Rule):
    """A replica handle captured outside the pool's checkout/checkin seam.

    The ReplicaPool's failover and rolling-swap guarantees rest on one
    invariant: an engine handle leaves the pool ONLY through
    ``checkout()`` and comes back through ``checkin()`` in the same
    dispatch scope. A handle stored on ``self`` or at module level pins
    work to one replica past the seam — the pool drains a replica the
    stored handle keeps using (swap corrupts in-flight work), and a dead
    replica's handle keeps receiving dispatches failover can never see.
    A checkout whose result neither checks back in nor escapes via
    return leaks the inflight slot outright: the replica's admission
    budget never recovers and the pool slowly wedges. Scoped to serve/
    (pool.py itself implements the seam and is exempt).
    """

    id = "VMT117"
    name = "replica-affinity-leak"
    severity = "error"
    description = ("replica handle from pool.checkout() stored on self/"
                   "module scope, or checked out with no checkin() and no "
                   "return of the handle in the same function — the "
                   "handle outlives the checkout/checkin seam, pinning "
                   "work to a replica the pool may drain, swap, or "
                   "declare dead")

    @staticmethod
    def _is_checkout(call: ast.AST) -> bool:
        return (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "checkout")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _SCHED_PLANE_RE.search(ctx.rel_path):
            return
        if _POOL_MODULE_RE.search(ctx.rel_path):
            return
        # Module-level captures: `REP = pool.checkout()` pins forever.
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                if value is not None and any(
                        self._is_checkout(n) for n in ast.walk(value)):
                    yield self.finding(
                        ctx, stmt, "replica handle checked out into module "
                        "scope — it outlives every drain/swap/failover; "
                        "checkout per dispatch and checkin in the same "
                        "function")
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            checkouts = [n for n in ast.walk(fn)
                         if self._is_checkout(n)
                         and ctx.enclosing_function(n) is fn]
            if not checkouts:
                continue
            has_checkin = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "checkin"
                for n in ast.walk(fn))
            # Local names bound to a checkout result (x = pool.checkout()).
            handle_names: Set[str] = set()
            stored: List[ast.AST] = []
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if not any(self._is_checkout(n)
                           for n in ast.walk(node.value)):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        # self.rep = pool.checkout(...) — affinity pinned
                        # on the instance, past the seam.
                        stored.append(node)
                    elif isinstance(tgt, ast.Name):
                        handle_names.add(tgt.id)
            for node in stored:
                yield self.finding(
                    ctx, node, "replica handle stored on an attribute — "
                    "the engine stays pinned after the pool drains, "
                    "swaps, or kills that replica; keep the handle local "
                    "and checkin() in the same function")
            if has_checkin:
                continue
            # No checkin: the function must at least hand the handle back
            # to its caller (a seam-forwarding helper returns it).
            # Only the handle ITSELF escaping counts (`return rep` /
            # `return pool.checkout()`): returning a value computed FROM
            # the handle (`return rep.engine.run(...)`) still strands it.
            returns_handle = any(
                isinstance(n, ast.Return) and n.value is not None
                and (self._is_checkout(n.value)
                     or (isinstance(n.value, ast.Name)
                         and n.value.id in handle_names))
                for n in ast.walk(fn))
            if not returns_handle:
                yield self.finding(
                    ctx, checkouts[0], "checkout() with no checkin() and "
                    "no return of the handle in this function — the "
                    "replica's inflight slot leaks and its breaker never "
                    "hears the outcome; pair every checkout with a "
                    "checkin on both success and failure paths")


# --------------------------------------------------------------------- 118
_QUANT_IMPL_RE = re.compile(r"(^|/)quant\.py$")
_DEQUANT_FUNCS = ("quant.dequantize_tree", "quant.dequantize_leaf")


class DequantOutsideJit(Rule):
    """Host-side dequantization of an int8-quantized param tree.

    The point of ``param_dtype="int8"`` is that weight HBM reads stay one
    byte per element: the jitted forward dequantizes in-program
    (engine/runtime.py ``_apply_heads``) so XLA fuses
    ``values.astype(compute) * scale`` into the consuming matmul and no
    fat copy ever exists. Calling ``quant.dequantize_tree`` /
    ``dequantize_leaf`` — or hand-rolling ``pair["int8"].astype(...)`` —
    OUTSIDE a jit boundary materializes the widened tree eagerly
    (host-side: a full second tree in RAM plus a fat re-upload; eager
    device-side: a standing 4× copy), silently refunding everything int8
    storage bought. quant.py itself (the implementation) is exempt, as is
    any function the jit plane provably or plausibly traces: lexical jit
    bodies, call-graph-traced helpers, and functions whose name is
    referenced inside a jit body of the same module (the bound-alias
    ``engine = self`` closure pattern the call graph cannot resolve).
    """

    id = "VMT118"
    name = "dequant-outside-jit"
    severity = "error"
    description = ("quant.dequantize_tree/dequantize_leaf (or a hand-"
                   "rolled pair['int8'].astype(...)) called outside any "
                   "jit boundary — the widened tree materializes eagerly, "
                   "defeating int8 weight storage; dequantize inside the "
                   "compiled program instead")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if _QUANT_IMPL_RE.search(ctx.rel_path):
            return
        traced: Set[int] = {id(info.body) for info in ctx.jit_bodies}
        if ctx.project is not None:
            traced |= {id(info.body)
                       for info, _ in ctx.project.traced_helpers(ctx)}
        # Names referenced inside any jit body here: methods invoked
        # through a captured self-alias inherit traced context even though
        # the call graph cannot prove it. Generous by design — this rule
        # polices the serve/boot/bench planes, not the forward builders.
        referenced: Set[str] = set()
        for info in ctx.jit_bodies:
            for n in ast.walk(info.body):
                if isinstance(n, ast.Attribute):
                    referenced.add(n.attr)
                elif isinstance(n, ast.Name):
                    referenced.add(n.id)

        def is_traced(node: ast.AST) -> bool:
            for anc in ctx.ancestors(node):
                if id(anc) in traced:
                    return True
                if (isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and anc.name in referenced):
                    return True
            return False

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = ctx.resolve(node.func)
            if resolved.endswith(_DEQUANT_FUNCS):
                if not is_traced(node):
                    yield self.finding(
                        ctx, node, f"`{resolved.rsplit('.', 1)[-1]}` "
                        f"outside any jit boundary widens the whole int8 "
                        f"tree eagerly — a standing fat copy per call; "
                        f"dequantize inside the compiled forward (or wrap "
                        f"the call in jax.jit) so HBM reads stay int8")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "astype"
                  and isinstance(node.func.value, ast.Subscript)
                  and isinstance(node.func.value.slice, ast.Constant)
                  and node.func.value.slice.value == "int8"):
                if not is_traced(node):
                    yield self.finding(
                        ctx, node, "hand-rolled dequant "
                        "(pair['int8'].astype(...)) outside any jit "
                        "boundary — use quant.dequantize_leaf inside the "
                        "compiled program so the widening fuses into the "
                        "consuming matmul")


# --------------------------------------------------------------------- 122
class ConfigKnobDrift(Rule):
    """ServingConfig/EngineConfig fields vs. what the project actually reads.

    Two drift directions, both real after PRs 5-9 added 40+ knobs: a knob
    declared but never read anywhere (dead weight that silently ignores the
    operator's intent), and an attribute read that matches no declared field
    (a typo that returns AttributeError at runtime — or worse, never runs).
    Reads are recognized by their access spelling: ``*.serving.<knob>`` /
    ``*._serving.<knob>`` for ServingConfig, ``*cfg.engine.<knob>`` for
    EngineConfig — the only idioms the codebase uses.
    """

    id = "VMT122"
    name = "config-knob-drift"
    severity = "warning"
    description = ("ServingConfig/EngineConfig knob declared but never read "
                   "anywhere in the project, or an attribute read matching "
                   "no declared knob (typo detector)")

    _SERVING_BASES = ("serving", "_serving")
    _ENGINE_CLS = "EngineConfig"
    _SERVING_CLS = "ServingConfig"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Set by the --changed driver: a subset scan cannot prove a knob is
        # read *nowhere*, so the dead-knob direction is suppressed there.
        self.partial_scan = False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        audit = _knob_audit(ctx.project)
        if not self.partial_scan:
            for cls_name, field, node, rel in audit["declared"]:
                if rel != ctx.rel_path:
                    continue
                if field in audit["reads"].get(cls_name, set()):
                    continue
                yield self.finding(
                    ctx, node,
                    f"`{cls_name}.{field}` is declared but never read "
                    f"anywhere in the scanned project — a dead knob "
                    f"silently ignores whatever the operator sets it to; "
                    f"wire it up or delete it")
        for rel, node, cls_name, attr in audit["suspect_reads"]:
            if rel != ctx.rel_path:
                continue
            import difflib

            close = difflib.get_close_matches(
                attr, sorted(audit["members"].get(cls_name, ())), n=2)
            hint = f" (did you mean {' or '.join(close)}?)" if close else ""
            yield self.finding(
                ctx, node,
                f"`.{attr}` matches no declared {cls_name} field{hint} — "
                f"a typo here raises AttributeError on the serving path, "
                f"or reads a knob that no longer exists")


def _knob_audit(project) -> Dict:
    """Cross-module knob audit, cached on the ProjectGraph."""
    cached = getattr(project, "_knob_audit", None)
    if cached is not None:
        return cached
    audited = (ConfigKnobDrift._SERVING_CLS, ConfigKnobDrift._ENGINE_CLS)
    declared: List[Tuple[str, str, ast.AST, str]] = []
    members: Dict[str, Set[str]] = {}
    for mod in project.modules.values():
        for node in ast.walk(mod.ctx.tree):
            if not (isinstance(node, ast.ClassDef) and node.name in audited):
                continue
            mem = members.setdefault(node.name, set())
            for stmt in node.body:
                if (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    declared.append((node.name, stmt.target.id, stmt,
                                     mod.ctx.rel_path))
                    mem.add(stmt.target.id)
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            declared.append((node.name, t.id, stmt,
                                             mod.ctx.rel_path))
                            mem.add(t.id)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    mem.add(stmt.name)
    reads: Dict[str, Set[str]] = {}
    suspects: List[Tuple[str, ast.AST, str, str]] = []
    seen_suspects: Set[int] = set()

    def record(mod, node: ast.AST, cls_name: str, attr: str) -> None:
        reads.setdefault(cls_name, set()).add(attr)
        if (members.get(cls_name) and attr not in members[cls_name]
                and not attr.startswith("__")
                and id(node) not in seen_suspects):
            seen_suspects.add(id(node))
            suspects.append((mod.ctx.rel_path, node, cls_name, attr))

    for mod in project.modules.values():
        tree = mod.ctx.tree
        module_aliases = _knob_aliases(tree)
        for scope in ast.walk(tree):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                aliases = dict(module_aliases)
                aliases.update(_knob_aliases(scope))
            elif isinstance(scope, ast.Module):
                aliases = module_aliases
            else:
                continue
            for node in ast.walk(scope):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)):
                    cls_name = _knob_base_class(node.value)
                    if cls_name is None and isinstance(node.value, ast.Name):
                        cls_name = aliases.get(node.value.id)
                    if cls_name is not None:
                        record(mod, node, cls_name, node.attr)
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in ("getattr", "hasattr")
                        and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and isinstance(node.args[1].value, str)):
                    # getattr(api.serving, "admin_token", None) is a read
                    # too — and has a default, so never a typo suspect.
                    base = node.args[0]
                    cls_name = _knob_value_class(base)
                    if cls_name is None and isinstance(base, ast.Name):
                        cls_name = aliases.get(base.id)
                    if cls_name is not None:
                        reads.setdefault(cls_name, set()).add(
                            node.args[1].value)
    audit = {"declared": declared, "members": members, "reads": reads,
             "suspect_reads": suspects}
    project._knob_audit = audit
    return audit


def _knob_aliases(scope: ast.AST) -> Dict[str, str]:
    """Local names that denote an audited config object in ``scope``:
    annotated parameters (``ecfg: EngineConfig``) and assignment aliases
    (``s = cfg.serving``, ``s = serving or ServingConfig()``)."""
    aliases: Dict[str, str] = {}
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = scope.args
        for arg in (list(getattr(a, "posonlyargs", ())) + a.args
                    + a.kwonlyargs):
            cls = _annotation_class(arg.annotation)
            if cls is not None:
                aliases[arg.arg] = cls
        stmts: List[ast.AST] = list(ast.walk(scope))
    else:
        # Module scope: only direct top-level statements — function-local
        # names must not leak into the module alias map.
        stmts = list(getattr(scope, "body", ()))
    for node in stmts:
        if isinstance(node, ast.Assign):
            cls = _knob_value_class(node.value)
            if cls is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = cls
        elif (isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)):
            cls = (_annotation_class(node.annotation)
                   or (_knob_value_class(node.value)
                       if node.value is not None else None))
            if cls is not None:
                aliases[node.target.id] = cls
    return aliases


def _annotation_class(ann: Optional[ast.expr]) -> Optional[str]:
    """ServingConfig/EngineConfig named anywhere in a type annotation,
    including ``Optional[...]`` wrappers and string annotations."""
    if ann is None:
        return None
    names = (ConfigKnobDrift._SERVING_CLS, ConfigKnobDrift._ENGINE_CLS)
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        for n in names:
            if n in ann.value:
                return n
        return None
    for node in ast.walk(ann):
        if isinstance(node, ast.Name) and node.id in names:
            return node.id
        if isinstance(node, ast.Attribute) and node.attr in names:
            return node.attr
    return None


def _knob_value_class(value: ast.expr) -> Optional[str]:
    """Which audited config class an rvalue expression denotes, if any."""
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            cls = _knob_value_class(v)
            if cls is not None:
                return cls
        return None
    if isinstance(value, ast.Call):
        f = value.func
        term = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None)
        if term in (ConfigKnobDrift._SERVING_CLS,
                    ConfigKnobDrift._ENGINE_CLS):
            return term
        return None
    if isinstance(value, ast.Attribute):
        if value.attr in ConfigKnobDrift._SERVING_BASES:
            return ConfigKnobDrift._SERVING_CLS
        if value.attr == "engine":
            base = value.value
            iterm = (base.id if isinstance(base, ast.Name)
                     else base.attr if isinstance(base, ast.Attribute)
                     else None)
            if iterm is not None and (iterm == "cfg"
                                      or iterm.endswith("_cfg")):
                return ConfigKnobDrift._ENGINE_CLS
    return None


def _knob_base_class(base: ast.expr) -> Optional[str]:
    """Which audited config class an attribute-access base denotes."""
    if isinstance(base, ast.Name):
        term = base.id
    elif isinstance(base, ast.Attribute):
        term = base.attr
    else:
        return None
    if term in ConfigKnobDrift._SERVING_BASES:
        return ConfigKnobDrift._SERVING_CLS
    if term == "engine" and isinstance(base, ast.Attribute):
        inner = base.value
        iterm = (inner.id if isinstance(inner, ast.Name)
                 else inner.attr if isinstance(inner, ast.Attribute)
                 else None)
        if iterm is not None and (iterm == "cfg" or iterm.endswith("_cfg")):
            return ConfigKnobDrift._ENGINE_CLS
    return None


# --------------------------------------------------------------------- 123
class InstrumentNameDrift(Rule):
    """Registered ``vmt_*`` instruments vs. the names the project reads.

    The VMT122 pattern applied to the metrics namespace. Two drift
    directions: an instrument registered (``REGISTRY.counter("vmt_x")``)
    whose handle is never used and whose name no string ever references —
    dead weight every exposition renders and every fleet flush ships —
    and a name-string read (a snapshot key lookup, a test asserting an
    exposition line) that matches no registered instrument: reads by
    name fail SILENTLY (a missing dict key, an assertion against a line
    that can never exist), so a typo here is a metric that quietly
    flatlines. Exposition suffixes (``_bucket``/``_sum``/``_count``) and
    the Sampler's derived ``*_per_s``-from-``*_total`` rates normalize to
    their base instrument; foreign ``vmt_``-prefixed strings (temp dirs,
    native symbols) are ignored unless they sit within typo distance of a
    real instrument name.
    """

    id = "VMT123"
    name = "instrument-name-drift"
    severity = "warning"
    description = ("vmt_* instrument registered but never written or "
                   "referenced anywhere (dead metric), or a name-string "
                   "read matching no registered instrument (typo detector "
                   "for the metrics namespace)")

    # A suspect read must be at least this SequenceMatcher-close to a real
    # name: genuine typos measure >=0.96, while foreign vmt_ strings
    # (vmt_demo, vmt_xla_cache, native symbols) top out near 0.72.
    _TYPO_CUTOFF = 0.85

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Set by the --changed driver: a subset scan cannot prove a name
        # is unused *anywhere*, so the dead direction is suppressed there.
        self.partial_scan = False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        audit = _instrument_audit(ctx.project)
        registered = audit["registered"]
        if not self.partial_scan:
            for name, sites in sorted(registered.items()):
                if name in audit["alive"]:
                    continue
                for node, rel, kind in sites:
                    if rel != ctx.rel_path:
                        continue
                    yield self.finding(
                        ctx, node,
                        f"{kind} `{name}` is registered but nothing ever "
                        f"writes to it or references it by name — a dead "
                        f"instrument that every exposition still renders; "
                        f"wire an observation to it or delete it")
        import difflib

        for rel, node, token in audit["suspect_reads"]:
            if rel != ctx.rel_path:
                continue
            close = difflib.get_close_matches(
                token, sorted(registered), n=2, cutoff=self._TYPO_CUTOFF)
            if not close:
                continue  # foreign vmt_ string, not the metrics namespace
            yield self.finding(
                ctx, node,
                f"`{token}` matches no registered instrument (did you "
                f"mean {' or '.join(close)}?) — a name-string read fails "
                f"silently: the key is absent, the asserted exposition "
                f"line can never exist")


_INSTRUMENT_KINDS = ("counter", "gauge", "histogram")
_METRIC_TOKEN_RE = re.compile(r"vmt_[a-z0-9_]+")


def _canon_metric(token: str, registered) -> Optional[str]:
    """The base instrument a name-string denotes, or None if unknown.
    Handles Prometheus exposition suffixes and the Sampler's derived
    rate keys (``X_total`` -> ``X_per_s``)."""
    if token in registered:
        return token
    for suf in ("_bucket", "_sum", "_count"):
        if token.endswith(suf) and token[: -len(suf)] in registered:
            return token[: -len(suf)]
    if token.endswith("_per_s"):
        base = token[: -len("_per_s")] + "_total"
        if base in registered:
            return base
    if token.endswith("_"):
        # f-string prefix part (f"vmt_foo_{x}"): dynamic suffix — credit
        # every instrument it could expand to, never a typo suspect.
        for name in registered:
            if name.startswith(token):
                return name
    return None


def _instrument_audit(project) -> Dict:
    """Cross-module instrument audit, cached on the ProjectGraph."""
    cached = getattr(project, "_instrument_audit", None)
    if cached is not None:
        return cached
    # name -> [(registration node, rel_path, kind)]
    registered: Dict[str, List[Tuple[ast.AST, str, str]]] = {}
    # Write/use evidence, gathered per direction below.
    chained: Set[str] = set()            # REGISTRY.counter("x").inc()
    bindings: Dict[str, Set[str]] = {}   # metric name -> bound identifiers
    loaded: Set[str] = set()             # identifiers loaded anywhere
    string_reads: List[Tuple[str, ast.AST, str]] = []  # (rel, node, token)

    for mod in project.modules.values():
        tree = mod.ctx.tree
        reg_calls: Dict[int, str] = {}   # id(Call) -> metric name
        reg_args: Set[int] = set()       # id(Constant) of registration names
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _INSTRUMENT_KINDS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("vmt_")):
                name = node.args[0].value
                registered.setdefault(name, []).append(
                    (node, mod.ctx.rel_path, node.func.attr))
                reg_calls[id(node)] = name
                reg_args.add(id(node.args[0]))
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute):
                if id(node.value) in reg_calls:
                    chained.add(reg_calls[id(node.value)])
                if isinstance(node.ctx, ast.Load):
                    loaded.add(node.attr)
            elif isinstance(node, ast.Name) and isinstance(node.ctx,
                                                           ast.Load):
                loaded.add(node.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    loaded.add(alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.Assign) and id(node.value) in reg_calls:
                targets = bindings.setdefault(reg_calls[id(node.value)],
                                              set())
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        targets.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        targets.add(t.attr)
            elif (isinstance(node, ast.AnnAssign) and node.value is not None
                    and id(node.value) in reg_calls
                    and isinstance(node.target, ast.Name)):
                bindings.setdefault(reg_calls[id(node.value)],
                                    set()).add(node.target.id)
            elif (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in reg_args
                    and "vmt_" in node.value):
                for token in _METRIC_TOKEN_RE.findall(node.value):
                    string_reads.append((mod.ctx.rel_path, node, token))

    alive: Set[str] = set(chained)
    for name, idents in bindings.items():
        # A bound handle counts as used when its identifier is loaded
        # anywhere in the project — local increments, `from obs import
        # SHED_COUNTER`, `self._errors.inc()` all qualify. Identifier-
        # level (not scope-aware) on purpose: generous beats false drift.
        if idents & loaded:
            alive.add(name)
    suspects: List[Tuple[str, ast.AST, str]] = []
    seen: Set[Tuple[int, str]] = set()
    for rel, node, token in string_reads:
        canon = _canon_metric(token, registered)
        if canon is not None:
            alive.add(canon)
        elif (id(node), token) not in seen:
            seen.add((id(node), token))
            suspects.append((rel, node, token))
    audit = {"registered": registered, "alive": alive,
             "suspect_reads": suspects}
    project._instrument_audit = audit
    return audit


# --------------------------------------------------------------------- 136
class ExemplarCardinality(Rule):
    """``observe(..., exemplar_trace_id=...)`` alongside an unbounded-
    origin label value. Exemplars live per label series (one slot per
    bucket per labelset, each holding a value + trace id + timestamp) —
    a label fed from request data or an unconstrained parameter mints a
    new series per distinct value, so the exemplar map grows without
    bound exactly where tail-sampling was supposed to bound retention.
    Label values routed through bucketizers/config knobs/literals are
    bounded and clean — the VMT124 origin lattice, applied to the
    metrics→trace link instead of the compile cache.
    """

    id = "VMT136"
    name = "exemplar-cardinality"
    severity = "error"
    description = ("histogram observe() attaching an exemplar while a "
                   "label value is request/caller-derived — an unbounded "
                   "label universe turns the per-series exemplar slots "
                   "into an unbounded map")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        from vilbert_multitask_tpu.analysis.shaperules import (
            _module_functions,
            _own_scope,
            _project_knobs,
        )
        from vilbert_multitask_tpu.analysis.shapes import (
            Scalar,
            call_nodes_in,
            flows_from,
            interpret_function,
        )

        knobs = None
        seen: Set[Tuple[int, str]] = set()
        for fn in _module_functions(ctx):
            targets = {
                id(n) for n in _own_scope(fn)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "observe"
                and any(kw.arg == "exemplar_trace_id"
                        for kw in n.keywords)
            }
            if not targets:
                continue
            if knobs is None:
                knobs = _project_knobs(ctx)
            interp = interpret_function(ctx, fn, knobs)
            for event, fact in interp.iter_facts():
                for call in call_nodes_in(event):
                    if id(call) not in targets:
                        continue
                    for kw in call.keywords:
                        if kw.arg in (None, "exemplar_trace_id"):
                            continue
                        key = (id(call), kw.arg)
                        if key in seen:
                            continue
                        val = interp.eval(kw.value, fact)
                        if not (isinstance(val, Scalar)
                                and val.origin in ("param", "data")):
                            continue
                        seen.add(key)
                        f = self.finding(
                            ctx, call,
                            f"label `{kw.arg}` on an exemplar-carrying "
                            f"observe() is {_EX_ORIGIN_DESC[val.origin]} "
                            f"— every distinct value mints a label "
                            f"series with its own exemplar slot; route "
                            f"it through a bounded vocabulary (task "
                            f"registry, config knob, bucketizer) before "
                            f"labelling")
                        f.flows = flows_from(
                            val.witness,
                            (ctx.rel_path, call.lineno,
                             f"flows into label `{kw.arg}` of an "
                             f"exemplar-carrying observe() — a new "
                             f"value here is a new exemplar series"))
                        yield f


_EX_ORIGIN_DESC = {
    "param": "caller-controlled (an unconstrained parameter)",
    "data": "derived from request data (e.g. a payload field)",
}


from vilbert_multitask_tpu.analysis.locks import (  # noqa: E402
    JitClosureCapture, LockOrderInversion, WaitHoldingForeignLock)
from vilbert_multitask_tpu.analysis.shaperules import (  # noqa: E402
    BucketShapeDrift, DtypePromotionLeak, PartitionRankMismatch,
    UnboundedCompileKey)
from vilbert_multitask_tpu.analysis.txnrules import (  # noqa: E402
    MultiWriteNoTxn, NondeterministicClaim, RmwDeferredTxn, SqlSchemaDrift)
from vilbert_multitask_tpu.analysis.protorules import (  # noqa: E402
    FaultPointCoverage, JobTerminalProtocol, ResourceLeakOnException,
    TerminalFrameDrift)
from vilbert_multitask_tpu.analysis.excrules import (  # noqa: E402
    BreakerBlindException, ErrorFrameDrift, HandlerShadowsTerminal,
    ThreadRunLoopEscape)

RULES = [HostTransferInJit, RecompileTrigger, DonatedBufferReuse,
         BenchTimingHazard, StrayPrint, SqliteThreadSharing,
         SwallowedException, ModuleLevelNumpyMutation, WallClockDuration,
         LockDisciplineRace, PartitionSpecAxisMismatch, LayeringViolation,
         PerRowTransferInLoop, NakedRetryLoop, UnboundedObsBuffer,
         BlockingCallUnderSchedulerLock, ReplicaAffinityLeak,
         DequantOutsideJit, LockOrderInversion, WaitHoldingForeignLock,
         JitClosureCapture, ConfigKnobDrift, InstrumentNameDrift,
         UnboundedCompileKey, DtypePromotionLeak, PartitionRankMismatch,
         BucketShapeDrift, RmwDeferredTxn, MultiWriteNoTxn, SqlSchemaDrift,
         NondeterministicClaim, JobTerminalProtocol,
         ResourceLeakOnException, FaultPointCoverage, TerminalFrameDrift,
         ThreadRunLoopEscape, BreakerBlindException,
         HandlerShadowsTerminal, ErrorFrameDrift, ExemplarCardinality]


def default_rules(severity_overrides: Optional[Dict[str, str]] = None,
                  rule_paths: Optional[Dict[str, Sequence[str]]] = None,
                  ) -> List[Rule]:
    """Instantiate the registry, applying per-repo severity overrides and
    per-rule path exclusions (keys may be rule ids or names)."""
    over = {k.lower(): v for k, v in (severity_overrides or {}).items()}
    gates = {k.lower(): v for k, v in (rule_paths or {}).items()}
    return [cls(severity=over.get(cls.id.lower(), over.get(cls.name.lower())),
                not_under=gates.get(cls.id.lower(),
                                    gates.get(cls.name.lower(), ())))
            for cls in RULES]
