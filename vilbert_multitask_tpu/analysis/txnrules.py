"""VMT128–131: SQL-transaction atomicity rules over the txn tier.

The durable stores (`serve/queue.py`, `serve/db.py`, `obs/fleet.py`) are
the one piece of state shared across OS processes once ROADMAP item 3
goes horizontal, and sqlite only makes cross-process read-modify-write
atomic when the scope takes the write lock *before* the read (``BEGIN
IMMEDIATE``). These rules re-anchor the findings
:class:`analysis.txn.TxnFlow` precomputes project-wide — the same
cached-flow consumption shape as the VMT119/120 lock rules.
"""

from __future__ import annotations

from typing import Iterator

from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.core import Finding, Rule
from vilbert_multitask_tpu.analysis.locks import _Anchor
from vilbert_multitask_tpu.analysis.txn import txn_flow


class RmwDeferredTxn(Rule):
    """SELECT feeding a dependent same-table write without the write lock.

    The live counterexample that motivated the tier: ``nack()`` read
    ``attempts`` and wrote a dependent ``status`` under a deferred
    transaction while ``claim()`` in the same file took BEGIN IMMEDIATE —
    two worker processes sharing the db either lose one update or die on
    the SQLITE_BUSY lock upgrade. The witness chain (read → dataflow →
    write) renders as SARIF codeFlows.
    """

    id = "VMT128"
    name = "rmw-deferred-txn"
    severity = "error"
    description = ("SELECT whose result feeds a later write on the same "
                   "table inside a deferred or absent transaction — a "
                   "cross-process lost update / SQLITE_BUSY upgrade hazard")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = txn_flow(ctx.project)
        for e in flow.rmw:
            if e["path"] != ctx.rel_path:
                continue
            f = self.finding(ctx, _Anchor(e["line"], e["col"]),
                             e["message"])
            f.flows = [list(chain) for chain in e["flows"]]
            yield f


class MultiWriteNoTxn(Rule):
    """Dependent same-table writes split across autocommit statements.

    pysqlite autocommits every DDL statement individually (since 3.6 DDL
    neither opens nor commits a transaction) — so a CREATE + ALTER
    migration run in a plain ``with`` scope is N separate transactions,
    and two processes booting at once race the PRAGMA-guarded ALTERs.
    """

    id = "VMT129"
    name = "multi-write-no-txn"
    severity = "error"
    description = ("dependent writes to the same table split across "
                   "autocommit transactions (schema DDL autocommits "
                   "per-statement) — partial migration on crash or "
                   "concurrent boot")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = txn_flow(ctx.project)
        for e in flow.multi_write:
            if e["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])


class SqlSchemaDrift(Rule):
    """Query columns vs the modeled schema — the SQL twin of VMT122.

    Two directions: a column referenced by a statement that no CREATE
    TABLE or ALTER migration declares (typo → OperationalError at
    runtime, with did-you-mean), and a declared column never read by any
    statement in the project (dead durable state). Like VMT122, the dead
    direction needs whole-project evidence, so ``--changed`` subset scans
    suppress it via ``partial_scan``.
    """

    id = "VMT130"
    name = "sql-schema-drift"
    severity = "warning"
    description = ("SQL column not declared by any modeled CREATE/ALTER "
                   "(typo detector with did-you-mean), or a declared "
                   "column never read anywhere (dead durable state)")

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Set by the --changed driver: a subset scan cannot prove a column
        # is read *nowhere*, so the dead-column direction is suppressed.
        self.partial_scan = False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = txn_flow(ctx.project)
        for e in flow.drift:
            if e["path"] != ctx.rel_path:
                continue
            if e["kind"] == "dead" and self.partial_scan:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])


class NondeterministicClaim(Rule):
    """Competitive SELECT-for-claim without a total ORDER BY.

    A claim-style read (``LIMIT`` feeding a write on the same table)
    without a total ordering lets sqlite pick an arbitrary row per
    process — claim order flaps across the fleet and starves fairness,
    exactly what ROADMAP item 3(a) ("safe and fair") forbids.
    """

    id = "VMT131"
    name = "nondeterministic-claim"
    severity = "warning"
    description = ("SELECT ... LIMIT without a total ORDER BY feeding a "
                   "claim-style write — arbitrary cross-process claim "
                   "order")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = txn_flow(ctx.project)
        for e in flow.claims:
            if e["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])
