"""Generic forward dataflow over the CFGs of ``analysis.cfg``.

The solver is a plain worklist fixed point over a join-semilattice.  An
analysis supplies three things:

* ``initial()`` — the fact at the function entry,
* ``join(a, b)`` — the least upper bound of two facts (both reachable), and
* ``transfer(event, fact)`` — the fact after one block event.

``None`` is the implicit ⊤/unreached element: blocks no reachable predecessor
has produced a fact for are skipped, and ``join`` is never called with
``None``.  Termination needs the usual conditions — monotone transfer, finite
chains — which both domains here satisfy (facts are frozensets over the finite
universe of lock ids / definition sites).

Two concrete domains live here:

* ``LockSetAnalysis`` — *must*-hold lock sets (join = intersection), driven by
  ``WithEnter``/``WithExit`` markers and explicit ``.acquire()``/``.release()``
  calls on expressions a resolver maps to canonical lock ids.
* ``ReachingDefs`` — may-reach definition sites for local names (join =
  union), used by the flow-sensitive jit-closure rule.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, Iterator, List, Optional, Tuple

from .cfg import CFG, Block, Event, WithEnter, WithExit, iter_event_nodes


class ForwardAnalysis:
    """Interface for a forward dataflow analysis (subclass and override)."""

    def initial(self):
        raise NotImplementedError

    def join(self, a, b):
        raise NotImplementedError

    def transfer(self, event: Event, fact):
        raise NotImplementedError


def solve(cfg: CFG, analysis: ForwardAnalysis) -> Dict[int, object]:
    """Run ``analysis`` to a fixed point; return the IN fact per block id.

    Only blocks reachable from the entry get a fact; unreachable block ids are
    absent from the result.
    """
    in_facts: Dict[int, object] = {cfg.entry.id: analysis.initial()}
    preds: Dict[int, List[Block]] = {}
    for blk in cfg.blocks:
        for succ in blk.succs:
            preds.setdefault(succ.id, []).append(blk)

    out_cache: Dict[int, object] = {}

    def block_out(blk: Block) -> object:
        fact = in_facts[blk.id]
        for event in blk.events:
            fact = analysis.transfer(event, fact)
        return fact

    worklist: List[Block] = [cfg.entry]
    on_list = {cfg.entry.id}
    while worklist:
        blk = worklist.pop(0)
        on_list.discard(blk.id)
        out = block_out(blk)
        if blk.id in out_cache and out_cache[blk.id] == out:
            continue
        out_cache[blk.id] = out
        for succ in blk.succs:
            merged = out
            if succ.id in in_facts:
                merged = analysis.join(in_facts[succ.id], out)
            if succ.id not in in_facts or merged != in_facts[succ.id]:
                in_facts[succ.id] = merged
                if succ.id not in on_list:
                    worklist.append(succ)
                    on_list.add(succ.id)
    return in_facts


def iter_event_facts(
    cfg: CFG, analysis: ForwardAnalysis, in_facts: Dict[int, object]
) -> Iterator[Tuple[Event, object]]:
    """Yield ``(event, fact-before-event)`` for every reachable block."""
    for blk in cfg.reachable():
        if blk.id not in in_facts:
            continue
        fact = in_facts[blk.id]
        for event in blk.events:
            yield event, fact
            fact = analysis.transfer(event, fact)


# ---------------------------------------------------------------------------
# Lock-set domain (must-hold: join = intersection)
# ---------------------------------------------------------------------------

LockSet = FrozenSet[str]

_ACQUIRE_METHODS = ("acquire",)
_RELEASE_METHODS = ("release",)


class LockSetAnalysis(ForwardAnalysis):
    """Which canonical lock ids are *definitely* held before each event.

    ``resolver`` maps a lock expression (``ast.expr``) to a canonical lock id
    string, or ``None`` when the expression is not a known lock.  Identity
    resolution (unifying ``self._compile_lock`` across methods, chasing
    module-level locks through imports) lives with the caller — typically
    ``analysis.locks.LockRegistry``.
    """

    def __init__(self, resolver: Callable[[ast.expr], Optional[str]]) -> None:
        self.resolver = resolver

    def initial(self) -> LockSet:
        return frozenset()

    def join(self, a: LockSet, b: LockSet) -> LockSet:
        return a & b

    def transfer(self, event: Event, fact: LockSet) -> LockSet:
        if isinstance(event, WithEnter):
            lock = self.resolver(_strip_acquire_call(event.item.context_expr))
            if lock is not None:
                return fact | {lock}
            return fact
        if isinstance(event, WithExit):
            lock = self.resolver(_strip_acquire_call(event.item.context_expr))
            if lock is not None:
                return fact - {lock}
            return fact
        for node in iter_event_nodes(event):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr in _ACQUIRE_METHODS:
                lock = self.resolver(node.func.value)
                if lock is not None:
                    fact = fact | {lock}
            elif node.func.attr in _RELEASE_METHODS:
                lock = self.resolver(node.func.value)
                if lock is not None:
                    fact = fact - {lock}
        return fact


def _strip_acquire_call(expr: ast.expr) -> ast.expr:
    """``with lock.acquire_timeout(...)``-style wrappers: look at the receiver."""
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Attribute):
            return expr.func.value
        return expr.func
    return expr


# ---------------------------------------------------------------------------
# Reaching definitions (may: join = union)
# ---------------------------------------------------------------------------

# A definition site is (name, line) — line numbers are unique enough within a
# single function and keep the facts hashable and readable.
DefSite = Tuple[str, int]
DefSet = FrozenSet[DefSite]


class ReachingDefs(ForwardAnalysis):
    """Which assignments to ``names`` may reach each event."""

    def __init__(self, names: FrozenSet[str], params_line: int = 0) -> None:
        self.names = names
        self.params_line = params_line

    def initial(self) -> DefSet:
        # Function parameters act as a definition at the entry.
        return frozenset((n, self.params_line) for n in self.names)

    def join(self, a: DefSet, b: DefSet) -> DefSet:
        return a | b

    def transfer(self, event: Event, fact: DefSet) -> DefSet:
        assigned = _assigned_names(event) & self.names
        if not assigned:
            return fact
        line = getattr(event, "lineno", self.params_line)
        fact = frozenset(d for d in fact if d[0] not in assigned)
        return fact | frozenset((n, line) for n in assigned)


def _assigned_names(event: Event) -> FrozenSet[str]:
    if isinstance(event, WithEnter):
        vars_ = event.item.optional_vars
        return _target_names(vars_) if vars_ is not None else frozenset()
    if isinstance(event, WithExit):
        return frozenset()
    names: set = set()
    if isinstance(event, ast.Assign):
        for tgt in event.targets:
            names |= _target_names(tgt)
    elif isinstance(event, (ast.AugAssign, ast.AnnAssign)):
        names |= _target_names(event.target)
    elif isinstance(event, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        names.add(event.name)
    elif isinstance(event, (ast.Name, ast.Tuple, ast.List)):
        # A loop target appended to the loop header by the CFG builder.
        names |= _target_names(event)
    return frozenset(names)


def _target_names(target: ast.expr) -> FrozenSet[str]:
    names: set = set()
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
    return frozenset(names)
