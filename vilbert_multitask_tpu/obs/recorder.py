"""Flight recorder: black-box postmortem capture for the serving plane.

When something trips — a breaker opens, an SLO pages, a fault fires, a
deadline spike, a drain, an unhandled worker exception — the in-process
evidence (recent spans, the time-series window, instrument values) is
exactly what a postmortem needs and exactly what is gone by the time a
human attaches. The recorder freezes it: trigger sites enqueue a cheap
event; a background writer thread assembles a bundle (last-N spans, the
time-series window, a full instrument snapshot, config fingerprint,
recent trace/job ids) and atomically dumps it to a rotated, size-bounded
directory of ``pm_<unix_ms>_<event>.json`` files.

Disabled-mode discipline matches ``resilience/faults.py``: the module
plane is one global read — ``record_event``/``record_spike`` with no
recorder installed cost a ``None`` compare (<5 µs tier-1 guard), so
trigger sites stay unconditional in production code.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from vilbert_multitask_tpu.obs import trace as _trace
from vilbert_multitask_tpu.obs.instruments import (
    Counter, Gauge, Histogram, REGISTRY, percentile)

RECORDER_THREAD_NAME = "flight-recorder"
_EVENT_SAFE = re.compile(r"[^a-z0-9_-]+")

_DROPPED = REGISTRY.counter(
    "vmt_recorder_dropped_total",
    "Flight-recorder triggers dropped (queue full or rate-limited)",
    labelnames=("reason",))
_BUNDLES = REGISTRY.counter(
    "vmt_recorder_bundles_total", "Flight-recorder bundles written",
    labelnames=("event",))


def _instrument_snapshot() -> List[dict]:
    """Every registered instrument's current values, JSON-shaped."""
    out: List[dict] = []
    for inst in REGISTRY.instruments():
        row: dict = {"name": inst.name, "kind": inst.kind}
        if isinstance(inst, (Counter, Gauge)):
            row["values"] = {"|".join(k) or "_": v
                             for k, v in inst.collect().items()}
        elif isinstance(inst, Histogram):
            series = {}
            for key, info in inst.collect().items():
                xs = inst.samples(**dict(zip(inst.labelnames, key)))
                series["|".join(key) or "_"] = {
                    "count": info["count"],
                    "sum": round(info["sum"], 3),
                    "p50": percentile(xs, 0.5),
                    "p95": percentile(xs, 0.95),
                    "p99": percentile(xs, 0.99),
                }
            row["series"] = series
        out.append(row)
    return out


class FlightRecorder:
    """Rotated, size-bounded postmortem bundles on trigger events.

    Trigger sites call :meth:`trigger` (enqueue only — never I/O); the
    single writer thread does the snapshotting and the disk work, so a
    breaker opening under load costs the hot path one queue put.
    ``sources`` maps extra section names to zero-arg callables evaluated
    at dump time (the serve layer wires ``timeseries`` and config here).
    """

    def __init__(self, dir: str, max_bundles: int = 16,
                 max_bytes: int = 1_000_000, spans: int = 256,
                 min_interval_s: float = 30.0,
                 sources: Optional[Dict[str, Callable[[], object]]] = None):
        self.dir = dir
        self.max_bundles = max(1, int(max_bundles))
        self.max_bytes = max(4096, int(max_bytes))
        self.spans_limit = max(0, int(spans))
        self.min_interval_s = float(min_interval_s)
        self.sources = dict(sources or {})
        self._q: "queue.Queue" = queue.Queue(maxsize=64)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._last_fire: Dict[str, float] = {}
        self._spikes: Dict[str, deque] = {}

    # ------------------------------------------------------------ triggers
    def trigger(self, event: str, **detail) -> bool:
        """Enqueue a postmortem dump; returns False when rate-limited or
        the writer is saturated (both counted, never raised)."""
        now = time.perf_counter()
        with self._lock:
            last = self._last_fire.get(event)
            if last is not None and now - last < self.min_interval_s:
                _DROPPED.inc(reason="rate_limited")
                return False
            self._last_fire[event] = now
            self._ensure_thread_locked()
        try:
            self._q.put_nowait((event, detail, time.time()))
        except queue.Full:
            _DROPPED.inc(reason="queue_full")
            return False
        return True

    def spike(self, event: str, threshold: int = 5,
              window_s: float = 10.0, **detail) -> bool:
        """Count occurrences in a sliding window; trigger once the window
        holds ``threshold`` of them (deadline-exceeded spikes: one expiry
        is traffic, a burst is an incident)."""
        now = time.perf_counter()
        with self._lock:
            ring = self._spikes.get(event)
            if ring is None:
                ring = self._spikes[event] = deque(
                    maxlen=max(int(threshold), 64))
            while ring and now - ring[0] > window_s:
                ring.popleft()
            ring.append(now)
            n = len(ring)
            if n < threshold:
                return False
            ring.clear()
        return self.trigger(event, spike_count=n, spike_window_s=window_s,
                            **detail)

    # ----------------------------------------------------------- lifecycle
    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=RECORDER_THREAD_NAME, daemon=True)
            self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        """Drain pending triggers, write them, join the writer."""
        with self._lock:
            t = self._thread
            self._thread = None
        if t is None or not t.is_alive():
            return
        self._q.put(None)  # FIFO sentinel: everything queued before it
        t.join(timeout)    # still gets written

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._write_bundle(*item)
            except Exception:  # noqa: BLE001 — a disk error must not kill
                # the writer loop; the failed dump is counted, the next
                # trigger still gets its bundle.
                _DROPPED.inc(reason="write_error")

    # ---------------------------------------------------------- bundle I/O
    def _bundle(self, event: str, detail: dict, ts: float) -> dict:
        spans = [dataclasses.asdict(s)
                 for s in _trace.default_tracer().spans(self.spans_limit)]
        trace_ids, job_ids = [], []
        for s in spans:
            tid = s.get("trace_id")
            if tid and tid not in trace_ids:
                trace_ids.append(tid)
            jid = (s.get("attrs") or {}).get("job_id")
            if jid and jid not in job_ids:
                job_ids.append(jid)
        bundle = {
            "event": event,
            "detail": detail,
            "time_unix": round(ts, 3),
            "trace_ids": trace_ids[-64:],
            "job_ids": job_ids[-64:],
            "instruments": _instrument_snapshot(),
            "spans": spans,
        }
        for name, fn in self.sources.items():
            try:
                bundle[name] = fn()
            except Exception as e:  # noqa: BLE001 — a broken source loses
                # its own section only, never the bundle.
                bundle[name] = {"error": repr(e)}
        return bundle

    def _write_bundle(self, event: str, detail: dict, ts: float) -> None:
        bundle = self._bundle(event, detail, ts)
        payload = json.dumps(bundle, default=repr)
        # Size-bound by shedding the bulkiest sections, spans first.
        while len(payload) > self.max_bytes and bundle["spans"]:
            bundle["spans"] = bundle["spans"][len(bundle["spans"]) // 2:]
            bundle["spans_truncated"] = True
            payload = json.dumps(bundle, default=repr)
        if len(payload) > self.max_bytes and "timeseries" in bundle:
            bundle["timeseries"] = {"truncated": True}
            payload = json.dumps(bundle, default=repr)
        safe = _EVENT_SAFE.sub("_", event.lower()) or "event"
        name = f"pm_{int(ts * 1000)}_{safe}.json"
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)  # readers never see a half-written bundle
        _BUNDLES.inc(event=event)
        self._rotate()

    def _rotate(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("pm_") and n.endswith(".json"))
        except OSError:
            return
        for stale in names[:-self.max_bundles]:
            try:
                os.remove(os.path.join(self.dir, stale))
            except OSError:
                continue  # racing rotation from a previous process is fine

    def bundles(self) -> List[str]:
        """Paths of current bundles, oldest first."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("pm_") and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]


# ----------------------------------------------------------- module plane
# Same shape as faults._PLAN: one global, trigger sites pay a read + a
# None compare when no recorder is installed.
_RECORDER: Optional[FlightRecorder] = None


def install_recorder(rec: FlightRecorder) -> FlightRecorder:
    global _RECORDER
    _RECORDER = rec
    return rec


def clear_recorder() -> None:
    global _RECORDER
    rec = _RECORDER
    _RECORDER = None
    if rec is not None:
        rec.close()


def active_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def record_event(event: str, **detail) -> bool:
    """Unconditional trigger site. No recorder installed: a None check."""
    rec = _RECORDER
    if rec is None:
        return False
    return rec.trigger(event, **detail)


def record_spike(event: str, threshold: int = 5, window_s: float = 10.0,
                 **detail) -> bool:
    """Unconditional spike-counting trigger site (see
    :meth:`FlightRecorder.spike`)."""
    rec = _RECORDER
    if rec is None:
        return False
    return rec.spike(event, threshold=threshold, window_s=window_s, **detail)
