"""Counters, gauges, and log-bucket histograms — one implementation.

Before this package, percentile math lived in three places with three
semantics: ``serve/metrics.py`` (upward-biased nearest-rank — p50 of two
samples returned the max), ``bench.py`` (``statistics.median`` + manual
ceil nearest-rank p95), and ``scripts/serve_soak.py`` (a third variant).
:func:`percentile` below is now the only one; ``Metrics``, the bench, and
the soak all route through it (linear interpolation — exact median, no
off-by-one bias).

The :class:`Histogram` keeps fixed log-spaced buckets (Prometheus
exposition needs cumulative bucket counts) *and* a bounded reservoir of
raw samples (exact percentiles for JSON snapshots and bench artifacts) —
"replacing/augmenting the reservoir" per the round-6 telemetry design.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple


def percentile(values: Sequence[float], p: float) -> Optional[float]:
    """Linear-interpolated percentile of raw samples, ``p`` in [0, 1].

    THE shared implementation: index space is ``p * (n - 1)`` (not the
    upward-biased ``p * n``), interpolating between the two neighboring
    order statistics. ``percentile(xs, 0.5)`` equals ``statistics.median``.
    Returns None on an empty sample set.
    """
    if not values:
        return None
    xs = sorted(float(v) for v in values)
    if len(xs) == 1:
        return xs[0]
    k = min(max(p, 0.0), 1.0) * (len(xs) - 1)
    lo = int(k)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (k - lo)


def log_buckets(lo: float = 0.1, hi: float = 60_000.0,
                per_decade: int = 4) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds (defaults: 0.1 ms … 60 s in
    quarter-decade steps — latency-shaped). Deterministic, so every
    histogram in the process exposes comparable buckets."""
    out: List[float] = []
    k = math.ceil(round(math.log10(lo) * per_decade, 9))
    while True:
        bound = round(10 ** (k / per_decade), 6)
        out.append(bound)
        if bound >= hi:
            break
        k += 1
    return tuple(out)


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.labelnames)


class Counter(_Instrument):
    """Monotonically increasing value per label set."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def collect(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)


class Gauge(_Instrument):
    """Point-in-time value per label set (queue depth, cache entries)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(self._key(labels))

    def remove(self, **labels) -> bool:
        """Withdraw one label set's series entirely.

        A gauge is point-in-time state, not history: when the thing it
        describes stops existing (a retired replica), its series must
        leave exposition too, or fleet views show ghosts at the last
        value forever. Returns True when a series was actually dropped.
        """
        with self._lock:
            return self._values.pop(self._key(labels), None) is not None

    def collect(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)


class _HistSeries:
    """One label set's state: bucket counts + count/sum + raw reservoir +
    a timestamped window ring for sliding-window aggregation."""

    __slots__ = ("counts", "count", "sum", "reservoir", "window",
                 "exemplars")

    def __init__(self, n_buckets: int, reservoir: int):
        self.counts = [0] * (n_buckets + 1)  # +1: the implicit +Inf bucket
        self.count = 0
        self.sum = 0.0
        self.reservoir: deque = deque(maxlen=reservoir)
        # (t, value) pairs, same bound as the reservoir: the window is a
        # VIEW of recent samples, never an unbounded log.
        self.window: deque = deque(maxlen=reservoir)
        # bucket index -> (value, trace_id, unix_ts): the newest exemplar
        # per bucket — bounded by the bucket count, the OpenMetrics shape.
        self.exemplars: Dict[int, Tuple[float, str, float]] = {}


class Histogram(_Instrument):
    """Fixed log-bucket histogram with an exact-percentile reservoir.

    ``le`` semantics match Prometheus: a sample lands in the first bucket
    whose upper bound is >= the value; exposition cumulates the counts.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None,
                 reservoir: int = 2048):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets)) if buckets else log_buckets()
        self._reservoir = reservoir
        self._series: Dict[Tuple[str, ...], _HistSeries] = {}
        # Monotonic by default; injectable so tests can age samples out of
        # the sliding window without sleeping through it.
        self.clock = time.perf_counter

    def _get_series(self, key: Tuple[str, ...]) -> _HistSeries:
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = _HistSeries(
                len(self.buckets), self._reservoir)
        return series

    def observe(self, value: float, *,
                exemplar_trace_id: Optional[str] = None, **labels) -> None:
        key = self._key(labels)
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        now = self.clock()
        with self._lock:
            series = self._get_series(key)
            series.counts[i] += 1
            series.count += 1
            series.sum += value
            series.reservoir.append(value)
            series.window.append((now, value))
            if exemplar_trace_id:
                # Newest-wins per bucket: an exemplar is a SAMPLE linking
                # the bucket to one concrete trace, not a log. The stamp
                # is wall-clock because OpenMetrics exemplar timestamps
                # are unix epoch (a stamp, not a duration).
                series.exemplars[i] = (
                    value, str(exemplar_trace_id), time.time())

    # ----------------------------------------------------------- inspection
    def samples(self, **labels) -> List[float]:
        """Raw reservoir for one label set (newest ``reservoir`` samples)."""
        with self._lock:
            series = self._series.get(self._key(labels))
            return list(series.reservoir) if series else []

    def all_samples(self) -> List[float]:
        """Reservoirs merged across every label set."""
        with self._lock:
            return [v for s in self._series.values() for v in s.reservoir]

    def percentile(self, p: float, **labels) -> Optional[float]:
        """Exact percentile over the reservoir via the one shared
        implementation (merged across label sets when none are given on a
        labeled histogram)."""
        if not labels and self.labelnames:
            return percentile(self.all_samples(), p)
        return percentile(self.samples(**labels), p)

    def count(self, **labels) -> int:
        with self._lock:
            series = self._series.get(self._key(labels))
            return series.count if series else 0

    # ------------------------------------------------------ sliding window
    def _window_values(self, window_s: float,
                       labels: Dict[str, object]) -> List[float]:
        """Samples observed in the last ``window_s`` seconds. Merged
        across label sets when none are given on a labeled histogram
        (matching :meth:`percentile`). Filtering, never pruning: the same
        ring answers queries for DIFFERENT windows (the burn-rate fast and
        slow panes), so a short-window read must not evict samples a
        longer window still needs — the deque's maxlen is the only
        eviction."""
        cutoff = self.clock() - window_s
        with self._lock:
            if not labels and self.labelnames:
                rings = list(self._series.values())
            else:
                series = self._series.get(self._key(labels))
                rings = [series] if series else []
            return [v for s in rings for t, v in s.window if t >= cutoff]

    def window_samples(self, window_s: float, **labels) -> List[float]:
        """Raw samples inside the sliding window (bounded by the
        reservoir size — a window longer than the ring retains covers at
        most the newest ``reservoir`` samples)."""
        return self._window_values(window_s, labels)

    def window_count(self, window_s: float, **labels) -> int:
        return len(self._window_values(window_s, labels))

    def window_sum(self, window_s: float, **labels) -> float:
        return sum(self._window_values(window_s, labels))

    def window_percentile(self, p: float, window_s: float,
                          **labels) -> Optional[float]:
        """Exact percentile over the sliding window only — the live-p95
        answer the lifetime-cumulative reservoir cannot give."""
        return percentile(self._window_values(window_s, labels), p)

    def series_counts(self) -> Dict[Tuple[str, ...], int]:
        """Observation count per label set (per-task request counts)."""
        with self._lock:
            return {k: s.count for k, s in self._series.items()}

    def collect(self) -> Dict[Tuple[str, ...], dict]:
        """Per-label-set {"buckets": [(le, cumulative)...], "count", "sum"}
        — cumulativity is applied here, the one place exposition reads."""
        out: Dict[Tuple[str, ...], dict] = {}
        with self._lock:
            for key, series in self._series.items():
                cumulative, acc = [], 0
                for bound, n in zip(self.buckets, series.counts):
                    acc += n
                    cumulative.append((bound, acc))
                cumulative.append((math.inf, series.count))
                out[key] = {"buckets": cumulative, "count": series.count,
                            "sum": series.sum}
        return out

    def collect_exemplars(self) -> Dict[Tuple[str, ...],
                                        Dict[int, Tuple[float, str, float]]]:
        """Per-label-set {bucket index: (value, trace_id, unix_ts)} — the
        OpenMetrics renderer attaches these to the matching bucket lines."""
        with self._lock:
            return {key: dict(series.exemplars)
                    for key, series in self._series.items()
                    if series.exemplars}

    def slowest_exemplars(self, n: int = 3) -> List[Tuple[float, str]]:
        """The ``n`` largest exemplar-bearing observations across every
        label set, ``(value, trace_id)`` descending — the SLO page's
        "top offending traces" link to stored autopsies."""
        with self._lock:
            pairs = [(v, tid) for s in self._series.values()
                     for v, tid, _ts in s.exemplars.values()]
        return sorted(pairs, key=lambda p: p[0], reverse=True)[:max(n, 0)]


class Registry:
    """Name-keyed get-or-create instrument store (one per process is the
    normal mode — :data:`REGISTRY`); re-registration with a different
    type or label set is a programming error and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        self._default_labels: Dict[str, str] = {}

    # -------------------------------------------------------- default labels
    def set_default_labels(self, **labels: str) -> None:
        """Label pairs stamped onto EVERY sample at exposition time
        (process identity: ``instance``, ``role``). Applied by the
        renderer, not at observe time — instruments keep their declared
        label sets, so ``_key`` validation and cross-process merge code
        see unchanged schemas. Call with no kwargs to clear."""
        with self._lock:
            self._default_labels = {k: str(v) for k, v in labels.items()}

    def default_labels(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._default_labels)

    def _get(self, cls, name: str, help: str,
             labelnames: Sequence[str], **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(
                    name, help, labelnames, **kwargs)
            elif type(inst) is not cls or inst.labelnames != tuple(labelnames):
                raise ValueError(
                    f"instrument {name!r} already registered as "
                    f"{inst.kind} with labels {inst.labelnames}")
            return inst

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(Histogram, name, help, labelnames, buckets=buckets)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments.values())


REGISTRY = Registry()
