"""Thread-liveness watchdog: the runtime twin of the static exc tier.

``analysis/exc.py`` proves which exception classes can escape each
thread entry point; this module is the fix its scan demands — a shared
crash guard every project-spawned loop runs under, plus a process-wide
registry health checks and tests can interrogate:

* :func:`crash_guard` — a context manager wrapped around a thread's
  loop body.  On entry it registers the thread (``vmt_thread_alive
  {name}`` = 1); on clean exit it retires it; on an escaping
  ``Exception`` it records a ``thread_died`` flight-recorder event
  (which trips the recorder's bundle capture), drops the gauge, files
  the death in the registry, and *swallows* the exception — the thread
  still dies, but loudly.  ``SystemExit``/``KeyboardInterrupt`` pass
  through: a shutdown is not a death.
* :class:`ThreadWatchdog` — the process-global registry behind the
  guard.  ``/healthz`` turns unready while :meth:`dead_threads` is
  non-empty; the sampler's probe publishes the alive gauges each tick
  and reconciles silent deaths (a thread that stopped scheduling
  without ever raising).

Process-global on purpose: the soak's chaos worker runs in its own
ServeWorker but its intake threads' deaths must be visible in the
app's ``/healthz`` — one registry per process, keyed by thread name,
with re-registration self-healing (a restarted loop under the same
name clears the prior death).

Stdlib-only except for sibling obs modules.
"""

from __future__ import annotations

import contextlib
import threading
import traceback
from typing import Dict, Iterator, List, Optional

from vilbert_multitask_tpu.obs.instruments import REGISTRY
from vilbert_multitask_tpu.obs.recorder import record_event

THREAD_ALIVE_GAUGE = REGISTRY.gauge(
    "vmt_thread_alive",
    "1 while a registered project thread is running its guarded loop, "
    "0 once it exited (cleanly or by dying).",
    labelnames=("name",),
)


class ThreadWatchdog:
    """Process-wide registry of guarded threads and their deaths."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> the thread object currently running under the guard.
        self._alive: Dict[str, threading.Thread] = {}
        # name -> short reason string for threads that died by exception.
        self._died: Dict[str, str] = {}
        # Every name ever guarded in this process — the conftest guard
        # checks spawned daemon threads against this inventory.
        self._known: set = set()

    # ------------------------------------------------------------ guard API
    def adopt(self, name: str, thread: threading.Thread) -> None:
        with self._lock:
            self._alive[name] = thread
            self._known.add(name)
            # Re-registration self-heals: a restarted loop under the
            # same name supersedes the prior death record.
            self._died.pop(name, None)
        THREAD_ALIVE_GAUGE.set(1, name=name)

    def retire(self, name: str) -> None:
        with self._lock:
            self._alive.pop(name, None)
        THREAD_ALIVE_GAUGE.set(0, name=name)

    def record_death(self, name: str, error: BaseException) -> None:
        reason = f"{type(error).__name__}: {error}"
        with self._lock:
            self._alive.pop(name, None)
            self._died[name] = reason
        THREAD_ALIVE_GAUGE.set(0, name=name)

    # ----------------------------------------------------------- inspection
    def dead_threads(self) -> Dict[str, str]:
        """name -> reason for every guarded thread that died (by
        exception, or silently — reconciled via ``is_alive``)."""
        with self._lock:
            out = dict(self._died)
            for name, thread in list(self._alive.items()):
                if not thread.is_alive():
                    out.setdefault(name, "thread no longer alive")
        return out

    def alive_threads(self) -> List[str]:
        with self._lock:
            return sorted(n for n, t in self._alive.items()
                          if t.is_alive())

    def is_known_thread(self, name: str) -> bool:
        with self._lock:
            return name in self._known

    def probe(self) -> Dict[str, float]:
        """Sampler-tick reconciliation: re-publish the alive gauge for
        every registered thread (catching silent deaths) and return
        ``thread_alive_<name>`` series for the timeseries store."""
        out: Dict[str, float] = {}
        with self._lock:
            alive = dict(self._alive)
            died = set(self._died)
        for name, thread in alive.items():
            up = 1.0 if thread.is_alive() else 0.0
            THREAD_ALIVE_GAUGE.set(up, name=name)
            out[f"thread_alive_{name}"] = up
        for name in died:
            THREAD_ALIVE_GAUGE.set(0, name=name)
            out[f"thread_alive_{name}"] = 0.0
        return out

    def reset(self) -> None:
        """Forget everything — test isolation only."""
        with self._lock:
            self._alive.clear()
            self._died.clear()
            self._known.clear()


_WATCHDOG = ThreadWatchdog()


def watchdog() -> ThreadWatchdog:
    return _WATCHDOG


@contextlib.contextmanager
def crash_guard(name: Optional[str] = None) -> Iterator[None]:
    """Run a thread's loop body loudly: an escaping ``Exception``
    records a ``thread_died`` event (flight-recorder bundle), drops
    ``vmt_thread_alive{name}``, and files the death so ``/healthz``
    turns unready — then swallows, because the thread is dying either
    way and a second traceback to stderr helps no one.  Exit exceptions
    (``SystemExit``, ``KeyboardInterrupt``) propagate."""
    thread = threading.current_thread()
    label = name or thread.name
    _WATCHDOG.adopt(label, thread)
    try:
        yield
    except Exception as e:  # noqa: BLE001 — the guard IS the handler
        _WATCHDOG.record_death(label, e)
        record_event(
            "thread_died",
            thread=label,
            error=str(e),
            error_type=type(e).__name__,
            traceback=traceback.format_exc(limit=16),
        )
    else:
        _WATCHDOG.retire(label)
