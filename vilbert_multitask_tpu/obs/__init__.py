"""Process-wide observability: span tracing, instruments, exporters.

Three pillars (see ARCHITECTURE.md "Observability"):

- ``obs.span("engine.forward", task_id=...)`` — monotonic-clocked spans
  with thread-local parenting and cross-queue trace-id resumption
  (:mod:`vilbert_multitask_tpu.obs.trace`);
- ``obs.REGISTRY`` — counters / gauges / log-bucket histograms, plus the
  one shared :func:`percentile` used by serve, bench, and the soak
  (:mod:`vilbert_multitask_tpu.obs.instruments`);
- Prometheus text exposition, Chrome-trace JSON, and ``jax.profiler``
  toggles (:mod:`vilbert_multitask_tpu.obs.export`).

Importing the package wires the default tracer's observer to feed every
completed span into the ``vmt_span_ms{name,task}`` histogram, which is
what ``GET /metrics?format=prometheus`` serves as per-task stage
latencies.
"""

from __future__ import annotations

from vilbert_multitask_tpu.obs.trace import (
    Span,
    Tracer,
    current_trace_id,
    default_tracer,
    new_trace_id,
    span,
    trace_scope,
)
from vilbert_multitask_tpu.obs.instruments import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    log_buckets,
    percentile,
)
from vilbert_multitask_tpu.obs.export import (
    OPENMETRICS_CONTENT_TYPE,
    PROMETHEUS_CONTENT_TYPE,
    chrome_trace,
    dump_trace,
    render_openmetrics,
    render_prometheus,
    start_profile,
    stop_profile,
)
from vilbert_multitask_tpu.obs.attrib import (
    STAGES as COST_STAGES,
    CostAttributor,
    JobCost,
    get_attributor,
    job_batch,
    job_begin,
    job_charge,
    job_finish,
    set_attributor,
)
from vilbert_multitask_tpu.obs.tracestore import TraceStore
from vilbert_multitask_tpu.obs.timeseries import (
    SAMPLER_THREAD_NAME,
    Sampler,
    TimeSeriesStore,
)
from vilbert_multitask_tpu.obs.recorder import (
    RECORDER_THREAD_NAME,
    FlightRecorder,
    active_recorder,
    clear_recorder,
    install_recorder,
    record_event,
    record_spike,
)
from vilbert_multitask_tpu.obs.watchdog import (
    THREAD_ALIVE_GAUGE,
    ThreadWatchdog,
    crash_guard,
    watchdog,
)
from vilbert_multitask_tpu.obs.slo import (
    STATE_OK,
    STATE_PAGE,
    STATE_WARN,
    Slo,
    SloEvaluator,
    availability_slo,
    latency_slo,
    slack_floor_slo,
)
from vilbert_multitask_tpu.obs.identity import (
    WorkerIdentity,
    mint_identity,
    process_identity,
    reset_process_identity,
)
from vilbert_multitask_tpu.obs.fleet import (
    FleetSpine,
    default_spine_path,
)
from vilbert_multitask_tpu.obs.ledger import (
    append_entry as ledger_append,
    check as ledger_check,
    default_ledger_path,
    read_entries as ledger_entries,
)

__all__ = [
    "Span", "Tracer", "current_trace_id", "default_tracer", "new_trace_id",
    "span", "trace_scope",
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "log_buckets", "percentile",
    "OPENMETRICS_CONTENT_TYPE", "PROMETHEUS_CONTENT_TYPE", "chrome_trace",
    "dump_trace", "render_openmetrics", "render_prometheus",
    "start_profile", "stop_profile",
    "COST_STAGES", "CostAttributor", "JobCost", "TraceStore",
    "get_attributor", "job_batch", "job_begin", "job_charge", "job_finish",
    "set_attributor",
    "SHED_COUNTER", "RETRY_COUNTER", "BREAKER_GAUGE", "DEADLINE_SLACK",
    "BATCH_FILL", "SCHED_WAIT", "QUEUE_WAIT", "BATCHES_DISPATCHED",
    "REPLICA_STATE", "FAILOVER_COUNTER", "POISON_COUNTER",
    "RESULT_CACHE_HITS", "RESULT_CACHE_MISSES",
    "RESULT_CACHE_INVALIDATIONS", "COALESCED_SUBMITS", "TENANT_DEFICIT",
    "SAMPLER_THREAD_NAME", "Sampler", "TimeSeriesStore",
    "RECORDER_THREAD_NAME", "FlightRecorder", "active_recorder",
    "clear_recorder", "install_recorder", "record_event", "record_spike",
    "THREAD_ALIVE_GAUGE", "ThreadWatchdog", "crash_guard", "watchdog",
    "STATE_OK", "STATE_PAGE", "STATE_WARN", "Slo", "SloEvaluator",
    "availability_slo", "latency_slo", "slack_floor_slo",
    "WorkerIdentity", "mint_identity", "process_identity",
    "reset_process_identity",
    "FleetSpine", "default_spine_path",
    "ledger_append", "ledger_check", "ledger_entries",
    "default_ledger_path",
]

SPAN_HISTOGRAM = REGISTRY.histogram(
    "vmt_span_ms",
    "Span durations by span name and task (ms).",
    labelnames=("name", "task"),
)

# Resilience instruments (resilience/ policy plane). Defined here so the
# policy module stays import-light and every exporter sees them.
SHED_COUNTER = REGISTRY.counter(
    "vmt_shed_total",
    "Requests/jobs shed before doing work, by reason "
    "(queue_depth, queue_age, deadline).",
    labelnames=("reason",),
)
RETRY_COUNTER = REGISTRY.counter(
    "vmt_retries_total",
    "Retry attempts actually slept for, by call site.",
    labelnames=("site",),
)
BREAKER_GAUGE = REGISTRY.gauge(
    "vmt_breaker_state",
    "Circuit-breaker state: 0 closed, 1 half-open, 2 open.",
    labelnames=("breaker",),
)
DEADLINE_SLACK = REGISTRY.histogram(
    "vmt_deadline_slack_ms",
    "Remaining deadline budget when the worker picked the job up (ms).",
    labelnames=("task",),
)

# Continuous-batching scheduler instruments (serve/scheduler.py).
BATCH_FILL = REGISTRY.histogram(
    "vmt_batch_fill",
    "Dispatched-chunk occupancy as a fraction of its row bucket (1.0 = "
    "the bucket was full; lower = padded rows burned).",
    labelnames=("bucket",),
    buckets=tuple(i / 16 for i in range(1, 17)),
)
SCHED_WAIT = REGISTRY.histogram(
    "vmt_sched_wait_ms",
    "Time a ready (claimed + prepped) job waited in the scheduler's "
    "ready-queue before its batch fired (ms).",
)
QUEUE_WAIT = REGISTRY.histogram(
    "vmt_queue_wait_ms",
    "Publish-to-claim latency (ms): POST / stamp to worker claim, the "
    "queueing delay Metrics.record's intake-anchored e2e cannot see. "
    "The tenant label is the deficit scheduler's user-facing effect: a "
    "tenant throttled below its weighted share queues longer, visibly.",
    labelnames=("task", "tenant"),
)
BATCHES_DISPATCHED = REGISTRY.counter(
    "vmt_batches_dispatched_total",
    "Device chunks dispatched by the continuous-batching scheduler.",
)

# Replica-pool instruments (serve/pool.py).
REPLICA_STATE = REGISTRY.gauge(
    "vmt_replica_state",
    "Replica health state: 0 booting, 1 warming, 2 ready, 3 degraded, "
    "4 draining, 5 dead.",
    labelnames=("replica",),
)
FAILOVER_COUNTER = REGISTRY.counter(
    "vmt_failovers_total",
    "In-flight jobs released back to the queue because their replica "
    "died or tripped its breaker mid-dispatch.",
    labelnames=("replica",),
)
POISON_COUNTER = REGISTRY.counter(
    "vmt_poison_jobs_total",
    "Jobs dead-lettered by the queue after exhausting queue_max_deliveries "
    "total deliveries (poison-job quarantine).",
)

# Duplicate-traffic tier instruments (serve/resultcache.py + scheduler).
RESULT_CACHE_HITS = REGISTRY.counter(
    "vmt_result_cache_hits_total",
    "Submits answered from the durable result cache — no queue publish, "
    "no TPU forward.",
)
RESULT_CACHE_MISSES = REGISTRY.counter(
    "vmt_result_cache_misses_total",
    "Submits that missed the result cache and published a real job "
    "(the submit became the singleflight leader).",
)
RESULT_CACHE_INVALIDATIONS = REGISTRY.counter(
    "vmt_result_cache_invalidations_total",
    "Cache rows dropped because a rolling swap changed the config "
    "fingerprint / model generation.",
)
COALESCED_SUBMITS = REGISTRY.counter(
    "vmt_coalesced_submits_total",
    "Submits attached as followers to an identical in-flight job "
    "(singleflight): they pay one shared forward instead of N.",
)
TENANT_DEFICIT = REGISTRY.gauge(
    "vmt_tenant_deficit",
    "Weighted-deficit scheduler credit per tenant (rows); persistently "
    "negative means the tenant is consuming above its weighted share.",
    labelnames=("tenant",),
)


def _observe_span(s: Span) -> None:
    SPAN_HISTOGRAM.observe(
        s.dur_s * 1e3, name=s.name, task=str(s.attrs.get("task_id", "")))


default_tracer().set_observer(_observe_span)
