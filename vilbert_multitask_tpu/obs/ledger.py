"""Perf ledger: the append-only trajectory behind ``PERF_LEDGER.jsonl``.

Bench and soak results used to land in ad-hoc ``BENCH_*.json`` /
``SERVE_SOAK*.json`` artifacts — rich individually, invisible as a
sequence (the ROADMAP's BENCH trajectory was literally ``[]``). The
ledger is the machine-readable sequence: every bench/soak/smoke run
appends ONE json line of headline numbers (p50/p95, qps, knee_rows,
boot_s ...) stamped with wall time, git rev, and the serving
``config_fingerprint()``, and :func:`check` turns the trailing window
into a regression verdict with noise bounds.

Direction is inferred from key names (the repo's metric-naming
convention is already consistent): ``*_ms``/``*_s`` are latencies
(lower is better), ``*qps``/``*_per_s``/``*_rows``/``speedup``/``value``
are throughputs (higher is better); anything else is recorded but never
gated on. Entries that fail to parse are skipped, never fatal — a
half-written line from a crashed bench must not wedge CI.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Dict, List, Optional

LEDGER_BASENAME = "PERF_LEDGER.jsonl"

# Bookkeeping keys never compared as metrics.
_META_KEYS = {"ts_unix", "metric", "git_rev", "config_fingerprint",
              "run_id", "artifact", "verdict", "partial"}


def default_ledger_path(root: Optional[str] = None) -> str:
    """``PERF_LEDGER.jsonl`` at the repo root (or ``$VMT_PERF_LEDGER``)."""
    env = os.environ.get("VMT_PERF_LEDGER")
    if env:
        return env
    if root is None:
        # obs/ledger.py -> obs -> package -> repo root
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, LEDGER_BASENAME)


def git_rev(cwd: Optional[str] = None) -> Optional[str]:
    """Short HEAD rev, best-effort (None outside a git checkout)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd or os.path.dirname(default_ledger_path()),
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:  # noqa: BLE001 — ledger stamping must never raise
        return None


def append_entry(metric: str, values: Dict[str, Any], *,
                 path: Optional[str] = None,
                 config_fingerprint: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Append one run's headline numbers; returns the written entry.

    Best-effort by design: a bench must publish its artifact even when
    the ledger file is unwritable, so IO errors are swallowed (the entry
    is still returned for the caller's own report).
    """
    entry: Dict[str, Any] = {
        "ts_unix": round(time.time(), 3),
        "metric": metric,
        "git_rev": git_rev(),
        "config_fingerprint": config_fingerprint,
    }
    entry.update(values)
    if extra:
        entry.update(extra)
    try:
        p = path or default_ledger_path()
        if os.path.dirname(p):
            os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
    except OSError:
        pass
    return entry


def read_entries(path: Optional[str] = None,
                 metric: Optional[str] = None) -> List[Dict[str, Any]]:
    """All parseable entries, oldest first (filtered by ``metric``)."""
    p = path or default_ledger_path()
    out: List[Dict[str, Any]] = []
    try:
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    continue
                if isinstance(entry, dict) and (
                        metric is None or entry.get("metric") == metric):
                    out.append(entry)
    except OSError:
        return []
    return out


def key_direction(key: str) -> Optional[str]:
    """'lower' / 'higher' is-better, or None for ungated keys."""
    if key in _META_KEYS or not isinstance(key, str):
        return None
    # Throughputs first: "_per_s" also ends with "_s", and a rate that
    # went UP must never gate as a latency regression.
    if (key.endswith(("qps", "_per_s", "_rows", "speedup"))
            or key == "value" or key == "knee_rows"):
        return "higher"
    if key.endswith(("_ms", "_s")) or "latency" in key:
        return "lower"
    return None


def _noise_floor(key: str) -> float:
    """Minimum ABSOLUTE delta that can count as a regression.

    Relative tolerance alone is meaningless near zero: a dryrun app's
    boot_s jittering 31 ms -> 40 ms is +29% "worse" and pure scheduler
    noise. Time-unit keys get a floor below which no delta gates;
    rates/counts stay relative-only (their magnitudes are O(10+) here).
    """
    if key.endswith("_ms") or "latency" in key:
        return 2.0
    if key.endswith("_s"):
        return 0.25
    return 0.0


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    mid = len(xs) // 2
    if len(xs) % 2:
        return xs[mid]
    return (xs[mid - 1] + xs[mid]) / 2.0


def check(path: Optional[str] = None, *, metric: Optional[str] = None,
          window: int = 5, tolerance: float = 0.20,
          min_baseline: int = 2) -> Dict[str, Any]:
    """Compare the newest run of each metric against its trailing window.

    Baseline per key = median of up to ``window`` prior runs; a key
    regresses when it is worse than baseline by more than ``tolerance``
    (relative — the noise bound; bench-to-bench jitter on shared CPU
    boxes routinely hits 10-15%) AND by more than the key's absolute
    noise floor (:func:`_noise_floor` — a 9 ms boot_s wobble is not a
    29% regression). Verdicts: ``pass`` / ``regress`` /
    ``empty`` (no entries) / ``no-baseline`` (fewer than
    ``min_baseline`` prior runs for every gated key).
    """
    entries = read_entries(path, metric=None)
    if metric is not None:
        entries = [e for e in entries if e.get("metric") == metric]
    if not entries:
        return {"verdict": "empty", "checked": [], "regressions": [],
                "window": window, "tolerance": tolerance}
    by_metric: Dict[str, List[Dict[str, Any]]] = {}
    for e in entries:
        by_metric.setdefault(str(e.get("metric")), []).append(e)
    checked: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    any_baseline = False
    for m, runs in sorted(by_metric.items()):
        newest, prior = runs[-1], runs[:-1][-window:]
        for key, value in sorted(newest.items()):
            direction = key_direction(key)
            if direction is None or not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                continue
            history = [r[key] for r in prior
                       if isinstance(r.get(key), (int, float))
                       and not isinstance(r.get(key), bool)]
            if len(history) < min_baseline:
                continue
            any_baseline = True
            baseline = _median([float(v) for v in history])
            if direction == "lower":
                worse = value > baseline * (1.0 + tolerance)
                delta = (value - baseline) / baseline if baseline else 0.0
            else:
                worse = value < baseline * (1.0 - tolerance)
                delta = (baseline - value) / baseline if baseline else 0.0
            if abs(float(value) - baseline) <= _noise_floor(key):
                worse = False
            record = {"metric": m, "key": key, "value": value,
                      "baseline": round(baseline, 6),
                      "direction": direction,
                      "delta_frac": round(delta, 4),
                      "n_baseline": len(history),
                      "regressed": worse}
            checked.append(record)
            if worse:
                regressions.append(record)
    if not any_baseline:
        return {"verdict": "no-baseline", "checked": [], "regressions": [],
                "window": window, "tolerance": tolerance,
                "metrics": sorted(by_metric)}
    return {"verdict": "regress" if regressions else "pass",
            "checked": checked, "regressions": regressions,
            "window": window, "tolerance": tolerance,
            "metrics": sorted(by_metric)}
