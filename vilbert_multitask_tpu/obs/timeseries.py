"""Bounded in-process time-series: the "last 60 seconds" the registry
cannot answer.

Counters and lifetime reservoirs say what happened since boot; a soak or
an incident needs *trajectories* — queue depth over the last minute,
sheds/sec around a breaker trip. :class:`TimeSeriesStore` keeps a bounded
ring of ``(unix_ts, value)`` points per named series, and
:class:`Sampler` is the background thread that feeds it from a single
probe callable at a configurable cadence. Keys ending ``_total`` are
counters: the sampler additionally derives a ``*_per_s`` rate series from
consecutive samples (monotonic-clock deltas), which is how sheds/sec and
windowed qps fall out of plain counter probes.

Everything here is bounded by construction (``points`` per ring) — the
store is resident in a serving process for days and snapshotted wholesale
into flight-recorder bundles.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from vilbert_multitask_tpu.obs.instruments import REGISTRY

SAMPLER_THREAD_NAME = "obs-sampler"

_SAMPLER_ERRORS = REGISTRY.counter(
    "vmt_sampler_errors_total",
    "Probe failures swallowed by the background sampler")


class TimeSeriesStore:
    """Name-keyed bounded rings of ``(unix_ts, value)`` points.

    Unix stamps (not perf_counter) so a dumped window reads as real
    times in a postmortem; no duration math is ever done on them here —
    rates use the sampler's monotonic deltas.
    """

    def __init__(self, points: int = 512):
        self._lock = threading.Lock()
        self._points = max(2, int(points))
        self._series: Dict[str, deque] = {}

    def record(self, name: str, value: float,
               ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                ring = self._series[name] = deque(maxlen=self._points)
            ring.append((ts, float(value)))

    def record_many(self, values: Dict[str, float],
                    ts: Optional[float] = None) -> None:
        """One timestamp, one lock hold, many series — a sampler tick."""
        ts = time.time() if ts is None else ts
        with self._lock:
            for name, value in values.items():
                ring = self._series.get(name)
                if ring is None:
                    ring = self._series[name] = deque(maxlen=self._points)
                ring.append((ts, float(value)))

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, name: str,
               window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        # Window filtering compares stored wall stamps against now; wall
        # time is the point (postmortem-readable axes), and a clock step
        # only widens/narrows the view, never corrupts a measurement.
        cutoff = (time.time() - window_s  # vmtlint: disable=VMT109
                  if window_s is not None else None)
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                return []
            if cutoff is None:
                return list(ring)
            return [(t, v) for t, v in ring if t >= cutoff]

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            ring = self._series.get(name)
            return ring[-1][1] if ring else None

    def snapshot(self, window_s: Optional[float] = None
                 ) -> Dict[str, List[Tuple[float, float]]]:
        """Every series' recent points — the flight-recorder payload."""
        return {name: self.points(name, window_s) for name in self.names()}


class Sampler:
    """Daemon thread snapshotting one probe callable into a store.

    ``sample_fn() -> Dict[str, float]`` is built by the serving layer
    (it knows the queue/worker/engine wiring); the sampler owns only the
    cadence, the rate derivation for ``*_total`` keys, and the thread
    lifecycle. ``tick()`` is public so tests and the soak can sample
    synchronously without a thread.
    """

    def __init__(self, store: TimeSeriesStore,
                 sample_fn: Callable[[], Dict[str, float]],
                 cadence_s: float = 1.0):
        self.store = store
        self._sample_fn = sample_fn
        self.cadence_s = max(0.01, float(cadence_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Previous (perf_counter, value) per counter key, for rates.
        self._prev: Dict[str, Tuple[float, float]] = {}

    def tick(self) -> Dict[str, float]:
        """One sample pass: probe, derive rates, record. Returns what was
        recorded (probe keys + derived ``*_per_s`` keys)."""
        now_mono = time.perf_counter()
        values = dict(self._sample_fn())
        out = dict(values)
        for key, value in values.items():
            if not key.endswith("_total"):
                continue
            prev = self._prev.get(key)
            self._prev[key] = (now_mono, value)
            if prev is None:
                continue
            dt = now_mono - prev[0]
            if dt <= 0:
                continue
            out[key[:-len("_total")] + "_per_s"] = max(
                0.0, (value - prev[1]) / dt)
        self.store.record_many(out)
        return out

    def _run(self) -> None:
        while not self._stop.wait(self.cadence_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a flaky probe must not
                # kill the sampler thread mid-soak; the failure is counted
                # where /metrics can see it.
                _SAMPLER_ERRORS.inc()

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=SAMPLER_THREAD_NAME, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
