"""Durable tail-sampled trace persistence on the fleet-spine sqlite.

The tracer ring (obs/trace.py) holds the last ~4096 spans in memory —
by the time an operator chases a paging SLO burn, the offending trace
has usually been evicted. This store keeps the tail *durably*: a new
``traces`` table in the shared fleet db (WAL, retention-trimmed), one
row per kept trace carrying its spans and the :class:`JobCost` record,
flushed by the existing sampler tick.

Keep policy (verdict-based, the Dapper tail-sampling shape):

=========== =========================================================
verdict     every non-``ok`` terminal — dead_letter, deadline, error,
            requeued, failover (breaker-touched) — kept 100%
slow        completion-time top-K slowest ``ok`` jobs per task
pinned      SLO page offenders force-kept by trace id
sampled     p-sampled ``ok`` normals (``tracestore_sample_rate``)
=========== =========================================================

Reads NEVER filter by peer liveness: a SIGKILL'd worker's heartbeat
goes stale and its metrics leave the fleet merges, but its stored
traces — like its ``fleet_spans`` rows — are exactly the autopsies the
store exists for, so ``list()``/``get()`` see every ident on disk.
"""

from __future__ import annotations

import json
import os
import random
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from vilbert_multitask_tpu.obs.attrib import JobCost

_SCHEMA = """
CREATE TABLE IF NOT EXISTS traces (
    trace_id TEXT PRIMARY KEY,
    ident TEXT NOT NULL,
    task TEXT NOT NULL DEFAULT '',
    tenant TEXT NOT NULL DEFAULT 'anon',
    verdict TEXT NOT NULL DEFAULT '',
    keep_reason TEXT NOT NULL DEFAULT '',
    dur_ms REAL NOT NULL DEFAULT 0,
    stored_unix REAL NOT NULL,
    spans TEXT NOT NULL DEFAULT '[]',
    cost TEXT NOT NULL DEFAULT '{}'
);
CREATE INDEX IF NOT EXISTS traces_verdict ON traces (verdict, task);
CREATE INDEX IF NOT EXISTS traces_stored ON traces (stored_unix);
"""


def _span_dict(span) -> Dict[str, Any]:
    return {"name": span.name, "trace_id": span.trace_id,
            "span_id": span.span_id, "parent_id": span.parent_id,
            "start_s": span.start_s, "dur_s": span.dur_s,
            "thread_name": span.thread_name, "attrs": dict(span.attrs)}


class TraceStore:
    """One process's handle on the shared ``traces`` table.

    Writer side buffers kept traces in memory (``offer``/``pin``) and
    persists them on ``flush()`` — the sampler-tick ride-along, same
    failure domain as the fleet spine flush. Reader side serves
    ``/debug/traces`` lists and the ``/debug/trace``/``/debug/autopsy``
    store fallback, across every ident on disk (stale peers included —
    see the module docstring).
    """

    def __init__(self, path: str, ident: str, *, keep_top_k: int = 8,
                 sample_rate: float = 0.05, retention_s: float = 3600.0,
                 rng: Optional[random.Random] = None):
        self.path = path
        self.ident = ident
        self.keep_top_k = int(keep_top_k)
        self.sample_rate = float(sample_rate)
        self.retention_s = float(retention_s)
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()
        self._pending: List[tuple] = []
        # Completion-time top-K tracking: per task, the K fastest of the
        # kept-slow set — a new completion slower than the slot floor
        # displaces it (in keep verdicts only; stored rows stay until
        # retention trims them).
        self._slow: Dict[str, List[float]] = {}
        self._pinned: set = set()
        self.offered = 0
        self.kept = 0
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._conn() as c:
            c.executescript(_SCHEMA)

    def _conn(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=30.0)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        return conn

    # ------------------------------------------------------------- keep side
    def _keep_reason(self, cost: JobCost) -> Optional[str]:
        if cost.verdict and cost.verdict != "ok":
            return "verdict"
        if cost.trace_id in self._pinned:
            self._pinned.discard(cost.trace_id)
            return "pinned"
        dur = cost.total_ms()
        task = cost.task or "unknown"
        heap = self._slow.setdefault(task, [])
        if len(heap) < self.keep_top_k:
            heap.append(dur)
            heap.sort()
            return "slow"
        if dur > heap[0]:
            heap[0] = dur
            heap.sort()
            return "slow"
        if self._rng.random() < self.sample_rate:
            return "sampled"
        return None

    def offer(self, cost: JobCost,
              spans: Sequence[Any] = ()) -> Optional[str]:
        """Tail-sampling decision for one completed job. Returns the
        keep reason, or None when the trace is dropped."""
        with self._lock:
            self.offered += 1
            reason = self._keep_reason(cost)
            if reason is None:
                return None
            self.kept += 1
            self._pending.append((
                cost.trace_id, self.ident, cost.task or "unknown",
                cost.tenant or "anon", cost.verdict or "ok", reason,
                cost.total_ms(),
                cost.finished_unix or time.time(),
                json.dumps([_span_dict(s) for s in spans
                            if s.trace_id == cost.trace_id],
                           default=str),
                json.dumps(cost.as_dict(), default=str)))
        return reason

    def pin(self, trace_ids: Sequence[str]) -> None:
        """Force-keep upcoming offers for these trace ids (the SLO page
        path: an offender identified from exemplars must persist even
        if the sampler would have dropped it)."""
        with self._lock:
            self._pinned.update(t for t in trace_ids if t)

    def flush(self) -> int:
        """Persist buffered keeps and trim expired rows. Sampler-tick
        ride-along; returns the number of rows written."""
        with self._lock:
            rows = list(self._pending)
            self._pending.clear()
        # Retention compares stored wall stamps across processes; the
        # monotonic clock does not cross the db boundary.
        cutoff = time.time() - self.retention_s  # vmtlint: disable=VMT109
        with self._conn() as c:
            if rows:
                c.executemany(
                    "INSERT OR REPLACE INTO traces (trace_id, ident, task, "
                    "tenant, verdict, keep_reason, dur_ms, stored_unix, "
                    "spans, cost) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    rows)
            c.execute("DELETE FROM traces WHERE stored_unix < ?", (cutoff,))
        return len(rows)

    # ------------------------------------------------------------- read side
    _COLS = ("trace_id", "ident", "task", "tenant", "verdict",
             "keep_reason", "dur_ms", "stored_unix")

    def list(self, *, verdict: Optional[str] = None,
             task: Optional[str] = None, tenant: Optional[str] = None,
             scope: str = "fleet", limit: int = 50) -> List[Dict[str, Any]]:
        """Row summaries, newest first. ``verdict`` matches the terminal
        verdict, or — for ``slow``/``sampled``/``pinned`` — the keep
        reason. ``scope="local"`` restricts to this process's ident;
        the default reads every ident on disk, stale peers included."""
        clauses, params = [], []
        if verdict in ("slow", "sampled", "pinned"):
            clauses.append("keep_reason = ?")
            params.append(verdict)
        elif verdict:
            clauses.append("verdict = ?")
            params.append(verdict)
        if task:
            clauses.append("task = ?")
            params.append(task)
        if tenant:
            clauses.append("tenant = ?")
            params.append(tenant)
        if scope == "local":
            clauses.append("ident = ?")
            params.append(self.ident)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        params.append(max(int(limit), 1))
        with self._conn() as c:
            rows = c.execute(
                f"SELECT {', '.join(self._COLS)} FROM traces{where} "
                f"ORDER BY stored_unix DESC LIMIT ?", params).fetchall()
        return [dict(zip(self._COLS, r)) for r in rows]

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Full stored record — spans and cost parsed — regardless of
        which (possibly dead) peer stored it."""
        with self._conn() as c:
            row = c.execute(
                f"SELECT {', '.join(self._COLS)}, spans, cost FROM traces "
                f"WHERE trace_id = ?", (trace_id,)).fetchone()
        if row is None:
            return None
        out = dict(zip(self._COLS, row[:-2]))
        out["spans"] = json.loads(row[-2])
        out["cost"] = json.loads(row[-1])
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            offered, kept = self.offered, self.kept
            pending = len(self._pending)
        return {"offered": offered, "kept": kept, "pending": pending,
                "tail_kept_frac": round(kept / offered, 4)
                if offered else None}
