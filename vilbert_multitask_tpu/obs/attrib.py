"""Per-request cost attribution: where each job's milliseconds went.

The aggregate histograms (``vmt_span_ms``, ``request_latency_ms``) answer
"how slow is the system"; this plane answers the question an autoscaler
or a tenant-fairness scheduler has to ask — *which task and which tenant
spent which stage's milliseconds and whose device-seconds* — per job,
assembled across the pipeline and rolled into billable totals.

One :class:`JobCost` record per claimed job, keyed by trace id. Stages
(all wall milliseconds):

========== ============================================================
queue_wait publish → claim (from the job body's ``published_unix``)
intake     claim → prepared request (tokenize + feature I/O)
ready_wait prepared → selected into a batch (scheduler EDF window)
pack       batch assembly up to the forward dispatch
forward    amortized device share: batch forward wall × member_rows /
           batch_rows, charged per member by the completion stage — the
           batch-fill inefficiency a per-request view otherwise hides
decode     result marshal + persist
push       terminal frame → socket hub
========== ============================================================

The forward share is double-entry bookkeeping: :meth:`charge_batch` adds
the full batch wall to an engine-busy ledger once per dispatch and the
per-member shares to the jobs, so ``sum(job.device_s) == busy_s`` exactly
when every member streams — the conservation invariant the soak gates at
10%. A member that dies mid-batch is simply never charged (its share
stays on the busy ledger as waste the amortization gauge shows).

Totals feed three instruments — ``vmt_device_seconds_total{task,tenant}``,
``vmt_cost_ms{stage,task}``, ``vmt_batch_amortization{bucket}`` — and a
bounded completed-ring serves ``GET /debug/costs?window_s=&by=`` windowed
aggregates.

Module plane: like the flight recorder, the process installs one
:class:`CostAttributor` (``set_attributor``) and the pipeline calls the
``job_*`` helpers, which are a single None-check when attribution is off
(<5 µs, the span/fault-point discipline).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from vilbert_multitask_tpu.obs.instruments import REGISTRY

STAGES = ("queue_wait", "intake", "ready_wait", "pack", "forward",
          "decode", "push")

# Billable totals the autoscaler / tenant-QoS tiers consume. task+tenant
# and stage+task are bounded vocabularies (the task registry and the
# fixed stage table) — never raw request data.
DEVICE_SECONDS = REGISTRY.counter(
    "vmt_device_seconds_total",
    "Amortized device-forward seconds attributed per task and tenant.",
    labelnames=("task", "tenant"))
COST_MS = REGISTRY.histogram(
    "vmt_cost_ms",
    "Per-job stage cost (ms) observed at job completion.",
    labelnames=("stage", "task"))
BATCH_AMORTIZATION = REGISTRY.gauge(
    "vmt_batch_amortization",
    "Charged-row fraction of the last dispatched batch per row bucket "
    "(1.0 = every forward second billed to a streamed member).",
    labelnames=("bucket",))


@dataclasses.dataclass
class JobCost:
    """One job's attributed cost, assembled claim → terminal verdict."""

    trace_id: str
    job_id: Optional[int] = None
    task: str = ""
    tenant: str = "anon"
    replica: str = ""
    bucket: str = ""
    verdict: str = ""
    stages: Dict[str, float] = dataclasses.field(
        default_factory=dict)  # stage -> ms
    device_s: float = 0.0
    member_rows: int = 0
    batch_rows: int = 0
    started_unix: float = 0.0
    finished_unix: float = 0.0

    def total_ms(self) -> float:
        return sum(self.stages.values())

    def as_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["total_ms"] = round(self.total_ms(), 3)
        return d


class CostAttributor:
    """Assembles :class:`JobCost` records across pipeline threads.

    Open records live in a bounded dict keyed by trace id (claim begins
    one, the terminal verdict closes it); closed records move to a
    bounded ring the windowed aggregates read. ``on_finish`` is the
    trace-store hook — called outside the lock with the completed record.
    """

    def __init__(self, *, max_open: int = 4096, ring: int = 4096,
                 on_finish: Optional[Callable[[JobCost], None]] = None):
        self._lock = threading.Lock()
        self._open: Dict[str, JobCost] = {}
        self._open_order: deque = deque()
        self._max_open = int(max_open)
        self._done: deque = deque(maxlen=int(ring))
        self.on_finish = on_finish
        self.busy_s = 0.0          # engine ledger: full batch walls, once
        self.attributed_s = 0.0    # job ledger: per-member shares
        self.finished = 0

    # ------------------------------------------------------------- writers
    def begin(self, trace_id: str, *, job_id: Optional[int] = None,
              task: str = "", tenant: str = "anon") -> None:
        if not trace_id:
            return
        with self._lock:
            cost = self._open.get(trace_id)
            if cost is None:
                if len(self._open) >= self._max_open and self._open_order:
                    self._open.pop(self._open_order.popleft(), None)
                cost = JobCost(trace_id=trace_id)
                # Wall stamp (cross-process correlation key, not a
                # duration) — durations all come in via charge().
                cost.started_unix = time.time()
                self._open[trace_id] = cost
                self._open_order.append(trace_id)
            cost.job_id = job_id if job_id is not None else cost.job_id
            cost.task = task or cost.task
            cost.tenant = tenant or cost.tenant

    def charge(self, trace_id: str, stage: str, dur_s: float) -> None:
        """Add ``dur_s`` of wall time to one stage of one job."""
        if not trace_id:
            return
        with self._lock:
            cost = self._open.get(trace_id)
            if cost is None:
                return
            cost.stages[stage] = cost.stages.get(stage, 0.0) \
                + max(dur_s, 0.0) * 1e3

    def charge_batch(self, batch_wall_s: float,
                     members: Sequence[Tuple[str, int]], *,
                     batch_rows: int, bucket: int = 0,
                     replica: str = "") -> None:
        """Amortize one dispatched batch's forward wall over its
        (streamed) members: share_i = wall × rows_i / batch_rows.

        ``members`` lists only the jobs that actually streamed a result —
        a mid-batch failure's members are never charged, so the busy
        ledger (credited the FULL wall exactly once here) shows the
        difference as unbilled waste.
        """
        batch_wall_s = max(batch_wall_s, 0.0)
        rows_total = max(int(batch_rows), 1)
        charged_rows = 0
        with self._lock:
            self.busy_s += batch_wall_s
            for trace_id, rows in members:
                rows = max(int(rows), 1)
                charged_rows += rows
                cost = self._open.get(trace_id)
                if cost is None:
                    continue
                share = batch_wall_s * rows / rows_total
                cost.device_s += share
                cost.stages["forward"] = cost.stages.get("forward", 0.0) \
                    + share * 1e3
                cost.member_rows += rows
                cost.batch_rows = rows_total
                cost.bucket = str(bucket)
                cost.replica = replica or cost.replica
                self.attributed_s += share
                if cost.task:
                    DEVICE_SECONDS.inc(share, task=cost.task,
                                       tenant=cost.tenant)
        BATCH_AMORTIZATION.set(min(charged_rows / rows_total, 1.0),
                               bucket=str(bucket))

    def finish(self, trace_id: str, verdict: str) -> Optional[JobCost]:
        """Close a job's record with its terminal verdict; rolls the
        stage histograms and hands the record to ``on_finish``."""
        if not trace_id:
            return None
        with self._lock:
            cost = self._open.pop(trace_id, None)
            if cost is None:
                return None
            cost.verdict = verdict
            cost.finished_unix = time.time()  # wall stamp, not a duration
            self._done.append(cost)
            self.finished += 1
        for stage, ms in cost.stages.items():
            COST_MS.observe(ms, stage=stage, task=cost.task or "unknown")
        hook = self.on_finish
        if hook is not None:
            try:
                hook(cost)
            except Exception:  # the store must never fail the pipeline
                pass
        return cost

    # ------------------------------------------------------------- readers
    def completed(self, since_unix: float = 0.0) -> List[JobCost]:
        with self._lock:
            return [c for c in self._done if c.finished_unix >= since_unix]

    def get(self, trace_id: str) -> Optional[JobCost]:
        with self._lock:
            c = self._open.get(trace_id)
            if c is not None:
                return c
            for c in reversed(self._done):
                if c.trace_id == trace_id:
                    return c
        return None

    def window(self, window_s: Optional[float] = None,
               by: str = "task") -> Dict[str, Any]:
        """The ``/debug/costs`` payload: per-``by`` (task|tenant) job
        counts, stage-ms totals, and device-seconds over the window."""
        key = "tenant" if by == "tenant" else "task"
        # Wall cutoff against finished_unix wall stamps (cross-restart
        # comparable, like the fleet heartbeat ages).
        cutoff = (time.time() - window_s  # vmtlint: disable=VMT109
                  if window_s else 0.0)
        groups: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            done = list(self._done)
        for cost in done:
            if cost.finished_unix < cutoff:
                continue
            g = groups.setdefault(getattr(cost, key) or "unknown", {
                "jobs": 0, "device_s": 0.0, "stage_ms": {},
                "verdicts": {}})
            g["jobs"] += 1
            g["device_s"] = round(g["device_s"] + cost.device_s, 6)
            g["verdicts"][cost.verdict] = \
                g["verdicts"].get(cost.verdict, 0) + 1
            for stage, ms in cost.stages.items():
                g["stage_ms"][stage] = round(
                    g["stage_ms"].get(stage, 0.0) + ms, 3)
        return {"by": key, "window_s": window_s, "groups": groups,
                "conservation": self.conservation()}

    def conservation(self) -> Dict[str, float]:
        """The double-entry verdict: attributed shares vs. the engine
        busy ledger. ratio == 1.0 when every batch member streamed."""
        with self._lock:
            busy, attr = self.busy_s, self.attributed_s
        return {"busy_s": round(busy, 6), "attributed_s": round(attr, 6),
                "ratio": round(attr / busy, 4) if busy > 0 else 1.0}


# ------------------------------------------------------- module-level plane
_ATTRIB: Optional[CostAttributor] = None


def set_attributor(attrib: Optional[CostAttributor]) -> None:
    global _ATTRIB
    _ATTRIB = attrib


def get_attributor() -> Optional[CostAttributor]:
    return _ATTRIB


def job_begin(trace_id: str, *, job_id: Optional[int] = None,
              task: str = "", tenant: str = "anon") -> None:
    a = _ATTRIB
    if a is None:
        return
    a.begin(trace_id, job_id=job_id, task=task, tenant=tenant)


def job_charge(trace_id: str, stage: str, dur_s: float) -> None:
    a = _ATTRIB
    if a is None:
        return
    a.charge(trace_id, stage, dur_s)


def job_batch(batch_wall_s: float, members: Sequence[Tuple[str, int]], *,
              batch_rows: int, bucket: int = 0, replica: str = "") -> None:
    a = _ATTRIB
    if a is None:
        return
    a.charge_batch(batch_wall_s, members, batch_rows=batch_rows,
                   bucket=bucket, replica=replica)


def job_finish(trace_id: str, verdict: str) -> None:
    a = _ATTRIB
    if a is None:
        return
    a.finish(trace_id, verdict)
