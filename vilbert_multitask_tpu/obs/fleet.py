"""Fleet metrics spine: one sqlite file where every process reports.

The observability plane built so far (registry, timeseries, tracer,
recorder) is strictly per-process, but the deployment it models is not —
a web tier and queue-fed workers run as separate OS processes sharing
only the durable queue. The spine extends that sharing to telemetry: a
WAL-mode sqlite db (by convention ``fleet.sqlite3`` next to the queue db)
into which each process's sampler tick flushes

- a **heartbeat** row (identity + health payload, staleness-evicted),
- **instrument snapshots** (full ``collect()`` payloads per instrument),
- **timeseries deltas** (only points newer than the last flush), and
- recent **spans** keyed by ``trace_id`` (bounded per process,
  rate-limited per flush).

Any process holding a :class:`FleetSpine` on the same path can then
answer fleet-scoped queries: ``render_prometheus()`` merges live peers
(counters summed, gauges per-identity via an ``instance`` label,
histograms bucket-merged), ``health()`` lists peers with staleness
verdicts, and ``chrome_trace(trace_id)`` stitches ONE timeline from
spans recorded in different processes.

Clock alignment: spans are recorded with per-process ``perf_counter``
stamps, meaningless across processes. At export each span start is
anchored to the wall clock (``time.time() - (perf_now - start_s)``), so
stitched timelines share the unix epoch; the residual skew is NTP-level,
far below the queue latencies being visualized.
"""

from __future__ import annotations

import json
import math
import os
import sqlite3
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from vilbert_multitask_tpu.obs.export import (
    _escape_help,
    _fmt,
    _labels,
    _metric_name,
)
from vilbert_multitask_tpu.obs.identity import WorkerIdentity
from vilbert_multitask_tpu.obs.instruments import Registry, REGISTRY
from vilbert_multitask_tpu.obs.timeseries import TimeSeriesStore
from vilbert_multitask_tpu.obs.trace import Tracer, default_tracer

_SCHEMA = """
CREATE TABLE IF NOT EXISTS fleet_heartbeats (
    ident TEXT PRIMARY KEY,
    host TEXT NOT NULL,
    pid INTEGER NOT NULL,
    role TEXT NOT NULL,
    boot_nonce TEXT NOT NULL,
    started_unix REAL NOT NULL,
    updated_unix REAL NOT NULL,
    payload TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS fleet_instruments (
    ident TEXT NOT NULL,
    name TEXT NOT NULL,
    kind TEXT NOT NULL,
    help TEXT NOT NULL DEFAULT '',
    labelnames TEXT NOT NULL DEFAULT '[]',
    payload TEXT NOT NULL,
    updated_unix REAL NOT NULL,
    PRIMARY KEY (ident, name)
);
CREATE TABLE IF NOT EXISTS fleet_timeseries (
    ident TEXT NOT NULL,
    name TEXT NOT NULL,
    ts REAL NOT NULL,
    value REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS fleet_ts_lookup
    ON fleet_timeseries (ident, name, ts);
CREATE TABLE IF NOT EXISTS fleet_spans (
    ident TEXT NOT NULL,
    span_id TEXT NOT NULL,
    trace_id TEXT NOT NULL,
    parent_id TEXT,
    name TEXT NOT NULL,
    start_unix REAL NOT NULL,
    dur_s REAL NOT NULL,
    thread_id INTEGER NOT NULL,
    thread_name TEXT NOT NULL,
    attrs TEXT NOT NULL DEFAULT '{}',
    PRIMARY KEY (ident, span_id)
);
CREATE INDEX IF NOT EXISTS fleet_spans_trace ON fleet_spans (trace_id);
"""


def default_spine_path(queue_db_path: str) -> str:
    """The convention: the spine lives next to the queue db — the queue
    is already the one file every process in the fleet can reach."""
    d = os.path.dirname(queue_db_path) or "."
    return os.path.join(d, "fleet.sqlite3")


class FleetSpine:
    """One process's handle on the shared fleet telemetry db.

    Writer side (``flush``/``retire``) publishes this process; reader
    side (``render_prometheus``/``health``/``timeseries``/
    ``chrome_trace``) merges every live peer. All sqlite access opens a
    short-lived connection per call (the DurableQueue idiom — WAL mode
    makes cross-process readers and the single writer coexist).
    """

    def __init__(self, path: str, identity: WorkerIdentity, *,
                 heartbeat_stale_s: float = 15.0,
                 max_spans_per_ident: int = 2048,
                 spans_per_flush: int = 256,
                 timeseries_window_s: float = 600.0,
                 registry: Optional[Registry] = None,
                 tracer: Optional[Tracer] = None,
                 timeseries: Optional[TimeSeriesStore] = None):
        self.path = path
        self.identity = identity
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.max_spans_per_ident = int(max_spans_per_ident)
        self.spans_per_flush = int(spans_per_flush)
        self.timeseries_window_s = float(timeseries_window_s)
        self._registry = registry if registry is not None else REGISTRY
        self._tracer = tracer if tracer is not None else default_tracer()
        self._timeseries = timeseries
        self._lock = threading.Lock()
        # Flush bookkeeping: newest timeseries stamp already written per
        # series, and span ids already exported (bounded — the dedup set
        # only needs to cover what the tracer ring can still hold).
        self._ts_high_water: Dict[str, float] = {}
        self._exported_ids: deque = deque(maxlen=2 * max_spans_per_ident)
        self._exported_set: set = set()
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._conn() as c:
            c.executescript(_SCHEMA)

    def _conn(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=30.0)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        return conn

    # ------------------------------------------------------------ writer side
    def flush(self, health_payload: Optional[Dict[str, Any]] = None) -> None:
        """Publish this process's current telemetry (one sampler tick)."""
        with self._lock:
            now = time.time()
            ident = self.identity
            inst_rows = []
            for inst in self._registry.instruments():
                payload = [[list(k), v] for k, v in
                           sorted(inst.collect().items())]
                # json.dumps writes histogram +Inf bounds as the (python-
                # parseable) Infinity literal; json.loads restores them.
                inst_rows.append((
                    ident.ident, inst.name, inst.kind, inst.help,
                    json.dumps(list(inst.labelnames)),
                    json.dumps(payload), now))
            ts_rows = self._timeseries_deltas()
            span_rows = self._span_rows()
            with self._conn() as c:
                c.execute(
                    "INSERT INTO fleet_heartbeats (ident, host, pid, role, "
                    "boot_nonce, started_unix, updated_unix, payload) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(ident) DO UPDATE SET "
                    "updated_unix=excluded.updated_unix, "
                    "payload=excluded.payload",
                    (ident.ident, ident.host, ident.pid, ident.role,
                     ident.boot_nonce, ident.started_unix, now,
                     json.dumps(health_payload or {})))
                c.executemany(
                    "INSERT INTO fleet_instruments (ident, name, kind, help, "
                    "labelnames, payload, updated_unix) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(ident, name) DO UPDATE SET "
                    "payload=excluded.payload, "
                    "updated_unix=excluded.updated_unix", inst_rows)
                if ts_rows:
                    c.executemany(
                        "INSERT INTO fleet_timeseries (ident, name, ts, value)"
                        " VALUES (?, ?, ?, ?)", ts_rows)
                    c.execute(
                        "DELETE FROM fleet_timeseries WHERE ident=? AND ts<?",
                        # Wall-clock retention cutoff in a SHARED db: rows
                        # carry time.time() stamps so peers can compare them.
                        (ident.ident,
                         now - self.timeseries_window_s))  # vmtlint: disable=VMT109
                if span_rows:
                    c.executemany(
                        "INSERT OR IGNORE INTO fleet_spans (ident, span_id, "
                        "trace_id, parent_id, name, start_unix, dur_s, "
                        "thread_id, thread_name, attrs) "
                        "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)", span_rows)
                    # Per-ident bound: keep only the newest rows.
                    c.execute(
                        "DELETE FROM fleet_spans WHERE ident=? AND span_id "
                        "NOT IN (SELECT span_id FROM fleet_spans WHERE "
                        "ident=? ORDER BY start_unix DESC LIMIT ?)",
                        (ident.ident, ident.ident, self.max_spans_per_ident))

    def _timeseries_deltas(self) -> List[Tuple[str, str, float, float]]:
        if self._timeseries is None:
            return []
        rows = []
        for name, points in self._timeseries.snapshot().items():
            high = self._ts_high_water.get(name, -math.inf)
            fresh = [(t, v) for t, v in points if t > high]
            if fresh:
                self._ts_high_water[name] = fresh[-1][0]
                rows.extend((self.identity.ident, name, t, v)
                            for t, v in fresh)
        return rows

    def _span_rows(self) -> List[Tuple]:
        rows = []
        # Wall-anchor per-process monotonic span stamps so timelines from
        # different processes share an epoch. This is an epoch conversion,
        # not duration math: dur_s stays pure perf_counter.
        offset = time.time() - time.perf_counter()  # vmtlint: disable=VMT109
        for s in self._tracer.spans():
            if s.span_id in self._exported_set:
                continue
            rows.append((self.identity.ident, s.span_id, s.trace_id,
                         s.parent_id, s.name, offset + s.start_s, s.dur_s,
                         s.thread_id, s.thread_name,
                         json.dumps(s.attrs, default=str)))
            if len(self._exported_ids) == self._exported_ids.maxlen:
                self._exported_set.discard(self._exported_ids[0])
            self._exported_ids.append(s.span_id)
            self._exported_set.add(s.span_id)
            if len(rows) >= self.spans_per_flush:
                break
        return rows

    def retire(self) -> None:
        """Graceful shutdown: withdraw this process's live presence (its
        heartbeat/instruments/timeseries). Spans stay — a finished
        submitter's half of a trace must remain stitchable."""
        with self._lock, self._conn() as c:
            c.execute("DELETE FROM fleet_heartbeats WHERE ident=?",
                      (self.identity.ident,))
            c.execute("DELETE FROM fleet_instruments WHERE ident=?",
                      (self.identity.ident,))
            c.execute("DELETE FROM fleet_timeseries WHERE ident=?",
                      (self.identity.ident,))

    # ------------------------------------------------------------ reader side
    def peers(self, include_stale: bool = False) -> List[Dict[str, Any]]:
        """Heartbeat rows, newest first, with ``alive`` staleness verdicts.
        Stale peers (SIGKILL'd, hung) are excluded unless asked for."""
        now = time.time()
        with self._conn() as c:
            rows = c.execute(
                "SELECT ident, host, pid, role, boot_nonce, started_unix, "
                "updated_unix, payload FROM fleet_heartbeats "
                "ORDER BY updated_unix DESC").fetchall()
        out = []
        for (ident, host, pid, role, nonce, started, updated, payload) in rows:
            # Staleness compares persisted wall stamps from OTHER processes;
            # monotonic clocks do not cross process boundaries.
            age = now - updated  # vmtlint: disable=VMT109
            alive = age <= self.heartbeat_stale_s
            if not alive and not include_stale:
                continue
            out.append({"ident": ident, "host": host, "pid": pid,
                        "role": role, "boot_nonce": nonce,
                        "started_unix": started, "updated_unix": updated,
                        "age_s": round(age, 3), "alive": alive,
                        "payload": json.loads(payload)})
        return out

    def live_idents(self) -> List[str]:
        return [p["ident"] for p in self.peers()]

    def health(self) -> Dict[str, Any]:
        """The ``/healthz?scope=fleet`` payload: every live peer's own
        health block plus the fleet-level verdict (every peer ready)."""
        peers = self.peers(include_stale=True)
        live = [p for p in peers if p["alive"]]
        ready = bool(live) and all(
            p["payload"].get("phase", "ready") == "ready" for p in live)
        return {"scope": "fleet", "fleet_ready": ready,
                "processes": peers, "alive": len(live),
                "stale": len(peers) - len(live),
                "heartbeat_stale_s": self.heartbeat_stale_s}

    def _live_instruments(self) -> Dict[str, Dict[str, Any]]:
        """name -> merged descriptor {kind, help, labelnames,
        series: {ident: payload}} across live peers only."""
        live = set(self.live_idents())
        with self._conn() as c:
            rows = c.execute(
                "SELECT ident, name, kind, help, labelnames, payload "
                "FROM fleet_instruments").fetchall()
        merged: Dict[str, Dict[str, Any]] = {}
        for ident, name, kind, help_, labelnames, payload in rows:
            if ident not in live:
                continue
            entry = merged.setdefault(name, {
                "kind": kind, "help": help_,
                "labelnames": tuple(json.loads(labelnames)), "series": {}})
            entry["series"][ident] = [
                (tuple(k), v) for k, v in json.loads(payload)]
        return merged

    def render_prometheus(self) -> str:
        """Fleet-scoped exposition: counters summed across live peers,
        gauges emitted per peer (``instance`` label), histograms
        bucket-merged. One scrape, whole fleet."""
        lines: List[str] = []
        merged = self._live_instruments()
        for name in sorted(merged):
            entry = merged[name]
            mname = _metric_name(name)
            labelnames = entry["labelnames"]
            if entry["help"]:
                lines.append(f"# HELP {mname} {_escape_help(entry['help'])}")
            lines.append(f"# TYPE {mname} {entry['kind']}")
            if entry["kind"] == "counter":
                totals: Dict[Tuple[str, ...], float] = {}
                for series in entry["series"].values():
                    for key, value in series:
                        totals[key] = totals.get(key, 0.0) + value
                for key in sorted(totals):
                    lines.append(f"{mname}{_labels(labelnames, key)} "
                                 f"{_fmt(totals[key])}")
            elif entry["kind"] == "gauge":
                for ident in sorted(entry["series"]):
                    for key, value in sorted(entry["series"][ident]):
                        lines.append(
                            f"{mname}"
                            f"{_labels(labelnames, key, [('instance', ident)])}"
                            f" {_fmt(value)}")
            else:  # histogram: merge cumulative buckets by bound
                agg: Dict[Tuple[str, ...], Dict[str, Any]] = {}
                for series in entry["series"].values():
                    for key, h in series:
                        slot = agg.setdefault(
                            key, {"buckets": {}, "count": 0, "sum": 0.0})
                        for bound, cum in h["buckets"]:
                            b = math.inf if bound is None else float(bound)
                            slot["buckets"][b] = slot["buckets"].get(b, 0) + cum
                        slot["count"] += h["count"]
                        slot["sum"] += h["sum"]
                for key in sorted(agg):
                    slot = agg[key]
                    for bound in sorted(slot["buckets"]):
                        lines.append(
                            f"{mname}_bucket"
                            f"{_labels(labelnames, key, [('le', _fmt(bound))])}"
                            f" {slot['buckets'][bound]}")
                    lines.append(f"{mname}_sum{_labels(labelnames, key)} "
                                 f"{_fmt(slot['sum'])}")
                    lines.append(f"{mname}_count{_labels(labelnames, key)} "
                                 f"{slot['count']}")
        return "\n".join(lines) + "\n"

    def timeseries(self, window_s: Optional[float] = None
                   ) -> Dict[str, Any]:
        """Fleet-scoped ``/debug/timeseries`` payload: every live peer's
        series, keyed ``ident:name`` so per-process trajectories stay
        distinguishable on one chart."""
        live = set(self.live_idents())
        cutoff = (time.time() - window_s  # vmtlint: disable=VMT109
                  if window_s is not None else None)
        with self._conn() as c:
            if cutoff is None:
                rows = c.execute(
                    "SELECT ident, name, ts, value FROM fleet_timeseries "
                    "ORDER BY ts").fetchall()
            else:
                rows = c.execute(
                    "SELECT ident, name, ts, value FROM fleet_timeseries "
                    "WHERE ts >= ? ORDER BY ts", (cutoff,)).fetchall()
        series: Dict[str, List[Tuple[float, float]]] = {}
        for ident, name, ts, value in rows:
            if ident not in live:
                continue
            series.setdefault(f"{ident}:{name}", []).append((ts, value))
        return {"scope": "fleet", "window_s": window_s,
                "processes": sorted(live), "series": series}

    def chrome_trace(self, trace_id: Optional[str] = None,
                     limit: Optional[int] = None) -> Dict[str, Any]:
        """ONE Chrome-trace timeline stitched across processes.

        Each contributing process becomes a Chrome-trace ``pid`` row
        (named ``role ident``); timestamps are µs relative to the
        earliest span so the submitter's ``http.submit`` and the
        worker's ``worker.job`` line up on one axis.

        Deliberately liveness-blind, unlike the metrics/health merges:
        staleness eviction (and ``retire()``) withdraws a peer's
        *presence*, never its spans — a SIGKILL'd worker's half of a
        trace is exactly the autopsy this view exists for, so span
        reads include every ident still on disk. The trace store
        (obs/tracestore.py) reads under the same contract.
        """
        with self._conn() as c:
            if trace_id:
                rows = c.execute(
                    "SELECT ident, span_id, trace_id, parent_id, name, "
                    "start_unix, dur_s, thread_id, thread_name, attrs "
                    "FROM fleet_spans WHERE trace_id=? ORDER BY start_unix",
                    (trace_id,)).fetchall()
            else:
                rows = c.execute(
                    "SELECT ident, span_id, trace_id, parent_id, name, "
                    "start_unix, dur_s, thread_id, thread_name, attrs "
                    "FROM fleet_spans ORDER BY start_unix DESC LIMIT ?",
                    (int(limit or 1000),)).fetchall()
                rows.reverse()
        if not rows:
            return {"traceEvents": [], "displayTimeUnit": "ms",
                    "scope": "fleet", "trace_id": trace_id}
        epoch = min(r[5] for r in rows)
        roles = {p["ident"]: p["role"]
                 for p in self.peers(include_stale=True)}
        pids: Dict[str, int] = {}
        thread_names: Dict[Tuple[int, int], str] = {}
        events: List[Dict[str, Any]] = []
        for (ident, span_id, tid_, parent_id, name, start_unix, dur_s,
             thread_id, thread_name, attrs) in rows:
            pid = pids.setdefault(ident, len(pids) + 1)
            thread_names.setdefault((pid, thread_id), thread_name)
            events.append({
                "name": name, "ph": "X", "cat": "obs",
                "ts": round((start_unix - epoch) * 1e6, 3),
                "dur": round(dur_s * 1e6, 3),
                "pid": pid, "tid": thread_id,
                "args": {"trace_id": tid_, "span_id": span_id,
                         "parent_id": parent_id, "ident": ident,
                         **json.loads(attrs)},
            })
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": f"{roles.get(ident, 'proc')} {ident}"}}
                for ident, pid in sorted(pids.items(), key=lambda kv: kv[1])]
        meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                  "args": {"name": tname}}
                 for (pid, tid), tname in sorted(thread_names.items())]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "scope": "fleet", "trace_id": trace_id,
                "processes": {ident: pid for ident, pid in pids.items()}}

    def snapshot(self) -> Dict[str, Any]:
        """Compact fleet view for flight-recorder bundles: who is alive,
        how stale, and how much telemetry each peer has spined."""
        with self._conn() as c:
            span_counts = dict(c.execute(
                "SELECT ident, COUNT(*) FROM fleet_spans "
                "GROUP BY ident").fetchall())
        return {"path": self.path, "self": self.identity.as_dict(),
                "peers": self.peers(include_stale=True),
                "span_rows": span_counts}
