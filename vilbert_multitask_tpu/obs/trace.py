"""Span tracing: correlated, cross-thread request timelines.

One request in this framework crosses three threads (HTTP handler →
durable queue → worker) and four subsystems (serve, engine, decode, push);
the only prior visibility was aggregate latency percentiles. A
:class:`Tracer` records *spans* — named, monotonic-clocked intervals with
attributes — into a lock-protected ring buffer, with two correlation
mechanisms:

- **thread-local parenting**: nested ``with span("..."):`` blocks on one
  thread form a parent/child tree automatically;
- **trace resumption**: a ``trace_id`` minted at HTTP submit rides in the
  queue job body and is re-entered by the worker via
  ``with tracer.trace(trace_id):`` — every span either thread opens
  carries the same ``trace_id``, so one request's timeline reassembles
  across the queue boundary.

Timing is ``time.perf_counter`` throughout (monotonic — wall-clock
``time.time()`` in a duration is the VMT109 lint hazard). The disabled
fast path returns a shared no-op context manager after a single attribute
check, so instrumentation can stay on hot serving paths permanently
(tier-1 guards < 5 µs per disabled call).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id (the cross-thread correlation key)."""
    return uuid.uuid4().hex[:16]


@dataclass
class Span:
    """One completed, immutable span (what the ring buffer holds)."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float  # time.perf_counter() at entry (monotonic seconds)
    dur_s: float
    thread_id: int
    thread_name: str
    attrs: Dict[str, Any] = field(default_factory=dict)


class _NoopSpan:
    """The disabled-mode singleton: enter/exit/set are all no-ops."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


class _TlsState:
    __slots__ = ("stack", "trace_id")

    def __init__(self):
        self.stack: List["_ActiveSpan"] = []
        self.trace_id: Optional[str] = None


class _ActiveSpan:
    """A span being measured; becomes a :class:`Span` on ``__exit__``."""

    __slots__ = ("_tracer", "name", "attrs", "trace_id", "span_id",
                 "parent_id", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def set(self, **attrs) -> "_ActiveSpan":
        """Attach attributes discovered mid-span (job ids, bucket sizes)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_ActiveSpan":
        state = self._tracer._state()
        if state.stack:
            parent = state.stack[-1]
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            # Root span: adopt the thread's resumed trace id (set by
            # Tracer.trace) or mint a fresh one.
            self.trace_id = state.trace_id or new_trace_id()
            self.parent_id = None
        self.span_id = new_trace_id()
        state.stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        state = self._tracer._state()
        if state.stack and state.stack[-1] is self:
            state.stack.pop()
        else:  # mispaired exit (generator abandoned mid-span): unwind past it
            try:
                state.stack.remove(self)
            except ValueError:
                pass
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"[:200]
        th = threading.current_thread()
        self._tracer._record(Span(
            self.name, self.trace_id, self.span_id, self.parent_id,
            self._t0, dur, th.ident or 0, th.name, self.attrs))
        return False


class _TraceScope:
    """Context manager binding a resumed trace id to the current thread."""

    __slots__ = ("_tracer", "_trace_id", "_prev")

    def __init__(self, tracer: "Tracer", trace_id: Optional[str]):
        self._tracer = tracer
        self._trace_id = trace_id

    def __enter__(self) -> "_TraceScope":
        state = self._tracer._state()
        self._prev = state.trace_id
        state.trace_id = self._trace_id
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._state().trace_id = self._prev
        return False


class Tracer:
    """Process-wide span recorder: thread-local parenting, bounded ring."""

    def __init__(self, max_spans: int = 4096, enabled: bool = True):
        self._enabled = enabled
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max_spans)
        self._tls = threading.local()
        self._observer: Optional[Callable[[Span], None]] = None
        self._default_attrs: Dict[str, Any] = {}
        # Monotonic epoch: exporters place span starts relative to this
        # (Chrome-trace ts must be small positive µs, not raw perf_counter).
        self.epoch_perf = time.perf_counter()

    # ------------------------------------------------------------- tls state
    def _state(self) -> _TlsState:
        state = getattr(self._tls, "state", None)
        if state is None:
            state = self._tls.state = _TlsState()
        return state

    # --------------------------------------------------------------- control
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def set_observer(self, fn: Optional[Callable[[Span], None]]) -> None:
        """Called with every completed span (metrics bridging). One slot."""
        self._observer = fn

    def set_default_attrs(self, **attrs: Any) -> None:
        """Attributes merged into every recorded span (process identity —
        how a stitched fleet trace tells submitter spans from worker
        spans). Span-local attrs win on collision; no kwargs clears."""
        self._default_attrs = dict(attrs)

    # ------------------------------------------------------------- recording
    def span(self, name: str, **attrs):
        """``with tracer.span("engine.forward", bucket=8):`` — the API."""
        if not self._enabled:
            return _NOOP
        return _ActiveSpan(self, name, attrs)

    def trace(self, trace_id: Optional[str]) -> _TraceScope:
        """Adopt ``trace_id`` for root spans opened on this thread (the
        worker's side of cross-queue correlation). ``None`` means "mint
        fresh ids" — safe for jobs published by pre-tracing clients."""
        return _TraceScope(self, trace_id)

    def current_trace_id(self) -> Optional[str]:
        """The innermost active span's trace id (or the resumed one)."""
        state = self._state()
        if state.stack:
            return state.stack[-1].trace_id
        return state.trace_id

    def record_span(self, name: str, start_s: float, dur_s: float, *,
                    trace_id: Optional[str] = None, **attrs) -> None:
        """Record an already-measured interval (for spans whose identity is
        only known after the fact — e.g. a queue claim joins the claimed
        job's trace)."""
        if not self._enabled:
            return
        th = threading.current_thread()
        self._record(Span(name, trace_id or new_trace_id(), new_trace_id(),
                          None, start_s, dur_s, th.ident or 0, th.name,
                          dict(attrs)))

    def _record(self, span: Span) -> None:
        if self._default_attrs:
            span.attrs = {**self._default_attrs, **span.attrs}
        with self._lock:
            self._ring.append(span)
        observer = self._observer
        if observer is not None:
            try:
                observer(span)
            except Exception:  # noqa: BLE001 — telemetry must not raise
                logging.getLogger(__name__).exception(
                    "span observer failed for %s", span.name)

    # ------------------------------------------------------------ inspection
    def spans(self, limit: Optional[int] = None) -> List[Span]:
        """Snapshot of the newest ``limit`` completed spans (all if None)."""
        with self._lock:
            out = list(self._ring)
        return out[-limit:] if limit else out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer every instrumented subsystem records into."""
    return _DEFAULT


def span(name: str, **attrs):
    """Module-level shorthand for ``default_tracer().span(...)``."""
    return _DEFAULT.span(name, **attrs)


def trace_scope(trace_id: Optional[str]) -> _TraceScope:
    """Module-level shorthand for ``default_tracer().trace(...)``."""
    return _DEFAULT.trace(trace_id)


def current_trace_id() -> Optional[str]:
    return _DEFAULT.current_trace_id()
