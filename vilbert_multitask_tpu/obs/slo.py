"""Declarative SLOs evaluated with multi-window burn rates.

An :class:`Slo` is a named objective plus a ``counts_fn(window_s) ->
(good, bad)`` probe over a sliding window — everything else (targets,
which histogram feeds it) is closed over by the builder helpers below.
The burn rate of a window is ``bad_ratio / error_budget``: burn 1.0
means the budget is being spent exactly as fast as the objective allows;
burn 10 means a month's budget gone in three days.

:class:`SloEvaluator` applies the Google-SRE multi-window rule: a PAGE
requires the burn to exceed the page threshold on *both* a fast window
(is it happening now?) and a slow window (is it sustained, not a blip?).
Because both windows are sliding, an old burst that has aged out of the
fast window cannot hold a PAGE — exactly the property the tier-1 gate
asserts. States publish as ``vmt_slo_state{slo}`` (0/1/2) and
``vmt_slo_burn_rate{slo,window}``; an OK/WARN→PAGE transition trips the
flight recorder.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from vilbert_multitask_tpu.obs.instruments import REGISTRY, Histogram
from vilbert_multitask_tpu.obs.recorder import record_event

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_PAGE = "page"
_STATE_CODES = {STATE_OK: 0, STATE_WARN: 1, STATE_PAGE: 2}

SLO_STATE_GAUGE = REGISTRY.gauge(
    "vmt_slo_state", "SLO health (0=ok 1=warn 2=page)", labelnames=("slo",))
SLO_BURN_GAUGE = REGISTRY.gauge(
    "vmt_slo_burn_rate", "Error-budget burn rate per evaluation window",
    labelnames=("slo", "window"))


class Slo:
    """One objective: ``counts_fn(window_s) -> (good, bad)`` + a budget."""

    def __init__(self, name: str, objective: str,
                 counts_fn: Callable[[float], Tuple[int, int]],
                 error_budget: float = 0.01,
                 exemplars_fn: Optional[
                     Callable[[], List[Tuple[float, str]]]] = None):
        if not 0.0 < error_budget < 1.0:
            raise ValueError(f"slo {name!r}: error_budget must be in (0,1), "
                             f"got {error_budget}")
        self.name = name
        self.objective = objective
        self.error_budget = float(error_budget)
        self._counts_fn = counts_fn
        # Optional metrics→trace link: (value, trace_id) pairs for the
        # worst recent observations of the histogram feeding this SLO.
        # Page payloads embed them so a burn links straight to stored
        # autopsies (/debug/autopsy?trace_id=).
        self._exemplars_fn = exemplars_fn

    def exemplar_trace_ids(self) -> List[str]:
        if self._exemplars_fn is None:
            return []
        try:
            return [tid for _v, tid in self._exemplars_fn() if tid]
        except Exception:  # an exemplar probe must never fail evaluation
            return []

    def burn_rate(self, window_s: float) -> Tuple[float, int, int]:
        """(burn, good, bad) over the window; an empty window burns 0 —
        no traffic spends no budget."""
        good, bad = self._counts_fn(window_s)
        total = good + bad
        if total <= 0:
            return 0.0, 0, 0
        return (bad / total) / self.error_budget, good, bad


# ------------------------------------------------------------ SLO builders
def latency_slo(name: str, hist: Histogram, target_ms: float,
                error_budget: float = 0.05, **labels) -> Slo:
    """Requests completing within ``target_ms`` (windowed samples of a
    latency histogram; a sample over target is a bad event)."""
    def counts(window_s: float) -> Tuple[int, int]:
        xs = hist.window_samples(window_s, **labels)
        bad = sum(1 for v in xs if v > target_ms)
        return len(xs) - bad, bad
    return Slo(name, f"latency <= {target_ms:g} ms", counts,
               error_budget=error_budget,
               exemplars_fn=lambda: hist.slowest_exemplars(3))


def slack_floor_slo(name: str, hist: Histogram, floor_ms: float,
                    error_budget: float = 0.05, **labels) -> Slo:
    """Deadline slack staying above a floor: a job arriving at the engine
    with less than ``floor_ms`` of budget left is a bad event (it will
    deadline on any hiccup)."""
    def counts(window_s: float) -> Tuple[int, int]:
        xs = hist.window_samples(window_s, **labels)
        bad = sum(1 for v in xs if v < floor_ms)
        return len(xs) - bad, bad
    return Slo(name, f"deadline slack >= {floor_ms:g} ms", counts,
               error_budget=error_budget)


def availability_slo(name: str, ok_hist: Histogram, fail_hist: Histogram,
                     error_budget: float = 0.02) -> Slo:
    """Terminal results vs. failures, both counted over sliding windows."""
    def counts(window_s: float) -> Tuple[int, int]:
        return (ok_hist.window_count(window_s),
                fail_hist.window_count(window_s))
    return Slo(name, "requests reach a successful terminal result", counts,
               error_budget=error_budget)


class SloEvaluator:
    """Multi-window burn-rate evaluation over a set of SLOs.

    Thread-safe: evaluated from the sampler tick, ``/debug/slo``, and
    ``/healthz`` concurrently. PAGE requires BOTH windows over the page
    threshold; WARN requires both over the warn threshold (fast-only
    spikes are visible in the burn gauges but do not change state).
    """

    def __init__(self, slos: List[Slo], fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0, warn_burn: float = 1.0,
                 page_burn: float = 4.0,
                 on_page: Optional[Callable[[str, dict], None]] = None):
        self.slos = list(slos)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.warn_burn = float(warn_burn)
        self.page_burn = float(page_burn)
        self._on_page = on_page if on_page is not None else self._page_event
        self._lock = threading.Lock()
        self._last_state: Dict[str, str] = {}

    @staticmethod
    def _page_event(slo_name: str, report: dict) -> None:
        record_event("slo_page", slo=slo_name,
                     burn_fast=report["burn"]["fast"],
                     burn_slow=report["burn"]["slow"],
                     exemplar_trace_ids=report.get("exemplar_trace_ids", []))

    def evaluate(self) -> List[dict]:
        """Evaluate every SLO now; publishes gauges, fires the PAGE
        trigger on a transition, returns the full report."""
        reports, paged = [], []
        with self._lock:
            for slo in self.slos:
                fast, fg, fb = slo.burn_rate(self.fast_window_s)
                slow, sg, sb = slo.burn_rate(self.slow_window_s)
                both = min(fast, slow)
                if both >= self.page_burn:
                    state = STATE_PAGE
                elif both >= self.warn_burn:
                    state = STATE_WARN
                else:
                    state = STATE_OK
                report = {
                    "slo": slo.name,
                    "objective": slo.objective,
                    "error_budget": slo.error_budget,
                    "state": state,
                    "burn": {"fast": round(fast, 4), "slow": round(slow, 4)},
                    "windows_s": {"fast": self.fast_window_s,
                                  "slow": self.slow_window_s},
                    "events": {"fast": {"good": fg, "bad": fb},
                               "slow": {"good": sg, "bad": sb}},
                    # Top offending traces (newest slowest exemplars) —
                    # each resolves via /debug/autopsy?trace_id=.
                    "exemplar_trace_ids": slo.exemplar_trace_ids(),
                }
                SLO_STATE_GAUGE.set(_STATE_CODES[state], slo=slo.name)
                SLO_BURN_GAUGE.set(round(fast, 4), slo=slo.name,
                                   window="fast")
                SLO_BURN_GAUGE.set(round(slow, 4), slo=slo.name,
                                   window="slow")
                prev = self._last_state.get(slo.name, STATE_OK)
                if state == STATE_PAGE and prev != STATE_PAGE:
                    paged.append((slo.name, report))
                self._last_state[slo.name] = state
                reports.append(report)
        # Trigger sites run OUTSIDE the evaluator lock: the recorder
        # enqueue is cheap but nothing that does I/O belongs under it.
        for name, report in paged:
            self._on_page(name, report)
        return reports

    def states(self) -> Dict[str, str]:
        """Fresh state per SLO (evaluates; cheap — pure window math)."""
        return {r["slo"]: r["state"] for r in self.evaluate()}

    def worst_state(self) -> str:
        states = self.states().values()
        if STATE_PAGE in states:
            return STATE_PAGE
        if STATE_WARN in states:
            return STATE_WARN
        return STATE_OK
