"""Telemetry export: Prometheus text exposition, Chrome-trace JSON, and
the ``jax.profiler`` toggles behind ``POST /debug/profile/{start,stop}``.

- :func:`render_prometheus` serializes a :class:`Registry` in the
  Prometheus text exposition format (version 0.0.4): HELP/TYPE headers,
  escaped label values, cumulative histogram buckets ending at ``+Inf``
  plus ``_sum``/``_count``. ``GET /metrics?format=prometheus`` serves it.
- :func:`chrome_trace` renders a tracer's span ring as a Chrome-trace /
  Perfetto JSON document (``ph: "X"`` complete events, µs timestamps,
  thread-name metadata) — ``GET /debug/trace`` serves it, and
  :func:`dump_trace` writes it to a file for bench/smoke artifacts. Open
  at https://ui.perfetto.dev (drag the file in) or chrome://tracing.
- :func:`start_profile`/:func:`stop_profile` wrap the existing device
  trace toggles (serve/metrics.py → ``jax.profiler``) with idempotence
  bookkeeping so the HTTP endpoints can't double-start a trace.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from vilbert_multitask_tpu.obs.instruments import (
    Histogram,
    Registry,
    REGISTRY,
)
from vilbert_multitask_tpu.obs.trace import Span, Tracer, default_tracer

# ------------------------------------------------------------- prometheus
_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    name = _NAME_SANITIZE_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\")
                 .replace("\n", "\\n")
                 .replace('"', '\\"'))


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _labels(names: Sequence[str], values: Sequence[str],
            extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = [(k, v) for k, v in zip(names, values)] + list(extra)
    if not pairs:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
            + "}")


PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def render_prometheus(registry: Optional[Registry] = None,
                      extra: Sequence = ()) -> str:
    """The whole registry in Prometheus text exposition format.

    ``extra`` appends instruments living outside the registry (e.g. the
    per-``Metrics``-instance request-latency histogram). The registry's
    default labels (process identity, set by ``Registry.set_default_labels``)
    are merged into every sample line here — exposition is the one place
    identity stamping happens, so observe-time call sites stay unchanged.
    """
    registry = registry if registry is not None else REGISTRY
    defaults = list(registry.default_labels().items())
    lines: List[str] = []
    for inst in sorted(registry.instruments() + list(extra),
                       key=lambda i: i.name):
        name = _metric_name(inst.name)
        base = [(k, v) for k, v in defaults if k not in inst.labelnames]
        if inst.help:
            lines.append(f"# HELP {name} {_escape_help(inst.help)}")
        lines.append(f"# TYPE {name} {inst.kind}")
        if isinstance(inst, Histogram):
            for key, series in sorted(inst.collect().items()):
                for bound, cumulative in series["buckets"]:
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels(inst.labelnames, key, [('le', _fmt(bound))] + base)}"
                        f" {cumulative}")
                lines.append(f"{name}_sum{_labels(inst.labelnames, key, base)} "
                             f"{_fmt(series['sum'])}")
                lines.append(f"{name}_count{_labels(inst.labelnames, key, base)} "
                             f"{series['count']}")
        else:
            for key, value in sorted(inst.collect().items()):
                lines.append(
                    f"{name}{_labels(inst.labelnames, key, base)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def render_openmetrics(registry: Optional[Registry] = None,
                       extra: Sequence = ()) -> str:
    """The registry in OpenMetrics text format, exemplars included.

    Differences from :func:`render_prometheus` that matter here: the
    metric *family* name drops a counter's ``_total`` suffix (the sample
    line keeps it), histogram bucket lines carry their bucket's exemplar
    as ``# {trace_id="..."} value timestamp`` — the metrics→trace link
    Grafana/Prometheus follow straight to a stored autopsy — and the
    exposition ends with ``# EOF``. ``GET /metrics?format=openmetrics``
    serves it.
    """
    registry = registry if registry is not None else REGISTRY
    defaults = list(registry.default_labels().items())
    lines: List[str] = []
    for inst in sorted(registry.instruments() + list(extra),
                       key=lambda i: i.name):
        name = _metric_name(inst.name)
        family = (name[: -len("_total")]
                  if inst.kind == "counter" and name.endswith("_total")
                  else name)
        base = [(k, v) for k, v in defaults if k not in inst.labelnames]
        if inst.help:
            lines.append(f"# HELP {family} {_escape_help(inst.help)}")
        lines.append(f"# TYPE {family} {inst.kind}")
        if isinstance(inst, Histogram):
            exemplars = inst.collect_exemplars()
            for key, series in sorted(inst.collect().items()):
                key_ex = exemplars.get(key, {})
                for i, (bound, cumulative) in enumerate(series["buckets"]):
                    line = (f"{name}_bucket"
                            f"{_labels(inst.labelnames, key, [('le', _fmt(bound))] + base)}"
                            f" {cumulative}")
                    ex = key_ex.get(i)
                    if ex is not None:
                        value, trace_id, ts = ex
                        line += (f' # {{trace_id="{_escape_label(trace_id)}"}}'
                                 f" {_fmt(value)} {ts:.3f}")
                    lines.append(line)
                lines.append(f"{name}_sum{_labels(inst.labelnames, key, base)} "
                             f"{_fmt(series['sum'])}")
                lines.append(f"{name}_count{_labels(inst.labelnames, key, base)} "
                             f"{series['count']}")
        else:
            suffix = "_total" if inst.kind == "counter" else ""
            for key, value in sorted(inst.collect().items()):
                lines.append(f"{family}{suffix}"
                             f"{_labels(inst.labelnames, key, base)} "
                             f"{_fmt(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------- chrome trace
def chrome_trace(spans: Optional[Sequence[Span]] = None,
                 tracer: Optional[Tracer] = None,
                 limit: Optional[int] = None) -> Dict[str, Any]:
    """Chrome-trace JSON (``traceEvents``) of the newest ``limit`` spans.

    Timestamps are µs relative to the tracer's monotonic epoch; ``ph: "X"``
    complete events carry trace/span/parent ids and span attributes in
    ``args``, so Perfetto's flow/search tooling can follow one trace_id
    across the HTTP and worker threads.
    """
    tracer = tracer if tracer is not None else default_tracer()
    if spans is None:
        spans = tracer.spans(limit=limit)
    elif limit:
        spans = list(spans)[-limit:]
    pid = os.getpid()
    thread_names: Dict[int, str] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        thread_names.setdefault(s.thread_id, s.thread_name)
        events.append({
            "name": s.name,
            "ph": "X",
            "cat": "obs",
            "ts": round((s.start_s - tracer.epoch_perf) * 1e6, 3),
            "dur": round(s.dur_s * 1e6, 3),
            "pid": pid,
            "tid": s.thread_id,
            "args": {"trace_id": s.trace_id, "span_id": s.span_id,
                     "parent_id": s.parent_id, **s.attrs},
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": tname}}
            for tid, tname in sorted(thread_names.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_trace(path: str, tracer: Optional[Tracer] = None,
               limit: Optional[int] = None) -> str:
    """Write the span ring as a Chrome-trace JSON file; returns ``path``."""
    doc = chrome_trace(tracer=tracer, limit=limit)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return path


# -------------------------------------------------------- profile toggles
_PROFILE_LOCK = threading.Lock()
_PROFILE_DIR: Optional[str] = None


def start_profile(log_dir: str) -> Dict[str, Any]:
    """Begin a ``jax.profiler`` device trace into ``log_dir``.

    Returns ``{"ok": True, "log_dir": ...}`` or ``{"ok": False, "error"}``
    when a trace is already running (jax supports one at a time) or the
    profiler itself refuses — the HTTP surface must answer JSON either way.
    """
    global _PROFILE_DIR
    with _PROFILE_LOCK:
        if _PROFILE_DIR is not None:
            return {"ok": False,
                    "error": f"profile already running into {_PROFILE_DIR}"}
        from vilbert_multitask_tpu.serve.metrics import start_device_trace

        try:
            start_device_trace(log_dir)
        except Exception as e:  # noqa: BLE001 — surface, don't 500
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        _PROFILE_DIR = log_dir
        return {"ok": True, "log_dir": log_dir}


def stop_profile() -> Dict[str, Any]:
    """Stop the running device trace; ``{"ok": False}`` if none is."""
    global _PROFILE_DIR
    with _PROFILE_LOCK:
        if _PROFILE_DIR is None:
            return {"ok": False, "error": "no profile running"}
        from vilbert_multitask_tpu.serve.metrics import stop_device_trace

        log_dir, _PROFILE_DIR = _PROFILE_DIR, None
        try:
            stop_device_trace()
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        return {"ok": True, "log_dir": log_dir}
