"""Process identity: the one name a process answers to fleet-wide.

The reference deployment is multi-process (web tier + queue-fed worker),
but a (host, pid) pair is not a stable identity — pids recycle, and a
worker that crash-loops five times in a minute is five *different*
processes that all look alike in the queue's ``claimed_by`` column. A
:class:`WorkerIdentity` therefore adds a boot nonce minted once per
process: ``host:pid:nonce`` distinguishes incarnations, so a claim row
stamped by a dead incarnation can never be mistaken for the live one.

Minted lazily on first use (:func:`process_identity`) and cached for the
process lifetime; ``role`` is fixed by whichever subsystem mints first
(the ServeApp boot path passes its own). Everything downstream — default
instrument labels, span attributes, queue claim rows, heartbeat rows in
the fleet spine, ``/healthz`` payloads, flight-recorder bundles — reads
the same object, so one process presents one identity everywhere.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class WorkerIdentity:
    """Stable per-process identity, minted once at boot."""

    host: str
    pid: int
    boot_nonce: str  # 8 hex chars, fresh per process incarnation
    role: str  # "serve", "worker", "bench", ... — coarse process kind
    started_unix: float = field(default_factory=time.time)

    @property
    def ident(self) -> str:
        """The canonical fleet-wide key: ``host:pid:nonce``."""
        return f"{self.host}:{self.pid}:{self.boot_nonce}"

    def as_dict(self) -> Dict[str, object]:
        return {"ident": self.ident, "host": self.host, "pid": self.pid,
                "boot_nonce": self.boot_nonce, "role": self.role,
                "started_unix": self.started_unix}

    def labels(self) -> Dict[str, str]:
        """The label pairs stamped onto instruments/spans (small on
        purpose: ``instance`` is the join key, ``role`` the human one)."""
        return {"instance": self.ident, "role": self.role}


def mint_identity(role: str = "worker") -> WorkerIdentity:
    """A fresh identity (new nonce). Tests mint freely; processes should
    go through :func:`process_identity` so there is exactly one."""
    return WorkerIdentity(host=socket.gethostname(), pid=os.getpid(),
                          boot_nonce=uuid.uuid4().hex[:8], role=role)


_LOCK = threading.Lock()
_IDENTITY: Optional[WorkerIdentity] = None


def process_identity(role: Optional[str] = None) -> WorkerIdentity:
    """THE process identity — minted on first call, cached forever.

    The first caller's ``role`` wins (later calls may pass None or the
    same role; a *different* role is ignored rather than re-minting —
    identity must never change mid-process).
    """
    global _IDENTITY
    with _LOCK:
        if _IDENTITY is None:
            _IDENTITY = mint_identity(role or "worker")
        return _IDENTITY


def reset_process_identity() -> None:
    """Forget the cached identity (tests only — a real process keeps one
    identity for life)."""
    global _IDENTITY
    with _LOCK:
        _IDENTITY = None
