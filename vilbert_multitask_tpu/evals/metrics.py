"""Standard metric definitions for the served task families.

- VQA soft accuracy: the official VQAv2 metric — ``min(#annotators who gave
  the predicted answer / 3, 1)``, averaged over all 10-choose-9 annotator
  subsets, which reduces to the closed form below.
- Grounding accuracy: top-1 predicted box hits iff IoU with the ground-truth
  box > 0.5 (the RefCOCO/Visual7W convention).
- Retrieval recall@k: fraction of queries whose aligned image ranks in the
  top k.
"""

from __future__ import annotations

from typing import Sequence


def vqa_soft_accuracy(pred: str, annotator_answers: Sequence[str]) -> float:
    """Official VQAv2 accuracy for one example (10 annotator answers)."""
    pred = pred.strip().lower()
    answers = [a.strip().lower() for a in annotator_answers]
    n = len(answers)
    if n == 0:
        return 0.0
    if n < 4:
        # degenerate annotation sets: plain match-rate
        return sum(a == pred for a in answers) / n
    # average of min(matches_in_subset / 3, 1) over all leave-one-out subsets
    total = 0.0
    for i in range(n):
        matches = sum(1 for j, a in enumerate(answers) if j != i and a == pred)
        total += min(matches / 3.0, 1.0)
    return total / n


def box_iou_single(a: Sequence[float], b: Sequence[float]) -> float:
    """IoU of two xyxy boxes."""
    ax1, ay1, ax2, ay2 = a
    bx1, by1, bx2, by2 = b
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = iw * ih
    union = ((ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter)
    return inter / union if union > 0 else 0.0


def grounding_hit(pred_box: Sequence[float], gt_box: Sequence[float],
                  iou_threshold: float = 0.5) -> bool:
    return box_iou_single(pred_box, gt_box) > iou_threshold


def retrieval_recall_at_k(rank_of_target: int, k: int) -> bool:
    """``rank_of_target`` is 1-based."""
    return rank_of_target <= k
