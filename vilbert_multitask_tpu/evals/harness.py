"""Engine-driven evaluator over JSONL datasets.

JSONL schemas (one example per line):

- vqa / gqa:    {"question": str, "image": key, "answers": [str, ...]}
                (``answers`` = the 10 annotator strings; a single-element
                list works for exact-match sets like GQA)
- grounding:    {"expression": str, "image": key, "gt_box": [x1,y1,x2,y2]}
                (pixel coords in the original image)
- retrieval:    {"caption": str, "images": [key, ...], "target": 0-based idx}
- retrieval_gallery: {"caption": str, "image": key}
                (Flickr30k protocol: every caption ranks against the FULL
                gallery — by default the distinct ``image`` keys of the
                dataset, ~1k for the Flickr30k test split — not the ≤10
                uploaded candidates of the demo task)
- nlvr2:        {"caption": str, "images": [key1, key2], "label": true|false}

Image keys resolve through the engine's FeatureStore (basename-sans-extension
keys, features/store.py). VQA/GQA/grounding examples run through
``engine.run_many`` in bucket-sized micro-batches — the same packed path
serving uses — so evaluation measures the production code path.
"""

from __future__ import annotations

import argparse
import json
import statistics
import time
from typing import Any, Dict, Iterable, List

from vilbert_multitask_tpu.config import TASK_REGISTRY
from vilbert_multitask_tpu.evals import metrics as M


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class Evaluator:
    def __init__(self, engine, *, batch: int = 8):
        self.engine = engine
        self.batch = batch

    # ------------------------------------------------------------ per-task
    def _run_single_image(self, task_id: int, questions: List[str],
                          images: List[str]):
        """Micro-batched single-image forward for a (question, image) list.

        prepare_from_store is the production prepare path (serving _intake
        and predict() use it): it carries the device-input-cache identities,
        so repeat images across eval examples skip the feature upload."""
        results = []
        for i in range(0, len(questions), self.batch):
            reqs = [
                self.engine.prepare_from_store(task_id, q, [img])
                for q, img in zip(questions[i : i + self.batch],
                                  images[i : i + self.batch])
            ]
            results.extend(self.engine.run_many(reqs))
        return results

    def eval_vqa(self, examples: Iterable[Dict], task_id: int = 1) -> Dict:
        examples = list(examples)
        results = self._run_single_image(
            task_id, [e["question"] for e in examples],
            [e["image"] for e in examples])
        accs = [
            M.vqa_soft_accuracy(r.answers[0]["answer"], e["answers"])
            for e, r in zip(examples, results)
        ]
        return {"metric": "vqa_accuracy", "task_id": task_id,
                "n": len(accs), "accuracy": sum(accs) / max(len(accs), 1)}

    def eval_grounding(self, examples: Iterable[Dict],
                       task_id: int = 11) -> Dict:
        examples = list(examples)
        results = self._run_single_image(
            task_id, [e["expression"] for e in examples],
            [e["image"] for e in examples])
        hits = [
            M.grounding_hit(r.boxes[0]["box_xyxy"], e["gt_box"])
            for e, r in zip(examples, results)
        ]
        return {"metric": "grounding_acc@0.5", "task_id": task_id,
                "n": len(hits), "accuracy": sum(hits) / max(len(hits), 1)}

    def _run_multi_image(self, task_id: int, captions: List[str],
                         image_lists: List[List[str]]):
        """Micro-batched multi-image forwards: run_many packs mixed image
        counts into shared chunks (each request's rows consecutive), so
        retrieval candidate sets and NLVR2 pairs batch instead of paying
        one dispatch per example (``batch`` counts examples per call)."""
        results = []
        for i in range(0, len(captions), self.batch):
            reqs = [
                self.engine.prepare_from_store(task_id, cap, keys)
                for cap, keys in zip(captions[i : i + self.batch],
                                     image_lists[i : i + self.batch])
            ]
            results.extend(self.engine.run_many(reqs))
        return results

    def eval_retrieval(self, examples: Iterable[Dict],
                       task_id: int = 7) -> Dict:
        examples = list(examples)
        results = self._run_multi_image(
            task_id, [e["caption"] for e in examples],
            [e["images"] for e in examples])
        r1 = r5 = r10 = 0
        for e, result in zip(examples, results):
            target_key = e["images"][e["target"]]
            rank = next(r["rank"] for r in result.ranking
                        if r["image"] == target_key)
            r1 += M.retrieval_recall_at_k(rank, 1)
            r5 += M.retrieval_recall_at_k(rank, 5)
            r10 += M.retrieval_recall_at_k(rank, 10)
        n = max(len(examples), 1)
        return {"metric": "retrieval_recall", "task_id": task_id,
                "n": len(examples), "R@1": r1 / n, "R@5": r5 / n,
                "R@10": r10 / n}

    def eval_retrieval_gallery(self, examples: Iterable[Dict],
                               task_id: int = 7,
                               gallery: List[str] | None = None,
                               chunk: int | None = None) -> Dict:
        """Benchmark-protocol image retrieval: rank each caption against an
        N-image gallery (BASELINE "Flickr30k IR R@1"; N≈1000), vs the demo
        task's ≤10 uploaded candidates (reference worker.py:278-284 scores
        only the uploaded set — demo parity lives in :meth:`eval_retrieval`).

        The gallery is split into ≤``chunk``-image task-7 requests whose raw
        per-image ``vil_logit`` scores are comparable across forwards (each
        batch row scores (caption, image) independently; the softmax in
        decode_ranking is presentation only). run_many packs the chunk
        requests of ``batch`` captions into throughput-bucket-sized
        forwards, and the device input cache keeps gallery features
        resident after the first caption — each later caption ships only
        its text.

        The target's rank counts strictly-greater scores, so ties break in
        the model's favor (a deterministic, standard choice).
        """
        examples = list(examples)
        if gallery is None:
            gallery = [e["image"] for e in examples]
        # Dataset order, first occurrence wins — the standard protocol
        # galleries are exactly the split's distinct images. Explicit
        # galleries dedupe too: a repeated key would waste a forward and
        # shift chunk boundaries without changing any rank.
        gallery = list(dict.fromkeys(gallery))
        spec = TASK_REGISTRY[task_id]
        if chunk is None:
            chunk = min(spec.max_images,
                        self.engine.cfg.engine.max_batch_rows())
        if not (spec.min_images <= chunk <= spec.max_images):
            raise ValueError(
                f"chunk={chunk} outside task {task_id}'s "
                f"{spec.min_images}..{spec.max_images} images/request")
        missing = {e["image"] for e in examples} - set(gallery)
        if missing:
            raise ValueError(
                f"{len(missing)} target images absent from the gallery, "
                f"e.g. {sorted(missing)[:3]}")
        chunks = [gallery[i : i + chunk]
                  for i in range(0, len(gallery), chunk)]
        if len(chunks) > 1 and len(chunks[-1]) < spec.min_images:
            # Undersized tail: merge the last two chunks and re-split into
            # halves so BOTH stay >= min_images (shaving one element off the
            # donor could push it under the gate too, e.g. chunk=2 over 5
            # images). When even halves can't both clear the gate (combined
            # size 3 at min 2) keep one merged chunk — combined = chunk +
            # tail <= max + (min-1), and min*2 <= max for every registry
            # task, so a merged fallback chunk always fits max_images.
            merged = chunks[-2] + chunks[-1]
            half = len(merged) // 2
            if half >= spec.min_images and len(merged) - half <= spec.max_images:
                chunks[-2:] = [merged[:-half], merged[-half:]]
            else:
                chunks[-2:] = [merged]
        ranks: List[int] = []
        step = max(1, self.batch)
        for i in range(0, len(examples), step):
            window = examples[i : i + step]
            reqs = [self.engine.prepare_from_store(task_id, e["caption"], c)
                    for e in window for c in chunks]
            results = self.engine.run_many(reqs)
            for j, e in enumerate(window):
                scores: Dict[str, float] = {}
                for res in results[j * len(chunks):(j + 1) * len(chunks)]:
                    for entry in res.ranking:
                        scores[entry["image"]] = entry["score"]
                target = scores[e["image"]]
                ranks.append(1 + sum(
                    1 for img, s in scores.items()
                    if s > target and img != e["image"]))
        n = max(len(ranks), 1)
        return {"metric": "retrieval_gallery_recall", "task_id": task_id,
                "n": len(ranks), "n_gallery": len(gallery),
                "chunk": chunk,
                "R@1": sum(r <= 1 for r in ranks) / n,
                "R@5": sum(r <= 5 for r in ranks) / n,
                "R@10": sum(r <= 10 for r in ranks) / n,
                # statistics.median == the protocol "Med r" (np.median):
                # mean of the two middles on even counts.
                "median_rank": (float(statistics.median(ranks))
                                if ranks else None)}

    def eval_nlvr2(self, examples: Iterable[Dict], task_id: int = 12) -> Dict:
        examples = list(examples)
        results = self._run_multi_image(
            task_id, [e["caption"] for e in examples],
            [e["images"] for e in examples])
        correct = 0
        for e, result in zip(examples, results):
            pred = result.answers[0]["answer"] == "True"
            correct += pred == bool(e["label"])
        n = max(len(examples), 1)
        return {"metric": "nlvr2_accuracy", "task_id": task_id,
                "n": len(examples), "accuracy": correct / n}

    # ---------------------------------------------------------------- entry
    EVAL_FNS = {
        "vqa": ("eval_vqa", 1),
        "gqa": ("eval_vqa", 15),
        "grounding": ("eval_grounding", 11),
        "visual7w": ("eval_grounding", 4),
        "retrieval": ("eval_retrieval", 7),
        "retrieval_gallery": ("eval_retrieval_gallery", 7),
        "nlvr2": ("eval_nlvr2", 12),
    }

    def run(self, task: str, examples: Iterable[Dict], **kwargs) -> Dict:
        if task not in self.EVAL_FNS:
            raise ValueError(f"unknown eval task {task!r}; "
                             f"one of {sorted(self.EVAL_FNS)}")
        fn_name, task_id = self.EVAL_FNS[task]
        t0 = time.perf_counter()
        out = getattr(self, fn_name)(examples, task_id=task_id, **kwargs)
        out["wall_s"] = round(time.perf_counter() - t0, 3)
        return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="score-parity evaluation")
    p.add_argument("--task", required=True,
                   choices=sorted(Evaluator.EVAL_FNS))
    p.add_argument("--data", required=True, help="JSONL examples")
    p.add_argument("--features", required=True,
                   help="precomputed feature dir")
    p.add_argument("--checkpoint", default=None, help="Orbax params dir")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--gallery", default=None,
                   help="retrieval_gallery only: file of image keys (one "
                        "per line) to rank against instead of the "
                        "dataset's distinct targets")
    p.add_argument("--gallery-chunk", type=int, default=None,
                   help="retrieval_gallery only: images per scoring "
                        "request (default: task max, 10)")
    from vilbert_multitask_tpu.config import (
        FrameworkConfig,
        add_backend_args,
        apply_backend_args,
    )

    add_backend_args(p)
    args = p.parse_args(argv)

    cfg = apply_backend_args(FrameworkConfig(), args)

    from vilbert_multitask_tpu.engine.runtime import InferenceEngine
    from vilbert_multitask_tpu.features.store import FeatureStore

    params = None
    if args.checkpoint:
        from vilbert_multitask_tpu.checkpoint import restore_params

        params = restore_params(args.checkpoint)
    engine = InferenceEngine(cfg, params=params,
                             feature_store=FeatureStore(args.features))
    kwargs = {}
    if args.task == "retrieval_gallery":
        if args.gallery:
            with open(args.gallery) as f:
                kwargs["gallery"] = [ln.strip() for ln in f if ln.strip()]
        if args.gallery_chunk:
            kwargs["chunk"] = args.gallery_chunk
    result = Evaluator(engine, batch=args.batch).run(
        args.task, load_jsonl(args.data), **kwargs)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
