"""Evaluation harness: the score-parity metrics from BASELINE.md.

The reference repo publishes no evaluation code (scores live in the 12-in-1
paper, reference README.md:6); the driver's BASELINE.json nevertheless sets
score parity — VQAv2 accuracy, image-retrieval R@1, RefCOCO accuracy — as an
acceptance metric. This package provides the harness: dataset readers
(simple JSONL schemas), the standard metric definitions, and a batched
engine-driven evaluator with a CLI.
"""

from vilbert_multitask_tpu.evals.metrics import (
    box_iou_single,
    grounding_hit,
    retrieval_recall_at_k,
    vqa_soft_accuracy,
)
from vilbert_multitask_tpu.evals.harness import Evaluator, load_jsonl

__all__ = [
    "Evaluator",
    "box_iou_single",
    "grounding_hit",
    "load_jsonl",
    "retrieval_recall_at_k",
    "vqa_soft_accuracy",
]
