"""One typed config tree for the whole framework.

The reference spreads configuration over five uncoordinated mechanisms
(SURVEY.md §5): hardcoded SimpleNamespace blobs (reference worker.py:67-76,
470-493), a BertConfig JSON plus post-hoc attribute pokes (worker.py:495-522),
a YAML task registry (worker.py:496-503), a YACS detector config (worker.py:79),
and Django settings. This module collapses all five into frozen dataclasses:

- :class:`ViLBertConfig`   — the model (mirrors config/bert_base_6layer_6conect.json
  plus the overrides applied at worker.py:509-522).
- :class:`TaskSpec` / :data:`TASK_REGISTRY` — the 8 served task types
  (UI dropdown result.html:318-336; dispatch worker.py:250-263).
- :class:`EngineConfig`    — inference runtime (shape buckets, dtypes, mesh).
- :class:`ServingConfig`   — queue/HTTP/websocket/DB tier.
- :class:`FrameworkConfig` — the root aggregate.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class ViLBertConfig:
    """Two-stream ViLBERT architecture knobs.

    Field names follow the reference config JSON (``bert_base_6layer_6conect.json``,
    loaded at reference worker.py:472,495) so checkpoints and configs translate
    1:1. Defaults are the values the reference demo actually serves with,
    including the runtime overrides at worker.py:509-523 (``v_target_size=1601``,
    ``predict_feature=False``, ``task_specific_tokens=True``,
    ``visualization=True``, ``num_labels=3129``).
    """

    # --- text stream (BERT-base) ---
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12

    # --- visual stream ---
    v_feature_size: int = 2048
    v_target_size: int = 1601
    v_hidden_size: int = 1024
    v_num_hidden_layers: int = 6
    v_num_attention_heads: int = 8
    v_intermediate_size: int = 1024
    v_hidden_act: str = "gelu"
    v_hidden_dropout_prob: float = 0.1
    v_attention_probs_dropout_prob: float = 0.1
    v_initializer_range: float = 0.02

    # --- co-attention bridge ---
    bi_hidden_size: int = 1024
    bi_num_attention_heads: int = 8
    bi_intermediate_size: int = 1024
    # Text layer i in t_biattention_id co-attends with visual layer j at the
    # same position in v_biattention_id ("6 connect" in the config name).
    v_biattention_id: Sequence[int] = (0, 1, 2, 3, 4, 5)
    t_biattention_id: Sequence[int] = (6, 7, 8, 9, 10, 11)
    fusion_method: str = "mul"  # pooled_t ∘ pooled_v fusion for vil_* heads

    # --- behavior flags (reference worker.py:509-523) ---
    predict_feature: bool = False
    task_specific_tokens: bool = True
    num_task_tokens: int = 20  # task-token embedding table size
    dynamic_attention: bool = False
    visualization: bool = True  # return per-layer attention maps (10th output)
    # Run the co-attention bridges through the Pallas flash kernel
    # (ops/coattention.py). Off when attention maps are requested — the
    # blockwise kernel never materializes probabilities.
    use_pallas_coattention: bool = False
    # Same kernel for the single-stream self-attention; a stream only takes
    # the kernel path when its head_dim fills 128-lane tiles exactly (the
    # 1024/8 visual stream does; BERT-base text's 64 would waste half the
    # MXU, so it stays on XLA).
    use_pallas_self_attention: bool = False
    # Rematerialize encoder layers in the backward pass (jax.checkpoint via
    # nn.remat): trades ~30% more FLOPs for activation memory that scales
    # with ONE layer instead of the full 18-layer stack — the standard HBM
    # lever for large-batch training.
    remat: bool = False

    # --- heads ---
    num_labels: int = 3129  # VQA answer space (worker.py:523)
    gqa_num_labels: int = 1533  # GQA answer space (12-in-1 head width)

    def __post_init__(self):
        if len(self.v_biattention_id) != len(self.t_biattention_id):
            raise ValueError("v_biattention_id and t_biattention_id must pair up")
        if self.hidden_size % self.num_attention_heads:
            raise ValueError("hidden_size must divide num_attention_heads")
        if self.v_hidden_size % self.v_num_attention_heads:
            raise ValueError("v_hidden_size must divide v_num_attention_heads")
        if self.bi_hidden_size % self.bi_num_attention_heads:
            raise ValueError("bi_hidden_size must divide bi_num_attention_heads")

    @property
    def num_connection_layers(self) -> int:
        return len(self.v_biattention_id)

    @classmethod
    def from_json_file(cls, path: str) -> "ViLBertConfig":
        """Load a reference-format config JSON (ignores unknown keys)."""
        with open(path) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["v_biattention_id"] = list(self.v_biattention_id)
        d["t_biattention_id"] = list(self.t_biattention_id)
        return json.dumps(d, indent=2, sort_keys=True)

    def tiny(self, **overrides) -> "ViLBertConfig":
        """A scaled-down config for CPU tests (same topology, small dims)."""
        small = dict(
            # >= the committed assets/wordpiece_vocab.txt size, so tiny
            # models accept ids from the default serving tokenizer.
            vocab_size=1088,
            hidden_size=48,
            num_hidden_layers=4,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=64,
            v_feature_size=32,
            v_target_size=11,
            v_hidden_size=32,
            v_num_hidden_layers=2,
            v_num_attention_heads=2,
            v_intermediate_size=32,
            bi_hidden_size=32,
            bi_num_attention_heads=2,
            bi_intermediate_size=32,
            v_biattention_id=(0, 1),
            t_biattention_id=(2, 3),
            num_labels=17,
            gqa_num_labels=13,
        )
        small.update(overrides)
        return dataclasses.replace(self, **small)


@dataclasses.dataclass(frozen=True)
class TaskSpec:
    """One served task type (reference: UI dropdown result.html:318-336 +
    worker dispatch worker.py:250-263,295-386)."""

    task_id: int
    name: str
    head: str  # which model output decodes this task
    decode: str  # decode family: "labels" | "binary" | "trinary" | "ranking" | "grounding"
    min_images: int
    max_images: int
    top_k: int  # how many ranked answers the demo shows
    label_map: str | None = None  # key into the label-map store, if any
    description: str = ""
    placeholder: str = ""

    def validate_num_images(self, n: int) -> None:
        """Image-count gating, matching the asserts at worker.py:256-263."""
        if not (self.min_images <= n <= self.max_images):
            raise ValueError(
                f"task {self.task_id} ({self.name}) requires "
                f"{self.min_images}..{self.max_images} images, got {n}"
            )


# The 8 served task types. task_id values are the reference's wire protocol —
# they appear in queue messages (demo/sender.py:26-31) and the UI (result.html:318-336).
TASK_REGISTRY: Mapping[int, TaskSpec] = {
    t.task_id: t
    for t in [
        TaskSpec(1, "VQA", head="vil_prediction", decode="labels", min_images=1,
                 max_images=1, top_k=3, label_map="vqa",
                 description="Visual question answering (VQAv2)",
                 placeholder="e.g. What is the man holding?"),
        TaskSpec(2, "VQA-variant", head="vil_prediction", decode="labels", min_images=1,
                 max_images=1, top_k=3, label_map="vqa",
                 description="Alias of VQA; decodable but absent from the reference UI "
                             "(worker.py:295,564 vs result.html:318-336)"),
        TaskSpec(15, "GQA", head="vil_prediction_gqa", decode="labels", min_images=1,
                 max_images=1, top_k=3, label_map="gqa",
                 description="Spatial-reasoning QA (GQA)",
                 placeholder="e.g. Is the bowl to the right of the mug?"),
        TaskSpec(4, "Visual7W", head="vision_logit", decode="grounding", min_images=1,
                 max_images=1, top_k=3,
                 description="Pointing QA — answer is a box",
                 placeholder="e.g. Which object can you eat?"),
        TaskSpec(11, "RefCOCO", head="vision_logit", decode="grounding", min_images=1,
                 max_images=1, top_k=3,
                 description="Referring-expression grounding",
                 placeholder="e.g. the woman in the red coat"),
        TaskSpec(16, "GuessWhat", head="vision_logit", decode="grounding", min_images=1,
                 max_images=1, top_k=3,
                 description="Referring dialog grounding (Q:..? A:.. format)",
                 placeholder="e.g. Q: is it a person? A: no Q: is it red? A: yes"),
        TaskSpec(13, "SNLI-VE", head="vil_tri_prediction", decode="trinary", min_images=1,
                 max_images=1, top_k=3,
                 description="Visual entailment: contradiction/neutral/entailment",
                 placeholder="e.g. Two dogs are playing in the snow."),
        TaskSpec(12, "NLVR2", head="vil_binary_prediction", decode="binary", min_images=2,
                 max_images=2, top_k=2,
                 description="Does the caption describe the image pair? True/False",
                 placeholder="e.g. Both images contain exactly two wolves."),
        TaskSpec(7, "Retrieval", head="vil_logit", decode="ranking", min_images=2,
                 max_images=10, top_k=0,  # top_k=#images, resolved at decode time
                 description="Caption-based image retrieval over the uploaded set",
                 placeholder="e.g. A man riding a horse on the beach."),
    ]
}

# Decode label maps that are fixed (not loaded from disk).
NLVR2_LABELS = ("False", "True")  # worker.py:327
SNLI_VE_LABELS = ("contradiction (false)", "neutral", "entailment (true)")  # worker.py:342


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Inference-runtime knobs (replaces the SimpleNamespace blob at
    reference worker.py:470-493 and the implicit shapes in custom_prediction)."""

    max_text_len: int = 37  # wordpiece tokens incl. [CLS]/[SEP] (worker.py:408)
    max_regions: int = 101  # 100 detector boxes + 1 global feature (worker.py:71,433)
    num_features: int = 100  # detector boxes kept per image (worker.py:71)
    # Static shape buckets for the image axis: NLVR2 needs 2, retrieval 2..10
    # (worker.py:256-284). Each bucket compiles once.
    image_buckets: Sequence[int] = (1, 2, 4, 8, 10)
    # Row buckets used ONLY by run_many's chunking (the queue-backlog
    # batched path). The image buckets top out at 10 for retrieval
    # semantics, which caps batched MFU near 0.5%; throughput-sized chunks
    # keep the MXU fed — one batch-32 forward is ~0.8 TFLOP of real work
    # per dispatch. The intermediate 16 keeps mid-size batches (11-31 rows)
    # off the 32-row padding cliff. None/() → chunk at max(image_buckets)
    # (the round-3 behavior).
    throughput_buckets: Sequence[int] | None = (16, 32)
    compute_dtype: str = "bfloat16"  # MXU-native compute precision
    # Param STORAGE dtype for serving (init_params / checkpoint restore /
    # mesh placement all cast to it). "bfloat16" halves every weight read —
    # at serving batch sizes the forward is weight-read-bound (see
    # engine/flops.py roofline), so this is the serving-latency knob — and
    # halves the boot upload. "int8" halves it AGAIN: floating matrix
    # leaves are stored as per-channel symmetric {"int8", "scale"} pairs
    # (quant.py) and dequantized inside the jitted forward right before
    # each matmul, so HBM reads stay int8. Training is unaffected: the
    # trainer owns its own f32 master tree, and checkpoints on disk stay
    # f32 — quantization happens at the serving cast seam only.
    param_dtype: str = "float32"
    # Run the nine per-task decode heads as ONE batched program (stacked
    # weight slabs + in-program gather by task id, engine/runtime.py)
    # instead of nine sequential small matmuls. Mixed-task chunks stop
    # fragmenting into per-head dispatches; numerics match the per-head
    # path to LayerNorm rounding (~1e-6 f32). Off → the round-3 per-head
    # path, which the parity tests pin against.
    fused_task_heads: bool = True
    # Default ON (round 3): serving runs the flash co-attention kernel on
    # TPU; bench.py probe-compiles it and degrades to the XLA path if Mosaic
    # rejects it on the current backend. Off-TPU the kernel runs in
    # interpreter mode (same numerics, slower) — tests pin whichever path
    # they mean to exercise.
    use_pallas_coattention: bool = True
    use_pallas_self_attention: bool = True  # 128-aligned streams only
    # Region-count threshold for sequence-parallel ring attention on the
    # visual stream (parallel/ring.py): buckets at or above it route
    # v-stream self-attention through the mesh's "sp" axis (MeshConfig.sp
    # > 1), below it the dense path wins (ppermute latency beats the HBM
    # saving at demo scale — 101 regions). Static per compiled bucket.
    ring_min_regions: int = 256
    # Text/label assets. None → the committed defaults in assets/ (real
    # file-loading code paths; swap the files for the genuine bert-base-
    # uncased vocab / reference label pickles to get score parity).
    vocab_path: str | None = None
    labels_root: str | None = None
    # Persistent XLA compilation cache (process-global when set): serving
    # restarts and bench attempts skip the ~15s/bucket compile after the
    # first boot on a given chip generation. None → JAX default (off).
    compilation_cache_dir: str | None = None
    # Floor (seconds) below which XLA skips persisting a compilation to
    # compilation_cache_dir (jax_persistent_cache_min_compile_time_secs).
    # None → auto: 0.0 when the AOT cache is enabled (the small per-bucket
    # programs that dominate warmup count must persist too), else the JAX
    # default of 2.0.
    persistent_cache_min_compile_secs: float | None = None
    # AOT executable cache (engine/aotcache.py): serialized compiled
    # programs keyed by COMPILE_SURFACE.json record keys + a compatibility
    # fingerprint, stored next to the checkpoint. Warm boots deserialize
    # instead of trace+compile; misses compile and backfill. None → off.
    # serve/app.py defaults it next to the checkpoint when one is given.
    aot_cache_dir: str | None = None
    # Compile shape buckets concurrently at warmup — XLA compilation is C++
    # and releases the GIL, so 5 buckets warm in ~the longest single compile.
    parallel_warmup: bool = True
    # Device-side input cache (LRU entries): store-backed images are
    # content-stable, so their encoded region tensors are constants — pin
    # them in HBM after the first request instead of re-uploading ~0.4 MB/
    # image (bf16) per query over the host↔TPU link. 0 disables. Keys are
    # explicit (engine.prepare cache_keys) — never inferred from synthetic
    # path defaults. Entries are single image ROWS (max_regions ×
    # v_feature_size ≈ 0.41 MB bf16 / 0.83 MB f32 at serving size), shared
    # across buckets; eviction is entry-count LRU, so 64 entries ≈ 26 MB
    # bf16 (53 MB f32) against the v5e's 16 GB HBM.
    device_input_cache_entries: int = 64

    def bucket_for(self, n_images: int) -> int:
        for b in self.image_buckets:
            if n_images <= b:
                return b
        raise ValueError(f"no shape bucket holds {n_images} images")

    def all_row_buckets(self) -> list:
        """Every compiled row count serving can dispatch: the image buckets
        (run()) plus the throughput buckets (run_many), sorted. The single
        source for warmup coverage and chunk-fitting."""
        return sorted({*self.image_buckets,
                       *(self.throughput_buckets or ())})

    def row_bucket_for(self, n_rows: int) -> int:
        """Smallest compiled row count that fits a run_many chunk (batched
        rows are independent single-image requests, so the image-axis
        semantics of bucket_for don't constrain them)."""
        if n_rows < 1:
            raise ValueError(f"row count must be >=1, got {n_rows}")
        for b in self.all_row_buckets():
            if n_rows <= b:
                return b
        raise ValueError(f"no row bucket holds {n_rows} rows")

    def max_batch_rows(self) -> int:
        """Largest compiled row count — run_many's chunk size and the
        natural drain depth for a backlogged worker."""
        return max(max(self.image_buckets),
                   *(self.throughput_buckets or (0,)))


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    """Faster R-CNN region-feature extractor (detect/model.py).

    Defaults mirror the reference's X-152-32x8d-FPN geometry
    (maskrcnn_benchmark driven from reference worker.py:59-89): ResNeXt
    bottleneck stages (3, 8, 36, 3) with 32 groups × width 8, a 256-channel
    FPN, class-agnostic proposals, fc6 2048-d region features, 1601 VG
    classes. ``tiny()`` scales the same topology down for CPU tests.
    The serving default remains precomputed features (BASELINE.json);
    live extraction is the sanctioned stretch for novel uploads.
    """

    # --- backbone (ResNeXt) ---
    stem_channels: int = 64
    stage_blocks: Sequence[int] = (3, 8, 36, 3)  # X-152
    groups: int = 32
    width_per_group: int = 8
    stage_channels: Sequence[int] = (256, 512, 1024, 2048)
    # --- FPN ---
    fpn_channels: int = 256
    # --- RPN ---
    anchor_sizes: Sequence[int] = (32, 64, 128, 256, 512)  # per level P2..P6
    aspect_ratios: Sequence[float] = (0.5, 1.0, 2.0)
    rpn_pre_nms_top_n: int = 1000
    rpn_post_nms_top_n: int = 300
    rpn_nms_thresh: float = 0.7
    # --- ROI box head ---
    roi_resolution: int = 7
    roi_sampling: int = 2
    representation_size: int = 2048  # fc6/fc7 width → the ViLBERT v_feature
    num_classes: int = 1601  # VG classes incl. background col 0
    # --- input canvas (static shapes for XLA) ---
    canvas: int = 1344  # fits short-side-800/long-side-1333 preprocessing

    def tiny(self, **overrides) -> "DetectorConfig":
        small = dict(
            stem_channels=8, stage_blocks=(1, 1, 1, 1), groups=2,
            width_per_group=4, stage_channels=(16, 32, 64, 128),
            fpn_channels=16, rpn_pre_nms_top_n=64, rpn_post_nms_top_n=32,
            roi_resolution=3, roi_sampling=2, representation_size=32,
            num_classes=7, canvas=64,
        )
        small.update(overrides)
        return dataclasses.replace(self, **small)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout. The reference has no intra-model parallelism
    (SURVEY.md §2.3); here DP×TP over ICI is first-class."""

    dp: int = -1  # -1: all remaining devices
    tp: int = 1
    # Sequence-parallel axis size (ring attention over the visual stream,
    # parallel/ring.py). 1 = no sp axis; >1 adds an "sp" mesh axis and
    # engine/trainer route long region sets through the ring when they
    # clear EngineConfig.ring_min_regions.
    sp: int = 1
    axis_names: Sequence[str] = ("dp", "tp")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Web/queue tier (replaces Django settings + demo/constants.py +
    sender/worker pika constants)."""

    queue_name: str = "vilbert_multitask_queue"  # wire-compatible (sender.py:18)
    queue_db_path: str = "serve_state/queue.sqlite3"
    results_db_path: str = "serve_state/results.sqlite3"
    media_root: str = "media"
    refer_expr_dir: str = "refer_expressions_task"  # worker.py:600
    http_host: str = "127.0.0.1"
    http_port: int = 8400
    ws_port: int = 8401
    max_upload_images: int = 10
    max_delivery_attempts: int = 3  # poison-message bound (fixes worker.py:650-655)
    lowercase_questions: bool = True  # reference lowercases server-side (views.py:27)
    # Shared secret for the /worker/* endpoints (remote workers, serve/remote.py).
    # None → open, matching the reference broker's default-credentials posture
    # (sender.py:12-15); set it when workers cross host boundaries.
    worker_token: str | None = None
    # Shared secret for the ADMIN WRITE surface (POST /admin/*). The
    # reference's Django admin is login-gated (demo/admin.py); here edits
    # mutate the persistent task catalog, so when set, writes require
    # ``Authorization: Bearer <token>`` (admin.html prompts for it).
    # None → open — acceptable only on the loopback default bind.
    admin_token: str | None = None
    # --- resilience/ knobs (see ARCHITECTURE.md "Resilience") ---
    # Time budget minted at POST / and carried in the job body; the worker
    # and engine terminate expired jobs with a terminal push instead of
    # dispatching a forward. None disables deadlines; a per-request
    # "deadline_s" in the submit payload overrides the default.
    default_deadline_s: float | None = 300.0
    # Admission control at the HTTP door: shed with 429 + Retry-After when
    # pending+inflight depth, or the oldest pending job's age, crosses a
    # threshold (0 disables that signal).
    admission_max_queue_depth: int = 512
    admission_max_queue_age_s: float = 120.0
    admission_retry_after_s: float = 2.0
    # Shared RetryPolicy shape for the remote-worker transport (full
    # jitter; the per-process RetryBudget bounds total retry volume).
    retry_max_attempts: int = 5
    retry_base_delay_s: float = 0.5
    retry_max_delay_s: float = 30.0
    # CircuitBreaker over the remote transport: trip after
    # breaker_failure_threshold failures within breaker_window_s, probe
    # again after breaker_reset_timeout_s.
    breaker_failure_threshold: int = 5
    breaker_window_s: float = 30.0
    breaker_reset_timeout_s: float = 10.0
    # Graceful drain: how long stop() waits for the worker to finish
    # in-flight jobs before releasing them back to the queue.
    drain_grace_s: float = 10.0
    # --- replica pool (serve/pool.py) ---
    # Engine replicas behind the queue/scheduler seam: separate devices or
    # mesh shards on hardware, CPU threads in dryrun. 1 keeps the
    # single-engine data path but still health-gates it through the pool.
    pool_replicas: int = 1
    # How long checkout() waits for a ready replica before raising
    # NoReadyReplica (jobs stay queued; the durable queue absorbs brief
    # all-replicas-busy or rolling-swap windows).
    pool_checkout_timeout_s: float = 30.0
    # Dispatches a single replica may hold concurrently. 1 = strictly
    # serial per replica (scaling comes from replica count alone).
    pool_max_inflight_per_replica: int = 1
    # Per-replica dispatch breaker: stricter than the engine's own funnel
    # breaker — a replica that keeps failing leaves the rotation
    # (ready→degraded) after this many failures in the window, and is
    # probed again (half-open checkout) after the reset timeout.
    pool_breaker_failure_threshold: int = 3
    pool_breaker_window_s: float = 30.0
    pool_breaker_reset_timeout_s: float = 5.0
    # Rolling checkpoint swap: max seconds to wait for a draining replica's
    # in-flight dispatches to finish before swapping params anyway.
    pool_swap_drain_timeout_s: float = 30.0
    # Total deliveries (claims) a job gets before the queue dead-letters
    # it as poison — counts every redelivery, including visibility-timeout
    # and release()-based failover redeliveries that charge no *attempt*.
    queue_max_deliveries: int = 3
    # --- continuous-batching scheduler (serve/scheduler.py) ---
    # When enabled, run_forever drains through the pipelined three-stage
    # data plane (intake pool -> EDF window scheduler -> completion stage)
    # instead of the synchronous step_batch loop.
    sched_enabled: bool = True
    # Intake pool width: threads claiming jobs and running feature I/O +
    # prep concurrently with the device forward.
    sched_intake_threads: int = 4
    # Max READY (claimed + prepped, undispatched) jobs. Doubles as intake
    # backpressure AND the admission signal: ready jobs stay 'inflight' in
    # the durable queue, so they keep counting against the
    # AdmissionController's pending+inflight depth at the HTTP door.
    sched_ready_depth: int = 64
    # Adaptive batching window bounds: the scheduler lingers up to the
    # current window for co-arriving jobs before firing a partial batch;
    # the window stretches (x2 up to max) after full buckets and shrinks
    # (/2 down to min) after partial ones, so an idle system fires nearly
    # immediately and a backlogged one packs bigger batches.
    sched_window_min_s: float = 0.002
    sched_window_max_s: float = 0.05
    # A ready member whose deadline slack drops below this fires the batch
    # immediately (EDF front of the queue must not wait out the window).
    sched_near_deadline_ms: float = 250.0
    # Bound on completed-but-unpersisted results queued to the completion
    # stage (persist/push backpressure on the dispatch thread).
    sched_completion_depth: int = 128
    # --- obs/ live-health knobs (see ARCHITECTURE.md "SLOs & flight
    # recorder") ---
    # Background sampler: snapshot cadence and ring length of the
    # in-process time-series store (points per series; at a 1 s cadence
    # 512 points ≈ the last 8.5 minutes).
    sampler_cadence_s: float = 1.0
    timeseries_points: int = 512
    # Multi-window burn-rate evaluation: PAGE/WARN need the burn over the
    # threshold on BOTH windows (fast = "happening now", slow =
    # "sustained").
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0
    slo_warn_burn: float = 1.0
    slo_page_burn: float = 4.0
    # SLO targets: e2e latency p-objective, availability, and the
    # deadline-slack floor ROADMAP item 1 asks evidence for. Budgets are
    # the allowed bad-event ratio per objective.
    slo_e2e_target_ms: float = 2000.0
    slo_e2e_budget: float = 0.05
    slo_availability_budget: float = 0.02
    slo_slack_floor_ms: float = 1000.0
    slo_slack_budget: float = 0.05
    # Flight recorder: bundle directory (under serve_state by default so
    # a soak tmpdir sweeps it), rotation/size caps, spans per bundle, and
    # the per-event re-trigger floor.
    recorder_dir: str = "serve_state/postmortem"
    recorder_max_bundles: int = 16
    recorder_max_bytes: int = 1_000_000
    recorder_spans: int = 256
    recorder_min_interval_s: float = 30.0
    # Fleet observability spine (obs/fleet.py): every process's sampler
    # tick flushes instrument snapshots, timeseries deltas, spans, and a
    # heartbeat into a shared WAL sqlite db (next to the queue db when
    # unset), so any process can answer ?scope=fleet queries for the
    # whole fleet. A peer whose heartbeat is older than the staleness
    # bound is treated as dead (SIGKILL leaves no tombstone).
    fleet_enabled: bool = True
    fleet_db_path: str | None = None
    fleet_heartbeat_stale_s: float = 15.0
    fleet_max_spans: int = 2048
    fleet_spans_per_flush: int = 256
    fleet_timeseries_window_s: float = 600.0
    # Cost attribution + durable trace store (obs/attrib.py,
    # obs/tracestore.py): per-job stage/device-second accounting and
    # tail-sampled trace persistence on the fleet spine db. The keep
    # policy is verdict-based — non-ok terminals always persist, the
    # top-K slowest completions per task persist, the rest are
    # p-sampled — and rows older than the retention window are trimmed
    # on each flush.
    attrib_enabled: bool = True
    tracestore_keep_top_k: int = 8
    tracestore_sample_rate: float = 0.05
    tracestore_retention_s: float = 3600.0
    # --- duplicate-traffic tier (serve/resultcache.py; ROADMAP item 3) ---
    # Durable result cache: a WAL-sqlite table next to the jobs table
    # (same db file), keyed on (task, feature-content hash, canonical
    # question, config fingerprint/model generation). Hits skip the
    # queue and TPU entirely; a rolling swap bumps the model generation
    # and invalidates.
    result_cache_enabled: bool = True
    result_cache_max_rows: int = 4096
    result_cache_ttl_s: float = 3600.0
    # In-flight coalescing (singleflight): concurrent identical submits
    # attach as followers to the one in-flight leader job; every
    # terminal frame fans out to all followers. The lease bounds how
    # long a dead leader can strand its key before a fresh submit takes
    # the claim over and republishes.
    coalesce_enabled: bool = True
    coalesce_lease_s: float = 120.0
    # Tenant-weighted fairness in the EDF scheduler: select_batch grants
    # per-tenant row budgets by weighted deficit (DRR) ABOVE deadline
    # ordering, so one hot tenant cannot starve the rest. Weights are
    # relative shares; tenants absent from the map get the default
    # weight, and None weights means every tenant is equal.
    tenant_fairness_enabled: bool = True
    tenant_weights: Mapping[str, float] | None = None
    tenant_default_weight: float = 1.0
    # --- closed-loop autoscaler (serve/autoscale.py; ROADMAP item 1) ---
    # Target-tracking on queue-wait p95 and SLO burn rate, riding the obs
    # sampler cadence. Breach above target*band_high for breach_ticks
    # consecutive ticks scales OUT (pool.add_replica); slack below
    # target*band_low AND burn below threshold for slack_ticks ticks
    # scales IN (pool.retire_replica, never below min). Scale-out is
    # additionally gated on pool health: any open replica breaker or a
    # poison/dead-letter rate above max_poison_rate_per_s reads as
    # "unhealthy, don't scale", not "overloaded, add replicas".
    autoscale_enabled: bool = False
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 4
    autoscale_target_queue_wait_p95_ms: float = 500.0
    autoscale_burn_threshold: float = 1.0
    autoscale_band_high: float = 1.2
    autoscale_band_low: float = 0.5
    autoscale_breach_ticks: int = 3
    autoscale_slack_ticks: int = 12
    autoscale_cooldown_out_s: float = 30.0
    autoscale_cooldown_in_s: float = 60.0
    autoscale_max_poison_rate_per_s: float = 0.5
    autoscale_window_s: float = 30.0
    autoscale_decision_history: int = 128


@dataclasses.dataclass(frozen=True)
class FrameworkConfig:
    model: ViLBertConfig = dataclasses.field(default_factory=ViLBertConfig)
    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)


def config_fingerprint(cfg: FrameworkConfig) -> str:
    """Short stable hash of the full config tree — the "which exact
    configuration was this process running" field for `vmt_build_info`
    and flight-recorder bundles. Same config → same fingerprint across
    processes (sorted-key JSON over the dataclass dict)."""
    import hashlib

    blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def add_backend_args(parser) -> None:
    """The shared --tiny/--cpu CLI knobs (evals harness, onboarding CLI):
    one definition so a new backend knob can't silently diverge between
    entry points."""
    parser.add_argument("--tiny", action="store_true",
                        help="tiny model config (rehearsal/tests; must "
                             "match any checkpoint being loaded)")
    parser.add_argument("--cpu", action="store_true",
                        help="pin the CPU backend (f32, XLA attention)")


def apply_backend_args(cfg: FrameworkConfig, args) -> FrameworkConfig:
    """Apply add_backend_args selections. With --cpu this must run before
    any jax backend init: it pins jax_platforms in-process (this image's
    sitecustomize registers a remote TPU plugin that otherwise wins)."""
    if getattr(args, "cpu", False):
        import jax

        jax.config.update("jax_platforms", "cpu")
        cfg = dataclasses.replace(cfg, engine=dataclasses.replace(
            cfg.engine, compute_dtype="float32",
            use_pallas_coattention=False, use_pallas_self_attention=False))
    if getattr(args, "tiny", False):
        cfg = dataclasses.replace(cfg, model=cfg.model.tiny())
    return cfg
