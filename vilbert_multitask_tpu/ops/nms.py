"""Vectorized NMS + top-K region selection in pure JAX.

Reference capability: ``maskrcnn_benchmark.layers.nms`` (a C++/CUDA kernel,
reference worker.py:51) driven by the per-class box-selection loop at
worker.py:123-176. Only the offline feature extractor needs this — serving
reads precomputed features — but the selection semantics must match exactly
or regenerated features shift boxes and grounding answers (SURVEY.md §7
"hard parts" (b)).

TPU-first design: greedy NMS is inherently sequential in the number of
*kept* boxes, so we express it as a ``lax.fori_loop`` over a static box
count with masked updates (compiler-friendly control flow; no dynamic
shapes), and vmap it over the ~1600 detector classes instead of the
reference's Python loop over classes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def box_iou(boxes_a: jnp.ndarray, boxes_b: jnp.ndarray) -> jnp.ndarray:
    """(N,4) xyxy, (M,4) xyxy → (N,M) IoU matrix."""
    area_a = (boxes_a[:, 2] - boxes_a[:, 0]) * (boxes_a[:, 3] - boxes_a[:, 1])
    area_b = (boxes_b[:, 2] - boxes_b[:, 0]) * (boxes_b[:, 3] - boxes_b[:, 1])
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@functools.partial(jax.jit, static_argnames=("iou_threshold",))
def nms_mask(boxes: jnp.ndarray, scores: jnp.ndarray,
             iou_threshold: float = 0.5) -> jnp.ndarray:
    """Greedy NMS → (N,) bool keep mask.

    Matches torchvision/maskrcnn semantics: visit boxes in descending score
    order; keep a box iff it doesn't overlap (IoU > threshold) an
    already-kept higher-scoring box.
    """
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_sorted = boxes[order]
    iou = box_iou(boxes_sorted, boxes_sorted)

    def body(i, keep):
        # suppressed iff any kept earlier box overlaps it
        overlap = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~overlap.any())

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # scatter back to original order
    return jnp.zeros((n,), bool).at[order].set(keep_sorted)


@functools.partial(jax.jit, static_argnames=("num_keep", "iou_threshold",
                                             "background", "conf_threshold"))
def select_top_regions(
    boxes: jnp.ndarray,  # (N, 4) detector proposals, image coords
    class_scores: jnp.ndarray,  # (N, C) softmaxed class scores, col 0 = background
    num_keep: int = 100,
    iou_threshold: float = 0.5,
    conf_threshold: float = 0.0,
    background: bool = False,
):
    """Per-class NMS → per-box max surviving confidence → top-``num_keep``.

    Vectorized equivalent of the reference selection loop (worker.py:136-163):
    for each class, run NMS on that class's scores; a box's ``max_conf`` is
    the best score it achieved in any class where NMS kept it (and the score
    beat ``conf_threshold``); keep the ``num_keep`` highest. Returns
    ``(keep_indices (num_keep,), num_valid (), max_conf (N,), objects
    (num_keep,), top_class_conf (num_keep,))`` where ``num_valid`` counts
    kept boxes with nonzero confidence (worker.py:157), ``objects`` is the
    per-kept-box class argmax, and ``top_class_conf`` its confidence — NOT
    the full class-distribution rows; the saved-schema ``cls_prob``
    (worker.py:209-216) is ``class_scores[keep_indices]``, which callers
    take from their own scores array (features/extract.py).

    Note: the reference also derives ``objects``/``cls_prob`` for the saved
    schema with a row-slice quirk (``scores[keep_boxes][start_index:]`` drops
    a *row*, worker.py:162-163); we compute the evidently intended per-box
    class argmax/max over the non-background *columns* instead.
    """
    start = 0 if background else 1
    per_class = jax.vmap(
        lambda s: nms_mask(boxes, s, iou_threshold), in_axes=1, out_axes=1
    )(class_scores[:, start:])  # (N, C-start) keep masks
    eligible = per_class & (class_scores[:, start:] > conf_threshold)
    max_conf = jnp.max(
        jnp.where(eligible, class_scores[:, start:], 0.0), axis=1
    )  # (N,)

    top_conf, keep_indices = jax.lax.top_k(max_conf, num_keep)
    num_valid = jnp.sum(top_conf > 0)

    objects = jnp.argmax(class_scores[keep_indices, start:], axis=1)
    cls_prob = jnp.max(class_scores[keep_indices, start:], axis=1)
    return keep_indices, num_valid, max_conf, objects, cls_prob
