"""Pallas flash co-attention: blockwise region×token cross-attention.

The north-star kernel (BASELINE.json: "region-feature×token co-attention as a
Pallas kernel"). One grid program per (batch, head, query-block); keys/values
stream through VMEM in ``block_k`` tiles with the online-softmax recurrence,
so the score matrix never materializes in HBM and the same kernel scales from
the serving shapes (38 text × 101 regions, reference worker.py:408,433) to
long-context region sets without re-tiling.

Layout choices for the TPU memory system:
- head_dim is zero-padded to the 128-lane width (the serving config's
  bi-attention head_dim is exactly 128: 1024/8);
- Q/K/V tiles sized to the fp32 (8, 128) sublane×lane tile;
- scores/accumulator kept in fp32 regardless of input dtype (bf16 inputs are
  fine; the softmax statistics are not);
- additive mask bias rides in as a (B, Nk) row, broadcast across heads —
  identical semantics to :func:`..ops.attention.mask_to_bias`.

The XLA path in :mod:`..ops.attention` is the numerics reference
(tests/test_pallas_coattention.py); the kernel is used when
``ViLBertConfig.use_pallas_coattention`` is set and attention probabilities
are not requested (the reference's ``visualization`` contract needs probs —
that path stays on XLA, reference worker.py:288).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG_BIG = -2.0e9  # mask bias for padded KV rows; far below the -10000 mask


def _flash_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, *, block_k: int,
                  scale: float):
    """One (batch, head, q-block) program: online softmax over KV tiles."""
    q = q_ref[0, 0].astype(jnp.float32)  # (block_q, D)
    block_q, depth = q.shape
    nk = k_ref.shape[2]
    n_blocks = nk // block_k

    acc = jnp.zeros((block_q, depth), jnp.float32)
    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        bias = b_ref[0, :, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + bias  # (block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc, m, l))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_cross_attention(
    q: jnp.ndarray,  # (B, Nq, H, D)
    k: jnp.ndarray,  # (B, Nk, H, D)
    v: jnp.ndarray,  # (B, Nk, H, D)
    bias: jnp.ndarray,  # (B, 1, 1, Nk) additive mask bias (mask_to_bias)
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Blockwise cross-attention; returns context (B, Nq, H, D).

    Pads Nq/Nk/D to tile boundaries (masking padded keys via the bias) and
    slices the padding back off — callers keep reference shapes (37+1 text
    tokens, 101 regions).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, Nq, H, D = q.shape
    Nk = k.shape[1]
    out_dtype = q.dtype

    block_q = min(block_q, _round_up(max(Nq, 8), 8))
    block_k = min(block_k, _round_up(max(Nk, 8), 8))
    nq_p = _round_up(Nq, block_q)
    nk_p = _round_up(Nk, block_k)
    d_p = _round_up(D, 128)

    # (B, H, N, D) layout: heads become a grid axis, rows tile the sublanes.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    qt = jnp.pad(qt, ((0, 0), (0, 0), (0, nq_p - Nq), (0, d_p - D)))
    kt = jnp.pad(kt, ((0, 0), (0, 0), (0, nk_p - Nk), (0, d_p - D)))
    vt = jnp.pad(vt, ((0, 0), (0, 0), (0, nk_p - Nk), (0, d_p - D)))
    brow = jnp.pad(
        bias.reshape(B, 1, Nk).astype(jnp.float32),
        ((0, 0), (0, 0), (0, nk_p - Nk)),
        constant_values=_NEG_BIG,
    )

    grid = (B, H, nq_p // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, scale=1.0 / float(np.sqrt(D))
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d_p), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, nk_p, d_p), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, nk_p, d_p), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, nk_p), lambda b, h, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d_p), lambda b, h, i: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, nq_p, d_p), out_dtype),
        interpret=interpret,
    )(qt, kt, vt, brow)
    return jnp.transpose(out[:, :, :Nq, :D], (0, 2, 1, 3))
