"""Attention primitives shared by both streams and the co-attention bridge.

TPU-first choices:
- fused QKV projection (one MXU matmul instead of three skinny ones),
- einsum-based multi-head attention that XLA fuses into batched MXU ops,
- additive mask bias computed once per call in the compute dtype,
- probabilities optionally returned for the reference's ``visualization`` /
  ``output_all_attention_masks`` contract (reference worker.py:288).

Reference capability: the torch self-attention inside the external ``vilbert``
package (driven from worker.py:286-289); redesigned, not translated.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

if TYPE_CHECKING:  # annotation only — parallel.ring is imported lazily
    from vilbert_multitask_tpu.parallel.ring import RingContext


def mask_to_bias(mask: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """(B, N) {0,1} mask → (B, 1, 1, N) additive bias.

    Uses the BERT-family -10000 penalty (the reference model family's exact
    constant) rather than -inf so bf16 softmax stays finite.
    """
    bias = (1.0 - mask.astype(dtype)) * -10000.0
    return bias[:, None, None, :]


def multi_head_attention(
    q: jnp.ndarray,  # (B, Nq, H, D)
    k: jnp.ndarray,  # (B, Nk, H, D)
    v: jnp.ndarray,  # (B, Nk, H, D)
    bias: Optional[jnp.ndarray],  # broadcastable to (B, H, Nq, Nk)
    *,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    dropout_rng=None,
    dtype=jnp.float32,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (context (B, Nq, H, D), probs (B, H, Nq, Nk))."""
    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(depth, dtype=dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=dtype)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(dtype)
    # softmax at >= fp32 for numerical stability under bf16 compute
    # (promote, don't pin: f64 runs — the conversion-oracle tests — keep f64)
    softmax_dtype = jnp.promote_types(scores.dtype, jnp.float32)
    probs = jnp.asarray(
        nn.softmax(scores.astype(softmax_dtype), axis=-1), dtype=dtype
    )
    if dropout_rate > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs_dropped = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    else:
        probs_dropped = probs
    context = jnp.einsum("bhqk,bkhd->bqhd", probs_dropped, v, preferred_element_type=dtype)
    return context, probs


class FusedSelfAttention(nn.Module):
    """BERT-style self-attention with a fused QKV matmul.

    ``num_heads * head_dim == hidden`` always holds for both streams
    (768/12 and 1024/8 in the serving config).

    ``ring`` (a :class:`~vilbert_multitask_tpu.parallel.ring.RingContext`)
    opts this layer into sequence-parallel exact attention over the mesh's
    ``sp`` axis when the (static) sequence length clears the context's
    region-count threshold — the long-context path for region sets beyond
    one chip's HBM. Attention-probs collection and dropout keep the dense
    path (the ring never materializes the (Nq, Nk) matrix, same contract
    as the Pallas kernel below).
    """

    hidden_size: int
    num_heads: int
    dropout_rate: float = 0.1
    use_pallas: bool = False
    ring: Optional["RingContext"] = None  # parallel/ring.py
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask_bias, *, deterministic: bool = True):
        head_dim = self.hidden_size // self.num_heads
        qkv = nn.Dense(3 * self.hidden_size, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (*x.shape[:-1], self.num_heads, head_dim)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        use_dropout = not deterministic and self.dropout_rate > 0.0
        if (self.ring is not None and not use_dropout
                and self.ring.engages(x.shape[1], x.shape[0])):
            from vilbert_multitask_tpu.parallel.ring import ring_self_attention

            # Accumulate at >= fp32 (the same promotion the dense softmax
            # uses) — under bf16 compute the online-softmax recurrence is
            # where precision matters most.
            ctx = ring_self_attention(
                self.ring, q, k, v, mask_bias,
                dtype=jnp.promote_types(self.dtype, jnp.float32))
            ctx = ctx.astype(self.dtype)
            return ctx.reshape(*x.shape[:-1], self.hidden_size), None
        # Kernel path: self-attention probs are never surfaced (the encoder
        # discards them, and the reference's attn_data_list carries only the
        # co-attention maps), so only dropout and tile fit gate this.
        if self.use_pallas and not use_dropout and head_dim % 128 == 0:
            from vilbert_multitask_tpu.ops.coattention import (
                flash_cross_attention,
            )

            ctx = flash_cross_attention(q, k, v, mask_bias)
            return ctx.reshape(*x.shape[:-1], self.hidden_size), None
        dropout_rng = self.make_rng("dropout") if use_dropout else None
        ctx, probs = multi_head_attention(
            q, k, v, mask_bias,
            dropout_rate=self.dropout_rate,
            deterministic=deterministic,
            dropout_rng=dropout_rng,
            dtype=self.dtype,
        )
        ctx = ctx.reshape(*x.shape[:-1], self.hidden_size)
        return ctx, probs


class CrossAttention(nn.Module):
    """One direction of co-attention: queries from ``x``, keys/values from ``y``.

    Projects both operands into the shared ``bi_hidden`` space. The connection
    layer instantiates this twice — text→image and image→text — each direction
    with its own independent Q/K/V projections (matching the reference model
    family, whose bi-attention keeps per-stream projection weights).
    """

    bi_hidden_size: int
    num_heads: int
    dropout_rate: float = 0.1
    use_pallas: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, y, y_mask_bias, *, deterministic: bool = True,
                 need_probs: bool = True):
        head_dim = self.bi_hidden_size // self.num_heads
        q = nn.Dense(self.bi_hidden_size, dtype=self.dtype, name="query")(x)
        k = nn.Dense(self.bi_hidden_size, dtype=self.dtype, name="key")(y)
        v = nn.Dense(self.bi_hidden_size, dtype=self.dtype, name="value")(y)
        B, Nq = x.shape[0], x.shape[1]
        Nk = y.shape[1]
        q = q.reshape(B, Nq, self.num_heads, head_dim)
        k = k.reshape(B, Nk, self.num_heads, head_dim)
        v = v.reshape(B, Nk, self.num_heads, head_dim)
        use_dropout = not deterministic and self.dropout_rate > 0.0
        if self.use_pallas and not need_probs and not use_dropout:
            from vilbert_multitask_tpu.ops.coattention import (
                flash_cross_attention,
            )

            ctx = flash_cross_attention(q, k, v, y_mask_bias)
            return ctx.reshape(B, Nq, self.bi_hidden_size), None
        dropout_rng = self.make_rng("dropout") if use_dropout else None
        ctx, probs = multi_head_attention(
            q, k, v, y_mask_bias,
            dropout_rate=self.dropout_rate,
            deterministic=deterministic,
            dropout_rng=dropout_rng,
            dtype=self.dtype,
        )
        return ctx.reshape(B, Nq, self.bi_hidden_size), probs
