"""Live region-feature extraction: the JAX/Pallas-era Faster R-CNN.

Reference capability: ``FeatureExtractor`` (reference worker.py:59-223),
which drives the maskrcnn_benchmark X-152-32x8d-FPN C++/CUDA stack. Serving
defaults to precomputed features per BASELINE.json; this package is the
sanctioned stretch that brings the upload→answer flow alive for images with
no precomputed ``.npy``.
"""

from vilbert_multitask_tpu.detect.extractor import (  # noqa: F401
    FallbackFeatureStore,
    LiveFeatureExtractor,
)
from vilbert_multitask_tpu.detect.model import FasterRCNN  # noqa: F401
