"""Torch detector checkpoint → native FasterRCNN param tree.

Reference capability: the maskrcnn_benchmark checkpoint load at reference
worker.py:82-85 (``build_detection_model`` + ``load_state_dict`` of the
X-152-32x8d-FPN weights). Same design as checkpoint/convert.py for the
trunk: a declarative name map with the layout transforms TPU params need —

- torch conv ``weight`` (O, I, kH, kW) → flax kernel (kH, kW, I, O);
- torch linear ``weight`` (out, in) → flax kernel (in, out);
- **FrozenBatchNorm fold**: torch carries (weight, bias, running_mean,
  running_var); inference only ever uses the affine form
  ``scale = weight / sqrt(var + eps)``, ``bias' = bias - mean · scale``,
  which is exactly what :class:`..detect.model.FrozenBN` parametrizes.
  The fold is one-way by construction (mean/var are not recoverable);
  ``to_torch_state_dict`` emits the folded affine with zero mean / unit
  var, which is functionally identical under FrozenBN semantics.

The genuine X-152 weights are not present in this image (no egress), so the
tests prove the bookkeeping instead: full coverage of the flax tree, exact
BN-fold math, and a converted tree that runs through the live extractor.
Torch key names follow the torchvision-style layout
(``backbone.body.layer{n}`` / ``backbone.fpn.fpn_inner{n}`` /
``rpn.head`` / ``roi_heads.box``); the map is declarative, so a variant
naming scheme is a table edit, not a rewrite.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from vilbert_multitask_tpu.config import DetectorConfig

Arr = np.ndarray
BN_EPS = 1e-5


def _conv(w: Arr) -> Arr:  # (O, I, kH, kW) → (kH, kW, I, O)
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def _conv_inv(k: Arr) -> Arr:
    return np.ascontiguousarray(np.transpose(k, (3, 2, 0, 1)))


def _lin(w: Arr) -> Arr:
    return np.ascontiguousarray(w.T)


def fold_bn(weight: Arr, bias: Arr, mean: Arr, var: Arr,
            eps: float = BN_EPS) -> Tuple[Arr, Arr]:
    """FrozenBatchNorm (w, b, μ, σ²) → affine (scale, bias)."""
    scale = weight / np.sqrt(var + eps)
    return scale, bias - mean * scale


def _conv_entry(flax_path, torch_prefix):
    return [(flax_path + ("kernel",),
             ([f"{torch_prefix}.weight"], lambda w: _conv(w),
              lambda k: [_conv_inv(k)]))]


def _conv_bias_entry(flax_path, torch_prefix):
    return [
        (flax_path + ("kernel",),
         ([f"{torch_prefix}.weight"], lambda w: _conv(w),
          lambda k: [_conv_inv(k)])),
        (flax_path + ("bias",),
         ([f"{torch_prefix}.bias"], lambda b: b, lambda b: [b])),
    ]


def _bn_entry(flax_path, torch_prefix):
    keys = [f"{torch_prefix}.{s}" for s in
            ("weight", "bias", "running_mean", "running_var")]
    return [
        (flax_path + ("scale",),
         (keys, lambda w, b, m, v: fold_bn(w, b, m, v)[0],
          lambda s: None)),  # one-way; inverse handled jointly below
        (flax_path + ("bias",),
         (keys, lambda w, b, m, v: fold_bn(w, b, m, v)[1],
          lambda b: None)),
    ]


def _linear_entry(flax_path, torch_prefix):
    return [
        (flax_path + ("kernel",),
         ([f"{torch_prefix}.weight"], lambda w: _lin(w),
          lambda k: [_lin(k)])),
        (flax_path + ("bias",),
         ([f"{torch_prefix}.bias"], lambda b: b, lambda b: [b])),
    ]


def build_name_map(cfg: DetectorConfig) -> List[Tuple[Tuple[str, ...], tuple]]:
    entries: List[Tuple[Tuple[str, ...], tuple]] = []
    B = ("backbone",)
    entries += _conv_entry(B + ("stem_conv",), "backbone.body.stem.conv1")
    entries += _bn_entry(B + ("stem_bn",), "backbone.body.stem.bn1")
    for stage, blocks in enumerate(cfg.stage_blocks):
        for b in range(blocks):
            fx = B + (f"stage{stage + 2}_block{b}",)
            tp = f"backbone.body.layer{stage + 1}.{b}"
            for i in (1, 2, 3):
                entries += _conv_entry(fx + (f"conv{i}",), f"{tp}.conv{i}")
                entries += _bn_entry(fx + (f"bn{i}",), f"{tp}.bn{i}")
            if b == 0:  # projection shortcut (stride or width change)
                entries += _conv_entry(fx + ("downsample",),
                                       f"{tp}.downsample.0")
                entries += _bn_entry(fx + ("downsample_bn",),
                                     f"{tp}.downsample.1")
    for i in range(4):  # FPN levels 2..5
        entries += _conv_bias_entry(("fpn", f"lateral{i + 2}"),
                                    f"backbone.fpn.fpn_inner{i + 1}")
        entries += _conv_bias_entry(("fpn", f"output{i + 2}"),
                                    f"backbone.fpn.fpn_layer{i + 1}")
    entries += _conv_bias_entry(("rpn", "conv"), "rpn.head.conv")
    entries += _conv_bias_entry(("rpn", "objectness"), "rpn.head.cls_logits")
    entries += _conv_bias_entry(("rpn", "deltas"), "rpn.head.bbox_pred")
    entries += _linear_entry(("fc6",),
                             "roi_heads.box.feature_extractor.fc6")
    entries += _linear_entry(("fc7",),
                             "roi_heads.box.feature_extractor.fc7")
    entries += _linear_entry(("cls_score",),
                             "roi_heads.box.predictor.cls_score")
    return entries


def _set_path(tree: Dict, path: Tuple[str, ...], value: Arr) -> None:
    for p in path[:-1]:
        tree = tree.setdefault(p, {})
    tree[path[-1]] = value


def _get_path(tree: Dict, path: Tuple[str, ...]):
    for p in path:
        tree = tree[p]
    return tree


def convert_torch_state_dict(sd: Dict[str, Arr],
                             cfg: DetectorConfig) -> Dict:
    """Torch detector state dict → flax param tree (strict: every mapped
    torch key must exist; unknown torch keys are reported)."""
    sd = {k: np.asarray(v) for k, v in sd.items()}
    tree: Dict = {}
    used = set()
    missing = []
    for flax_path, (torch_keys, pack, _unpack) in build_name_map(cfg):
        try:
            args = [sd[k] for k in torch_keys]
        except KeyError as e:
            missing.append((flax_path, str(e)))
            continue
        used.update(torch_keys)
        _set_path(tree, flax_path, pack(*args))
    if missing:
        raise KeyError(f"{len(missing)} unmapped flax leaves; first: "
                       f"{missing[0]}")
    extra = set(sd) - used
    # bbox_pred of the box predictor et al. are legitimately unused (the
    # extractor consumes proposals + cls scores + fc6, worker.py:123-176);
    # anything else unknown is surfaced for the operator.
    benign = {k for k in extra
              if "bbox_pred" in k and k.startswith("roi_heads")}
    unknown = extra - benign
    if unknown:
        import logging

        logging.getLogger(__name__).warning(
            "detector checkpoint has %d unconsumed keys (e.g. %s)",
            len(unknown), sorted(unknown)[:3])
    return tree


def to_torch_state_dict(params: Dict, cfg: DetectorConfig) -> Dict[str, Arr]:
    """Inverse mapping. FrozenBN leaves re-emit as folded affine with zero
    running_mean / unit running_var — numerically identical under FrozenBN
    inference semantics (the fold is not invertible)."""
    sd: Dict[str, Arr] = {}
    for flax_path, (torch_keys, _pack, unpack) in build_name_map(cfg):
        val = np.asarray(_get_path(params, flax_path))
        if len(torch_keys) == 4:  # folded BN: joint inverse
            prefix = torch_keys[0].rsplit(".", 1)[0]
            if flax_path[-1] == "scale":
                sd[f"{prefix}.weight"] = val * np.sqrt(1.0 + BN_EPS)
                sd[f"{prefix}.running_mean"] = np.zeros_like(val)
                sd[f"{prefix}.running_var"] = np.ones_like(val)
            else:
                sd[f"{prefix}.bias"] = val
            continue
        outs = unpack(val)
        for k, v in zip(torch_keys, outs):
            sd[k] = v
    return sd


def load_torch_detector(path: str, cfg: DetectorConfig) -> Dict:
    """torch.load a detector ``.pth``/``.bin`` and convert (CPU-mapped —
    the reference loads the same way, worker.py:83)."""
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(raw, dict) and "model" in raw:  # maskrcnn ckpt wrapper
        raw = raw["model"]
    sd = {k.replace("module.", "", 1): v.numpy() for k, v in raw.items()}
    return convert_torch_state_dict(sd, cfg)
