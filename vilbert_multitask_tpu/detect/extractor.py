"""Live feature extraction + the serving fallback for novel uploads.

Reference capability: ``FeatureExtractor.extract_features`` (reference
worker.py:218-223) — every request ran the detector live. This build keeps
precomputed features as the default (BASELINE.json: "no GPU remains in the
loop") and adds live extraction as the fallback for images with no
precomputed file, so the demo's upload→answer flow works end-to-end:

    upload → media/demo/x.png → job → FeatureStore miss →
    LiveFeatureExtractor (preprocess → FasterRCNN → select_top_regions) →
    RegionFeatures → ViLBERT forward → answer

The preprocessing (RGB→BGR, mean subtract, 800/1333 resize) and the
per-class NMS + top-100 selection are the SAME code paths the offline CLI
uses (features/extract.py), so live and precomputed features agree by
construction given the same detector weights.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from vilbert_multitask_tpu.config import DetectorConfig
from vilbert_multitask_tpu.detect.model import FasterRCNN
from vilbert_multitask_tpu.features.extract import (
    preprocess_image,
    select_regions,
)
from vilbert_multitask_tpu.features.pipeline import RegionFeatures


class LiveFeatureExtractor:
    """One detector per process: image file/array → RegionFeatures."""

    def __init__(self, cfg: Optional[DetectorConfig] = None, *,
                 params=None, seed: int = 0, num_keep: int = 100):
        self.cfg = cfg or DetectorConfig()
        self.num_keep = num_keep
        self.model = FasterRCNN(self.cfg)
        canvas = self.cfg.canvas
        dummy = (jnp.zeros((canvas, canvas, 3), jnp.float32),
                 jnp.asarray([canvas, canvas], jnp.float32))
        if params is None:
            params = jax.jit(
                lambda r: self.model.init(r, *dummy)["params"]
            )(jax.random.PRNGKey(seed))
        self.params = jax.device_put(params)
        self._fwd = jax.jit(
            lambda p, img, hw: self.model.apply({"params": p}, img, hw))

    def warmup(self) -> None:
        canvas = self.cfg.canvas
        out = self._fwd(self.params,
                        jnp.zeros((canvas, canvas, 3), jnp.float32),
                        jnp.asarray([canvas, canvas], jnp.float32))
        jax.block_until_ready(out[0])

    # ----------------------------------------------------------- extraction
    def extract_array(self, rgb: np.ndarray) -> RegionFeatures:
        """(H, W, 3) RGB uint8 → RegionFeatures in original pixel coords."""
        h, w = rgb.shape[:2]
        # Reference preprocessing contract, scaled to fit the static canvas.
        canvas = self.cfg.canvas
        max_size = min(1333, canvas)
        min_size = min(800, max_size)
        bgr, scale = preprocess_image(rgb, min_size=min_size,
                                      max_size=max_size)
        ph, pw = bgr.shape[:2]
        padded = np.zeros((canvas, canvas, 3), np.float32)
        padded[:ph, :pw] = bgr

        boxes, cls_scores, feats = self._fwd(
            self.params, jnp.asarray(padded),
            jnp.asarray([ph, pw], jnp.float32))
        boxes = np.asarray(boxes, np.float32)
        cls_scores = np.asarray(cls_scores, np.float32)
        # 5th return is per-box TOP-class confidence (ops/nms.py), not the
        # class distribution — the schema cls_prob is the full score rows.
        keep, num_valid, _conf, _objects, _max_conf = select_regions(
            boxes, cls_scores, num_keep=self.num_keep)
        n = int(min(int(num_valid), len(keep))) or 1
        keep = np.asarray(keep[:n])
        return RegionFeatures(
            features=np.asarray(feats, np.float32)[keep],
            boxes=boxes[keep] / scale,  # back to original pixel coords
            image_width=w, image_height=h, num_boxes=n,
            cls_prob=cls_scores[keep])

    def extract(self, image_path: str) -> RegionFeatures:
        from PIL import Image

        rgb = np.asarray(Image.open(image_path).convert("RGB"))
        return self.extract_array(rgb)


class FallbackFeatureStore:
    """FeatureStore interface, with live extraction on a miss.

    Lookup order per key: (1) the precomputed store, (2) an in-memory cache
    of previous live extractions, (3) run the detector on the image file the
    key names (absolute path, or relative to ``media_root``). Matches the
    reference demo's behavior where uploads always work because the detector
    runs per request (worker.py:556-558).
    """

    def __init__(self, store, extractor: LiveFeatureExtractor, *,
                 media_root: str = "media", max_cached: int = 64):
        self.store = store
        self.extractor = extractor
        self.media_root = media_root
        self.max_cached = max_cached
        from collections import OrderedDict

        # LRU, same pattern as FeatureStore: ~1.5 MB per entry at the
        # serving num_keep (fc6 features + the full cls_prob rows the MRM
        # pretraining target needs); unbounded growth would OOM a
        # long-lived demo.
        self._cache: "OrderedDict[str, RegionFeatures]" = OrderedDict()
        self._lock = threading.Lock()

    def _resolve_image(self, key: str) -> Optional[str]:
        """Map a job's image key to a file STRICTLY under media_root.

        The key is client-supplied (it rides in the job payload), so the
        resolved path must stay confined — the same ``contained_path`` rule
        the HTTP media handler uses (utils.py). An absolute path is
        accepted only if it already points inside media_root (that is
        exactly what /upload_image returns).
        """
        import os

        from vilbert_multitask_tpu.utils import contained_path

        candidates = [key, os.path.join(self.media_root, key),
                      os.path.join(self.media_root, "demo",
                                   os.path.basename(key))]
        for c in candidates:
            full = contained_path(self.media_root, c)
            if full is not None and os.path.isfile(full):
                return full
        return None

    def identity(self, key: str) -> str:
        """Content-stable identity (see FeatureStore.identity): precomputed
        feature file when one exists, else the resolved image file —
        path + mtime + size, so a replaced upload never hits a stale
        device/host cache entry."""
        from vilbert_multitask_tpu.features.store import file_identity

        ident = getattr(self.store, "identity", None)
        if ident is not None:
            try:
                return ident(key)
            except (KeyError, FileNotFoundError):
                pass
        path = self._resolve_image(key)
        if path is None:
            raise KeyError(f"no features or image file for {key!r}")
        return file_identity(path)

    def fetch(self, key: str):
        """(features, content identity); identity stat'd BEFORE the read/
        extraction — see FeatureStore.fetch for why that ordering. The
        precomputed store is ALWAYS consulted first (the documented lookup
        order): a duck-typed store with only get() still wins — its hit just
        carries a None identity (host upload, no device caching)."""
        from vilbert_multitask_tpu.features.store import file_identity

        store_fetch = getattr(self.store, "fetch", None)
        if store_fetch is not None:
            try:
                return store_fetch(key)
            except (KeyError, FileNotFoundError):
                pass
        else:
            try:
                return self.store.get(key), None
            except (KeyError, FileNotFoundError):
                pass
        path = self._resolve_image(key)
        if path is None:
            raise KeyError(
                f"no precomputed features for {key!r} and no image file "
                f"under media_root to extract from")
        cache_key = file_identity(path)
        with self._lock:
            if cache_key in self._cache:  # content identity: one per version
                self._cache.move_to_end(cache_key)
                return self._cache[cache_key], cache_key
        region = self.extractor.extract(path)
        with self._lock:
            self._cache[cache_key] = region
            self._cache.move_to_end(cache_key)
            while len(self._cache) > self.max_cached:
                self._cache.popitem(last=False)
        return region, cache_key

    def get(self, key: str) -> RegionFeatures:
        return self.fetch(key)[0]

    def get_batch(self, keys: Sequence[str]):
        return [self.get(k) for k in keys]
