"""Faster R-CNN in Flax: ResNeXt-FPN backbone, RPN, ROIAlign box head.

Reference capability: the maskrcnn_benchmark detection model the reference
builds and drives from worker.py:78-89,192-193 (X-152-32x8d-FPN, C++/CUDA)
— redesigned for XLA rather than translated:

- **static shapes throughout**: one fixed input canvas
  (``DetectorConfig.canvas``), fixed per-level proposal counts, fixed
  ``post_nms_top_n`` region count — every tensor the TPU sees compiles once;
- **frozen BatchNorm as affine**: inference-semantics scale/bias params
  (maskrcnn's FrozenBatchNorm2d), no running stats to carry;
- **grouped convs** (ResNeXt 32×8d) via ``feature_group_count`` — XLA maps
  them straight onto the MXU;
- **NMS reuses** the vectorized ``lax.fori_loop`` kernel in
  :mod:`..ops.nms` — the same selection semantics serving features were
  produced with;
- **ROIAlign** is bilinear grid sampling + average pooling expressed as
  gathers, vmapped over boxes; FPN level per box follows the canonical
  ``floor(4 + log2(sqrt(area)/224))`` assignment via ``lax.switch``.

Weights: the genuine X-152 checkpoint is not present in this image (no
egress), so live extraction runs random-init unless a converted checkpoint
is supplied — the *flow* (upload → detect → features → answer) is real and
tested; score parity is weight-blocked, exactly like the vocab asset
(VERDICT r2 §2.2).
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from vilbert_multitask_tpu.config import DetectorConfig
from vilbert_multitask_tpu.ops.nms import nms_mask


class FrozenBN(nn.Module):
    """Inference-mode BatchNorm: y = x * scale + bias (per channel)."""

    channels: int

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (self.channels,))
        bias = self.param("bias", nn.initializers.zeros, (self.channels,))
        return x * scale + bias


class BottleneckX(nn.Module):
    """ResNeXt bottleneck: 1x1 → grouped 3x3 → 1x1, residual."""

    out_channels: int
    groups: int
    group_width: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        mid = self.groups * self.group_width
        residual = x
        h = nn.Conv(mid, (1, 1), use_bias=False, name="conv1")(x)
        h = nn.relu(FrozenBN(mid, name="bn1")(h))
        h = nn.Conv(mid, (3, 3), strides=(self.stride, self.stride),
                    feature_group_count=self.groups, use_bias=False,
                    padding=1, name="conv2")(h)
        h = nn.relu(FrozenBN(mid, name="bn2")(h))
        h = nn.Conv(self.out_channels, (1, 1), use_bias=False, name="conv3")(h)
        h = FrozenBN(self.out_channels, name="bn3")(h)
        if residual.shape[-1] != self.out_channels or self.stride != 1:
            residual = nn.Conv(self.out_channels, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, name="downsample")(x)
            residual = FrozenBN(self.out_channels, name="downsample_bn")(residual)
        return nn.relu(h + residual)


class Backbone(nn.Module):
    """Stem + 4 ResNeXt stages → (C2, C3, C4, C5)."""

    cfg: DetectorConfig

    @nn.compact
    def __call__(self, x) -> List[jnp.ndarray]:
        c = self.cfg
        h = nn.Conv(c.stem_channels, (7, 7), strides=(2, 2), padding=3,
                    use_bias=False, name="stem_conv")(x)
        h = nn.relu(FrozenBN(c.stem_channels, name="stem_bn")(h))
        h = nn.max_pool(h, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        outs = []
        group_width = c.width_per_group
        for stage, (blocks, channels) in enumerate(
                zip(c.stage_blocks, c.stage_channels)):
            for b in range(blocks):
                stride = 2 if (b == 0 and stage > 0) else 1
                h = BottleneckX(
                    out_channels=channels, groups=c.groups,
                    group_width=group_width * (2 ** stage),
                    stride=stride, name=f"stage{stage + 2}_block{b}")(h)
            outs.append(h)
        return outs  # strides 4, 8, 16, 32


class FPN(nn.Module):
    """Top-down pyramid: (C2..C5) → (P2..P5, P6)."""

    channels: int

    @nn.compact
    def __call__(self, feats: List[jnp.ndarray]) -> List[jnp.ndarray]:
        laterals = [
            nn.Conv(self.channels, (1, 1), name=f"lateral{i + 2}")(f)
            for i, f in enumerate(feats)
        ]
        out = [laterals[-1]]
        for lat in laterals[-2::-1]:
            top = out[0]
            up = jax.image.resize(top, lat.shape, "nearest")
            out.insert(0, lat + up)
        pyramid = [
            nn.Conv(self.channels, (3, 3), padding=1, name=f"output{i + 2}")(p)
            for i, p in enumerate(out)
        ]
        # P6: stride-2 subsample of P5 (maskrcnn LastLevelMaxPool).
        p6 = nn.max_pool(pyramid[-1], (1, 1), strides=(2, 2))
        return pyramid + [p6]  # strides 4, 8, 16, 32, 64


class RPNHead(nn.Module):
    """Shared 3x3 conv + per-anchor objectness / box deltas."""

    channels: int
    num_anchors: int

    @nn.compact
    def __call__(self, feats: List[jnp.ndarray]):
        conv = nn.Conv(self.channels, (3, 3), padding=1, name="conv")
        logit = nn.Conv(self.num_anchors, (1, 1), name="objectness")
        delta = nn.Conv(4 * self.num_anchors, (1, 1), name="deltas")
        outs = []
        for f in feats:
            h = nn.relu(conv(f))
            outs.append((logit(h), delta(h)))
        return outs


# --------------------------------------------------------------- box math
def make_anchors(h: int, w: int, stride: int, size: int,
                 aspect_ratios: Sequence[float]) -> np.ndarray:
    """(h*w*A, 4) xyxy anchors for one level (host-side, static)."""
    ys = (np.arange(h) + 0.5) * stride
    xs = (np.arange(w) + 0.5) * stride
    cy, cx = np.meshgrid(ys, xs, indexing="ij")
    anchors = []
    for ar in aspect_ratios:
        aw = size * math.sqrt(1.0 / ar)
        ah = size * math.sqrt(ar)
        anchors.append(np.stack(
            [cx - aw / 2, cy - ah / 2, cx + aw / 2, cy + ah / 2], axis=-1))
    return np.stack(anchors, axis=2).reshape(-1, 4).astype(np.float32)


def decode_boxes(anchors: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """maskrcnn box decoding: (dx, dy, dw, dh) on (cx, cy, w, h)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    dx, dy, dw, dh = (deltas[:, i] for i in range(4))
    # clamp like maskrcnn (log(1000/16)) so exp can't overflow
    dw = jnp.clip(dw, max=math.log(1000.0 / 16))
    dh = jnp.clip(dh, max=math.log(1000.0 / 16))
    cx = acx + dx * aw
    cy = acy + dy * ah
    w = aw * jnp.exp(dw)
    h = ah * jnp.exp(dh)
    return jnp.stack(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)


def roi_align(feat: jnp.ndarray, boxes: jnp.ndarray, stride: float,
              resolution: int, sampling: int) -> jnp.ndarray:
    """(H, W, C) level map + (R, 4) pixel boxes → (R, res, res, C).

    Bilinear grid sampling with ``sampling``² points per output bin,
    averaged — ROIAlign semantics, expressed as gathers so XLA fuses it.
    """
    H, W, _ = feat.shape
    n = resolution * sampling

    def sample_one(box):
        x1, y1, x2, y2 = box / stride
        gy = y1 + (jnp.arange(n) + 0.5) * (y2 - y1) / n
        gx = x1 + (jnp.arange(n) + 0.5) * (x2 - x1) / n
        yy = jnp.clip(gy, 0.0, H - 1.0)
        xx = jnp.clip(gx, 0.0, W - 1.0)
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 2)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 2)
        wy = (yy - y0)[:, None, None]
        wx = (xx - x0)[None, :, None]
        f00 = feat[y0][:, x0]
        f01 = feat[y0][:, x0 + 1]
        f10 = feat[y0 + 1][:, x0]
        f11 = feat[y0 + 1][:, x0 + 1]
        vals = (f00 * (1 - wy) * (1 - wx) + f01 * (1 - wy) * wx
                + f10 * wy * (1 - wx) + f11 * wy * wx)  # (n, n, C)
        return vals.reshape(resolution, sampling, resolution, sampling,
                            -1).mean(axis=(1, 3))

    return jax.vmap(sample_one)(boxes)


class FasterRCNN(nn.Module):
    """The full extractor graph: image canvas → proposals, scores, fc6.

    Output contract matches what the reference's post-processing consumes
    (worker.py:123-176): proposal boxes (``rpn_post_nms_top_n``, 4), class
    scores (R, num_classes) softmaxed with background col 0, and 2048-d fc6
    features (R, representation_size) — which then feed the SAME
    ``select_top_regions`` used for offline dumps.
    """

    cfg: DetectorConfig

    def setup(self):
        c = self.cfg
        self.backbone = Backbone(c)
        self.fpn = FPN(c.fpn_channels)
        self.rpn = RPNHead(c.fpn_channels, len(c.aspect_ratios))
        self.fc6 = nn.Dense(c.representation_size)
        self.fc7 = nn.Dense(c.representation_size)
        self.cls_score = nn.Dense(c.num_classes)

    def __call__(self, image: jnp.ndarray,
                 image_hw: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
        """image (canvas, canvas, 3) BGR mean-subtracted; image_hw (2,) the
        valid (h, w) region of the canvas. Returns (boxes, scores, fc6)."""
        c = self.cfg
        feats = self.fpn(self.backbone(image[None]))
        rpn_outs = self.rpn(feats)

        strides = [4, 8, 16, 32, 64]
        all_boxes, all_scores = [], []
        for (logit, delta), stride, size in zip(rpn_outs, strides,
                                                c.anchor_sizes):
            h, w = logit.shape[1:3]
            anchors = jnp.asarray(
                make_anchors(h, w, stride, size, c.aspect_ratios))
            scores = jax.nn.sigmoid(logit.reshape(-1))
            boxes = decode_boxes(anchors, delta.reshape(-1, 4))
            # clip to the valid image region, kill degenerate/out-of-image
            boxes = jnp.stack([
                jnp.clip(boxes[:, 0], 0, image_hw[1] - 1),
                jnp.clip(boxes[:, 1], 0, image_hw[0] - 1),
                jnp.clip(boxes[:, 2], 0, image_hw[1] - 1),
                jnp.clip(boxes[:, 3], 0, image_hw[0] - 1)], axis=1)
            degenerate = ((boxes[:, 2] - boxes[:, 0] < 1)
                          | (boxes[:, 3] - boxes[:, 1] < 1))
            scores = jnp.where(degenerate, 0.0, scores)
            k = min(c.rpn_pre_nms_top_n, scores.shape[0])
            top, idx = jax.lax.top_k(scores, k)
            sel = boxes[idx]
            keep = nms_mask(sel, top, c.rpn_nms_thresh)
            all_boxes.append(sel)
            all_scores.append(jnp.where(keep, top, 0.0))

        boxes = jnp.concatenate(all_boxes, axis=0)
        scores = jnp.concatenate(all_scores, axis=0)
        r = c.rpn_post_nms_top_n
        top, idx = jax.lax.top_k(scores, r)
        proposals = boxes[idx]  # (R, 4)

        # FPN level per box: floor(4 + log2(sqrt(area)/224)), clamped to
        # the P2..P5 maps (P6 is RPN-only, as in maskrcnn).
        area = ((proposals[:, 2] - proposals[:, 0])
                * (proposals[:, 3] - proposals[:, 1]))
        level = jnp.clip(
            jnp.floor(4 + jnp.log2(jnp.sqrt(jnp.maximum(area, 1.0)) / 224.0)),
            2, 5).astype(jnp.int32) - 2

        def pooled_at(lvl):
            return lambda box: roi_align(
                feats[lvl][0], box[None], float(strides[lvl]),
                c.roi_resolution, c.roi_sampling)[0]

        def pool_one(box, lvl):
            return jax.lax.switch(lvl, [pooled_at(i) for i in range(4)], box)

        pooled = jax.vmap(pool_one)(proposals, level)  # (R, res, res, C)
        flat = pooled.reshape(r, -1)
        fc6 = nn.relu(self.fc6(flat))
        fc7 = nn.relu(self.fc7(fc6))
        cls = jax.nn.softmax(self.cls_score(fc7), axis=-1)
        # fc6 is the 2048-d region feature ViLBERT consumes (worker.py:218-223).
        return proposals, cls, fc6
