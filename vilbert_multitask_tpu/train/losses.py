"""Per-task losses over the ViLBERT 10-tuple heads.

Loss families mirror the 12-in-1 training regime the served checkpoint came
from (reference README.md:6, arXiv 1912.02315):

- **labels** (VQA/GQA, ``vil_prediction*``): sigmoid BCE against soft answer
  scores, summed over the answer vocabulary (the standard VQA soft-target
  loss), mean over batch;
- **binary / trinary** (NLVR2 / SNLI-VE): softmax cross-entropy;
- **grounding** (``vision_logit``): KL between the region softmax and an
  IoU-derived soft target distribution over regions;
- **ranking** (``vil_logit``): contrastive cross-entropy over each question's
  candidate-image group (score the aligned image against distractors);
- **masked LM / masked region** (``linguisic_prediction`` /
  ``vision_prediction``): the Conceptual-Captions pretraining objectives the
  reference imports via ``BertForMultiModalPreTraining`` (worker.py:45).

All reductions are float32 regardless of compute dtype — softmax/log-sum-exp
in bf16 loses answers with close logits.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from vilbert_multitask_tpu.models.vilbert import ViLBertOutput


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def label_bce_loss(logits, soft_targets) -> jnp.ndarray:
    """Soft-target BCE, summed over the label axis (VQA convention)."""
    logits, t = _f32(logits), _f32(soft_targets)
    per = optax_sigmoid_bce(logits, t)
    return per.sum(axis=-1).mean()


def optax_sigmoid_bce(logits, targets):
    # Numerically-stable elementwise BCE-with-logits.
    return jnp.maximum(logits, 0) - logits * targets + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )


def softmax_ce_loss(logits, labels) -> jnp.ndarray:
    """Integer-label cross-entropy (NLVR2 binary, SNLI-VE trinary)."""
    logp = jax.nn.log_softmax(_f32(logits), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()


def grounding_loss(vision_logit, target_dist, image_mask) -> jnp.ndarray:
    """KL(region softmax ‖ IoU soft targets); padded regions masked out."""
    logits = _f32(vision_logit)[..., 0]  # (B, Nv)
    logits = jnp.where(image_mask > 0, logits, -1e4)
    logp = jax.nn.log_softmax(logits, axis=-1)
    t = _f32(target_dist)
    t = t / jnp.clip(t.sum(axis=-1, keepdims=True), 1e-6)
    return -(t * logp).sum(axis=-1).mean()


def retrieval_contrastive_loss(vil_logit, group_size: int) -> jnp.ndarray:
    """CE over each question's candidate group; index 0 is the aligned image.

    The engine's repeat-batching (worker.py:278-284 semantics) lays a
    question's candidates out contiguously, so (B, 1) → (B//K, K).
    """
    scores = _f32(vil_logit).reshape(-1, group_size)
    logp = jax.nn.log_softmax(scores, axis=-1)
    return -logp[:, 0].mean()


def masked_lm_loss(linguisic_prediction, mlm_labels) -> jnp.ndarray:
    """CE on masked positions; label -1 = not masked (BERT convention).

    With ``task_specific_tokens`` the prediction sequence is one longer than
    the input (task token inserted after [CLS], models/embeddings.py); labels
    are realigned by inserting an ignore label at that slot.
    """
    if linguisic_prediction.shape[1] == mlm_labels.shape[1] + 1:
        pad = jnp.full_like(mlm_labels[:, :1], -1)
        mlm_labels = jnp.concatenate(
            [mlm_labels[:, :1], pad, mlm_labels[:, 1:]], axis=1)
    logp = jax.nn.log_softmax(_f32(linguisic_prediction), axis=-1)
    mask = (mlm_labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(mlm_labels, 0)
    per = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return (per * mask).sum() / jnp.clip(mask.sum(), 1.0)


def masked_region_loss(vision_prediction, target_dist, region_mask) -> jnp.ndarray:
    """KL vs detector class distribution on masked regions
    (predict_feature=False path, reference worker.py:510-514)."""
    logp = jax.nn.log_softmax(_f32(vision_prediction), axis=-1)
    t = _f32(target_dist)
    mask = _f32(region_mask)
    per = -(t * logp).sum(axis=-1)
    return (per * mask).sum() / jnp.clip(mask.sum(), 1.0)


@dataclasses.dataclass(frozen=True)
class LossConfig:
    """Which heads train, with what weight. Static under jit."""

    heads: Sequence[str] = ("vqa",)
    weights: Tuple[float, ...] = ()
    retrieval_group_size: int = 2

    def weight_for(self, i: int) -> float:
        return self.weights[i] if i < len(self.weights) else 1.0


def multitask_loss(
    cfg: LossConfig, out: ViLBertOutput, batch: Dict[str, jnp.ndarray]
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Weighted sum of the configured head losses.

    Batch target keys by head: ``vqa``→``vqa_target`` (B, 3129 soft),
    ``gqa``→``gqa_target``, ``binary``→``binary_label`` int, ``tri``→
    ``tri_label`` int, ``grounding``→``grounding_target`` (B, Nv) +
    ``image_mask``, ``retrieval``→ (uses vil_logit + cfg.retrieval_group_size),
    ``mlm``→``mlm_labels`` int (-1 pad), ``mrm``→``mrm_target`` (B, Nv, C) +
    ``mrm_mask`` (B, Nv).
    """
    metrics: Dict[str, jnp.ndarray] = {}
    total = jnp.zeros((), jnp.float32)
    for i, head in enumerate(cfg.heads):
        if head == "vqa":
            l = label_bce_loss(out.vil_prediction, batch["vqa_target"])
        elif head == "gqa":
            l = label_bce_loss(out.vil_prediction_gqa, batch["gqa_target"])
        elif head == "binary":
            l = softmax_ce_loss(out.vil_binary_prediction, batch["binary_label"])
        elif head == "tri":
            l = softmax_ce_loss(out.vil_tri_prediction, batch["tri_label"])
        elif head == "grounding":
            l = grounding_loss(out.vision_logit, batch["grounding_target"],
                               batch["image_mask"])
        elif head == "retrieval":
            l = retrieval_contrastive_loss(out.vil_logit,
                                           cfg.retrieval_group_size)
        elif head == "mlm":
            l = masked_lm_loss(out.linguisic_prediction, batch["mlm_labels"])
        elif head == "mrm":
            l = masked_region_loss(out.vision_prediction, batch["mrm_target"],
                                   batch["mrm_mask"])
        else:
            raise ValueError(f"unknown loss head {head!r}")
        metrics[f"loss/{head}"] = l
        total = total + cfg.weight_for(i) * l
    metrics["loss/total"] = total
    return total, metrics
