"""Training subsystem: multi-task losses + sharded train step.

The reference demo is inference-only, but its checkpoint is the product of
the 12-in-1 multi-task training regime (paper cited at reference README.md:6;
the training-side loaders the worker imports but never calls are listed at
SURVEY.md §2.2 — ``ConceptCapLoaderTrain/Val``, ``BertForMultiModalPreTraining``
at reference worker.py:44-46). This package provides the TPU-native training
counterpart so the framework can fine-tune / reproduce such checkpoints:
per-task losses over the 10-tuple heads, and a ``pjit``-compiled train step
over the dp×tp mesh.
"""

from vilbert_multitask_tpu.train.losses import (
    LossConfig,
    grounding_loss,
    label_bce_loss,
    masked_lm_loss,
    masked_region_loss,
    multitask_loss,
    retrieval_contrastive_loss,
    softmax_ce_loss,
)
from vilbert_multitask_tpu.train.step import (
    TrainState,
    create_train_state,
    make_train_step,
    shard_train_state,
)

__all__ = [
    "LossConfig",
    "TrainState",
    "create_train_state",
    "grounding_loss",
    "label_bce_loss",
    "make_train_step",
    "masked_lm_loss",
    "masked_region_loss",
    "multitask_loss",
    "retrieval_contrastive_loss",
    "shard_train_state",
    "softmax_ce_loss",
]
