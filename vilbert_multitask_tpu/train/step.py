"""The sharded train step: one ``jit``-compiled optimizer update on the mesh.

TPU-first design (contrast SURVEY.md §2.3 — the reference has no training and
no device parallelism): params are placed by the Megatron-style partition
rules in :mod:`..parallel.sharding`, batches are dp-sharded on axis 0, and
``jax.jit`` lowers the whole value-grad-update to a single XLA program whose
collectives (psum over tp for contracting matmuls, grad all-reduce over dp)
ride ICI. State buffers are donated so the update is in-place in HBM.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks
from vilbert_multitask_tpu.parallel import sharding as shd
from vilbert_multitask_tpu.train.losses import LossConfig, multitask_loss


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: dict
    opt_state: optax.OptState
    rng: jax.Array


def default_optimizer(
    learning_rate: float = 4e-5,
    weight_decay: float = 0.01,
    warmup_steps: int = 1000,
    total_steps: int = 100_000,
    grad_clip: float = 1.0,
) -> optax.GradientTransformation:
    """AdamW + linear warmup/decay + global-norm clip (BERT fine-tune recipe)."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, weight_decay=weight_decay,
                    mask=_weight_decay_mask),
    )


def _weight_decay_mask(params):
    """No decay on biases / LayerNorm scales (standard BERT convention)."""

    def is_decayed(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        return leaf.ndim >= 2 and name not in ("bias", "scale")

    return jax.tree_util.tree_map_with_path(is_decayed, params)


def create_train_state(
    params, tx: optax.GradientTransformation, *, seed: int = 0
) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=jax.random.PRNGKey(seed),
    )


def shard_train_state(state: TrainState, mesh) -> TrainState:
    """Place params by partition rules; optimizer moments mirror their param's
    sharding (same shapes → same specs); scalars/rng replicate."""
    p_shard = shd.param_shardings(state.params, mesh)
    params = jax.device_put(state.params, p_shard)

    # adamw opt_state nests ScaleByAdamState whose mu/nu are exact param-tree
    # copies: shard them with the params' own shardings.
    def place_state(s):
        if isinstance(s, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(
                count=jax.device_put(s.count),
                mu=jax.device_put(s.mu, p_shard),
                nu=jax.device_put(s.nu, p_shard),
            )
        return s

    opt_state = jax.tree_util.tree_map(
        place_state, state.opt_state,
        is_leaf=lambda s: isinstance(s, optax.ScaleByAdamState),
    )
    return TrainState(
        step=jax.device_put(state.step),
        params=params,
        opt_state=opt_state,
        rng=jax.device_put(state.rng),
    )


def make_train_step(
    model: ViLBertForVLTasks,
    tx: optax.GradientTransformation,
    loss_cfg: LossConfig,
    *,
    donate: bool = True,
) -> Callable[[TrainState, Dict], Tuple[TrainState, Dict]]:
    """Build the jitted step. Re-jit per (model, tx, loss_cfg) triple."""

    def step_fn(state: TrainState, batch: Dict) -> Tuple[TrainState, Dict]:
        rng, dropout_rng = jax.random.split(state.rng)

        def loss_fn(params):
            out = model.apply(
                {"params": params},
                batch["input_ids"], batch["features"], batch["spatials"],
                batch["segment_ids"], batch["input_mask"],
                batch["image_mask"], None, batch.get("task_ids"),
                deterministic=False,
                rngs={"dropout": dropout_rng},
            )
            return multitask_loss(loss_cfg, out, batch)

        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics["grad_norm"] = optax.global_norm(grads)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state, rng=rng
        )
        return new_state, metrics

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())
