"""Multi-task training loop: sampler → sharded step → checkpoint/resume.

Reference capability: the demo serves a checkpoint produced by the 12-in-1
multi-task regime (reference README.md:4,6) whose trainer lives OUTSIDE the
repo — the worker imports its loaders and never calls them
(``ConceptCapLoaderTrain/Val``, ``LoadDatasetEval``, reference worker.py:44-46;
SURVEY.md §2.2 "document, don't build" row). This module is the TPU-native
trainer that closes the lifecycle: the framework can now fine-tune or
reproduce the checkpoints it serves.

TPU-first structure:

- **per-task compiled steps**: the 12-in-1 regime alternates task batches;
  here each head gets ONE jitted program (fixed shapes, its own LossConfig)
  chosen per step by the host-side sampler — the XLA analogue of the
  reference ecosystem's task-alternating loader, with zero retracing.
- **dp×tp mesh**: batches are dp-sharded, params/moments placed by the
  Megatron partition rules (train/step.py); state buffers are donated so the
  update is in-place in HBM.
- **full-state checkpoint/resume**: Orbax TrainState snapshots
  (checkpoint/store.py save_train_state) every ``ckpt_every`` steps; resume
  picks up step/params/opt-state/rng exactly where the last snapshot left
  off.

Data: ``SyntheticTaskData`` generates shape-correct batches for any head
(smoke/perf runs); ``JsonlTaskData`` reads the same JSONL + feature-store
formats the eval harness uses (evals/harness.py) for vqa/gqa/tri (SNLI-VE),
nlvr2 pairs, and grounding with IoU-derived soft targets.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.config import FrameworkConfig
from vilbert_multitask_tpu.train.losses import LossConfig
from vilbert_multitask_tpu.train.step import (
    TrainState,
    create_train_state,
    default_optimizer,
    make_train_step,
    shard_train_state,
)

# head → (serving task id, batch target keys). Task ids follow the demo's
# dispatch table (config.TASK_REGISTRY; reference result.html:318-336).
# "pretrain" is the Conceptual-Captions-style masked objective (the
# ``BertForMultiModalPreTraining`` capability the reference imports and
# never calls, worker.py:45); task token 0 is reserved for it.
HEAD_TASK_IDS = {"vqa": 1, "gqa": 15, "tri": 13, "binary": 12,
                 "grounding": 11, "retrieval": 7, "pretrain": 0}

# Heads that train as a GROUP under one compiled step (one LossConfig):
# pretraining jointly optimizes masked-LM + masked-region prediction.
HEAD_LOSS_GROUPS = {"pretrain": ("mlm", "mrm")}


def apply_mlm_masking(input_ids: np.ndarray, input_mask: np.ndarray,
                      rng, *, mask_id: int, vocab_size: int,
                      special_ids: Sequence[int],
                      mask_prob: float = 0.15) -> Tuple[np.ndarray,
                                                        np.ndarray]:
    """BERT dynamic masking: 15% of real, non-special positions; of those
    80% → [MASK], 10% → random id, 10% → kept. Returns (masked_ids, labels)
    with label -1 on unmasked positions (train/losses.py convention)."""
    ids = input_ids.copy()
    labels = np.full_like(ids, -1)
    special = np.isin(ids, np.asarray(list(special_ids)))
    candidates = (input_mask > 0) & ~special
    pick = candidates & (rng.random(ids.shape) < mask_prob)
    labels[pick] = ids[pick]
    action = rng.random(ids.shape)
    ids[pick & (action < 0.8)] = mask_id
    rand_pos = pick & (action >= 0.8) & (action < 0.9)
    ids[rand_pos] = rng.integers(0, vocab_size, int(rand_pos.sum()))
    return ids, labels


def apply_mrm_masking(regions, rng, *, n_classes: int, max_regions: int,
                      mask_prob: float = 0.15):
    """Masked-region modeling on RAW region sets, BEFORE encoding: ~15% of
    each image's detector rows get their features zeroed, so the global
    mean-pool row that ``encode_image`` prepends is computed over the
    masked features (masking after pooling would leak every masked region
    through row 0). The target is the detector's class distribution
    (reference schema ``cls_prob``), or uniform when the store carries
    none / the width disagrees with ``v_target_size``.

    Returns (masked_regions, mrm_target (B, max_regions, C),
    mrm_mask (B, max_regions)) — targets/mask aligned to the ENCODED
    layout (row 0 = global, never masked).
    """
    masked, targets, masks = [], [], []
    for r in regions:
        n = int(r.num_boxes)
        pick = rng.random((n,)) < mask_prob
        feats = np.asarray(r.features[:n], np.float32).copy()
        feats[pick] = 0.0
        masked.append(dataclasses.replace(r, features=feats, num_boxes=n))
        target = np.full((max_regions, n_classes), 1.0 / n_classes,
                         np.float32)
        cp = r.cls_prob
        if cp is not None and cp.ndim == 2 and cp.shape[1] == n_classes:
            k = min(cp.shape[0], n, max_regions - 1)
            row_sum = np.clip(cp[:k].sum(axis=-1, keepdims=True), 1e-9, None)
            target[1 : k + 1] = cp[:k] / row_sum
        targets.append(target)
        if n > max_regions - 1:
            raise ValueError(
                f"{n} regions exceed the {max_regions - 1} budget — run "
                f"clip_regions before masking")
        mask = np.zeros((max_regions,), np.float32)
        mask[1 : n + 1] = pick.astype(np.float32)
        masks.append(mask)
    return masked, np.stack(targets), np.stack(masks)


# ------------------------------------------------------------------ batching
def _text_batch(tokenizer, questions: Sequence[str], max_len: int,
                task_id: int) -> Dict[str, np.ndarray]:
    from vilbert_multitask_tpu.text.pipeline import encode_question

    enc = [encode_question(tokenizer, q, max_len, task_id=task_id)
           for q in questions]
    return dict(
        input_ids=np.stack([e.input_ids for e in enc]),
        segment_ids=np.stack([e.segment_ids for e in enc]),
        input_mask=np.stack([e.input_mask for e in enc]),
        task_ids=np.full((len(enc), 1), task_id, np.int32),
    )


def _image_batch(regions, max_regions: int) -> Dict[str, np.ndarray]:
    from vilbert_multitask_tpu.features.pipeline import (
        batch_images,
        encode_image,
    )

    feats, spatials, mask = batch_images(
        [encode_image(r, max_regions) for r in regions])
    return dict(features=feats, spatials=spatials, image_mask=mask)


def iou_grounding_target(boxes: np.ndarray, gt_box: Sequence[float],
                         n_regions: int, max_regions: int) -> np.ndarray:
    """Per-region soft target from a ground-truth box: IoU where ≥ 0.5
    (the 12-in-1 grounding supervision shape), renormalized; if no region
    clears 0.5 the single best-IoU region gets the full mass. Row 0 is the
    global region (never a target)."""
    target = np.zeros((max_regions,), np.float32)
    if n_regions == 0:
        return target
    b = np.asarray(boxes[:n_regions], np.float32)
    gx1, gy1, gx2, gy2 = [float(v) for v in gt_box]
    ix1 = np.maximum(b[:, 0], gx1)
    iy1 = np.maximum(b[:, 1], gy1)
    ix2 = np.minimum(b[:, 2], gx2)
    iy2 = np.minimum(b[:, 3], gy2)
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    area_g = max((gx2 - gx1) * (gy2 - gy1), 1e-9)
    iou = inter / np.clip(area_b + area_g - inter, 1e-9, None)
    keep = iou * (iou >= 0.5)
    if keep.sum() <= 0:
        keep = np.zeros_like(iou)
        keep[int(np.argmax(iou))] = 1.0
    target[1 : n_regions + 1] = keep / keep.sum()
    return target


def vqa_soft_target(answers: Sequence[str], ans2label: Dict[str, int],
                    num_labels: int) -> np.ndarray:
    """VQAv2 soft score: min(1, matching_annotators * 0.3) per label."""
    target = np.zeros((num_labels,), np.float32)
    for ans in set(answers):
        idx = ans2label.get(ans)
        if idx is not None:
            target[idx] = min(1.0, 0.3 * sum(a == ans for a in answers))
    return target


class SyntheticTaskData:
    """Shape-correct random batches for one head — smoke tests and perf
    runs (every head also has a real JSONL loader, JsonlTaskData)."""

    def __init__(self, head: str, cfg: FrameworkConfig, *, seed: int = 0,
                 group_size: int = 2):
        if head not in HEAD_TASK_IDS:
            raise ValueError(f"unknown head {head!r}")
        self.head = head
        self.cfg = cfg
        self.group_size = group_size
        self.seed = seed

    def batch(self, batch_size: int, *, step: int = 0
              ) -> Dict[str, np.ndarray]:
        # Stateless draw keyed by the global step: a resumed run sees the
        # exact batch sequence an uninterrupted run would have. The task id
        # decorrelates this stream from the sampler's head-selection draw
        # (same (seed, step) alone would reuse one PCG64 bitstream).
        rng = np.random.default_rng(
            (self.seed, step, HEAD_TASK_IDS[self.head]))
        m, e = self.cfg.model, self.cfg.engine
        B, Nt, Nv = batch_size, e.max_text_len, e.max_regions
        out = dict(
            input_ids=rng.integers(0, m.vocab_size, (B, Nt)).astype(np.int32),
            segment_ids=np.zeros((B, Nt), np.int32),
            input_mask=np.ones((B, Nt), np.int32),
            features=rng.standard_normal(
                (B, Nv, m.v_feature_size)).astype(np.float32),
            spatials=rng.random((B, Nv, 5)).astype(np.float32),
            image_mask=np.ones((B, Nv), np.int32),
            task_ids=np.full((B, 1), HEAD_TASK_IDS[self.head], np.int32),
        )
        h = self.head
        if h == "vqa":
            out["vqa_target"] = rng.random((B, m.num_labels)).astype(
                np.float32)
        elif h == "gqa":
            out["gqa_target"] = rng.random((B, m.gqa_num_labels)).astype(
                np.float32)
        elif h == "tri":
            out["tri_label"] = rng.integers(0, 3, (B,)).astype(np.int32)
        elif h == "binary":
            if B % 2:
                raise ValueError("binary (NLVR2) needs an even batch")
            out["binary_label"] = rng.integers(0, 2, (B // 2,)).astype(
                np.int32)
        elif h == "grounding":
            t = rng.random((B, Nv)).astype(np.float32)
            out["grounding_target"] = t / t.sum(axis=-1, keepdims=True)
        elif h == "retrieval":
            if B % self.group_size:
                raise ValueError(
                    "retrieval batch must be divisible by group_size")
        elif h == "pretrain":
            labels = np.full((B, Nt), -1, np.int32)
            pick = rng.random((B, Nt)) < 0.15
            labels[pick] = rng.integers(
                0, m.vocab_size, int(pick.sum())).astype(np.int32)
            out["mlm_labels"] = labels
            t = rng.random((B, Nv, m.v_target_size)).astype(np.float32)
            out["mrm_target"] = t / t.sum(axis=-1, keepdims=True)
            out["mrm_mask"] = (rng.random((B, Nv)) < 0.15).astype(np.float32)
        return out


class JsonlTaskData:
    """One head's real dataset: the eval-harness JSONL schema + a feature
    store (evals/harness.py; fixtures under tests/fixtures/golden/*.jsonl).

    vqa/gqa: {"question", "image", "answers": [...]}
    tri:     {"premise"|"question", "image", "label": 0..2}
    binary:  {"caption", "images": [a, b], "label": bool}
    grounding: {"expression", "image", "gt_box": [x1, y1, x2, y2]}
    pretrain: {"caption", "image"} — Conceptual-Captions-style pairs with
              DYNAMIC masking per (seed, step): BERT 80/10/10 token masking
              + ~15% region zeroing with the detector class distribution
              (store ``cls_prob``) as the MRM target.
    retrieval: {"caption", "images": [...], "target": i} — the caption
              replicates over ``group_size`` candidates (the positive at
              row offset 0; the contrastive loss scores within the group,
              train/losses.py retrieval_contrastive_loss).
    """

    def __init__(self, head: str, jsonl_path: str, feature_store, tokenizer,
                 cfg: FrameworkConfig, *, label_map=None, seed: int = 0,
                 group_size: int = 2):
        from vilbert_multitask_tpu.utils import IndexedJsonl

        if head not in ("vqa", "gqa", "tri", "binary", "grounding",
                        "pretrain", "retrieval"):
            raise ValueError(f"no JSONL loader for head {head!r}")
        self.group_size = group_size
        self.head = head
        # Offset-indexed, not loaded whole: the sampler draws random
        # indices per step, and at real 12-in-1 dataset sizes (hundreds of
        # thousands to millions of rows) resident parsed records would be
        # the trainer's memory bill.
        self.examples = IndexedJsonl(jsonl_path)
        if not self.examples:
            raise ValueError(f"empty dataset {jsonl_path}")
        self.store = feature_store
        self.tokenizer = tokenizer
        self.cfg = cfg
        # answer string → label index (vqa/gqa); accepts a LabelMapStore
        # list or a plain list of answer strings.
        self.ans2label: Dict[str, int] = {}
        if label_map is not None:
            self.ans2label = {a: i for i, a in enumerate(label_map)}
        if head in ("vqa", "gqa") and not self.ans2label:
            # Without the map every soft target is all-zero and BCE just
            # suppresses all logits — training runs but learns nothing.
            raise ValueError(
                f"head {head!r} needs a non-empty label_map "
                "(answer-string → index); got none")
        self.seed = seed

    def __len__(self) -> int:
        return len(self.examples)

    def close(self) -> None:
        """Release the dataset's file handle (owned here — the IndexedJsonl
        is constructed by and private to this loader)."""
        self.examples.close()

    def _question_of(self, ex: Dict) -> str:
        for k in ("question", "expression", "caption", "premise"):
            if k in ex:
                return ex[k]
        raise KeyError(f"no text field in example {sorted(ex)}")

    def batch(self, batch_size: int, *, step: int = 0
              ) -> Dict[str, np.ndarray]:
        m, e = self.cfg.model, self.cfg.engine
        h = self.head
        if h == "binary":
            if batch_size % 2:
                # Same contract as SyntheticTaskData: silently dropping a
                # row would also break dp-divisibility on a sharded mesh.
                raise ValueError(
                    f"NLVR2 batch {batch_size} must be even (2 images/row)")
            n_logical = batch_size // 2
        elif h == "retrieval":
            if batch_size % self.group_size:
                raise ValueError(
                    f"retrieval batch {batch_size} must be divisible by "
                    f"group_size {self.group_size}")
            n_logical = batch_size // self.group_size
        else:
            n_logical = batch_size
        # Stateless draw keyed by the global step (exact resume); task id
        # decorrelates from the sampler's head-selection stream.
        rng_idx = np.random.default_rng((self.seed, step, HEAD_TASK_IDS[h]))
        idx = rng_idx.integers(0, len(self.examples), (n_logical,))
        exs = [self.examples[i] for i in idx]
        task_id = HEAD_TASK_IDS[h]

        if h == "binary":  # NLVR2: text repeated per image of the pair
            questions, image_keys = [], []
            for ex in exs:
                questions.extend([self._question_of(ex)] * 2)
                image_keys.extend(ex["images"][:2])
        elif h == "retrieval":
            # Per caption: the positive image FIRST (loss convention:
            # retrieval_contrastive_loss scores index 0 as aligned), then
            # group_size-1 distractors drawn from the other candidates.
            questions, image_keys = [], []
            for ex in exs:
                imgs = list(ex["images"])
                pos = int(ex.get("target", 0))
                distract = [k for j, k in enumerate(imgs) if j != pos]
                need = self.group_size - 1
                if len(distract) < need:
                    raise ValueError(
                        f"retrieval example needs ≥{self.group_size} images")
                picks = list(rng_idx.choice(len(distract), size=need,
                                            replace=False))
                questions.extend([self._question_of(ex)] * self.group_size)
                image_keys.append(imgs[pos])
                image_keys.extend(distract[j] for j in picks)
        else:
            questions = [self._question_of(ex) for ex in exs]
            image_keys = [ex["image"] for ex in exs]

        from vilbert_multitask_tpu.features.pipeline import clip_regions

        regions = clip_regions(self.store.get_batch(image_keys),
                               e.max_regions)
        if h == "pretrain":
            # Region masking happens BEFORE encoding: encode_image builds
            # the global row 0 as the mean over region features, so masking
            # the already-encoded batch would leak every masked region's
            # content through the pool. Masking the raw rows first means
            # the global mean sees zeros, like the reference regime.
            rng = np.random.default_rng(
                (self.seed, step, HEAD_TASK_IDS[h], 1))
            if not getattr(self, "_warned_uniform_mrm", False):
                bad = sum(1 for r in regions
                          if r.cls_prob is None or r.cls_prob.ndim != 2
                          or r.cls_prob.shape[1] != m.v_target_size)
                if bad:
                    import logging

                    logging.getLogger(__name__).warning(
                        "%d/%d sampled images carry no usable cls_prob "
                        "(need (N, %d)); their MRM targets fall back to "
                        "uniform — detector supervision is lost for them",
                        bad, len(regions), m.v_target_size)
                    self._warned_uniform_mrm = True
            regions, mrm_target, mrm_mask = apply_mrm_masking(
                regions, rng, n_classes=m.v_target_size,
                max_regions=e.max_regions)
        out = _text_batch(self.tokenizer, questions, e.max_text_len, task_id)
        out.update(_image_batch(regions, e.max_regions))

        if h in ("vqa", "gqa"):
            key = "vqa_target" if h == "vqa" else "gqa_target"
            width = m.num_labels if h == "vqa" else m.gqa_num_labels
            out[key] = np.stack([
                vqa_soft_target(ex["answers"], self.ans2label, width)
                for ex in exs])
        elif h == "tri":
            out["tri_label"] = np.asarray([int(ex["label"]) for ex in exs],
                                          np.int32)
        elif h == "binary":
            out["binary_label"] = np.asarray(
                [int(bool(ex["label"])) for ex in exs], np.int32)
        elif h == "grounding":
            out["grounding_target"] = np.stack([
                iou_grounding_target(r.boxes, ex["gt_box"], r.num_boxes,
                                     e.max_regions)
                for ex, r in zip(exs, regions)])
        elif h == "pretrain":
            # Region masking already happened pre-encoding (above); here
            # only the text side masks, with the SAME per-step stream.
            rng = np.random.default_rng(
                (self.seed, step, HEAD_TASK_IDS[h], 2))
            tok = self.tokenizer
            specials = (tok.pad_id, tok.cls_id, tok.sep_id, tok.mask_id)
            out["input_ids"], out["mlm_labels"] = apply_mlm_masking(
                out["input_ids"], out["input_mask"], rng,
                mask_id=tok.mask_id, vocab_size=m.vocab_size,
                special_ids=specials)
            out["mrm_target"] = mrm_target
            out["mrm_mask"] = mrm_mask
        return out


# ------------------------------------------------------------------- sampler
class MultiTaskSampler:
    """Host-side task alternation: each step draws ONE head (weighted by
    dataset size unless overridden) and asks its dataset for a batch — the
    12-in-1 alternating-task schedule. Draws are STATELESS, keyed by the
    global step, so a resumed run replays the exact schedule an
    uninterrupted run would have produced (checkpoint/resume is bit-exact
    up to hardware nondeterminism)."""

    def __init__(self, datasets: Dict[str, object], *,
                 weights: Optional[Dict[str, float]] = None, seed: int = 0):
        if not datasets:
            raise ValueError("need at least one task dataset")
        self.datasets = dict(datasets)
        self.heads = sorted(self.datasets)
        if weights:
            w = np.asarray([float(weights.get(h, 1.0)) for h in self.heads])
        else:
            w = np.asarray([
                float(len(d)) if hasattr(d, "__len__") else 1.0
                for d in (self.datasets[h] for h in self.heads)])
        self.probs = w / w.sum()
        self.seed = seed

    # Distinct stream tag: head selection must not share a bitstream with
    # any dataset's example draws at the same (seed, step).
    _STREAM = 0x5A

    def next(self, batch_size: int, step: int
             ) -> Tuple[str, Dict[str, np.ndarray]]:
        rng = np.random.default_rng((self.seed, step, self._STREAM))
        head = self.heads[int(rng.choice(len(self.heads), p=self.probs))]
        return head, self.datasets[head].batch(batch_size, step=step)


# --------------------------------------------------------------------- loop
STEP_DIR_RE = re.compile(r"^step_(\d{8})$")


def latest_checkpoint(out_dir: str) -> Optional[Tuple[str, int]]:
    """(path, step) of the newest step_XXXXXXXX snapshot under out_dir."""
    try:
        entries = os.listdir(out_dir)
    except OSError:
        return None
    best = None
    for name in entries:
        mt = STEP_DIR_RE.match(name)
        if mt:
            step = int(mt.group(1))
            if best is None or step > best[1]:
                best = (os.path.join(out_dir, name), step)
    return best


@dataclasses.dataclass
class LoopConfig:
    total_steps: int = 1000
    batch_size: int = 8
    learning_rate: float = 4e-5
    warmup_steps: int = 100
    log_every: int = 10
    ckpt_every: int = 200
    keep_ckpts: int = 3
    seed: int = 0
    retrieval_group_size: int = 2
    # 0 disables; otherwise Trainer calls its eval_fn(step, state) at this
    # cadence (and at the final step) and logs the returned scores.
    eval_every: int = 0


class EvalHook:
    """In-training evaluation on the SERVING path: the trainer's current
    params drop into an InferenceEngine and run the eval harness — scores
    measure exactly what a deployed worker would answer, decode included
    (evals/harness.py), not a proxy metric.

    The engine is built lazily once (its jitted programs compile on first
    eval and are reused; only the param reference swaps per eval).
    """

    # Result fields that are metadata, not scores — kept out of the eval/
    # log keys so "max over eval/*" checkpoint selection can't pick up n.
    _META_KEYS = frozenset({"task_id", "n", "wall_s", "metric"})

    def __init__(self, cfg: FrameworkConfig, feature_store, tasks:
                 Dict[str, Sequence[Dict]], *, batch: int = 8,
                 label_store=None, tokenizer=None, mesh=None):
        from vilbert_multitask_tpu.evals.harness import Evaluator

        unknown = set(tasks) - set(Evaluator.EVAL_FNS)
        if unknown:
            raise ValueError(
                f"unknown eval tasks {sorted(unknown)}; the harness serves "
                f"{sorted(Evaluator.EVAL_FNS)}")
        self.cfg = cfg
        self.store = feature_store
        self.tasks = dict(tasks)  # eval task name → examples
        self.batch = batch
        self.label_store = label_store
        self.tokenizer = tokenizer
        self.mesh = mesh  # the TRAINER's mesh: sharded params need an
        # engine that places inputs with matching shardings
        self._engine = None

    def __call__(self, step: int, state) -> Dict[str, float]:
        from vilbert_multitask_tpu.engine.runtime import InferenceEngine
        from vilbert_multitask_tpu.evals.harness import Evaluator

        if self._engine is None:
            self._engine = InferenceEngine(
                self.cfg, params=state.params, feature_store=self.store,
                label_store=self.label_store, tokenizer=self.tokenizer,
                mesh=self.mesh)
        else:
            self._engine.params = state.params  # same tree structure
        ev = Evaluator(self._engine, batch=self.batch)
        out: Dict[str, float] = {}
        for task, examples in self.tasks.items():
            scores = ev.run(task, examples)
            for k, v in scores.items():
                if k not in self._META_KEYS and isinstance(v, (int, float)):
                    out[f"eval/{task}/{k}"] = round(float(v), 5)
        return out


class Trainer:
    """Owns model/optimizer/state and the per-head compiled steps."""

    def __init__(self, cfg: FrameworkConfig, sampler: MultiTaskSampler,
                 loop: LoopConfig, *, out_dir: Optional[str] = None,
                 mesh=None, init_params=None,
                 eval_fn: Optional[Callable[[int, TrainState],
                                            Dict[str, float]]] = None,
                 log_fn: Callable[[str], None] = print):
        import jax
        import jax.numpy as jnp

        from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks

        self.cfg, self.sampler, self.loop = cfg, sampler, loop
        self.out_dir, self.mesh, self.log = out_dir, mesh, log_fn
        self.eval_fn = eval_fn
        # The contrastive loss reshapes by loop.retrieval_group_size; a
        # dataset laying out a different group width would silently score
        # distractors as positives — fail construction instead.
        for head, ds in sampler.datasets.items():
            ds_group = getattr(ds, "group_size", None)
            if (head == "retrieval" and ds_group is not None
                    and ds_group != loop.retrieval_group_size):
                raise ValueError(
                    f"retrieval dataset group_size={ds_group} != "
                    f"LoopConfig.retrieval_group_size="
                    f"{loop.retrieval_group_size}")
        # Training computes in bf16 like serving; master params stay f32.
        # A mesh with a real "sp" axis routes the visual stream through
        # ring attention for ≥ring_min_regions buckets (long-context
        # training).
        from vilbert_multitask_tpu.parallel.ring import RingContext

        ring_v = RingContext.from_mesh(mesh,
                                       min_seq=cfg.engine.ring_min_regions)
        if ring_v is not None and cfg.model.v_attention_probs_dropout_prob > 0:
            # The ring never materializes attention probs, so probs-dropout
            # has no ring implementation — FusedSelfAttention keeps the
            # dense path whenever dropout is live, which on TRAIN steps is
            # every step. Silence would mean the sp axis the user asked for
            # does nothing exactly where it matters (long sequences, OOM).
            import logging

            logging.getLogger(__name__).warning(
                "MeshConfig.sp > 1 but v_attention_probs_dropout_prob=%.3f "
                "keeps TRAIN steps on dense attention (ring attention has "
                "no probs-dropout path). Set "
                "v_attention_probs_dropout_prob=0.0 to train "
                "sequence-parallel; eval/serving forwards ring regardless.",
                cfg.model.v_attention_probs_dropout_prob)
        self.model = ViLBertForVLTasks(
            dataclasses.replace(cfg.model,
                                use_pallas_coattention=False,
                                use_pallas_self_attention=False),
            ring_v=ring_v,
            dtype=jnp.dtype(cfg.engine.compute_dtype))
        self.tx = default_optimizer(
            learning_rate=loop.learning_rate, warmup_steps=loop.warmup_steps,
            total_steps=loop.total_steps)
        self._steps: Dict[str, Callable] = {}  # head → jitted step

        if init_params is None:
            init_params = self._init_params()
        state = create_train_state(init_params, self.tx, seed=loop.seed)
        resumed = None
        if out_dir:
            resumed = latest_checkpoint(out_dir)
        if resumed is not None:
            from vilbert_multitask_tpu.checkpoint.store import (
                restore_train_state,
            )

            path, step = resumed
            state = restore_train_state(path, state, mesh=mesh)
            self.log(f"# resumed from {path} at step {step}")
        elif mesh is not None:
            state = shard_train_state(state, mesh)
        else:
            state = jax.device_put(state)
        self.state = state

    def _init_params(self):
        import jax

        # Even batch: the paired NLVR2 binary head only materializes for
        # even batches — an odd init would mint a param tree without it and
        # break checkpoint-structure compatibility across batch sizes.
        B = max(2, self.loop.batch_size + self.loop.batch_size % 2)
        dummy = SyntheticTaskData("vqa", self.cfg).batch(B)
        variables = self.model.init(
            jax.random.PRNGKey(self.loop.seed), dummy["input_ids"],
            dummy["features"], dummy["spatials"], dummy["segment_ids"],
            dummy["input_mask"], dummy["image_mask"], None,
            dummy["task_ids"], deterministic=True)
        import jax.numpy as jnp

        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            variables["params"])

    def _step_for(self, head: str) -> Callable:
        if head not in self._steps:
            loss_cfg = LossConfig(
                heads=HEAD_LOSS_GROUPS.get(head, (head,)),
                retrieval_group_size=self.loop.retrieval_group_size)
            self._steps[head] = make_train_step(self.model, self.tx, loss_cfg)
        return self._steps[head]

    def _place_batch(self, batch: Dict[str, np.ndarray]):
        import jax

        if self.mesh is None:
            return batch
        from vilbert_multitask_tpu.parallel import sharding as shd

        # global_batch: the samplers draw from the GLOBAL step, so every
        # process holds this identical batch (the cross-process contract).
        return shd.place_batch(batch, self.mesh, global_batch=True)

    def _save(self, step: int) -> None:
        from vilbert_multitask_tpu.checkpoint.store import save_train_state

        path = os.path.join(self.out_dir, f"step_{step:08d}")
        save_train_state(path, self.state)
        # retention: keep the newest keep_ckpts snapshots
        snaps = sorted(
            (n for n in os.listdir(self.out_dir) if STEP_DIR_RE.match(n)))
        for name in snaps[: -self.loop.keep_ckpts]:
            import shutil

            shutil.rmtree(os.path.join(self.out_dir, name),
                          ignore_errors=True)

    def train(self) -> Dict[str, float]:
        """Run to ``loop.total_steps`` (from the resumed step); returns the
        final host metrics."""
        import jax

        lp = self.loop
        start = int(jax.device_get(self.state.step))
        last_metrics: Dict[str, float] = {}
        t0 = time.perf_counter()
        window = start
        import contextlib

        ctx = self.mesh if self.mesh is not None else contextlib.nullcontext()
        with ctx:
            for step in range(start, lp.total_steps):
                with obs.span("train.data", step=step):
                    head, batch = self.sampler.next(lp.batch_size, step)
                    batch = self._place_batch(batch)
                with obs.span("train.step", step=step, head=head):
                    self.state, metrics = self._step_for(head)(self.state,
                                                               batch)
                now = step + 1
                if now % lp.log_every == 0 or now == lp.total_steps:
                    m = {k: round(float(v), 5)
                         for k, v in jax.device_get(metrics).items()}
                    if not np.isfinite(m.get("loss/total", 0.0)):
                        # Fail at the first logged divergence, not after the
                        # remaining budget burns on NaN updates. The last
                        # snapshot (≤ ckpt_every steps old) is the restart
                        # point.
                        raise FloatingPointError(
                            f"non-finite loss at step {now} (head {head}): "
                            f"{m}")
                    dt = time.perf_counter() - t0
                    m.update(step=now, head=head,
                             steps_per_s=round((now - window) / max(dt, 1e-9),
                                               3))
                    self.log(json.dumps(m))
                    last_metrics = m
                    t0, window = time.perf_counter(), now
                if (self.eval_fn is not None and lp.eval_every
                        and (now % lp.eval_every == 0
                             or now == lp.total_steps)):
                    scores = self.eval_fn(now, self.state)
                    self.log(json.dumps({"step": now, **scores}))
                if self.out_dir and (now % lp.ckpt_every == 0
                                     or now == lp.total_steps):
                    # Never snapshot a diverged state: ckpt and log cadences
                    # differ, so the loss could have gone NaN since the last
                    # logged check — a poisoned snapshot would defeat the
                    # whole restart-point contract.
                    loss_now = float(jax.device_get(metrics["loss/total"]))
                    if not np.isfinite(loss_now):
                        raise FloatingPointError(
                            f"non-finite loss at step {now} (head {head}); "
                            f"snapshot NOT written")
                    with obs.span("train.checkpoint", step=now):
                        self._save(now)
        return last_metrics


# ----------------------------------------------------------------------- CLI
def main(argv=None) -> None:
    """``python -m vilbert_multitask_tpu.train.loop`` — synthetic-data or
    JSONL-backed multi-task training."""
    import argparse

    p = argparse.ArgumentParser(description="ViLBERT multi-task TPU trainer")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--heads", default="vqa,tri,grounding",
                   help="comma list of heads "
                        f"(choices: {sorted(HEAD_TASK_IDS)})")
    p.add_argument("--out", default=None, help="checkpoint/resume dir")
    p.add_argument("--data-root", default=None,
                   help="dir with <head>.jsonl files + features/ store; "
                        "omit for synthetic shape-correct data")
    p.add_argument("--tiny", action="store_true",
                   help="tiny model config (CPU smoke)")
    p.add_argument("--lr", type=float, default=4e-5)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--ckpt-every", type=int, default=200)
    p.add_argument("--eval-every", type=int, default=0,
                   help="run the eval harness on the current params every N "
                        "steps (needs --data-root with eval_<task>.jsonl "
                        "files; tasks: vqa/gqa/grounding/visual7w/"
                        "retrieval/nlvr2)")
    args = p.parse_args(argv)

    cfg = FrameworkConfig()
    if args.tiny:
        cfg = dataclasses.replace(cfg, model=cfg.model.tiny())
    heads = [h.strip() for h in args.heads.split(",") if h.strip()]

    datasets: Dict[str, object] = {}
    if args.data_root:
        from vilbert_multitask_tpu import assets
        from vilbert_multitask_tpu.engine.labels import LabelMapStore
        from vilbert_multitask_tpu.features.store import FeatureStore
        from vilbert_multitask_tpu.text.wordpiece import FullTokenizer

        store = FeatureStore(os.path.join(args.data_root, "features"))
        tok = FullTokenizer.from_vocab_file(
            cfg.engine.vocab_path or assets.default_vocab_path())
        labels = LabelMapStore(
            root=cfg.engine.labels_root or assets.default_labels_root(),
            sizes={"vqa": cfg.model.num_labels,
                   "gqa": cfg.model.gqa_num_labels})
        for h in heads:
            label_map = (labels.get("vqa") if h == "vqa"
                         else labels.get("gqa") if h == "gqa" else None)
            datasets[h] = JsonlTaskData(
                h, os.path.join(args.data_root, f"{h}.jsonl"), store, tok,
                cfg, label_map=label_map)
    else:
        for h in heads:
            datasets[h] = SyntheticTaskData(h, cfg)

    mesh = None
    import jax

    if jax.device_count() > 1:
        from vilbert_multitask_tpu.parallel import build_mesh

        mesh = build_mesh(cfg.mesh)
        print(f"# mesh: {dict(mesh.shape)}")

    loop = LoopConfig(total_steps=args.steps, batch_size=args.batch,
                      learning_rate=args.lr, log_every=args.log_every,
                      ckpt_every=args.ckpt_every, eval_every=args.eval_every,
                      warmup_steps=max(1, args.steps // 10))
    eval_fn = None
    if args.eval_every and not args.data_root:
        print("# --eval-every needs --data-root (eval_<task>.jsonl files); "
              "no evals will run")
    if args.eval_every and args.data_root:
        from vilbert_multitask_tpu.evals.harness import Evaluator, load_jsonl

        eval_tasks = {}
        for name in sorted(Evaluator.EVAL_FNS):  # the harness's task names
            path = os.path.join(args.data_root, f"eval_{name}.jsonl")
            if os.path.exists(path):
                eval_tasks[name] = load_jsonl(path)
        if eval_tasks:
            # Share the training run's tokenizer/labels/mesh so the eval
            # engine measures exactly this configuration.
            eval_fn = EvalHook(cfg, store, eval_tasks, label_store=labels,
                               tokenizer=tok, mesh=mesh)
            print(f"# eval tasks: {sorted(eval_tasks)}")
        else:
            print("# --eval-every set but no eval_<task>.jsonl under "
                  "--data-root; skipping evals")
    trainer = Trainer(cfg, MultiTaskSampler(datasets), loop,
                      out_dir=args.out, mesh=mesh, eval_fn=eval_fn)
    final = trainer.train()
    print(json.dumps({"final": final}))


if __name__ == "__main__":
    main()
