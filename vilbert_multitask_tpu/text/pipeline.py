"""Host-side text preprocessing: query → fixed-shape int32 buffers.

Reference capability: the text half of ``custom_prediction`` (reference
worker.py:388-419):

- wordpiece-encode the query and wrap with [CLS]/[SEP] (worker.py:402-403);
- pad **by appending** zeros up to ``max_length=37`` (worker.py:408-413 — the
  comment there claims front-padding but the code appends; the checkpoint was
  trained against append semantics, so append is the contract);
- segment ids all zero, input mask 1 on real tokens (worker.py:405-406);
- GuessWhat (task 16) dialog reformatting: the reference builds the
  reformatted string and then **discards it** (worker.py:390-402 — dead code).
  Here the reformat actually takes effect by default; pass
  ``guesswhat_raw_query=True`` for bug-compatible raw-query behavior.

Divergence (knowing fix): the reference never truncates, so an over-long
query changes tensor shape per request; static TPU shapes require truncation
to ``max_len`` (keeping [SEP] as the final token).
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np

from vilbert_multitask_tpu.text.wordpiece import FullTokenizer


@dataclasses.dataclass
class EncodedText:
    """Fixed-shape (max_len,) int32 buffers ready to batch."""

    input_ids: np.ndarray
    input_mask: np.ndarray
    segment_ids: np.ndarray

    def stack(self, n: int) -> "EncodedText":
        """Replicate to an (n, max_len) batch — NLVR2/retrieval repeat
        semantics (reference worker.py:266-284)."""
        return EncodedText(
            input_ids=np.tile(self.input_ids, (n, 1)),
            input_mask=np.tile(self.input_mask, (n, 1)),
            segment_ids=np.tile(self.segment_ids, (n, 1)),
        )


def reformat_guesswhat_dialog(query: str) -> str:
    """``q: ...? a: ...`` dialog → ``start <q> answer <a> stop`` per turn.

    Implements the *intent* of reference worker.py:390-400 (whose result is
    discarded by the bug at worker.py:402). Falls back to the raw query when
    the query has no ``q:`` turns.
    """
    lowered = query.lower()
    turns = lowered.split("q:")[1:]
    if not turns:
        return query
    parts: List[str] = []
    for turn in turns:
        qa = turn.split("a:")
        question = qa[0].strip()
        answer = qa[1].strip() if len(qa) > 1 else ""
        parts.append(f"start {question} answer {answer} stop")
    return " ".join(parts)


def encode_question(
    tokenizer: FullTokenizer,
    query: str,
    max_len: int = 37,
    *,
    task_id: int | None = None,
    guesswhat_raw_query: bool = False,
    lowercase: bool = True,
) -> EncodedText:
    """Query string → padded (max_len,) id/mask/segment buffers.

    ``lowercase`` mirrors the web tier's server-side lowercasing before
    enqueue (reference views.py:27) so direct library users get identical
    tokenization to queue users.
    """
    if lowercase:
        query = query.lower()
    if task_id == 16 and not guesswhat_raw_query:
        query = reformat_guesswhat_dialog(query)

    ids = tokenizer.add_special_tokens_single_sentence(tokenizer.encode(query))
    if len(ids) > max_len:
        ids = ids[: max_len - 1] + [tokenizer.sep_id]

    n = len(ids)
    input_ids = np.zeros((max_len,), np.int32)
    input_ids[:n] = ids
    input_mask = np.zeros((max_len,), np.int32)
    input_mask[:n] = 1
    segment_ids = np.zeros((max_len,), np.int32)
    return EncodedText(input_ids, input_mask, segment_ids)
