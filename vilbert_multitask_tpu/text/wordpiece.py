"""Pure-host WordPiece tokenizer (no torch, no network).

Reference capability: ``pytorch_transformers.tokenization_bert.BertTokenizer``
("bert-base-uncased", lower-cased), built at reference worker.py:537-539 and
used at worker.py:402-403 (``encode`` + ``add_special_tokens_single_sentence``).

Pipeline: basic tokenization (clean → lowercase → accent-strip → punctuation
split) then greedy longest-match-first WordPiece with ``##`` continuations.
Runs entirely on host CPU; the TPU only ever sees the padded int32 id buffers
built in :mod:`.pipeline`.

A ``vocab.txt`` in the standard BERT one-token-per-line format is required for
checkpoint parity; :func:`demo_vocab` builds a small self-contained vocabulary
so the framework runs standalone (tests, demos) with zero external assets.
"""

from __future__ import annotations

import unicodedata
from typing import Dict, Iterable, List, Sequence

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIAL_TOKENS = (PAD, UNK, CLS, SEP, MASK)


def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges that BERT treats as punctuation even when unicode doesn't.
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F
        or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF
        or 0x2F800 <= cp <= 0x2FA1F
    )


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting with optional lowercasing."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        text = self._clean(text)
        text = self._pad_cjk(text)
        tokens: List[str] = []
        for tok in text.split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = self._strip_accents(tok)
            tokens.extend(self._split_punct(tok))
        return tokens

    @staticmethod
    def _clean(text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    @staticmethod
    def _pad_cjk(text: str) -> str:
        out = []
        for ch in text:
            if _is_cjk(ord(ch)):
                out.extend((" ", ch, " "))
            else:
                out.append(ch)
        return "".join(out)

    @staticmethod
    def _strip_accents(text: str) -> str:
        return "".join(
            ch for ch in unicodedata.normalize("NFD", text)
            if unicodedata.category(ch) != "Mn"
        )

    @staticmethod
    def _split_punct(token: str) -> List[str]:
        pieces: List[List[str]] = []
        start_new = True
        for ch in token:
            if _is_punctuation(ch):
                pieces.append([ch])
                start_new = True
            else:
                if start_new:
                    pieces.append([])
                    start_new = False
                pieces[-1].append(ch)
        return ["".join(p) for p in pieces if p]


class WordPieceTokenizer:
    """Greedy longest-match-first subword splitting over a fixed vocab."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = UNK,
                 max_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars_per_word = max_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            pieces.append(cur)
            start = end
        return pieces


class FullTokenizer:
    """BasicTokenizer → WordPiece; the drop-in equivalent of the reference's
    BertTokenizer usage (encode / add_special_tokens / decode helpers)."""

    def __init__(self, vocab: Dict[str, int], do_lower_case: bool = True):
        self.vocab = dict(vocab)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordPieceTokenizer(self.vocab)
        for tok in (UNK, CLS, SEP, PAD):
            if tok not in self.vocab:
                raise ValueError(f"vocab is missing required token {tok}")

    # --- construction ---

    @classmethod
    def from_vocab_file(cls, path: str, do_lower_case: bool = True) -> "FullTokenizer":
        vocab: Dict[str, int] = {}
        with open(path, encoding="utf-8") as f:
            for idx, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = idx
        return cls(vocab, do_lower_case)

    # --- core API ---

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(word))
        return out

    def convert_tokens_to_ids(self, tokens: Iterable[str]) -> List[int]:
        unk = self.vocab[UNK]
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids: Iterable[int]) -> List[str]:
        return [self.inv_vocab.get(i, UNK) for i in ids]

    def encode(self, text: str) -> List[int]:
        """Text → ids, no special tokens (reference worker.py:402)."""
        return self.convert_tokens_to_ids(self.tokenize(text))

    def add_special_tokens_single_sentence(self, ids: Sequence[int]) -> List[int]:
        """[CLS] ids [SEP] (reference worker.py:403)."""
        return [self.vocab[CLS], *ids, self.vocab[SEP]]

    def detokenize(self, tokens: Sequence[str]) -> List[str]:
        """Undo wordpiece (reference worker.py:232-240 capability)."""
        words: List[str] = []
        for tok in tokens:
            if tok.startswith("##") and words:
                words[-1] += tok[2:]
            else:
                words.append(tok)
        return words

    @property
    def cls_id(self) -> int:
        return self.vocab[CLS]

    @property
    def sep_id(self) -> int:
        return self.vocab[SEP]

    @property
    def pad_id(self) -> int:
        return self.vocab[PAD]

    @property
    def mask_id(self) -> int:
        return self.vocab[MASK]


def demo_vocab(extra_words: Sequence[str] = ()) -> Dict[str, int]:
    """Self-contained vocabulary: specials, ascii chars, common-word stems and
    ``##`` continuations. Deterministic, so ids are stable across runs."""
    words = [
        "a", "an", "the", "is", "are", "was", "what", "who", "where", "when",
        "why", "how", "many", "much", "color", "colour", "man", "woman", "dog",
        "cat", "person", "people", "hold", "wear", "ride", "play", "stand",
        "sit", "left", "right", "red", "green", "blue", "yellow", "white",
        "black", "on", "in", "of", "and", "or", "to", "q", "start", "answer",
        "stop", "yes", "no", "image", "picture",
    ]
    vocab: Dict[str, int] = {}
    for tok in SPECIAL_TOKENS:
        vocab[tok] = len(vocab)
    for ch in (chr(c) for c in range(33, 127)):
        vocab.setdefault(ch, len(vocab))
        vocab.setdefault("##" + ch, len(vocab))
    for w in [*words, *extra_words]:
        vocab.setdefault(w, len(vocab))
        vocab.setdefault("##" + w, len(vocab))
        vocab.setdefault("##ing", len(vocab))
        vocab.setdefault("##ed", len(vocab))
        vocab.setdefault("##s", len(vocab))
    return vocab
