"""ctypes bindings for the native C++ runtime components (native/*.cpp).

Reference capability: the C++/CUDA layer the reference drives through
``maskrcnn_benchmark`` (NMS kernel + box selection, reference
worker.py:51,123-176) and fast feature IO. The library builds on demand with
the in-image toolchain (``make`` + g++); every entry point has a pure
JAX/numpy twin (ops/nms.py, features/store.py), so the framework degrades
gracefully when no compiler is present — ``available()`` gates the fast
path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libvmt_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                       capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _load_failed = True
            return None
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.vmt_nms.argtypes = [f32p, f32p, ctypes.c_int, ctypes.c_float, u8p]
        lib.vmt_nms.restype = ctypes.c_int
        lib.vmt_select_top_regions.argtypes = [
            f32p, f32p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, i32p, f32p, i32p,
            f32p,
        ]
        lib.vmt_select_top_regions.restype = ctypes.c_int
        lib.vmt_vlfr_header.argtypes = [ctypes.c_char_p] + [
            ctypes.POINTER(ctypes.c_int32)] * 4
        lib.vmt_vlfr_header.restype = ctypes.c_int
        lib.vmt_vlfr_read.argtypes = [ctypes.c_char_p, f32p, f32p]
        lib.vmt_vlfr_read.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def nms(boxes: np.ndarray, scores: np.ndarray,
        iou_threshold: float = 0.5) -> np.ndarray:
    """Greedy NMS → (N,) bool keep mask; ops/nms.py:nms_mask semantics."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable (no compiler?)")
    boxes = np.ascontiguousarray(boxes, np.float32)
    scores = np.ascontiguousarray(scores, np.float32)
    keep = np.zeros((boxes.shape[0],), np.uint8)
    lib.vmt_nms(boxes, scores, boxes.shape[0], iou_threshold, keep)
    return keep.astype(bool)


def select_top_regions(
    boxes: np.ndarray,
    class_scores: np.ndarray,
    num_keep: int = 100,
    iou_threshold: float = 0.5,
    conf_threshold: float = 0.0,
    background: bool = False,
) -> Tuple[np.ndarray, int, np.ndarray, np.ndarray, np.ndarray]:
    """Native twin of ops/nms.py:select_top_regions (same return layout)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable (no compiler?)")
    boxes = np.ascontiguousarray(boxes, np.float32)
    class_scores = np.ascontiguousarray(class_scores, np.float32)
    n, c = class_scores.shape
    keep_indices = np.zeros((num_keep,), np.int32)
    max_conf = np.zeros((n,), np.float32)
    objects = np.zeros((num_keep,), np.int32)
    cls_prob = np.zeros((num_keep,), np.float32)
    num_valid = lib.vmt_select_top_regions(
        boxes, class_scores, n, c, num_keep, iou_threshold, conf_threshold,
        int(background), keep_indices, max_conf, objects, cls_prob,
    )
    return keep_indices, num_valid, max_conf, objects, cls_prob


def read_vlfr(path: str):
    """Fast .vlfr loader (features/store.py format) → RegionFeatures."""
    from vilbert_multitask_tpu.features.pipeline import RegionFeatures

    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable (no compiler?)")
    n = ctypes.c_int32()
    d = ctypes.c_int32()
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    rc = lib.vmt_vlfr_header(path.encode(), ctypes.byref(n), ctypes.byref(d),
                             ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        raise IOError(f"vmt_vlfr_header({path}) failed with {rc}")
    feats = np.empty((n.value, d.value), np.float32)
    boxes = np.empty((n.value, 4), np.float32)
    rc = lib.vmt_vlfr_read(path.encode(), feats, boxes)
    if rc != 0:
        raise IOError(f"vmt_vlfr_read({path}) failed with {rc}")
    return RegionFeatures(features=feats, boxes=boxes, image_width=w.value,
                          image_height=h.value, num_boxes=n.value)
