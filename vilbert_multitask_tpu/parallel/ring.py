"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context is first-class in this framework: when a sequence (here, a
region set or text stream) is too long for one chip's HBM, shard it over a
mesh axis ``sp`` and compute EXACT attention by rotating KV blocks around
the ring with ``jax.lax.ppermute`` while each device keeps only its local
Q block. Per step, each device consumes one KV block with an online-
softmax update (running max / denominator / numerator — the same
flash-attention recurrence the Pallas kernel uses intra-chip,
ops/coattention.py), so peak memory is O(N/P) per device and the P
permutes ride ICI neighbor links — the cheapest collective on a TPU torus
(scaling-book recipe: annotate shardings, let compute overlap the
ppermute of the NEXT block).

The demo contract itself never needs this (38 text / 101 region tokens,
SURVEY §2.3), so serving keeps the dense path; this module is the scale
path for long region sets (e.g. video frames or tiled detections) and is
validated for exactness against dense attention on the virtual mesh
(tests/test_ring_attention.py) and in the driver's multichip dryrun.

No Python-level loop over devices: one ``lax.fori_loop`` inside
``shard_map``, traced once, P iterations at run time.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class RingContext:
    """Everything the MODEL needs to route self-attention through the ring.

    Carried as a (hashable) Flax module attribute down
    ``ViLBertForVLTasks → TwoStreamEncoder → TransformerLayer →
    FusedSelfAttention`` — the mesh cannot live in :class:`ViLBertConfig`
    (that tree is JSON-serializable checkpoint metadata). ``min_seq`` is the
    region-count threshold: below it the dense path wins (the ring's P
    ppermute hops cost more than they save on the demo's 101 regions; the
    threshold decision is static per compiled bucket, so each bucket
    compiles exactly one of the two paths).
    """

    mesh: Mesh
    sp_axis: str = "sp"
    batch_axis: Optional[str] = None
    # Shard the HEAD axis over tp inside the ring: the Megatron rules
    # already shard the QKV projections' output features on tp, so keeping
    # heads tp-sharded through the attention avoids an all-gather per
    # layer and the tp-redundant recompute of identical attention.
    head_axis: Optional[str] = None
    min_seq: int = 256  # authoritative serving knob: EngineConfig.ring_min_regions

    @classmethod
    def from_mesh(cls, mesh: Optional[Mesh], *, min_seq: int,
                  sp_axis: str = "sp", batch_axis: str = "dp",
                  head_axis: str = "tp") -> Optional["RingContext"]:
        """The ONE construction rule engine, trainer, and dryrun share:
        None unless the mesh has a real sp axis; batch/head axes included
        only when those mesh axes are real."""
        if mesh is None or mesh.shape.get(sp_axis, 1) <= 1:
            return None
        return cls(
            mesh, sp_axis=sp_axis,
            batch_axis=(batch_axis
                        if mesh.shape.get(batch_axis, 1) > 1 else None),
            head_axis=(head_axis
                       if mesh.shape.get(head_axis, 1) > 1 else None),
            min_seq=min_seq)

    def engages(self, seq_len: int, batch: Optional[int] = None) -> bool:
        """Static (trace-time) decision: ring only when the sp axis is real,
        the sequence clears the threshold, and shapes divide the axes."""
        sp = self.mesh.shape.get(self.sp_axis, 1)
        if sp <= 1 or seq_len < self.min_seq or seq_len % sp:
            return False
        if self.batch_axis is not None:
            b = self.mesh.shape.get(self.batch_axis, 1)
            if batch is not None and batch % b:
                return False
        return True


def ring_self_attention(ctx: RingContext, q, k, v, mask_bias, *,
                        dtype=jnp.float32):
    """Sequence-parallel self-attention for use INSIDE a jitted model.

    Global-array in/out, (B, N, H, D) each; ``mask_bias`` additive
    (B, 1, 1, N) or None. Unlike :func:`make_ring_attention` (a standalone
    jitted op that device_puts its inputs), this is a bare ``shard_map``
    the caller's surrounding ``jit`` composes with — activations reshard
    onto the sp axis at entry and back at exit, and XLA overlaps the
    per-step ppermute with the next block's compute.
    """
    b_ax = ctx.batch_axis
    # Head axis rides tp when it divides (composes with the Megatron
    # tp-sharded QKV projections — no per-layer all-gather); otherwise
    # heads replicate, which is merely the pre-tp-aware behavior.
    h_ax = ctx.head_axis
    if h_ax is not None and q.shape[2] % ctx.mesh.shape.get(h_ax, 1):
        h_ax = None
    qkv_spec = P(b_ax, ctx.sp_axis, h_ax)
    specs = (qkv_spec, qkv_spec, qkv_spec,
             P(b_ax, None, None, ctx.sp_axis))
    if mask_bias is None:
        mask_bias = jnp.zeros((q.shape[0], 1, 1, k.shape[1]), dtype)
    mapped = jax.shard_map(
        functools.partial(ring_attention_shard, axis_name=ctx.sp_axis,
                          dtype=dtype),
        mesh=ctx.mesh,
        in_specs=specs,
        out_specs=qkv_spec,
        check_vma=False,
    )
    return mapped(q, k, v, mask_bias.astype(dtype))


def _online_update(carry, scores, v_blk):
    """Flash/online-softmax accumulator update for one KV block.

    carry = (m, l, acc): running row max (..., Nq, 1), running denominator
    (..., Nq, 1), running numerator (..., Nq, D). scores (..., Nq, Nk_blk)
    are pre-bias-added; v_blk (..., Nk_blk, D).
    """
    m, l, acc = carry
    blk_max = jnp.max(scores, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    # rescale old accumulator to the new max, fold in this block
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m)
    new_l = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    new_acc = acc * correction + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return new_m, new_l, new_acc


def ring_attention_shard(q, k, v, kv_bias, *, axis_name: str,
                         dtype=jnp.float32):
    """Per-shard body: exact attention of local Q against the FULL K/V.

    Shapes (per device): q (B, Nq_loc, H, D), k/v (B, Nk_loc, H, D),
    kv_bias (B, 1, 1, Nk_loc) additive mask bias for the LOCAL kv block
    (rotates with it), or None. Returns (B, Nq_loc, H, D).

    Run inside ``shard_map`` with Q and KV sharded on ``axis_name``.
    """
    p_size = jax.lax.psum(1, axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], dtype))
    qf = q.astype(dtype) * scale

    b, nq, h, d = q.shape
    neg = jnp.asarray(jnp.finfo(dtype).min, dtype)
    m0 = jnp.full((b, h, nq, 1), neg, dtype)
    l0 = jnp.zeros((b, h, nq, 1), dtype)
    acc0 = jnp.zeros((b, h, nq, d), dtype)
    if kv_bias is None:
        kv_bias = jnp.zeros((b, 1, 1, k.shape[1]), dtype)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def consume(carry, k_blk, v_blk, bias_blk):
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(dtype),
                            preferred_element_type=dtype)
        scores = scores + bias_blk.astype(dtype)
        return _online_update(
            carry, scores,
            jnp.swapaxes(v_blk.astype(dtype), 1, 2))  # (B, H, Nk, D)

    def step(_, state):
        m, l, acc, k_blk, v_blk, bias_blk = state
        m, l, acc = consume((m, l, acc), k_blk, v_blk, bias_blk)
        # rotate KV (+ its mask bias) to the next device
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        bias_blk = jax.lax.ppermute(bias_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk, bias_blk

    # p-1 rotating steps, then the final block is consumed WITHOUT a
    # rotation — collectives inside a fori_loop are not dead-code
    # eliminated, so rotating on the last step would ship every K/V/bias
    # block over ICI once more with nothing left to overlap it.
    m, l, acc, k_last, v_last, bias_last = jax.lax.fori_loop(
        0, p_size - 1, step, (m0, l0, acc0, k, v, kv_bias))
    m, l, acc = consume((m, l, acc), k_last, v_last, bias_last)
    out = acc / jnp.maximum(l, jnp.asarray(1e-30, dtype))  # (B, H, Nq, D)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # (B, Nq, H, D)


def make_ring_attention(mesh: Mesh, *, sp_axis: str = "sp",
                        batch_axis: Optional[str] = None,
                        dtype=jnp.float32):
    """Jitted global-array ring attention over ``mesh``'s ``sp_axis``.

    Takes GLOBAL q (B, Nq, H, D), k/v (B, Nk, H, D), mask (B, Nk) {0,1}
    (or None → all-valid), returns global context (B, Nq, H, D) — exact,
    bit-for-intent equal to dense softmax attention. The ``sp_axis`` size
    must divide Nq and Nk (static-shape contract, like the image buckets).
    With ``batch_axis`` the batch dim shards too (dp×sp composition: each
    dp row group runs its own independent KV ring — rings never cross dp);
    the ``batch_axis`` size must then divide B, same contract shape.
    """
    from vilbert_multitask_tpu.ops.attention import mask_to_bias

    b_ax = batch_axis
    specs = (P(b_ax, sp_axis), P(b_ax, sp_axis), P(b_ax, sp_axis),
             P(b_ax, None, None, sp_axis))
    shard = functools.partial(ring_attention_shard, axis_name=sp_axis,
                              dtype=dtype)
    mapped = jax.shard_map(
        shard, mesh=mesh,
        in_specs=specs,
        out_specs=P(b_ax, sp_axis),
        check_vma=False,
    )

    @jax.jit
    def run(q, k, v, mask: Optional[jnp.ndarray] = None):
        if mask is None:
            mask = jnp.ones(k.shape[:2], jnp.int32)
        bias = mask_to_bias(mask, dtype)  # (B, 1, 1, Nk)
        placed = [
            jax.device_put(a, NamedSharding(mesh, spec))
            for a, spec in zip((q, k, v, bias), specs)
        ]
        return mapped(*placed)

    return run
