"""Device-mesh parallelism: mesh construction + partition rules.

The TPU-native replacement for the reference's (absent) distributed stack —
see SURVEY.md §2.3.
"""

from vilbert_multitask_tpu.parallel.mesh import build_mesh, local_mesh_info
from vilbert_multitask_tpu.parallel.ring import (
    make_ring_attention,
    ring_attention_shard,
)
from vilbert_multitask_tpu.parallel.sharding import (
    batch_shardings,
    batch_spec,
    param_shardings,
    param_specs,
    place_batch,
    shard_params,
)

__all__ = [
    "build_mesh",
    "local_mesh_info",
    "batch_shardings",
    "batch_spec",
    "make_ring_attention",
    "param_shardings",
    "param_specs",
    "place_batch",
    "ring_attention_shard",
    "shard_params",
]
