"""Parameter & activation partition rules (Megatron-style TP + DP batches).

The reference's "distributed backend" is NCCL-free and nonexistent
(SURVEY.md §2.3); this module IS the TPU-native replacement: declarative
``PartitionSpec`` rules that ``jax.jit`` lowers to XLA collectives over ICI.

Layout (standard two-matmul transformer sharding, à la scaling-book):
- expanding matmuls (fused QKV, FFN ``intermediate``, cross-attention
  Q/K/V, classifier ``dense1``) shard their OUTPUT dim on ``tp``;
- contracting matmuls (attention-output ``dense``, FFN ``output``,
  classifier ``dense2``) shard their INPUT dim on ``tp`` — XLA inserts the
  closing ``psum`` on the residual add;
- the word-embedding table (and its tied LM decoder) shards the vocab dim;
- everything else (LayerNorms, biases of contracting matmuls, poolers,
  small heads) is replicated;
- activations shard batch on ``dp`` everywhere.
"""

from __future__ import annotations

import re
from typing import Any, List, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vilbert_multitask_tpu import quant

# (regex over "/"-joined param path, spec). First match wins; paths end with
# the leaf name (kernel/bias/embedding/scale/...).
_RULES: List[Tuple[str, P]] = [
    # --- expanding matmuls: shard output features ---
    (r".*/attention/qkv/kernel$", P(None, "tp")),
    (r".*/attention/qkv/bias$", P("tp")),
    (r".*/(text_attends_image|image_attends_text)/(query|key|value)/kernel$",
     P(None, "tp")),
    (r".*/(text_attends_image|image_attends_text)/(query|key|value)/bias$",
     P("tp")),
    (r".*/ffn/intermediate/kernel$", P(None, "tp")),
    (r".*/ffn/intermediate/bias$", P("tp")),
    (r".*/dense1/kernel$", P(None, "tp")),
    (r".*/dense1/bias$", P("tp")),
    # --- contracting matmuls: shard input features, replicate bias ---
    (r".*/attention_output/dense/kernel$", P("tp", None)),
    (r".*/(v_output|t_output)/dense/kernel$", P("tp", None)),
    (r".*/ffn/output/kernel$", P("tp", None)),
    (r".*/dense2/kernel$", P("tp", None)),
    # --- vocab-sharded embedding (tied LM decoder shards with it) ---
    (r".*/word_embeddings/embedding$", P("tp", None)),
    (r".*/cls_text/decoder_bias$", P("tp")),
    # --- default: replicated ---
    (r".*", P()),
]


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def _spec_fits(spec: P, shape, mesh: Mesh) -> bool:
    for dim, axis in zip(shape, spec):
        if axis is None:
            continue
        if dim % mesh.shape[axis]:
            return False
    return True


def param_specs(params: Any, mesh: Mesh) -> Any:
    """PartitionSpec pytree for a param tree. Rules whose sharded dim does not
    divide the mesh axis fall back to replication (tiny test configs)."""

    def spec_for(path, leaf):
        p = _path_str(path)
        # int8 param storage (quant.py) nests each kernel one level deeper
        # as {"int8": values, "scale": scales}: the values keep the kernel's
        # shape, so the kernel's own rule applies — strip the suffix. The
        # per-channel scale vectors fall through to the default (replicated).
        if p.endswith("/" + quant.QVALUES):
            p = p[: -len("/" + quant.QVALUES)]
        for pattern, spec in _RULES:
            if re.match(pattern, p):
                if len(spec) > leaf.ndim or not _spec_fits(spec, leaf.shape, mesh):
                    return P()
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs(params, mesh)
    )


def cast_floating(params: Any, dtype) -> Any:
    """Cast the floating leaves of a param tree to ``dtype`` (ints/bools
    pass through; leaves already in ``dtype`` are returned untouched).

    The serving param-storage cast (EngineConfig.param_dtype): applied
    host-side before the boot upload when possible — a bf16 serving tree
    ships half the bytes of its f32 master. ``dtype="int8"`` is the
    weight-only quantized storage mode: floating matrix leaves become
    per-channel ``{"int8", "scale"}`` pairs (quant.py) instead of being
    value-cast; already-quantized pairs pass through untouched, so the
    restore -> ``load_params`` double cast and the /admin/swap
    re-quantization path are both idempotent. ``dtype=None`` is the
    identity (the training path: f32 masters are never cast here).
    """
    if dtype is None:
        return params
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    if dt.kind in "iu":
        if dt != jnp.dtype(jnp.int8):
            raise ValueError(
                f"integer param storage supports int8 only, got {dt}")
        return quant.quantize_tree(params)

    def one(x):
        if quant.is_quantized_leaf(x):
            return x
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dt:
            return x.astype(dt)
        return x

    return jax.tree_util.tree_map(one, params,
                                  is_leaf=quant.is_quantized_leaf)


def shard_params(params: Any, mesh: Mesh, *, dtype=None) -> Any:
    """Place a host param tree onto the mesh per the rules (one-time at
    boot). ``dtype`` applies :func:`cast_floating` first — the serving
    param-storage dtype rides the same placement call on the mesh path as
    on the single-device path."""
    params = cast_floating(params, dtype)
    return jax.device_put(params, param_shardings(params, mesh))


def batch_spec() -> P:
    """Activations: batch dim sharded over dp, everything else replicated."""
    return P("dp")


def batch_shardings(batch: Any, mesh: Mesh) -> Any:
    """NamedShardings for a batch pytree: shard axis 0 on dp when divisible."""

    def one(leaf):
        if leaf.ndim and leaf.shape[0] % mesh.shape["dp"] == 0:
            return NamedSharding(mesh, P("dp"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(one, batch)


def place_batch(batch: Any, mesh: Mesh, *, global_batch: bool = False,
                _force_callback: bool = False) -> Any:
    """Place a batch pytree onto the mesh, dp-sharded.

    When every mesh device is addressable (the virtual CPU mesh, a single
    TPU slice host): one ``device_put``. On a mesh spanning processes,
    ``device_put`` cannot target non-addressable devices; the ONLY
    supported cross-process placement is ``global_batch=True`` — the
    caller guarantees every process holds the IDENTICAL global batch, and
    each contributes its addressable shards via
    ``jax.make_array_from_callback``. The trainer qualifies (loaders draw
    statelessly from the GLOBAL step — the bit-exact-resume design,
    train/loop.py: same batch on every host, DCN carries no tensors).
    Serving does NOT (each host builds batches from its own requests), so
    its calls leave the default and fail loudly here instead of silently
    stitching a global array out of mismatched per-host rows.
    """
    import numpy as np

    shardings = batch_shardings(batch, mesh)
    local_mesh = all(d.process_index == jax.process_index()
                     for d in mesh.devices.flat)
    if (jax.process_count() == 1 or local_mesh) and not _force_callback:
        return jax.device_put(batch, shardings)
    if not global_batch and not _force_callback:
        raise NotImplementedError(
            "batch placement on a mesh spanning processes needs "
            "global_batch=True (identical batch on every process) — "
            "per-host serving batches cannot shard onto a cross-process "
            "mesh; route requests per host instead")

    def one(leaf, sh):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(arr.shape, sh,
                                            lambda idx: arr[idx])

    return jax.tree_util.tree_map(one, batch, shardings)
