"""Multi-host initialization and the cross-host serving topology.

Reference capability check (SURVEY.md §2.3): the reference has no tensor
transport at all — its "distributed fabric" is RabbitMQ + Redis + Postgres.
The TPU-native design keeps that boundary: **ICI carries tensors, DCN
carries jobs.**

- Within a slice, one process per host joins a single JAX runtime via
  :func:`initialize`; ``jax.devices()`` then spans the slice and the
  dp×tp mesh (parallel/mesh.py) lays over all chips, with XLA collectives
  riding ICI.
- Across slices/regions, hosts stay independent serving replicas: the
  durable queue (serve/queue.py) is the only cross-host channel, mirroring
  the reference's queue boundary (demo/sender.py:26-31 → worker.py:672) —
  no tensor ever crosses DCN, so there is no custom transport to maintain.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or skip) the multi-host JAX runtime.

    Arguments fall back to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``), which TPU pod launchers set.
    Returns True when distributed init ran, False for the single-process
    fallback (no coordinator configured) — so one binary serves dev boxes
    and pods alike.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None

    if coordinator_address is None:
        return False
    if num_processes is None or process_id is None:
        raise ValueError(
            "multi-host init needs num_processes and process_id alongside "
            "coordinator_address (or JAX_NUM_PROCESSES / JAX_PROCESS_ID)")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def runtime_info() -> dict:
    """Process/device topology summary (for /healthz and logs)."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "backend": jax.default_backend(),
    }
