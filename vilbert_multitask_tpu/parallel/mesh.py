"""Device-mesh construction.

The reference has no device-level parallelism at all — one model on one CUDA
device (reference worker.py:87,536; SURVEY.md §2.3). Here a
``jax.sharding.Mesh`` over ICI is first-class: a 2-D ``(dp, tp)`` layout where
``dp`` shards request batches and ``tp`` shards weight matrices
(Megatron-style) for checkpoints too large to replicate. Multi-host extends
the same mesh over DCN via ``jax.distributed`` — tensors ride ICI within a
slice; cross-host work distribution stays on the job queue, mirroring the
reference's queue boundary (demo/sender.py:26-31).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from vilbert_multitask_tpu.config import MeshConfig


def build_mesh(
    cfg: Optional[MeshConfig] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a ``(dp, tp)`` — or ``(dp, tp, sp)`` when ``cfg.sp > 1`` — mesh
    from the config over the given devices.

    ``dp == -1`` means "all remaining devices after tp (and sp)" — the
    serving default, so one binary works on 1-chip dev boxes and full
    slices alike. The sp axis is innermost: ring attention's per-step
    ppermute rides neighbor ICI links, which an innermost axis maps to on
    a TPU torus.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    tp = max(1, cfg.tp)
    sp = max(1, cfg.sp)
    model = tp * sp
    if cfg.dp > 0:
        dp = cfg.dp
    else:
        if len(devices) % model:
            raise ValueError(
                f"{len(devices)} devices not divisible by tp*sp={model}")
        dp = len(devices) // model
    if dp * model > len(devices):
        raise ValueError(
            f"mesh {dp}x{tp}x{sp} needs {dp * model} devices, "
            f"have {len(devices)}"
        )
    if sp > 1:
        grid = np.asarray(devices[: dp * model]).reshape(dp, tp, sp)
        return Mesh(grid, (*cfg.axis_names, "sp"))
    grid = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(grid, tuple(cfg.axis_names))


def local_mesh_info(mesh: Mesh) -> dict:
    """Small debug/observability summary (exported by the metrics endpoint)."""
    return {
        "axis_names": list(mesh.axis_names),
        "shape": dict(mesh.shape),
        "n_devices": int(np.prod(list(mesh.shape.values()))),
        "device_kinds": sorted({d.device_kind for d in mesh.devices.flat}),
    }
