"""Serving-latency benchmark: p50 per query over a full task round-robin.

Measures the BASELINE.md north-star metric — per-query latency across all
served task endpoints (reference instrumented but never published this;
worker.py:657-658) — on whatever accelerator `jax.devices()` offers, and
prints ONE JSON line:

    {"metric": "p50_latency_ms", "value": N, "unit": "ms", "vs_baseline": R}

``vs_baseline`` is target/measured against the <150 ms p50 target from
BASELINE.json ("north_star"): >1.0 beats the target.

Robustness (the round-1 run died in TPU backend init before producing any
number): the default entrypoint is an ORCHESTRATOR that runs the measurement
in a fresh subprocess, retries backend-init failures with backoff (a fresh
process is the only reliable way to drop a poisoned PJRT client), and on
total failure still emits the JSON line — with ``value: null`` and an
``error`` field — instead of a stack trace.

Env knobs:
- ``BENCH_TINY=1``     tiny model config + CPU platform pinned in-process
  (smoke runs; the real TPU run uses the 270M serving config).
- ``BENCH_COMPARE=0``  skip the kernel-on-vs-off comparison (default ON: the
  headline JSON carries both p50s so the Pallas delta is recorded on
  hardware every round — BASELINE.json's north star).
- ``BENCH_COMPARE_TIMEOUT_S`` (900) hard bound on the compare child;
  ``BENCH_COMPARE_MAX_P50_MS`` (5000) health gate — no compare child is
  launched if the headline p50 came in above it.
- ``BENCH_PALLAS=0|1``  force the kernel path off/on in a child process
  (the orchestrator sets 0 for the compare child); unset → config defaults.
- ``BENCH_ATTEMPTS`` / ``BENCH_ATTEMPT_TIMEOUT_S`` retry knobs.
- ``BENCH_AOT_CACHE_DIR`` AOT executable cache root (default
  ``$TMPDIR/vmt_aot_cache``): retries and compare children deserialize the
  warmup programs instead of re-tracing; a fully-warm boot records
  ``warm_cache_s`` in the headline and a ``boot.warm_cache_s`` ledger line.
- ``BENCH_PROBE=0`` skip the pre-attempt backend probe (default ON for the
  hardware path; TINY mode never probes). ``BENCH_PROBE_TIMEOUT_S`` (240),
  ``BENCH_PROBE_BACKOFF_S`` (45) tune the probe cycle.
- ``BENCH_PROBE_WINDOW_S`` (300) dead-tunnel fast-fail: if the backend has
  NEVER answered a probe by this deadline, emit a partial JSON line (with a
  ``last_known_good`` pointer at the newest committed BENCH artifact) and
  exit — ~5 minutes of evidence instead of burning the whole wall budget
  probing a tunnel that was down from the start. Once any probe succeeds
  the window is disarmed; later flakiness gets the full budget.
- ``BENCH_PROBE_MAX_FAILS`` (6) consecutive-failed-probe cap, armed once
  the backend has been seen alive (the hole the WINDOW leaves open): a
  tunnel dying mid-run emits best-so-far/partial JSON after ~N probe
  timeouts instead of spinning "probe hung" cycles to the wall budget.
- Successful (non-partial) runs append their headline keys to
  ``PERF_LEDGER.jsonl`` (``scripts/perf_ledger.py check`` gates on it);
  tiny runs ledger under a separate metric name.
- ``BENCH_ANATOMY_REPS`` (20) reps for the post-headline latency-anatomy
  probes (dispatch floor / many-arg execute / host round-trip — see
  ``_anatomy_probes``); ``BENCH_ANATOMY=0`` skips the stage.
- ``BENCH_SWEEP_ROWS`` comma-separated extra run_many chunk sizes (e.g.
  ``64,128``) to time alongside the configured buckets — the chunk-size
  knee finder for an execute-bound backend (round-5 hardware showed p50
  barely moves from 1 to 10 rows, so bigger chunks are near-free qps).
  Each size costs one extra bucket compile; the headline ``batch_qps``
  becomes the best size measured.
- ``BENCH_PROFILE_DIR`` capture a ``jax.profiler`` device trace of one
  warm round-robin pass into this directory (inspect with TensorBoard /
  xprof) — the diagnosis artifact for any surprising hardware number.
- ``BENCH_TRACE_OUT`` write the measurement's span trace (engine tokenize /
  features / forward / decode intervals) as Chrome-trace JSON to this path
  (open at https://ui.perfetto.dev).
- ``BENCH_WALL_BUDGET_S`` (7200) total wall budget for the orchestrator:
  attempts are sized to fit what remains, and no attempt starts that cannot
  finish inside it — a dead tunnel burns cheap probes, not 1800 s children.
  Generous by default: probe cycles are cheap, a tunnel recovering late in
  the window still gets its attempt, and a tighter outer ``timeout`` just
  triggers the kill trap's best-so-far JSON instead.

Kill-resilience: SIGTERM/SIGINT (what ``timeout`` sends before SIGKILL)
emits the best-so-far JSON line — the headline measurement if one is in
hand (e.g. killed mid-compare), else a structured failure with the probe
log — so an outer rc=124 still leaves parseable evidence on stdout.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

from vilbert_multitask_tpu.obs import Histogram, dump_trace, percentile

BASELINE_P50_MS = 150.0

# BENCH_TINY=1 swaps in the tiny model config for CPU smoke runs (the CPU
# backend is ~100x slower than a chip on the 270M config; the driver's TPU
# run uses the real model).
TINY = os.environ.get("BENCH_TINY", "") not in ("", "0")
COMPARE = os.environ.get("BENCH_COMPARE", "1") not in ("", "0")
# Never spend the round's number on a comparison: the orchestrator only
# launches the compare child when the headline p50 came in under this bound
# (a healthy engine is ~2 orders of magnitude under it), and kills it at
# BENCH_COMPARE_TIMEOUT_S regardless — the headline JSON is already in hand.
COMPARE_MAX_P50_MS = float(os.environ.get("BENCH_COMPARE_MAX_P50_MS", "5000"))
COMPARE_TIMEOUT_S = float(os.environ.get("BENCH_COMPARE_TIMEOUT_S", "900"))
# Forced kernel selection for a child process ("0"/"1"); unset → config
# defaults. The orchestrator sets 0 for the compare child.
FORCE_PALLAS = os.environ.get("BENCH_PALLAS", "")
# Extra run_many chunk sizes to time in the throughput pass (see docstring).
# Malformed or non-positive entries are dropped, not raised: a bad env var
# must never break the always-emit-JSON contract (the parse runs at import,
# before the orchestrator's kill trap exists).
def _parse_sweep(raw: str) -> tuple:
    out = []
    for s in raw.split(","):
        try:
            v = int(s)
        except ValueError:
            continue
        if v > 0:
            out.append(v)
    return tuple(out)


# Hardware default "64,128,256": round-5 showed dispatches are execute-bound
# (p50 flat from 1 to 10 rows), so the knee above the 32-row bucket is the
# open throughput question and the driver's own run should answer it —
# 256 rows brackets the analytic int8 knee (engine/flops.py:knee_rows)
# from above, so the sweep can actually observe the verdict flip. Three
# extra bucket compiles (~1-2 min amortized by the compile cache), per-size
# isolated so a failure costs only its key. TINY smoke keeps no sweep.
SWEEP_ROWS = _parse_sweep(
    os.environ.get("BENCH_SWEEP_ROWS", "" if TINY else "64,128,256"))


def synth_regions(rng, cfg, n_boxes=100):
    from vilbert_multitask_tpu.features.pipeline import synthetic_regions

    return synthetic_regions(cfg.model.v_feature_size, n_boxes=n_boxes,
                             rng=rng)


# The 8 served task types (config.TASK_REGISTRY). Retrieval runs at 2, 4, 8
# and 10 candidates so EVERY compiled shape bucket (EngineConfig.image_buckets
# = 1,2,4,8,10) is warmed and timed — the reference serves 2-10 candidate
# images (worker.py:278-284).
ROUND_ROBIN = [
    (1, "what is the man holding", 1),      # VQA
    (15, "is the bowl right of the mug", 1),  # GQA
    (4, "which object can you eat", 1),     # Visual7W pointing
    (11, "the woman in the red coat", 1),   # RefCOCO
    (16, "q: is it a person? a: no", 1),    # GuessWhat
    (13, "two dogs play in the snow", 1),   # SNLI-VE
    (12, "both images contain two wolves", 2),  # NLVR2
    (7, "a man riding a horse", 2),         # Retrieval, bucket 2
    (7, "a dog catching a frisbee", 4),     # Retrieval, bucket 4
    (7, "a red car parked outside", 8),     # Retrieval, bucket 8
    (7, "people waiting for a train", 10),  # Retrieval, bucket 10
]
MAX_IMAGES = max(n for _, _, n in ROUND_ROBIN)


def _build_engine(pallas: bool | None):
    """Engine with the serving config; ``pallas`` overrides the kernel knobs."""
    import dataclasses

    from vilbert_multitask_tpu.config import FrameworkConfig
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine

    import tempfile

    cfg = FrameworkConfig()
    if TINY:
        cfg = dataclasses.replace(cfg, model=cfg.model.tiny())
    over = dict(
        # Persistent compile cache: retry attempts and the compare child
        # skip re-compiles (the serving binary enables the same thing).
        compilation_cache_dir=os.path.join(
            tempfile.gettempdir(), "vmt_xla_cache"),
        # AOT executable cache on top: retries/compare children deserialize
        # the warmup programs outright — zero re-traces, and the headline
        # JSON records the boot-phase split either way.
        aot_cache_dir=os.environ.get(
            "BENCH_AOT_CACHE_DIR",
            os.path.join(tempfile.gettempdir(), "vmt_aot_cache")),
    )
    if pallas is not None:
        over.update(use_pallas_coattention=pallas,
                    use_pallas_self_attention=pallas)
    # The CONFIGURED ceiling, recorded before any sweep extension below:
    # _measure_throughput always times this baseline size so artifacts
    # stay comparable across rounds whatever the sweep adds.
    base_tb = cfg.engine.max_batch_rows()
    if SWEEP_ROWS:
        # Sweep sizes must be compiled row buckets before run_many can
        # chunk at them; union with the configured ones.
        over["throughput_buckets"] = tuple(sorted(
            {*(cfg.engine.throughput_buckets or ()), *SWEEP_ROWS}))
    cfg = dataclasses.replace(
        cfg, engine=dataclasses.replace(cfg.engine, **over))
    return cfg, InferenceEngine(cfg), base_tb


def _round_opt(v, digits: int = 3):
    """Round-or-None: windowed percentiles are None on an empty window."""
    return round(v, digits) if v is not None else None


def _measure(engine, cfg, *, budget_s: float = 45.0):
    """Warm every bucket the round-robin hits, then time it."""
    from vilbert_multitask_tpu.engine.flops import serving_forward_flops

    rng = np.random.default_rng(0)
    regions = [synth_regions(rng, cfg) for _ in range(MAX_IMAGES)]
    # Stable per-image identities, as the serving worker passes for
    # store-backed media paths (serve/worker.py:_intake) — the demo-image
    # steady state: region tensors pin in HBM after first use and repeat
    # queries ship only the ~KB text payload. The cold (novel-upload) path
    # is measured separately below.
    reqs, tok_ms, feat_ms = [], [], []
    for task_id, q, n in ROUND_ROBIN:
        reqs.append(
            engine.prepare(task_id, q, regions[:n],
                           cache_keys=[f"bench_img_{i}" for i in range(n)]))
        # Host-side stage costs are paid at prepare() time; with no feature
        # store attached the "features" stage is the region encode.
        tok_ms.append(engine.stage_times.get("tokenize_s", 0.0) * 1e3)
        feat_ms.append(engine.stage_times.get("features_s", 0.0) * 1e3)
    # Warm exactly the buckets the timed loop hits: anything less recompiles
    # mid-measurement, anything more burns the one hardware run on compiles.
    buckets = sorted({r.bucket for r in reqs})
    t0 = time.perf_counter()
    engine.warmup(buckets=buckets)
    warm_s = time.perf_counter() - t0

    # One untimed pass absorbs host-side caches, then the timed epochs.
    t0 = time.perf_counter()
    for req in reqs:
        engine.run(req)
    per_pass_s = time.perf_counter() - t0
    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    if profile_dir:
        # One traced warm pass, separate from the timed epochs (tracing
        # adds overhead; the headline numbers must not carry it).
        import jax

        with jax.profiler.trace(profile_dir):
            for req in reqs:
                engine.run(req)
        print(f"# profiler trace written to {profile_dir}", file=sys.stderr)
    # Scale timed work to the budget so the bench fits on any backend
    # (CPU smoke runs are ~100x slower than the TPU path). The cap exists
    # for fast backends; 30 epochs × 11 queries gives percentiles real
    # support now that a query is ~100ms, not 24s.
    epochs = max(1, min(30, int(budget_s / max(per_pass_s, 1e-3))))
    lat_ms, fwd_ms, dec_ms, tflops = [], [], [], []
    # Ride the cost-attribution plane through the timed loop: each query
    # is a single-member batch (the latency bench's serving shape), so
    # device_s_conservation must come back 1.0 — the plumbing smoke — and
    # tail_kept_frac reports what the sampler kept of a real workload.
    # Best-effort: a store failure must never cost the headline p50.
    attrib = store = None
    try:
        import tempfile as _tempfile

        from vilbert_multitask_tpu import obs

        store = obs.TraceStore(os.path.join(
            _tempfile.mkdtemp(prefix="bench_attrib_"), "traces.sqlite3"),
            "bench")
        attrib = obs.CostAttributor(
            ring=8192, on_finish=lambda c: store.offer(c))
    except Exception as e:  # noqa: BLE001 — bonus metric only
        print(f"# cost-attrib smoke disabled: {e}", file=sys.stderr)
    # Live view beside the lifetime percentiles: the same sliding-window
    # aggregation the serving SLOs run on (obs.Histogram.window_percentile)
    # over the trailing slice of the run — on a long bench this is "what a
    # dashboard would show right now", and a drift between live and
    # lifetime p95 flags a run that degraded as it went.
    live = Histogram("bench_latency_ms", "Per-query bench latency (ms).",
                     reservoir=4096)
    live_window_s = 30.0
    for _ in range(epochs):
        for (task_id, _q, _n), req in zip(ROUND_ROBIN, reqs):
            t = time.perf_counter()
            engine.run(req)
            lat_ms.append((time.perf_counter() - t) * 1e3)
            live.observe(lat_ms[-1])
            fwd_s = engine.stage_times.get("forward_s", 0.0)
            fwd_ms.append(fwd_s * 1e3)
            dec_ms.append(engine.stage_times.get("decode_s", 0.0) * 1e3)
            if attrib is not None:
                tid = f"bench{len(lat_ms):06d}"
                attrib.begin(tid, task=str(task_id))
                attrib.charge_batch(fwd_s, [(tid, req.n_images)],
                                    batch_rows=req.n_images,
                                    bucket=req.bucket)
                attrib.charge(tid, "decode",
                              engine.stage_times.get("decode_s", 0.0))
                attrib.finish(tid, "ok")
            # Achieved FLOP/s for THIS query's compiled bucket (padding rows
            # count — they're real MXU work the bucketing strategy pays for).
            flops = serving_forward_flops(cfg.model, cfg.engine, req.bucket)
            tflops.append(flops / max(fwd_s, 1e-9) / 1e12)
    # Cold pass: the same round-robin with NO cache identities — every
    # query re-uploads its region tensors (the novel-upload serving path).
    cold_ms = []
    for task_id, q, n in ROUND_ROBIN:
        req = engine.prepare(task_id, q, regions[:n])
        t = time.perf_counter()
        engine.run(req)
        cold_ms.append((time.perf_counter() - t) * 1e3)
    # Dispatch floor: a trivial jitted op on a resident device array, timed
    # the same way as a query. Separates per-dispatch overhead (tunnel RTT
    # on the axon backend, PJRT launch cost locally) from model compute —
    # without it a remote-tunnel p50 reads as "slow model" when it is
    # mostly wire time.
    # Guarded: the probe runs LAST, after the latency stats are already
    # collected — a transient device/tunnel error in this trivial op must
    # cost only its own key, never the attempt's headline p50 (ADVICE r5).
    floor_ms = []
    try:
        import jax
        import jax.numpy as jnp

        tiny_fn = jax.jit(lambda x: x + 1.0)
        resident = jax.device_put(jnp.zeros((8, 128), jnp.float32))
        jax.block_until_ready(tiny_fn(resident))  # compile outside the timing
        for _ in range(20):
            t = time.perf_counter()
            jax.block_until_ready(tiny_fn(resident))
            floor_ms.append((time.perf_counter() - t) * 1e3)
    except Exception as e:  # noqa: BLE001 — the floor is a bonus metric
        print(f"# dispatch-floor probe failed: {e}", file=sys.stderr)
    # All percentiles through the one shared obs implementation (linear
    # interpolation) — bench, serve, and the soak now agree on the math.
    return {
        "dispatch_floor_ms": (round(percentile(floor_ms, 0.5), 3)
                              if floor_ms else None),
        "warmup_s": round(warm_s, 1),
        "n_queries": len(lat_ms),
        "cold_p50_ms": round(percentile(cold_ms, 0.5), 3),
        "buckets": buckets,
        "p50_ms": round(percentile(lat_ms, 0.5), 3),
        "p95_ms": round(percentile(lat_ms, 0.95), 3),
        # Trailing-window percentiles (last live_window_s of timed queries).
        "live_window_s": live_window_s,
        "live_p50_ms": _round_opt(live.window_percentile(0.5, live_window_s)),
        "live_p95_ms": _round_opt(
            live.window_percentile(0.95, live_window_s)),
        "forward_p50_ms": round(percentile(fwd_ms, 0.5), 3),
        "decode_p50_ms": round(percentile(dec_ms, 0.5), 3),
        "achieved_tflops_p50": round(percentile(tflops, 0.5), 4),
        # Where a query's milliseconds go, host to host (p50 per stage).
        "stage_ms": {
            "tokenize": round(percentile(tok_ms, 0.5), 3),
            "features": round(percentile(feat_ms, 0.5), 3),
            "forward": round(percentile(fwd_ms, 0.5), 3),
            "decode": round(percentile(dec_ms, 0.5), 3),
        },
        "cost_attrib": ({
            "device_s_conservation": attrib.conservation()["ratio"],
            "tail_kept_frac": store.stats()["tail_kept_frac"],
        } if attrib is not None else None),
    }


def _measure_throughput(engine, cfg, *, n: int = 160,
                        base_tb: int | None = None):
    """Micro-batched serving throughput: ``run_many`` over single-image
    tasks — the BASELINE "full 12-task round-robin batch (shared trunk, all
    heads hot)" mode. Measured per chunk size so the round's artifact
    records the throughput-bucket decision (VERDICT r3 weak-3): the
    10-row max image bucket (retrieval semantics, the round-3 ceiling) vs
    the dedicated throughput buckets that exist purely to keep the MXU
    fed, plus any ``BENCH_SWEEP_ROWS`` knee-finder sizes. ``n`` is raised
    to 2× the largest size (rounded to a multiple of it) so every size
    gets at least two full chunks and the biggest has no ragged tail."""
    from vilbert_multitask_tpu.engine.flops import serving_forward_flops

    max_img = max(cfg.engine.image_buckets)
    # Always time the max image bucket (the pre-throughput-bucket ceiling)
    # and the largest pre-sweep configured bucket (``base_tb`` from
    # _build_engine — artifacts stay comparable across rounds whatever the
    # sweep adds); BENCH_SWEEP_ROWS adds knee-finder sizes on top.
    # Headline batch_qps = the best size measured.
    tb = base_tb if base_tb is not None else cfg.engine.max_batch_rows()
    sizes = sorted({max_img, tb, *SWEEP_ROWS})
    biggest = max(sizes)
    if n < 2 * biggest:
        n = 2 * biggest
    n = -(-n // biggest) * biggest  # round up: no ragged tail at `biggest`

    rng = np.random.default_rng(1)
    regions = [synth_regions(rng, cfg)]
    single_tasks = [(1, "what is the man holding"),
                    (15, "is the bowl right of the mug"),
                    (4, "which object can you eat"),
                    (11, "the woman in the red coat"),
                    (16, "q: is it a person? a: no"),
                    (13, "two dogs play in the snow")]
    # Same store-backed steady state as the latency pass: one pinned image,
    # so the throughput number measures compute + text upload, not feature
    # re-shipping (run_many rides the same device row cache as run()).
    reqs = [
        engine.prepare(*single_tasks[i % len(single_tasks)], regions,
                       cache_keys=["bench_thr_img"])
        for i in range(n)
    ]

    def timed(chunk_rows: int) -> tuple:
        # Fair per-size comparison: time the largest multiple of the chunk
        # size that fits in the request list, so no size pays a ragged tail
        # dispatch the others don't (n is a multiple of the biggest size,
        # so every size keeps >= half the requests).
        n_s = (n // chunk_rows) * chunk_rows
        # The warm call pays this size's bucket compile (if the persistent
        # cache missed); log it so sweep sizes carry their real price in
        # the round's stderr record — "near-free qps" claims need the
        # compile bill next to them.
        t0 = time.perf_counter()
        engine.run_many(reqs[:chunk_rows], chunk_rows=chunk_rows)  # warm
        warm_s = time.perf_counter() - t0
        print(f"# chunk {chunk_rows}: warm+compile {warm_s:.1f}s",
              file=sys.stderr)
        t0 = time.perf_counter()
        results = engine.run_many(reqs[:n_s], chunk_rows=chunk_rows)
        dt = time.perf_counter() - t0
        assert len(results) == n_s
        # Padded rows count as real work the chunking pays for; the plan
        # comes from the engine (the single copy of the packing math).
        rows = engine.padded_rows([1] * n_s, chunk_rows=chunk_rows)
        tflops = serving_forward_flops(cfg.model, cfg.engine, rows) / dt / 1e12
        return round(n_s / dt, 2), round(tflops, 4), round(warm_s, 1)

    # Per-size isolation: one OOM/compile failure at a knee-finder size
    # must cost that key, not the whole throughput pass (the baseline
    # buckets may already have measured fine).
    by_size = {}
    for s in sizes:
        try:
            by_size[s] = timed(s)
        except Exception as e:  # noqa: BLE001 — sweep sizes are best-effort
            print(f"# chunk size {s} failed: {e}", file=sys.stderr)
    if not by_size:
        return {}
    sizes = sorted(by_size)
    best = max(sizes, key=lambda s: by_size[s][0])
    out = {}
    for s in sizes:
        if s != best:
            out[f"batch_qps_b{s}"] = by_size[s][0]
            out[f"batch_tflops_b{s}"] = by_size[s][1]
        # Per-size warm+compile cost: what the sweep size actually charged
        # this run (≈0 when the persistent compile cache hit).
        out[f"batch_warm_s_b{s}"] = by_size[s][2]
    out.update({"batch_qps": by_size[best][0],
                "batch_tflops": by_size[best][1],
                "batch_chunk_rows": best})
    # The CONFIGURED ceiling under a stable key: headline batch_qps means
    # "best size measured including sweep sizes", so round-over-round
    # comparisons need a key that doesn't depend on which BENCH_SWEEP_ROWS
    # ran (ADVICE r5). tb is the pre-sweep configured bucket from
    # _build_engine; absent only if its own measurement failed.
    if tb in by_size:
        out["batch_qps_base"] = by_size[tb][0]
        out["batch_chunk_rows_base"] = tb
    if best != max_img and max_img in by_size:
        out["batch_speedup_vs_max_image_bucket"] = round(
            by_size[best][0] / max(by_size[max_img][0], 1e-9), 3)
    out.update(_measure_throughput_mixed(engine, cfg))
    return out


def _measure_throughput_mixed(engine, cfg, *, groups_n: int = 8):
    """Literal "all heads hot" backlog: single-image tasks, NLVR2 pairs,
    and retrieval-4 sets in one run_many call (multi-image batching landed
    round 4 — this records that the 2-/10-image tasks stopped paying one
    dispatch each). Reported as examples/s plus the padded-row TFLOP/s."""
    from vilbert_multitask_tpu.engine.flops import serving_forward_flops

    rng = np.random.default_rng(2)
    regions = [synth_regions(rng, cfg) for _ in range(4)]
    keys = [f"bench_mix_img_{i}" for i in range(4)]
    pattern = [
        (1, "what is the man holding", 1),
        (12, "both images contain dogs", 2),
        (15, "is the bowl right of the mug", 1),
        (7, "a dog catching a frisbee", 4),
        (13, "two dogs play in the snow", 1),
        (12, "both images contain wolves", 2),
    ]
    reqs = []
    for _ in range(groups_n):
        for task_id, q, n in pattern:
            reqs.append(engine.prepare(task_id, q, regions[:n],
                                       cache_keys=keys[:n]))
    engine.run_many(reqs[: len(pattern)])  # warm the packed-chunk buckets
    t0 = time.perf_counter()
    results = engine.run_many(reqs)
    dt = time.perf_counter() - t0
    assert len(results) == len(reqs)
    # Padded-row FLOP accounting rides run_many's OWN plan (engine.padded_
    # rows) — not a re-derivation that could drift from the real packing.
    rows = engine.padded_rows([r.n_images for r in reqs])
    tflops = serving_forward_flops(cfg.model, cfg.engine, rows) / dt / 1e12
    return {"batch_qps_mixed": round(len(reqs) / dt, 2),
            "batch_tflops_mixed": round(tflops, 4),
            "batch_mixed_n": len(reqs)}


def _anatomy_probes(*, reps: int = 20, include_bigarg: bool = False,
                    include_tiny: bool = False) -> dict:
    """Latency anatomy: attribute the per-dispatch milliseconds.

    Round-5 hardware showed every serving dispatch costs ~72-78 ms whether
    the chunk is 1 row or 32, while a trivial jitted op completes in
    ~0.03 ms. These probes separate the candidate costs so the headline p50
    can be attributed instead of guessed at:

      manyarg_exec_ms   trivial jitted fn over 192 small resident arrays —
                        the per-ARGUMENT marshalling term (a serving forward
                        ships the whole ~190-leaf param tree every execute).
      roundtrip_ms      device_put of fresh host bytes + scalar fetch per
                        rep (fresh data defeats host-copy caching) — the
                        true host<->device RTT; on a tunneled backend this
                        is the wire.
      bigarg_exec_ms    (non-TINY only) trivial fn over 4 x 128 MB resident
                        arrays — per-BYTE cost for resident args; should be
                        ~free since only buffer handles cross the wire.

    Read together with the headline's ``dispatch_floor_ms`` (timed inside
    ``_measure``, same method): if manyarg >> floor the fix is fewer/larger
    leaves per execute (the O(1)-leaf rows path exists for exactly this);
    if roundtrip dominates, the latency is the tunnel's and vanishes on
    locally-attached TPU; if neither, the p50 is genuine device time and
    worth a ``BENCH_PROFILE_DIR`` trace. Every probe is best-effort — a
    failure costs its own key, never the headline.
    """
    import jax
    import jax.numpy as jnp

    def median_ms(fn) -> float:
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append((time.perf_counter() - t0) * 1e3)
        return round(percentile(ts, 0.5), 3)

    out: dict = {}
    if include_tiny:
        # One resident arg, trivial compute — the dispatch floor. The bench
        # headline times the same probe inside ``_measure`` (as
        # ``dispatch_floor_ms``); this flag exists for the standalone
        # scripts/tpu_latency_anatomy.py entrypoint.
        try:
            tiny = jax.jit(lambda x: x + 1.0)
            x = jax.device_put(jnp.zeros((8, 128), jnp.float32))
            jax.block_until_ready(tiny(x))
            out["tiny_exec_ms"] = median_ms(
                lambda: jax.block_until_ready(tiny(x)))
        except Exception as e:  # noqa: BLE001
            print(f"# tiny probe failed: {e}", file=sys.stderr)
    try:
        leaves = [jax.device_put(jnp.full((16,), float(i), jnp.float32))
                  for i in range(192)]
        manyarg = jax.jit(lambda *ls: ls[0][0] + ls[-1][0])
        jax.block_until_ready(manyarg(*leaves))  # compile outside the timing
        out["manyarg_exec_ms"] = median_ms(
            lambda: jax.block_until_ready(manyarg(*leaves)))
    except Exception as e:  # noqa: BLE001 — anatomy is diagnostic, not gating
        print(f"# manyarg probe failed: {e}", file=sys.stderr)

    try:
        counter = [0]

        def rt():
            counter[0] += 1
            y = jax.device_put(np.array([counter[0]], np.float32))
            assert float(y[0]) == counter[0]

        rt()
        out["roundtrip_ms"] = median_ms(rt)
    except Exception as e:  # noqa: BLE001
        print(f"# roundtrip probe failed: {e}", file=sys.stderr)

    if include_bigarg:
        # Serving-scale resident bytes (4 x 128 MB ≈ the f32 param tree);
        # skipped in TINY/CPU smoke where the 512 MB allocation is all cost
        # and no signal.
        try:
            big = [jax.device_put(jnp.zeros((32, 1024, 1024), jnp.float32))
                   for _ in range(4)]
            bigarg = jax.jit(lambda a, b, c, d: a[0, 0, 0] + d[0, 0, 0])
            jax.block_until_ready(bigarg(*big))
            out["bigarg_exec_ms"] = median_ms(
                lambda: jax.block_until_ready(bigarg(*big)))
        except Exception as e:  # noqa: BLE001
            print(f"# bigarg probe failed: {e}", file=sys.stderr)
    return out


def run_measurement() -> None:
    """Child-process body: build, warm, time, print the JSON line."""
    import jax

    if TINY:
        # Smoke mode means CPU: in this image a remote-TPU PJRT plugin wins
        # over JAX_PLATFORMS=cpu from the environment, so pin in-process
        # before backend init (same trick as tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")

    t0 = time.perf_counter()
    forced = {"0": False, "1": True}.get(FORCE_PALLAS)
    cfg, engine, base_tb = _build_engine(forced)
    init_s = time.perf_counter() - t0
    print(f"# engine init {init_s:.1f}s; compiling buckets...", file=sys.stderr)
    # No explicit probe needed: every forward funnels through the engine's
    # own degrade-to-XLA fallback (engine/runtime.py:_call_forward), so the
    # round's number survives a Mosaic rejection at ANY bucket. Read the
    # fallback state only after all buckets have compiled.
    stats = _measure(engine, cfg)
    pallas_fallback = engine.kernel_fallback
    try:
        thr = _measure_throughput(engine, cfg, base_tb=base_tb)
    except Exception as e:  # noqa: BLE001 — throughput is a bonus metric
        print(f"# throughput pass failed: {e}", file=sys.stderr)
        thr = {}
    # Post-headline anatomy stage (folded in from the old
    # scripts/tpu_latency_anatomy.py): bounded, best-effort, runs strictly
    # after the p50/throughput numbers are in hand.
    anatomy = {}
    if os.environ.get("BENCH_ANATOMY", "1") not in ("", "0"):
        t0 = time.perf_counter()
        anatomy = _anatomy_probes(
            reps=int(os.environ.get("BENCH_ANATOMY_REPS", "20")),
            include_bigarg=not TINY)
        print(f"# anatomy stage {time.perf_counter() - t0:.1f}s: {anatomy}",
              file=sys.stderr)
    trace_out = os.environ.get("BENCH_TRACE_OUT")
    if trace_out:
        # The engine spans recorded during _measure (tokenize / features /
        # forward / decode per query) as a Perfetto-loadable artifact.
        dump_trace(trace_out)
        print(f"# span trace written to {trace_out}", file=sys.stderr)
    device_kind = jax.devices()[0].device_kind
    print(
        f"# device={device_kind} "
        f"n_queries={stats['n_queries']} buckets={stats['buckets']} "
        f"p50={stats['p50_ms']}ms p95={stats['p95_ms']}ms "
        f"cold_p50={stats['cold_p50_ms']}ms "
        f"forward_p50={stats['forward_p50_ms']}ms "
        f"decode_p50={stats['decode_p50_ms']}ms init={init_s:.1f}s "
        f"warmup={stats['warmup_s']}s "
        f"achieved={stats['achieved_tflops_p50']}TFLOP/s "
        f"batch_qps={thr.get('batch_qps')} "
        f"batch_tflops={thr.get('batch_tflops')}",
        file=sys.stderr,
    )
    # MFU against the chip's peak dense bf16 rate (None off-TPU).
    from vilbert_multitask_tpu.engine.flops import (
        knee_rows,
        param_tree_bytes,
        peak_flops_for,
        serving_roofline,
        weight_bytes_per_row,
    )

    peak = peak_flops_for(device_kind)
    mfu = (round(stats["achieved_tflops_p50"] * 1e12 / peak, 5)
           if peak else None)
    # Boot-phase split + AOT cache outcome (engine/aotcache.py): where the
    # init+warmup seconds went, and whether this boot was served from the
    # executable cache. A fully-warm boot (every warmup program
    # deserialized, zero compiles) records its wall time under
    # ``warm_cache_s`` — the fast-restart number the ledger tracks.
    live = engine.live_stats()
    boot_phases = {k[len("engine_boot_"):]: round(v, 3)
                   for k, v in live.items() if k.startswith("engine_boot_")}
    aot_hits = int(live.get("engine_aot_hits", 0))
    aot_compiled = int(live.get("engine_aot_compiled", 0))
    warm_cache_s = (round(init_s + stats["warmup_s"], 2)
                    if aot_hits and not aot_compiled else None)
    # Roofline context for the MFU numbers: every forward reads the whole
    # param tree from HBM, so small batches are weight-read-bound and a low
    # measured MFU can be the ROOF, not a software gap. param_bytes sums the
    # tree as actually stored (f32 / bf16 / int8 values + f32 scales), so it
    # also records which storage dtype served; knee_rows is the analytic
    # batch size where the verdict flips to compute-bound — the sweep's
    # 64/128/256 chunks exist to bracket it with measurements.
    param_bytes = param_tree_bytes(engine.params)
    roof_batch = thr.get("batch_chunk_rows", max(stats["buckets"]))
    roofline = serving_roofline(cfg.model, cfg.engine, roof_batch,
                                device_kind, param_bytes)
    knee = knee_rows(cfg.model, cfg.engine, device_kind, param_bytes)

    print(json.dumps({
        "metric": "p50_latency_ms",
        "value": stats["p50_ms"],
        "unit": "ms",
        "vs_baseline": round(BASELINE_P50_MS / stats["p50_ms"], 3),
        "p95_ms": stats["p95_ms"],
        "cold_p50_ms": stats["cold_p50_ms"],
        "device_input_cache": True,
        # Hit rate over the warm round-robin: nearly all hits, one miss
        # per distinct image — hardware evidence the row cache engages.
        # (The cold pass doesn't show here: no cache identities means it
        # bypasses the cache entirely, touching neither counter.)
        "input_cache": engine.input_cache_stats,
        "forward_p50_ms": stats["forward_p50_ms"],
        "decode_p50_ms": stats["decode_p50_ms"],
        "stage_ms": stats["stage_ms"],
        "dispatch_floor_ms": stats["dispatch_floor_ms"],
        **({"cost_attrib": stats["cost_attrib"]}
           if stats.get("cost_attrib") else {}),
        **anatomy,
        "param_bytes": param_bytes,
        "param_dtype": cfg.engine.param_dtype,
        "fused_task_heads": cfg.engine.fused_task_heads,
        "achievable_mfu": roofline["achievable_mfu"],
        "roofline": roofline["reason"],
        "knee_rows": knee,
        "weight_bytes_per_row": round(
            weight_bytes_per_row(param_bytes, roof_batch), 1),
        "n_queries": stats["n_queries"],
        "buckets_timed": stats["buckets"],
        "init_s": round(init_s, 1),
        "warmup_s": stats["warmup_s"],
        "boot_phases": boot_phases,
        "aot_hits": aot_hits,
        "aot_compiled": aot_compiled,
        **({"warm_cache_s": warm_cache_s}
           if warm_cache_s is not None else {}),
        "achieved_tflops_p50": stats["achieved_tflops_p50"],
        "mfu": mfu,
        **thr,
        **({"batch_mfu": round(thr["batch_tflops"] * 1e12 / peak, 5)}
           if peak and "batch_tflops" in thr else {}),
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "pallas_coattention": engine.model.config.use_pallas_coattention,
        **({"pallas_fallback": True} if pallas_fallback else {}),
    }), flush=True)


def _err_line(lines) -> str:
    """Pick the most diagnostic stderr line: the actual error over the
    boilerplate JAX appends after it ("frames removed" etc.)."""
    lines = list(lines)
    return next(
        (ln for ln in reversed(lines)
         if "Error" in ln or "error:" in ln.lower()),
        lines[-1] if lines else "no stderr")


def _run_child(timeout_s: float, extra_env: dict) -> tuple:
    """Run one measurement child; returns (json_line|None, err_text).

    Child stderr streams through live (compile/warmup liveness lines) while
    a bounded tail is kept for failure diagnostics. Once the headline JSON
    is on stdout the measurement is complete — the child exits right after
    emitting it, so only a short drain wait follows.
    """
    import collections
    import threading

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, **extra_env},
    )
    # Registered so the orchestrator's kill trap can take the child down
    # with it — an orphaned measurement child would keep holding the TPU
    # backend for up to its full attempt timeout.
    _STATE["child"] = proc
    tail: collections.deque = collections.deque(maxlen=40)
    out_lines: list = []
    got_json = threading.Event()

    # One dedicated reader per pipe (communicate() would race the stderr
    # pump for the same fd and lose lines arbitrarily).
    def _pump_err(stream=proc.stderr, sink=tail):
        for ln in stream:
            sys.stderr.write(ln)
            sink.append(ln.rstrip())

    def _pump_out(stream=proc.stdout, sink=out_lines):
        for ln in stream:
            sink.append(ln)
            if ln.startswith('{"metric"'):
                # Echo the measurement to stderr THE MOMENT it exists:
                # stdout stays a single (possibly compare-enriched) JSON
                # line, but if the whole bench is killed mid-compare the
                # number survives in the stderr record.
                sys.stderr.write("# headline: " + ln)
                got_json.set()

    pumps = [threading.Thread(target=_pump_err, daemon=True),
             threading.Thread(target=_pump_out, daemon=True)]
    for t in pumps:
        t.start()
    deadline = time.monotonic() + timeout_s
    timed_out = False
    while proc.poll() is None:
        if got_json.is_set():
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                print("# headline JSON in hand; killing lingering child",
                      file=sys.stderr)
                proc.kill()
                proc.wait()
            break
        if time.monotonic() >= deadline:
            timed_out = True
            proc.kill()
            proc.wait()
            break
        time.sleep(0.5)
    for t in pumps:
        t.join(timeout=5)
    _STATE["child"] = None
    # A line already on stdout is a valid measurement even if the child then
    # hung or died — never throw away a number in hand.
    json_line = next(
        (ln for ln in out_lines if ln.startswith('{"metric"')), None)
    if json_line:
        return json_line, ""
    err_line = _err_line(tail)
    if timed_out:
        err = f"exceeded {timeout_s:.0f}s; last: {err_line}"[:400]
    else:
        err = f"rc={proc.returncode}: {err_line[:380]}"
    return None, err


def _maybe_compare(headline: dict, timeout_s: float | None = None) -> dict:
    """Kernel-on-vs-off delta for the headline JSON (BASELINE north star).

    Runs strictly AFTER the headline measurement is in hand, as a separate
    bounded child — a hung or failed compare can only ever cost itself, never
    the round's number. Skipped when the headline already fell back to XLA
    (nothing to compare) or is unhealthy (protect the hardware budget).
    """
    if not (COMPARE and headline.get("pallas_coattention")
            and not headline.get("pallas_fallback")
            and isinstance(headline.get("value"), (int, float))
            and headline["value"] < COMPARE_MAX_P50_MS):
        return headline
    print("# compare child: XLA-attention engine...", file=sys.stderr)
    # BENCH_PROFILE_DIR cleared: the compare child would otherwise write an
    # indistinguishable pallas-off trace into the same diagnosis directory.
    # BENCH_SWEEP_ROWS cleared too — only value/forward_p50 are read from
    # the child, so a sweep there is extra compiles burning the compare
    # timeout for discarded numbers.
    line, err = _run_child(min(COMPARE_TIMEOUT_S, timeout_s or COMPARE_TIMEOUT_S),
                           {"BENCH_PALLAS": "0", "BENCH_COMPARE": "0",
                            "BENCH_PROFILE_DIR": "", "BENCH_SWEEP_ROWS": ""})
    if line is None:
        print(f"# compare child failed ({err}); headline unchanged",
              file=sys.stderr)
        return headline
    try:
        off = json.loads(line)
        headline = dict(headline)
        headline["pallas_off_p50_ms"] = off["value"]
        headline["pallas_off_forward_p50_ms"] = off["forward_p50_ms"]
        headline["pallas_forward_speedup"] = round(
            off["forward_p50_ms"] / max(headline["forward_p50_ms"], 1e-9), 3)
        print(f"# pallas_on={headline['forward_p50_ms']}ms "
              f"pallas_off={off['forward_p50_ms']}ms (forward p50)",
              file=sys.stderr)
    except (ValueError, KeyError) as e:
        print(f"# compare JSON unusable ({e}); headline unchanged",
              file=sys.stderr)
    return headline


def _probe_backend(timeout_s: float) -> tuple:
    """Cheap liveness check: can a fresh interpreter see a backend at all?

    Costs ~3 s live / ~2 min on a hung tunnel — vs the 1800 s a full
    measurement child burns discovering the same thing (the round-3 loss:
    BENCH_r03.json is ``rc=124, parsed:null`` because every retry spent an
    attempt-sized timeout on a dead tunnel). Returns (ok, diagnostic).
    """
    code = ("import jax; d = jax.devices(); "
            "print('PROBE_OK', d[0].platform, len(d), flush=True)")
    t0 = time.monotonic()
    # Popen (not subprocess.run) so the kill trap can reach a hung probe:
    # an orphaned probe would keep re-attempting the backend handshake with
    # no deadline — the same hazard as an orphaned measurement child.
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    _STATE["child"] = proc
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            # HARD per-probe deadline: the post-kill drain used to be an
            # unbounded communicate() — a child stuck in uninterruptible
            # backend IO survives SIGKILL reaping long enough to hang the
            # "cheap" probe on exactly the dead tunnel it exists to detect.
            # Bound the drain and abandon an unreapable child (it holds no
            # lock we need; the orchestrator's budget math moves on).
            proc.communicate(timeout=10.0)
        except subprocess.TimeoutExpired:
            print("# probe child unreapable after kill; abandoning it",
                  file=sys.stderr)
        return False, f"probe hung >{timeout_s:.0f}s"
    finally:
        _STATE["child"] = None
    dt = time.monotonic() - t0
    if proc.returncode == 0 and "PROBE_OK" in out:
        return True, f"probe ok in {dt:.0f}s: {out.strip()[:120]}"
    return False, (f"probe rc={proc.returncode} in {dt:.0f}s: "
                   f"{_err_line(err.splitlines())[:200]}")


# Best-so-far state for the kill trap: ``best`` holds the headline JSON the
# moment a measurement child produces one (even if the compare pass is still
# running); the SIGTERM/SIGINT handler prints it — or a structured failure —
# before dying, so an outer `timeout` kill still leaves evidence.
_STATE = {"emitted": False, "best": None, "log": [], "t0": 0.0,
          "child": None}


def _last_known_good() -> dict:
    """Pointer at the newest committed BENCH_*_builder.json artifact, for
    failure emissions: a round that never got a number still tells its
    reader where the last real one lives (and what it was), so a dead
    tunnel doesn't read as "the engine got slow"."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    cands = sorted(glob.glob(os.path.join(here, "BENCH_*_builder.json")),
                   key=os.path.getmtime)
    if not cands:
        return {}
    path = cands[-1]
    out = {"last_known_good": os.path.basename(path)}
    try:
        with open(path) as f:
            prev = json.load(f)
        if isinstance(prev.get("value"), (int, float)):
            out["last_known_good_p50_ms"] = prev["value"]
    except (OSError, ValueError):
        pass
    return out


def _emit_final(obj: dict) -> None:
    if _STATE["emitted"]:
        return
    _STATE["emitted"] = True
    print(json.dumps(obj), flush=True)
    _ledger_append(obj)


def _ledger_append(obj: dict) -> None:
    """Perf-ledger ride-along: a run that produced a real number appends
    its comparable keys to PERF_LEDGER.jsonl (``perf_ledger.py check``
    diffs it against the trailing baseline window). Failure emissions —
    value null, killed early, partial — stay out: a dead tunnel is not a
    baseline. Best-effort: the headline JSON is already on stdout, so
    nothing here may raise."""
    if not isinstance(obj.get("value"), (int, float)):
        return
    if obj.get("partial") or obj.get("killed_early"):
        return
    try:
        from vilbert_multitask_tpu import obs
        from vilbert_multitask_tpu.config import (FrameworkConfig,
                                                  config_fingerprint)

        values = {k: obj[k] for k in (
            "value", "p95_ms", "forward_p50_ms", "decode_p50_ms",
            "batch_qps", "knee_rows", "init_s", "pallas_forward_speedup",
        ) if isinstance(obj.get(k), (int, float))
            and not isinstance(obj.get(k), bool)}
        # Tiny smokes ledger under their own metric: a 6-layer-CPU p50
        # median must never become the hardware run's baseline (or vice
        # versa — check() windows are per-metric).
        metric = "bench.p50_latency_ms" + (".tiny" if TINY else "")
        obs.ledger_append(
            metric, values,
            config_fingerprint=config_fingerprint(FrameworkConfig()),
            extra={"backend": obj.get("backend")})
        # Warm-boot ledger line: only runs that booted fully from the AOT
        # cache append it (the ``_s`` suffix gives it direction=lower in
        # perf_ledger check), so regressions in restart wall time gate.
        if isinstance(obj.get("warm_cache_s"), (int, float)):
            obs.ledger_append(
                "boot.warm_cache_s" + (".tiny" if TINY else ""),
                {"value": obj["warm_cache_s"],
                 **{k: obj["boot_phases"][k] for k in obj.get(
                     "boot_phases", {})}},
                config_fingerprint=config_fingerprint(FrameworkConfig()),
                extra={"backend": obj.get("backend")})
    except Exception as e:  # noqa: BLE001 — never after the emit
        print(f"# perf-ledger append skipped: {e}", file=sys.stderr)


def _on_kill_signal(signum, frame) -> None:
    child = _STATE.get("child")
    if child is not None and child.poll() is None:
        child.kill()  # don't orphan a TPU-holding measurement child
    if _STATE["best"] is not None:
        best = dict(_STATE["best"])
        best["killed_early"] = True
        _emit_final(best)
    else:
        _emit_final({
            "metric": "p50_latency_ms", "value": None, "unit": "ms",
            "vs_baseline": None, "partial": True,
            "error": (f"killed by signal {signum} after "
                      f"{time.monotonic() - _STATE['t0']:.0f}s; "
                      f"log: {' | '.join(_STATE['log'][-4:])}")[:600],
            **_last_known_good(),
        })
    os._exit(1)


def main() -> None:
    """Orchestrator: probe the backend, then measure in a subprocess.

    Failure history this guards against: round 1 died one-shot on backend
    init (fix: fresh-interpreter retries); round 3 died rc=124 with nothing
    on stdout because a dead tunnel ate full attempt timeouts until the
    driver's outer kill (fix: cheap pre-attempt probes, attempts sized to
    the remaining wall budget, and a kill trap that emits best-so-far JSON).
    """
    import signal

    attempts = int(os.environ.get("BENCH_ATTEMPTS", "4"))
    timeout_s = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "1800"))
    probe_on = (not TINY
                and os.environ.get("BENCH_PROBE", "1") not in ("", "0"))
    probe_timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "240"))
    probe_backoff_s = float(os.environ.get("BENCH_PROBE_BACKOFF_S", "45"))
    # Dead-tunnel fast-fail: if the backend has NEVER answered a probe by
    # this deadline, the tunnel was down before we started — report and get
    # out in ~5 minutes instead of probing out the whole wall budget (the
    # round-5 builder artifact spent 1798 s learning nothing a 5-minute
    # window wouldn't have). One successful probe disarms it for the run.
    probe_window_s = float(os.environ.get("BENCH_PROBE_WINDOW_S", "300"))
    # Mid-run dead-tunnel cap: the WINDOW above only guards a backend that
    # was never alive — one successful probe disarms it, after which a
    # tunnel that dies mid-run used to spin "probe hung >240s" cycles for
    # the whole wall budget (the r04/r05 builder artifacts each burned
    # >90 min re-learning the same dead tunnel). Once the backend HAS been
    # seen, this caps CONSECUTIVE failed probes; any success resets it.
    # Before first contact the window above owns the exit.
    probe_max_fails = int(os.environ.get("BENCH_PROBE_MAX_FAILS", "6"))
    wall_budget_s = float(os.environ.get("BENCH_WALL_BUDGET_S", "7200"))
    # Below this remaining-time floor a measurement attempt cannot plausibly
    # finish (engine init alone is ~30 s + compile ~60 s + measure ~90 s,
    # all behind a tunnel with minutes of jitter) — stop and report instead.
    min_attempt_s = float(os.environ.get("BENCH_MIN_ATTEMPT_S", "300"))
    backoff_s = 90.0

    _STATE["t0"] = time.monotonic()
    signal.signal(signal.SIGTERM, _on_kill_signal)
    signal.signal(signal.SIGINT, _on_kill_signal)

    def remaining() -> float:
        return wall_budget_s - (time.monotonic() - _STATE["t0"])

    def note(msg: str) -> None:
        _STATE["log"].append(msg)
        print(f"# {msg}", file=sys.stderr)

    attempt = 0
    backend_ever_seen = False
    probe_fails = 0  # consecutive; any successful probe resets
    while attempt < attempts:
        # Probe cycle: spin on cheap probes while the backend is dead —
        # never launch a child that will burn an attempt timeout learning
        # what a probe learns in seconds.
        while probe_on:
            window_left = (probe_window_s
                           - (time.monotonic() - _STATE["t0"]))
            cap = max(remaining() - min_attempt_s, 10.0)
            if not backend_ever_seen:
                # Keep the fast-fail honest: a single probe must not hang
                # past the window it is supposed to bound.
                cap = min(cap, max(window_left, 10.0))
            ok, diag = _probe_backend(min(probe_timeout_s, cap))
            note(diag)
            if ok:
                backend_ever_seen = True
                probe_fails = 0
                break
            probe_fails += 1
            if backend_ever_seen and probe_fails >= probe_max_fails:
                # Tunnel died mid-run (or never recovered): stop paying
                # probe timeouts for the same diagnosis. Emit the best
                # number in hand — else a structured partial — NOW, while
                # it is still our exit and not the driver's rc=124.
                if _STATE["best"] is not None:
                    best = dict(_STATE["best"])
                    best["partial"] = True
                    best["error"] = (f"{probe_fails} consecutive probe "
                                     "failures; tunnel presumed dead")
                    _emit_final(best)
                else:
                    _emit_final({
                        "metric": "p50_latency_ms", "value": None,
                        "unit": "ms", "vs_baseline": None, "partial": True,
                        "error": (f"{probe_fails} consecutive probe "
                                  "failures (BENCH_PROBE_MAX_FAILS="
                                  f"{probe_max_fails}); probes: "
                                  + " | ".join(_STATE["log"][-6:]))[:800],
                        **_last_known_good(),
                    })
                sys.exit(1)
            if remaining() < min_attempt_s + probe_backoff_s:
                _emit_final({
                    "metric": "p50_latency_ms", "value": None, "unit": "ms",
                    "vs_baseline": None,
                    "error": ("backend never came up within wall budget "
                              f"({wall_budget_s:.0f}s); probes: "
                              + " | ".join(_STATE["log"][-6:]))[:800],
                    **_last_known_good(),
                })
                sys.exit(1)
            if (not backend_ever_seen
                    and time.monotonic() - _STATE["t0"] >= probe_window_s):
                # FIRST probe window expired with zero signs of life: the
                # tunnel is dead-on-arrival. Partial JSON now beats a full
                # wall budget of probes saying the same thing — and the
                # last_known_good pointer tells the reader what the engine
                # measured when the backend last existed.
                _emit_final({
                    "metric": "p50_latency_ms", "value": None, "unit": "ms",
                    "vs_baseline": None, "partial": True,
                    "error": ("backend dead on arrival: no probe succeeded "
                              f"within BENCH_PROBE_WINDOW_S="
                              f"{probe_window_s:.0f}s; probes: "
                              + " | ".join(_STATE["log"][-6:]))[:800],
                    **_last_known_good(),
                })
                sys.exit(1)
            time.sleep(probe_backoff_s)
        # +60 s drain margin: the child is sized to remaining()-60, so this
        # gate guarantees child_timeout >= min_attempt_s — never a doomed
        # (or negative-deadline) attempt on scraps of budget.
        if remaining() < min_attempt_s + 60.0:
            break
        attempt += 1
        # Size the child to what's left: a kill from our own deadline beats
        # a kill from the driver's (ours leaves a diagnosed attempt, the
        # driver's leaves rc=124).
        child_timeout = min(timeout_s, remaining() - 60.0)
        note(f"bench attempt {attempt}/{attempts} "
             f"(timeout {child_timeout:.0f}s)")
        json_line, err = _run_child(child_timeout, {})
        if json_line:
            try:
                headline = json.loads(json_line)
            except ValueError:
                # e.g. a deadline kill truncated the line mid-write; the
                # remaining attempts/budget may still produce a clean one.
                note(f"attempt {attempt} emitted unparseable JSON: "
                     f"{json_line[:200]}")
                continue
            _STATE["best"] = headline  # number in hand — survives any kill
            # Same plausibility floor as a fresh attempt: a compare child
            # is a full measurement, so launching it with less than
            # min_attempt_s of budget just delays the headline emit.
            if remaining() > min_attempt_s + 60.0:
                headline = _maybe_compare(headline,
                                          timeout_s=remaining() - 30.0)
                _STATE["best"] = headline
            else:
                note("skipping compare pass: wall budget nearly spent")
            _emit_final(headline)
            return
        note(f"attempt {attempt} {err}")
        if attempt < attempts and remaining() > min_attempt_s + backoff_s:
            time.sleep(min(backoff_s * attempt,
                           max(remaining() - min_attempt_s, 0.0)))
    # Total failure: still one parseable JSON line, carrying diagnostics.
    _emit_final({
        "metric": "p50_latency_ms",
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "error": (f"no measurement within budget; log: "
                  + " | ".join(_STATE["log"][-6:]))[:800],
        **_last_known_good(),
    })
    sys.exit(1)


if __name__ == "__main__":
    if "--child" in sys.argv[1:]:
        run_measurement()
    else:
        main()
