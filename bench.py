"""Serving-latency benchmark: p50 per query over a full task round-robin.

Measures the BASELINE.md north-star metric — per-query latency across all
served task endpoints (reference instrumented but never published this;
worker.py:657-658) — on whatever accelerator `jax.devices()` offers, and
prints ONE JSON line:

    {"metric": "p50_latency_ms", "value": N, "unit": "ms", "vs_baseline": R}

``vs_baseline`` is target/measured against the <150 ms p50 target from
BASELINE.json ("north_star"): >1.0 beats the target.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np

BASELINE_P50_MS = 150.0

# BENCH_TINY=1 swaps in the tiny model config for CPU smoke runs (the CPU
# backend is ~100x slower than a chip on the 270M config; the driver's TPU
# run uses the real model).
TINY = os.environ.get("BENCH_TINY", "") not in ("", "0")


def synth_regions(rng, cfg, n_boxes=100):
    from vilbert_multitask_tpu.features.pipeline import RegionFeatures

    w, h = 640, 480
    x1 = rng.random((n_boxes,)) * (w - 32)
    y1 = rng.random((n_boxes,)) * (h - 32)
    boxes = np.stack(
        [x1, y1, x1 + 16 + rng.random(n_boxes) * (w / 4),
         y1 + 16 + rng.random(n_boxes) * (h / 4)], axis=1
    ).astype(np.float32)
    feats = rng.normal(size=(n_boxes, cfg.model.v_feature_size)).astype(
        np.float32)
    return RegionFeatures(feats, boxes, w, h)


# The 8 served task types (config.TASK_REGISTRY), with image counts that
# exercise buckets 1 and 2 — the shapes real traffic hits.
ROUND_ROBIN = [
    (1, "what is the man holding", 1),      # VQA
    (15, "is the bowl right of the mug", 1),  # GQA
    (4, "which object can you eat", 1),     # Visual7W pointing
    (11, "the woman in the red coat", 1),   # RefCOCO
    (16, "q: is it a person? a: no", 1),    # GuessWhat
    (13, "two dogs play in the snow", 1),   # SNLI-VE
    (12, "both images contain two wolves", 2),  # NLVR2
    (7, "a man riding a horse", 2),         # Retrieval
]


def main() -> None:
    import jax

    from vilbert_multitask_tpu.config import FrameworkConfig
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine

    cfg = FrameworkConfig()
    if TINY:
        import dataclasses

        cfg = dataclasses.replace(cfg, model=cfg.model.tiny())
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    engine = InferenceEngine(cfg)
    init_s = time.perf_counter() - t0
    regions = [synth_regions(rng, cfg) for _ in range(2)]

    reqs = [
        engine.prepare(task_id, q, regions[:n]) for task_id, q, n in ROUND_ROBIN
    ]

    print(f"# engine init {init_s:.1f}s; compiling buckets...", file=sys.stderr)
    t0 = time.perf_counter()
    engine.warmup(buckets=(1, 2))
    warm_s = time.perf_counter() - t0
    print(f"# warmup {warm_s:.1f}s; timing...", file=sys.stderr)

    # One untimed pass absorbs host-side caches, then the timed epochs.
    t0 = time.perf_counter()
    for req in reqs:
        engine.run(req)
    per_pass_s = time.perf_counter() - t0
    # Scale timed work to ~60s so the bench fits a fixed budget on any
    # backend (CPU smoke runs are ~100x slower than the TPU path).
    epochs = max(1, min(8, int(60.0 / max(per_pass_s, 1e-3))))
    lat_ms = []
    for _ in range(epochs):
        for req in reqs:
            t = time.perf_counter()
            engine.run(req)
            lat_ms.append((time.perf_counter() - t) * 1e3)

    p50 = statistics.median(lat_ms)
    p95 = sorted(lat_ms)[int(0.95 * len(lat_ms)) - 1]
    print(
        f"# device={jax.devices()[0].device_kind} n_queries={len(lat_ms)} "
        f"p50={p50:.2f}ms p95={p95:.2f}ms init={init_s:.1f}s "
        f"warmup={warm_s:.1f}s",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "p50_latency_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_P50_MS / p50, 3),
    }))


if __name__ == "__main__":
    main()
