"""Training-loop tests: multi-task sampling, per-head steps, JSONL data,
and bit-exact checkpoint/resume (SURVEY.md §5 checkpoint/resume — absent in
the reference, whose trainer lives out-of-repo; reference worker.py:44-46)."""

import dataclasses
import json
import os

import numpy as np
import pytest

from vilbert_multitask_tpu.config import EngineConfig, FrameworkConfig
from vilbert_multitask_tpu.train.loop import (
    JsonlTaskData,
    LoopConfig,
    MultiTaskSampler,
    SyntheticTaskData,
    Trainer,
    iou_grounding_target,
    latest_checkpoint,
    vqa_soft_target,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "fixtures", "golden")


@pytest.fixture(scope="module")
def train_cfg(tiny_config):
    return FrameworkConfig(
        model=tiny_config,
        engine=EngineConfig(max_text_len=12, max_regions=9,
                            compute_dtype="float32",
                            use_pallas_coattention=False,
                            use_pallas_self_attention=False))


def _loop(steps, **kw):
    kw.setdefault("batch_size", 4)
    kw.setdefault("log_every", 2)
    kw.setdefault("ckpt_every", 10_000)
    kw.setdefault("warmup_steps", 1)
    kw.setdefault("learning_rate", 1e-4)
    return LoopConfig(total_steps=steps, **kw)


def _sampler(cfg, heads=("vqa", "tri", "grounding", "binary")):
    return MultiTaskSampler({h: SyntheticTaskData(h, cfg) for h in heads})


def test_multitask_smoke_trains_all_heads(train_cfg):
    logs = []
    t = Trainer(train_cfg, _sampler(train_cfg), _loop(8),
                log_fn=lambda s: logs.append(json.loads(s)))
    final = t.train()
    assert np.isfinite(final["loss/total"])
    assert final["step"] == 8
    # the sampler actually alternated: over 8 steps at these weights more
    # than one head must appear (seeded, deterministic)
    assert len({m["head"] for m in logs}) > 1
    # per-head programs compiled lazily; every logged head has one (logs
    # sample every log_every steps, so compiled heads are a superset)
    assert {m["head"] for m in logs} <= set(t._steps)


def test_loss_decreases_on_fixed_batch(train_cfg):
    """Single head, SAME batch every step: the optimizer must make progress
    (loss strictly lower after 12 steps) — the training loop's end-to-end
    gradient plumbing check."""

    class FixedData(SyntheticTaskData):
        def batch(self, batch_size, *, step=0):
            return super().batch(batch_size, step=0)  # pinned batch

    sampler = MultiTaskSampler({"vqa": FixedData("vqa", train_cfg)})
    logs = []
    t = Trainer(train_cfg, sampler, _loop(12, log_every=1),
                log_fn=lambda s: logs.append(json.loads(s)))
    t.train()
    assert logs[-1]["loss/total"] < logs[0]["loss/total"]


def test_checkpoint_resume_is_bit_exact(train_cfg, tmp_path):
    """4 straight steps == 2 steps + checkpoint + resume + 2 steps, leaf for
    leaf. The sampler is stateless over the global step and TrainState.rng
    rides the snapshot, so the resumed run replays the identical schedule."""
    import jax

    out = str(tmp_path / "ckpts")
    # uninterrupted reference run
    ref = Trainer(train_cfg, _sampler(train_cfg), _loop(4),
                  log_fn=lambda s: None)
    ref.train()

    # interrupted run: stop at 2 (ckpt_every=2 snapshots there), new Trainer
    a = Trainer(train_cfg, _sampler(train_cfg), _loop(2, ckpt_every=2),
                out_dir=out, log_fn=lambda s: None)
    a.train()
    found = latest_checkpoint(out)
    assert found is not None and found[1] == 2

    b = Trainer(train_cfg, _sampler(train_cfg), _loop(4, ckpt_every=2),
                out_dir=out, log_fn=lambda s: None)
    assert int(jax.device_get(b.state.step)) == 2  # resumed, not restarted
    b.train()

    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(ref.state.params))
    b_leaves = jax.tree_util.tree_leaves(jax.device_get(b.state.params))
    for x, y in zip(ref_leaves, b_leaves):
        np.testing.assert_array_equal(x, y)


def test_checkpoint_retention(train_cfg, tmp_path):
    out = str(tmp_path / "ckpts")
    t = Trainer(train_cfg, _sampler(train_cfg),
                _loop(8, ckpt_every=2, keep_ckpts=2),
                out_dir=out, log_fn=lambda s: None)
    t.train()
    snaps = sorted(n for n in os.listdir(out) if n.startswith("step_"))
    assert snaps == ["step_00000006", "step_00000008"]


def test_jsonl_datasets_golden_fixtures(train_cfg):
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.text.wordpiece import FullTokenizer
    from vilbert_multitask_tpu import assets

    store = FeatureStore(os.path.join(GOLDEN, "features"))
    tok = FullTokenizer.from_vocab_file(assets.default_vocab_path())
    m, e = train_cfg.model, train_cfg.engine

    vqa = JsonlTaskData("vqa", os.path.join(GOLDEN, "vqa.jsonl"), store, tok,
                        train_cfg, label_map=["4", "brown", "left"])
    b = vqa.batch(3, step=1)
    assert b["vqa_target"].shape == (3, m.num_labels)
    assert b["features"].shape == (3, e.max_regions, m.v_feature_size)

    grd = JsonlTaskData("grounding", os.path.join(GOLDEN, "grounding.jsonl"),
                        store, tok, train_cfg)
    g = grd.batch(2, step=0)
    sums = g["grounding_target"].sum(axis=-1)
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)  # soft targets normalized
    assert (g["grounding_target"][:, 0] == 0).all()  # global region never gt

    nlvr = JsonlTaskData("binary", os.path.join(GOLDEN, "nlvr2.jsonl"),
                         store, tok, train_cfg)
    nb = nlvr.batch(4, step=0)
    assert nb["input_ids"].shape[0] == 4  # 2 pairs → 4 rows
    assert nb["binary_label"].shape == (2,)
    # pair rows share their caption tokens
    np.testing.assert_array_equal(nb["input_ids"][0], nb["input_ids"][1])

    # Contract errors, not silent misbehavior: an odd NLVR2 batch would
    # silently emit batch_size-1 rows (and break dp divisibility on a
    # mesh); vqa/gqa without a label map would train on all-zero targets.
    with pytest.raises(ValueError, match="even"):
        nlvr.batch(5, step=0)
    with pytest.raises(ValueError, match="label_map"):
        JsonlTaskData("vqa", os.path.join(GOLDEN, "vqa.jsonl"), store, tok,
                      train_cfg)


def test_jsonl_end_to_end_training_step(train_cfg):
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.text.wordpiece import FullTokenizer
    from vilbert_multitask_tpu import assets

    store = FeatureStore(os.path.join(GOLDEN, "features"))
    tok = FullTokenizer.from_vocab_file(assets.default_vocab_path())
    datasets = {
        "vqa": JsonlTaskData("vqa", os.path.join(GOLDEN, "vqa.jsonl"), store,
                             tok, train_cfg, label_map=["4", "brown"]),
        "grounding": JsonlTaskData(
            "grounding", os.path.join(GOLDEN, "grounding.jsonl"), store, tok,
            train_cfg),
    }
    t = Trainer(train_cfg, MultiTaskSampler(datasets), _loop(4),
                log_fn=lambda s: None)
    final = t.train()
    assert np.isfinite(final["loss/total"])


def test_target_builders():
    t = vqa_soft_target(["a", "a", "a", "b"], {"a": 0, "b": 1}, 4)
    assert t[0] == pytest.approx(0.9) and t[1] == pytest.approx(0.3)

    boxes = np.array([[0, 0, 10, 10], [0, 0, 100, 100], [90, 90, 99, 99]],
                     np.float32)
    g = iou_grounding_target(boxes, [0, 0, 100, 100], 3, 9)
    assert g.shape == (9,) and g[2] == pytest.approx(1.0)  # row0=global shift
    assert g.sum() == pytest.approx(1.0)
    # no region over IoU 0.5 → best region takes the full mass
    g2 = iou_grounding_target(boxes[:1], [50, 50, 60, 60], 1, 9)
    assert g2[1] == pytest.approx(1.0)


def _tp_divisible_cfg(train_cfg):
    """Tiny config with dims the tp=2 partition rules divide cleanly —
    shared by every mesh-sharded trainer test."""
    return dataclasses.replace(
        train_cfg,
        model=train_cfg.model.tiny(
            hidden_size=64, num_attention_heads=4, intermediate_size=128,
            v_hidden_size=64, v_num_attention_heads=4, v_intermediate_size=128,
            bi_hidden_size=64, bi_num_attention_heads=4,
            bi_intermediate_size=128, vocab_size=512, num_labels=16,
            gqa_num_labels=16, v_target_size=12))


def test_mesh_sharded_training_loop(train_cfg):
    """2 steps over the virtual 8-device dp×tp mesh (SURVEY.md §4 strategy)."""
    from vilbert_multitask_tpu.config import MeshConfig
    from vilbert_multitask_tpu.parallel import build_mesh

    cfg = _tp_divisible_cfg(train_cfg)
    mesh = build_mesh(MeshConfig(tp=2))
    t = Trainer(cfg, _sampler(cfg, heads=("vqa", "tri")),
                _loop(2, batch_size=8, log_every=1), mesh=mesh,
                log_fn=lambda s: None)
    final = t.train()
    assert np.isfinite(final["loss/total"])


def test_mesh_checkpoint_resume_is_bit_exact(train_cfg, tmp_path):
    """The single-device resume guarantee must survive the mesh: snapshot
    dp×tp-SHARDED TrainState (Orbax gathers the global arrays), resume
    onto a fresh mesh, and match an uninterrupted sharded run leaf for
    leaf — the multi-chip restart contract."""
    import jax

    from vilbert_multitask_tpu.config import MeshConfig
    from vilbert_multitask_tpu.parallel import build_mesh

    cfg = _tp_divisible_cfg(train_cfg)
    mesh = build_mesh(MeshConfig(tp=2))
    out = str(tmp_path / "mesh_ckpts")

    ref = Trainer(cfg, _sampler(cfg, heads=("vqa", "tri")),
                  _loop(4, batch_size=8), mesh=mesh, log_fn=lambda s: None)
    ref.train()

    a = Trainer(cfg, _sampler(cfg, heads=("vqa", "tri")),
                _loop(2, batch_size=8, ckpt_every=2), mesh=mesh,
                out_dir=out, log_fn=lambda s: None)
    a.train()

    b = Trainer(cfg, _sampler(cfg, heads=("vqa", "tri")),
                _loop(4, batch_size=8, ckpt_every=2), mesh=build_mesh(
                    MeshConfig(tp=2)),  # a FRESH mesh, like a restart
                out_dir=out, log_fn=lambda s: None)
    assert int(jax.device_get(b.state.step)) == 2
    # restored leaves keep their tp shardings (no silent replication)
    ffn = b.state.params["bert"]["encoder"]["t_layer_0"]["ffn"][
        "intermediate"]["kernel"]
    assert "tp" in str(ffn.sharding.spec)
    b.train()

    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(ref.state.params))
    b_leaves = jax.tree_util.tree_leaves(jax.device_get(b.state.params))
    for x, y in zip(ref_leaves, b_leaves):
        np.testing.assert_array_equal(x, y)


def test_indexed_jsonl_concurrent_reads(tmp_path):
    """ADVICE r4 #2: seek()+readline() on the shared handle is a critical
    section — 8 threads hammering random indices must every one parse the
    record the index names (interleaved seeks would cross-read lines)."""
    import threading

    from vilbert_multitask_tpu.utils import IndexedJsonl

    path = tmp_path / "d.jsonl"
    with open(path, "w") as f:
        for i in range(200):
            f.write(json.dumps({"i": i, "pad": "x" * (i % 37)}) + "\n")
    with IndexedJsonl(str(path)) as ds:
        assert len(ds) == 200
        errors = []

        def reader(seed):
            rng = np.random.default_rng(seed)
            for _ in range(300):
                i = int(rng.integers(0, 200))
                rec = ds[i]
                if rec["i"] != i:
                    errors.append((i, rec))

        threads = [threading.Thread(target=reader, args=(s,))
                   for s in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
    assert ds._f.closed  # context manager released the handle


def test_jsonl_clips_overprovisioned_store(train_cfg, tmp_path):
    """A store entry with more boxes than the region budget is clipped to
    the top max_regions-1 (confidence order), not a crash — same contract
    as engine.prepare."""
    from vilbert_multitask_tpu.features.pipeline import RegionFeatures
    from vilbert_multitask_tpu.features.store import save_reference_npy, FeatureStore
    from vilbert_multitask_tpu.text.wordpiece import FullTokenizer
    from vilbert_multitask_tpu import assets

    e = train_cfg.engine
    n_boxes = e.max_regions + 5  # over budget
    rng = np.random.RandomState(0)
    boxes = rng.uniform(10, 200, (n_boxes, 4)).astype(np.float32)
    boxes[:, 2:] = boxes[:, :2] + 20
    save_reference_npy(
        str(tmp_path / "big.npy"),
        RegionFeatures(rng.randn(n_boxes, train_cfg.model.v_feature_size)
                       .astype(np.float32), boxes, 640, 480), "big")
    jl = tmp_path / "grounding.jsonl"
    jl.write_text(json.dumps({"expression": "the thing", "image": "big",
                              "gt_box": [0, 0, 100, 100]}) + "\n")
    ds = JsonlTaskData("grounding", str(jl), FeatureStore(str(tmp_path)),
                       FullTokenizer.from_vocab_file(
                           assets.default_vocab_path()), train_cfg)
    b = ds.batch(2, step=0)
    assert b["features"].shape[1] == e.max_regions
    np.testing.assert_allclose(b["grounding_target"].sum(axis=-1), 1.0,
                               atol=1e-5)


def test_eval_hook_scores_on_serving_path(train_cfg, tmp_path):
    """eval_every runs the eval HARNESS on the trainer's current params via
    a real InferenceEngine — scores land in the training log with eval/
    prefixes and stay in range."""
    from vilbert_multitask_tpu.evals.harness import load_jsonl
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.train.loop import EvalHook

    hook = EvalHook(
        train_cfg, FeatureStore(os.path.join(GOLDEN, "features")),
        {"vqa": load_jsonl(os.path.join(GOLDEN, "vqa.jsonl")),
         "nlvr2": load_jsonl(os.path.join(GOLDEN, "nlvr2.jsonl"))})
    logs = []
    t = Trainer(train_cfg, _sampler(train_cfg),
                _loop(4, eval_every=2, log_every=1), eval_fn=hook,
                log_fn=lambda s: logs.append(json.loads(s)))
    t.train()
    evals = [m for m in logs if any(k.startswith("eval/") for k in m)]
    assert len(evals) == 2  # steps 2 and 4
    for m in evals:
        assert 0.0 <= m["eval/vqa/accuracy"] <= 1.0
        assert 0.0 <= m["eval/nlvr2/accuracy"] <= 1.0
    # engine built once, params swapped per eval (no rebuild per call)
    assert hook._engine is not None


def test_eval_hook_rejects_unknown_tasks_and_skips_metadata(train_cfg):
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.train.loop import EvalHook

    store = FeatureStore(os.path.join(GOLDEN, "features"))
    with pytest.raises(ValueError, match="unknown eval tasks"):
        EvalHook(train_cfg, store, {"snli_ve": []})

    from vilbert_multitask_tpu.evals.harness import load_jsonl
    hook = EvalHook(train_cfg, store,
                    {"vqa": load_jsonl(os.path.join(GOLDEN, "vqa.jsonl"))})
    t = Trainer(train_cfg, _sampler(train_cfg), _loop(1), log_fn=lambda s: None)
    scores = hook(1, t.state)
    assert any(k == "eval/vqa/accuracy" for k in scores)
    # metadata (n / task_id / wall_s) never masquerades as a score
    assert not any(k.endswith(("/n", "/task_id", "/wall_s")) for k in scores)


def test_eval_hook_on_mesh_sharded_params(train_cfg):
    """--eval-every on a multi-chip run: the hook's engine must accept the
    trainer's tp/dp-sharded params (mesh forwarded), not crash on
    incompatible device placements."""
    from vilbert_multitask_tpu.config import MeshConfig
    from vilbert_multitask_tpu.evals.harness import load_jsonl
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.parallel import build_mesh
    from vilbert_multitask_tpu.train.loop import EvalHook

    cfg = dataclasses.replace(
        train_cfg,
        model=train_cfg.model.tiny(
            hidden_size=64, num_attention_heads=4, intermediate_size=128,
            v_hidden_size=64, v_num_attention_heads=4, v_intermediate_size=128,
            bi_hidden_size=64, bi_num_attention_heads=4,
            bi_intermediate_size=128, vocab_size=2048, num_labels=16,
            gqa_num_labels=16, v_target_size=12))
    mesh = build_mesh(MeshConfig(tp=2))
    hook = EvalHook(cfg, FeatureStore(os.path.join(GOLDEN, "features")),
                    {"nlvr2": load_jsonl(os.path.join(GOLDEN, "nlvr2.jsonl"))},
                    mesh=mesh)
    t = Trainer(cfg, _sampler(cfg, heads=("tri",)),
                _loop(1, batch_size=8), mesh=mesh, log_fn=lambda s: None)
    scores = hook(1, t.state)
    assert 0.0 <= scores["eval/nlvr2/accuracy"] <= 1.0


def test_mlm_masking_properties(train_cfg):
    from vilbert_multitask_tpu.train.loop import apply_mlm_masking

    rng = np.random.default_rng(0)
    B, Nt = 64, 24
    ids = rng.integers(5, 400, (B, Nt)).astype(np.int32)
    ids[:, 0] = 101  # [CLS]-like special
    mask = np.ones((B, Nt), np.int32)
    mask[:, -4:] = 0  # padding
    masked, labels = apply_mlm_masking(
        ids.copy(), mask, np.random.default_rng(1), mask_id=103,
        vocab_size=400, special_ids=(0, 101, 102, 103))
    picked = labels >= 0
    rate = picked.mean()
    assert 0.10 < rate < 0.20  # ~15%
    assert not picked[:, 0].any()  # specials never masked
    assert not picked[:, -4:].any()  # padding never masked
    np.testing.assert_array_equal(labels[picked], ids[picked])  # originals
    assert (masked[picked] == 103).mean() > 0.6  # ~80% → [MASK]
    assert (masked[~picked] == ids[~picked]).all()  # others untouched


def test_mrm_masking_targets(train_cfg):
    """Masking happens on RAW regions (pre-encoding): the global mean-pool
    row must see zeros for masked regions, never their content."""
    from vilbert_multitask_tpu.features.pipeline import (
        RegionFeatures,
        encode_image,
    )
    from vilbert_multitask_tpu.train.loop import apply_mrm_masking

    Nr, D, C, MAX = 8, 16, 6, 9
    rng = np.random.RandomState(0)
    boxes = rng.uniform(10, 200, (Nr, 4)).astype(np.float32)
    boxes[:, 2:] = boxes[:, :2] + 20
    cp = rng.rand(Nr, C).astype(np.float32)
    regions = [
        RegionFeatures(np.ones((Nr, D), np.float32) * (i + 1), boxes,
                       640, 480, cls_prob=[cp, None, cp[:, :3]][i])
        for i in range(3)
    ]
    masked, target, mmask = apply_mrm_masking(
        regions, np.random.default_rng(3), n_classes=C, max_regions=MAX)
    assert not mmask[:, 0].any()  # global row never masked
    np.testing.assert_allclose(target.sum(-1), 1.0, atol=1e-5)
    # cls_prob rows carry the detector distribution; None / wrong width → uniform
    np.testing.assert_allclose(target[0, 1], cp[0] / cp[0].sum(), atol=1e-6)
    np.testing.assert_allclose(target[1, 1], np.full(C, 1 / C), atol=1e-6)
    np.testing.assert_allclose(target[2, 1], np.full(C, 1 / C), atol=1e-6)
    # leak check: encoding AFTER masking → the global mean is the mean of
    # the MASKED features (zeros included), not the originals
    for i, (r, m) in enumerate(zip(masked, mmask)):
        enc = encode_image(r, MAX)
        n_masked = int(m[1 : Nr + 1].sum())
        assert n_masked > 0  # seeded: every image masks something
        expected_mean = (i + 1) * (Nr - n_masked) / Nr
        np.testing.assert_allclose(enc.features[0], expected_mean, atol=1e-5)
        # masked encoded rows are zero
        rows = np.where(m[1 : Nr + 1] > 0)[0] + 1
        assert (enc.features[rows] == 0).all()


def test_pretrain_head_trains(train_cfg):
    """Joint MLM+MRM pretraining step (the BertForMultiModalPreTraining
    capability, reference worker.py:45) — synthetic data, finite loss,
    both objective losses present."""
    logs = []
    t = Trainer(train_cfg,
                MultiTaskSampler({"pretrain":
                                  SyntheticTaskData("pretrain", train_cfg)}),
                _loop(3, log_every=1),
                log_fn=lambda s: logs.append(json.loads(s)))
    final = t.train()
    assert np.isfinite(final["loss/total"])
    assert "loss/mlm" in final and "loss/mrm" in final


def test_pretrain_jsonl_captions(train_cfg, tmp_path):
    """Caption-pair pretraining from the reference .npy schema with
    cls_prob: the MRM target is the stored detector distribution."""
    from vilbert_multitask_tpu.features.pipeline import RegionFeatures
    from vilbert_multitask_tpu.features.store import (
        FeatureStore,
        save_reference_npy,
    )
    from vilbert_multitask_tpu.text.wordpiece import FullTokenizer
    from vilbert_multitask_tpu import assets

    m, e = train_cfg.model, train_cfg.engine
    rng = np.random.RandomState(0)
    boxes = rng.uniform(10, 200, (5, 4)).astype(np.float32)
    boxes[:, 2:] = boxes[:, :2] + 20
    cp = rng.random((5, m.v_target_size)).astype(np.float32)
    save_reference_npy(
        str(tmp_path / "cap_a.npy"),
        RegionFeatures(rng.randn(5, m.v_feature_size).astype(np.float32),
                       boxes, 640, 480, cls_prob=cp), "cap_a")
    jl = tmp_path / "pretrain.jsonl"
    jl.write_text(json.dumps({"caption": "a dog runs on the beach",
                              "image": "cap_a"}) + "\n")
    ds = JsonlTaskData("pretrain", str(jl), FeatureStore(str(tmp_path)),
                       FullTokenizer.from_vocab_file(
                           assets.default_vocab_path()), train_cfg)
    b = ds.batch(2, step=3)
    assert b["task_ids"][0, 0] == 0  # reserved pretraining task token
    assert b["mrm_target"].shape == (2, e.max_regions, m.v_target_size)
    np.testing.assert_allclose(
        b["mrm_target"][0, 1], cp[0] / cp[0].sum(), atol=1e-5)
    # dynamic masking: different steps mask differently
    b2 = ds.batch(2, step=4)
    assert not np.array_equal(b["mlm_labels"], b2["mlm_labels"])
    # round-trip through the store kept cls_prob (loader regression)
    region = FeatureStore(str(tmp_path)).get("cap_a")
    assert region.cls_prob is not None and region.cls_prob.shape == cp.shape

    t = Trainer(train_cfg, MultiTaskSampler({"pretrain": ds}), _loop(2),
                log_fn=lambda s: None)
    final = t.train()
    assert np.isfinite(final["loss/total"])


def test_retrieval_jsonl_group_layout(train_cfg):
    """Caption replicated over its group; the positive image occupies row 0
    of each group (the contrastive-loss alignment convention)."""
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.text.wordpiece import FullTokenizer
    from vilbert_multitask_tpu import assets

    store = FeatureStore(os.path.join(GOLDEN, "features"))
    tok = FullTokenizer.from_vocab_file(assets.default_vocab_path())
    ds = JsonlTaskData("retrieval", os.path.join(GOLDEN, "retrieval.jsonl"),
                       store, tok, train_cfg, group_size=2)
    b = ds.batch(4, step=0)
    assert b["input_ids"].shape[0] == 4  # 2 groups of 2
    # caption rows within a group are identical
    np.testing.assert_array_equal(b["input_ids"][0], b["input_ids"][1])
    np.testing.assert_array_equal(b["input_ids"][2], b["input_ids"][3])
    # positive-first: row 0 features come from the target image of the
    # drawn example — compare against the store directly
    from vilbert_multitask_tpu.evals.harness import load_jsonl
    from vilbert_multitask_tpu.features.pipeline import encode_image

    examples = load_jsonl(os.path.join(GOLDEN, "retrieval.jsonl"))
    drawn = np.random.default_rng((0, 0, 7)).integers(0, len(examples), (2,))
    ex0 = examples[drawn[0]]
    pos = encode_image(store.get(ex0["images"][int(ex0["target"])]),
                       train_cfg.engine.max_regions)
    np.testing.assert_allclose(b["features"][0], pos.features, atol=1e-6)

    t = Trainer(train_cfg, MultiTaskSampler({"retrieval": ds}),
                _loop(2, log_every=1), log_fn=lambda s: None)
    final = t.train()
    assert np.isfinite(final["loss/total"])
    assert "loss/retrieval" in final


def test_cli_main_synthetic_smoke(capsys):
    """The module CLI end-to-end on synthetic data: one step, final JSON on
    stdout (the `python -m vilbert_multitask_tpu.train.loop` contract)."""
    from vilbert_multitask_tpu.train import loop as loop_mod

    loop_mod.main(["--tiny", "--steps", "1", "--batch", "2",
                   "--heads", "tri", "--log-every", "1"])
    out = capsys.readouterr().out
    final = json.loads(out.strip().splitlines()[-1])
    assert np.isfinite(final["final"]["loss/total"])
    assert final["final"]["step"] == 1


def test_trainer_aborts_on_divergence(train_cfg):
    """A run that goes non-finite fails at the first logged step, not after
    the remaining budget burns on NaN updates."""

    class PoisonData(SyntheticTaskData):
        def batch(self, batch_size, *, step=0):
            b = super().batch(batch_size, step=step)
            b["features"] = np.full_like(b["features"], np.nan)
            return b

    t = Trainer(train_cfg,
                MultiTaskSampler({"vqa": PoisonData("vqa", train_cfg)}),
                _loop(6, log_every=1), log_fn=lambda s: None)
    with pytest.raises(FloatingPointError, match="non-finite loss at step 1"):
        t.train()


def test_trainer_never_snapshots_diverged_state(train_cfg, tmp_path):
    """ckpt cadence ≠ log cadence: a NaN between log points must abort the
    SAVE, never write a poisoned snapshot."""

    class PoisonData(SyntheticTaskData):
        def batch(self, batch_size, *, step=0):
            b = super().batch(batch_size, step=step)
            b["features"] = np.full_like(b["features"], np.nan)
            return b

    out = str(tmp_path / "ckpts")
    t = Trainer(train_cfg,
                MultiTaskSampler({"vqa": PoisonData("vqa", train_cfg)}),
                _loop(4, log_every=100, ckpt_every=1),
                out_dir=out, log_fn=lambda s: None)
    with pytest.raises(FloatingPointError, match="snapshot NOT written"):
        t.train()
    snaps = ([n for n in os.listdir(out) if n.startswith("step_")]
             if os.path.isdir(out) else [])
    assert not snaps


def test_indexed_jsonl_matches_eager_load(tmp_path):
    """IndexedJsonl is a drop-in for the eager loader: same records, same
    order, random access by offset, blank lines skipped, memory held is
    offsets not records."""
    from vilbert_multitask_tpu.evals.harness import load_jsonl
    from vilbert_multitask_tpu.utils import IndexedJsonl

    p = tmp_path / "data.jsonl"
    rows = [{"i": i, "text": f"q{i}" * (i % 5 + 1)} for i in range(57)]
    with open(p, "w") as f:
        for i, r in enumerate(rows):
            f.write(json.dumps(r) + "\n")
            if i % 7 == 0:
                f.write("\n")  # blank lines must not shift indices
    eager = load_jsonl(str(p))
    lazy = IndexedJsonl(str(p))
    assert len(lazy) == len(eager) == 57
    assert list(lazy) == eager
    assert lazy[13] == eager[13]
    assert lazy[-1] == eager[-1]  # negative indexing
    with pytest.raises(IndexError):
        lazy[57]
    # numpy integer indices (what the sampler draws) work
    assert lazy[np.int64(3)] == eager[3]
    lazy.close()
