"""Fleet observability: process identity, the shared metrics spine,
cross-process trace stitching, and the perf ledger.

The two-OS-process tests are the contract the whole tentpole exists
for: a REAL second python process (subprocess, its own registry and
tracer) flushes into the same ``fleet.sqlite3``, and this process's
spine must merge it — both identities visible, counters summed, one
stitched Chrome-trace timeline — and must evict it once its heartbeat
goes stale after a SIGKILL (the crash case ``retire()`` never sees).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.obs.fleet import FleetSpine, default_spine_path
from vilbert_multitask_tpu.obs.identity import (
    mint_identity,
    process_identity,
    reset_process_identity,
)
from vilbert_multitask_tpu.obs.instruments import Registry
from vilbert_multitask_tpu.obs.ledger import (
    append_entry,
    check,
    key_direction,
    read_entries,
)
from vilbert_multitask_tpu.obs.timeseries import TimeSeriesStore
from vilbert_multitask_tpu.obs.trace import Tracer
from vilbert_multitask_tpu.obs.tracestore import TraceStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_ID = "feedface00000000"


# ------------------------------------------------------------------ identity
def test_identity_fields_and_canonical_key():
    me = mint_identity(role="bench")
    assert me.ident == f"{me.host}:{me.pid}:{me.boot_nonce}"
    assert me.pid == os.getpid()
    assert len(me.boot_nonce) == 8
    assert me.labels() == {"instance": me.ident, "role": "bench"}
    d = me.as_dict()
    assert d["ident"] == me.ident and d["role"] == "bench"


def test_two_incarnations_differ_only_by_nonce():
    # Same host+pid (a crash-looping worker) must still be two identities.
    a, b = mint_identity(), mint_identity()
    assert (a.host, a.pid) == (b.host, b.pid)
    assert a.ident != b.ident


def test_process_identity_minted_once_first_role_wins():
    reset_process_identity()
    try:
        first = process_identity("serve")
        assert first.role == "serve"
        # Later callers share the object; a different role never re-mints.
        assert process_identity("worker") is first
        assert process_identity() is first
    finally:
        reset_process_identity()


# ------------------------------------------------- identity stamping planes
def test_registry_default_labels_applied_at_exposition_only():
    reg = Registry()
    c = reg.counter("vmt_stamp_total", "stamped")
    c.inc(2)
    reg.set_default_labels(instance="h:1:abc", role="serve")
    text = obs.render_prometheus(registry=reg)
    assert 'vmt_stamp_total{instance="h:1:abc",role="serve"} 2' in text
    # The instrument itself keeps its declared (empty) label schema —
    # stamping happens in the renderer, not at observe time.
    assert c.labelnames == ()
    assert c.collect() == {(): 2.0}
    reg.set_default_labels()  # no kwargs clears
    assert "vmt_stamp_total 2" in obs.render_prometheus(registry=reg)


def test_default_labels_never_shadow_declared_labels():
    reg = Registry()
    g = reg.gauge("vmt_stamp_gauge", "g", labelnames=("role",))
    g.set(1.0, role="declared")
    reg.set_default_labels(instance="h:1:abc", role="default")
    line = next(ln for ln in obs.render_prometheus(registry=reg).splitlines()
                if ln.startswith("vmt_stamp_gauge{"))
    assert 'role="declared"' in line and 'role="default"' not in line
    assert 'instance="h:1:abc"' in line


def test_tracer_default_attrs_merged_span_local_wins():
    tr = Tracer()
    tr.set_default_attrs(instance="h:1:abc", role="serve")
    with tr.span("a"):
        pass
    with tr.span("b", role="override"):
        pass
    spans = {s.name: s for s in tr.spans()}
    assert spans["a"].attrs["instance"] == "h:1:abc"
    assert spans["a"].attrs["role"] == "serve"
    assert spans["b"].attrs["role"] == "override"


# ------------------------------------------------------------- fleet spine
def _spine(db, role, *, stale_s=15.0):
    reg, tr = Registry(), Tracer()
    me = mint_identity(role=role)
    ts = TimeSeriesStore()
    return FleetSpine(db, me, heartbeat_stale_s=stale_s, registry=reg,
                      tracer=tr, timeseries=ts), reg, tr, ts


def test_default_spine_path_sits_next_to_queue_db(tmp_path):
    q = str(tmp_path / "queue.sqlite3")
    assert default_spine_path(q) == str(tmp_path / "fleet.sqlite3")


def test_retire_withdraws_presence_but_keeps_spans(tmp_path):
    db = str(tmp_path / "fleet.sqlite3")
    spine, reg, tr, _ = _spine(db, "serve")
    reg.counter("vmt_fleet_test_total").inc()
    with tr.trace(TRACE_ID):
        with tr.span("submit.work"):
            pass
    spine.flush({"phase": "ready"})
    reader, _, _, _ = _spine(db, "reader")
    reader.flush()
    assert spine.identity.ident in {p["ident"] for p in reader.peers()}
    spine.retire()
    assert spine.identity.ident not in {p["ident"] for p in reader.peers()}
    assert "vmt_fleet_test_total" not in reader.render_prometheus()
    # The retired submitter's half of the trace stays stitchable.
    names = {e["name"] for e in reader.chrome_trace(TRACE_ID)["traceEvents"]}
    assert "submit.work" in names


def test_timeseries_merge_keys_by_ident(tmp_path):
    db = str(tmp_path / "fleet.sqlite3")
    a, _, _, ts_a = _spine(db, "serve")
    b, _, _, ts_b = _spine(db, "worker")
    ts_a.record("vmt_qps", 10.0)
    ts_b.record("vmt_qps", 20.0)
    a.flush()
    b.flush()
    series = a.timeseries()["series"]
    assert [v for _, v in series[f"{a.identity.ident}:vmt_qps"]] == [10.0]
    assert [v for _, v in series[f"{b.identity.ident}:vmt_qps"]] == [20.0]


# --------------------------------------------------- two REAL OS processes
_PEER_SRC = r"""
import sys, time
from vilbert_multitask_tpu.obs.fleet import FleetSpine
from vilbert_multitask_tpu.obs.identity import mint_identity
from vilbert_multitask_tpu.obs.instruments import Registry
from vilbert_multitask_tpu.obs.trace import Tracer

db, mode = sys.argv[1], sys.argv[2]
reg, tr = Registry(), Tracer()
reg.counter("vmt_fleet_test_total", "cross-process sum subject").inc(5)
reg.gauge("vmt_fleet_test_depth", "per-ident subject").set(7)
reg.histogram("vmt_fleet_test_ms", "bucket-merge subject").observe(3.0)
with tr.trace("feedface00000000"):
    with tr.span("peer.work"):
        time.sleep(0.01)
me = mint_identity(role="peer")
spine = FleetSpine(db, me, registry=reg, tracer=tr)
spine.flush({"phase": "ready"})
# A tail-kept trace on the same spine db: the crash-autopsy subject the
# SIGKILL test reads back after this process is dead and evicted.
from vilbert_multitask_tpu.obs.attrib import JobCost
from vilbert_multitask_tpu.obs.tracestore import TraceStore
store = TraceStore(db, me.ident)
cost = JobCost(trace_id="feedface00000000", task="vqa", tenant="acme",
               verdict="ok")
cost.stages["forward"] = 250.0
cost.finished_unix = time.time()
store.offer(cost, tr.spans())
store.flush()
print("IDENT " + me.ident, flush=True)
if mode == "linger":
    time.sleep(120)
"""


def _spawn_peer(db, mode):
    proc = subprocess.Popen(
        [sys.executable, "-c", _PEER_SRC, db, mode],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    line = proc.stdout.readline().strip()
    assert line.startswith("IDENT "), (line, proc.stderr.read())
    return proc, line.split(" ", 1)[1]


def test_two_processes_merge_on_one_spine(tmp_path):
    db = str(tmp_path / "fleet.sqlite3")
    spine, reg, tr, _ = _spine(db, "serve")
    reg.counter("vmt_fleet_test_total", "cross-process sum subject").inc(3)
    reg.gauge("vmt_fleet_test_depth", "per-ident subject").set(2)
    reg.histogram("vmt_fleet_test_ms", "bucket-merge subject").observe(9.0)
    with tr.trace(TRACE_ID):
        with tr.span("local.submit"):
            pass
    proc, peer_ident = _spawn_peer(db, "once")
    try:
        assert proc.wait(timeout=60) == 0
        spine.flush({"phase": "ready"})

        health = spine.health()
        idents = {p["ident"] for p in health["processes"]}
        assert {spine.identity.ident, peer_ident} <= idents
        assert health["fleet_ready"] and health["alive"] == 2

        text = spine.render_prometheus()
        # Counters: summed across identities into ONE sample.
        assert "vmt_fleet_test_total 8" in text
        # Gauges: one line per identity, instance label tells them apart.
        assert f'vmt_fleet_test_depth{{instance="{spine.identity.ident}"}} 2' \
            in text
        assert f'vmt_fleet_test_depth{{instance="{peer_ident}"}} 7' in text
        # Histograms: bucket-merged — both observations in one _count.
        assert "vmt_fleet_test_ms_count 2" in text
        assert 'vmt_fleet_test_ms_bucket{le="+Inf"} 2' in text

        # ONE stitched timeline: spans recorded in different processes,
        # correlated by trace_id, one Chrome-trace pid per process.
        trace = spine.chrome_trace(TRACE_ID)
        events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert {e["name"] for e in events} == {"local.submit", "peer.work"}
        assert len({e["pid"] for e in events}) == 2
        assert {e["args"]["ident"] for e in events} == \
            {spine.identity.ident, peer_ident}
        pnames = [e["args"]["name"] for e in trace["traceEvents"]
                  if e.get("name") == "process_name"]
        assert any(peer_ident in n for n in pnames)
    finally:
        proc.kill()
        proc.wait()


def test_sigkilled_peer_evicted_after_heartbeat_staleness(tmp_path):
    db = str(tmp_path / "fleet.sqlite3")
    spine, reg, _, _ = _spine(db, "serve", stale_s=0.5)
    reg.counter("vmt_fleet_test_total", "cross-process sum subject").inc(3)
    proc, peer_ident = _spawn_peer(db, "linger")
    try:
        spine.flush({"phase": "ready"})
        assert peer_ident in {p["ident"] for p in spine.peers()}
        assert "vmt_fleet_test_total 8" in spine.render_prometheus()

        os.kill(proc.pid, signal.SIGKILL)  # no retire(), no goodbye
        proc.wait(timeout=30)
        time.sleep(0.7)  # > heartbeat_stale_s with no fresh heartbeat
        spine.flush({"phase": "ready"})  # keep OUR heartbeat live

        health = spine.health()
        assert health["alive"] == 1 and health["stale"] == 1
        stale = {p["ident"]: p for p in health["processes"]}[peer_ident]
        assert stale["alive"] is False
        # Evicted from the merged exposition: only the live counter shows.
        assert "vmt_fleet_test_total 3" in spine.render_prometheus()
        assert peer_ident not in spine.live_idents()

        # Span-retention asymmetry: eviction withdraws the peer from the
        # health/metrics merges ONLY. Its spans still stitch into the
        # fleet timeline, and its tail-kept trace is still readable from
        # the survivor — the crash autopsy the store exists for.
        events = [e for e in spine.chrome_trace(TRACE_ID)["traceEvents"]
                  if e.get("ph") == "X"]
        assert "peer.work" in {e["name"] for e in events}
        assert peer_ident in {e["args"]["ident"] for e in events}
        survivor = TraceStore(db, spine.identity.ident)
        rows = survivor.list(verdict="slow", task="vqa", scope="fleet")
        assert TRACE_ID in {r["trace_id"] for r in rows}
        stored = survivor.get(TRACE_ID)
        assert stored["ident"] == peer_ident
        assert stored["cost"]["total_ms"] == 250.0
        assert "peer.work" in {s["name"] for s in stored["spans"]}
        # scope=local on the survivor excludes the dead peer's rows —
        # the asymmetry is an explicit choice, not a missed filter.
        assert TRACE_ID not in {
            r["trace_id"] for r in survivor.list(scope="local")}
    finally:
        proc.kill()
        proc.wait()


def test_fleet_flush_errors_instrument_registered():
    # The sampler ride-along counts failed spine flushes here; the serve
    # app and the fleet-scope HTTP handlers share the one instrument.
    c = obs.REGISTRY.counter("vmt_fleet_flush_errors_total")
    assert c.kind == "counter"


# ------------------------------------------------------------- perf ledger
def test_ledger_append_read_and_direction(tmp_path):
    path = str(tmp_path / "PERF_LEDGER.jsonl")
    entry = append_entry("bench.p50_latency_ms", {"value": 120.0,
                                                 "p95_ms": 180.0},
                         path=path, config_fingerprint="abc123")
    assert entry["metric"] == "bench.p50_latency_ms"
    assert entry["config_fingerprint"] == "abc123"
    got = read_entries(path)
    assert len(got) == 1 and got[0]["p95_ms"] == 180.0
    assert key_direction("p95_ms") == "lower"
    assert key_direction("batch_qps") == "higher"
    assert key_direction("knee_rows") == "higher"
    # "_per_s" ends with "_s" too: rates must gate as throughput, not
    # latency (a faster txn.stress run is not a regression).
    assert key_direction("claims_per_s") == "higher"
    assert key_direction("wall_s") == "lower"
    assert key_direction("git_rev") is None  # meta, never gated


def test_ledger_check_verdicts(tmp_path):
    path = str(tmp_path / "PERF_LEDGER.jsonl")
    assert check(path)["verdict"] == "empty"
    append_entry("m", {"value": 100.0}, path=path)
    assert check(path)["verdict"] == "no-baseline"
    for v in (101.0, 99.0, 100.0):
        append_entry("m", {"value": v}, path=path)
    assert check(path)["verdict"] == "pass"
    # A 40% throughput drop against a ~100 baseline: regress.
    append_entry("m", {"value": 60.0}, path=path)
    result = check(path)
    assert result["verdict"] == "regress"
    assert result["regressions"][0]["key"] == "value"
    # Half-written garbage lines are skipped, never fatal.
    with open(path, "a") as f:
        f.write('{"metric": "m", "val\n')
    assert check(path)["verdict"] == "regress"


def test_ledger_check_absolute_noise_floor_on_time_keys(tmp_path):
    # Relative tolerance is meaningless near zero: a dryrun boot_s
    # wobbling 31 ms -> 40 ms is +29% and pure scheduler noise. Time
    # keys need an absolute floor too; a real 10x regression still gates.
    path = str(tmp_path / "PERF_LEDGER.jsonl")
    for v in (0.031, 0.030, 0.032):
        append_entry("m2", {"boot_s": v}, path=path)
    append_entry("m2", {"boot_s": 0.040}, path=path)
    assert check(path)["verdict"] == "pass"
    append_entry("m2", {"boot_s": 0.40}, path=path)
    assert check(path)["verdict"] == "regress"


def test_ledger_cli_exit_codes(tmp_path):
    path = str(tmp_path / "PERF_LEDGER.jsonl")
    cli = os.path.join(REPO, "scripts", "perf_ledger.py")

    def run(*args):
        return subprocess.run([sys.executable, cli, "--path", path, *args],
                              capture_output=True, text=True, cwd=REPO)

    assert run("check").returncode == 2  # empty, not tolerated
    assert run("check", "--tolerate-empty").returncode == 0
    for v in ("12.0", "11.5", "12.5", "12.1"):
        assert run("append", "soak.qps", f"value={v}").returncode == 0
    assert run("check").returncode == 0
    assert run("append", "soak.qps", "value=4.0").returncode == 0
    out = run("check")
    assert out.returncode == 1
    assert "REGRESS" in out.stderr
    assert json.loads(out.stdout)["verdict"] == "regress"


# -------------------------------------------- identity on the queue plane
def test_queue_claim_rows_carry_claimed_by(tmp_path):
    from vilbert_multitask_tpu.serve.queue import (
        DurableQueue,
        make_job_message,
    )

    q = DurableQueue(str(tmp_path / "q.sqlite3"))
    q.publish(make_job_message(["a.jpg"], "what is this", 1, "sock"))
    me = mint_identity(role="worker")
    job = q.claim(claimed_by=me.ident)
    assert job is not None
    claims = q.inflight_claims()
    assert [c["claimed_by"] for c in claims] == [me.ident]
    q.ack(job.id)
    assert q.inflight_claims() == []
