"""Dataflow-tier vmtlint suite: the CFG builder, the worklist solver, and
the flow-sensitive rules built on them (VMT119/120/121/122).

CFG semantics are asserted through the lock-set domain rather than block
topology — "the lock is released by the time this statement runs" is the
contract the rules depend on, and it survives builder refactors that
shuffle block boundaries.  Rule tests follow the repo's fixture
convention: every rule proves it fires on the minimal hazard AND stays
quiet on the correct twin.
"""

import ast
import json
import os
import subprocess
import textwrap
import time

import pytest

from vilbert_multitask_tpu.analysis import analyze_project
from vilbert_multitask_tpu.analysis.cfg import build_cfg
from vilbert_multitask_tpu.analysis.cli import main as cli_main
from vilbert_multitask_tpu.analysis.dataflow import (
    LockSetAnalysis, ReachingDefs, iter_event_facts, solve)
from vilbert_multitask_tpu.analysis.graph import import_closure

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LOCK_NAMES = ("a", "b", "c")


def _lock_facts(src):
    """{assigned name: lock-set before the assignment} for a function whose
    locks are the bare names a/b/c.  The single probe the CFG tests use:
    `x = 1` observes which locks are definitely held where it executes."""
    fn = ast.parse(textwrap.dedent(src)).body[-1]
    cfg = build_cfg(fn)

    def resolver(expr):
        if isinstance(expr, ast.Name) and expr.id in _LOCK_NAMES:
            return expr.id
        return None

    analysis = LockSetAnalysis(resolver)
    in_facts = solve(cfg, analysis)
    out = {}
    for event, fact in iter_event_facts(cfg, analysis, in_facts):
        if isinstance(event, ast.Assign) and isinstance(
                event.targets[0], ast.Name):
            name = event.targets[0].id
            out[name] = fact if name not in out else (out[name] & fact)
    return cfg, analysis, in_facts, out


# ------------------------------------------------------------- CFG builder
def test_with_scope_releases_on_exit():
    _, _, _, facts = _lock_facts("""
    def f():
        with a:
            inside = 1
        after = 2
    """)
    assert facts["inside"] == frozenset({"a"})
    assert facts["after"] == frozenset()


def test_branch_join_is_must_intersection():
    # One arm takes only `a`, the other `a` then `b`: after the join, only
    # `a` is *definitely* held.
    _, _, _, facts = _lock_facts("""
    def f(cond):
        if cond:
            a.acquire()
        else:
            a.acquire()
            b.acquire()
        merged = 1
    """)
    assert facts["merged"] == frozenset({"a"})


def test_branch_with_one_armed_acquire():
    _, _, _, facts = _lock_facts("""
    def f(cond):
        if cond:
            with a:
                held = 1
        after = 2
    """)
    assert facts["held"] == frozenset({"a"})
    assert facts["after"] == frozenset()


def test_early_return_unwinds_with_frames():
    # Both the return path and the fall-through path must reach the exit
    # with the lock released — the builder emits the unwinding WithExit
    # markers before the jump edge.
    cfg, analysis, in_facts, facts = _lock_facts("""
    def f(cond):
        with a:
            if cond:
                return 1
            kept = 1
        after = 2
    """)
    assert facts["kept"] == frozenset({"a"})
    assert facts["after"] == frozenset()
    assert in_facts[cfg.exit.id] == frozenset()


def test_break_unwinds_to_loop_depth():
    _, _, _, facts = _lock_facts("""
    def f(items, cond):
        for it in items:
            with a:
                if cond:
                    break
                inside = 1
        after = 2
    """)
    assert facts["inside"] == frozenset({"a"})
    assert facts["after"] == frozenset()


def test_loop_keeps_outer_lock_held():
    _, _, _, facts = _lock_facts("""
    def f(items):
        a.acquire()
        for it in items:
            body = 1
        end = 1
        a.release()
    """)
    assert facts["body"] == frozenset({"a"})
    assert facts["end"] == frozenset({"a"})


def test_try_finally_runs_with_lock_then_releases():
    _, _, _, facts = _lock_facts("""
    def f():
        with a:
            try:
                risky = 1
            finally:
                fin = 1
        after = 2
    """)
    assert facts["risky"] == frozenset({"a"})
    assert facts["fin"] == frozenset({"a"})
    assert facts["after"] == frozenset()


def test_except_handler_joins_boundary_states():
    # The exception may fire before OR after the acquire, so the handler
    # must-set is the intersection: nothing is definitely held there.
    _, _, _, facts = _lock_facts("""
    def f(risky):
        try:
            a.acquire()
            mid = 1
        except Exception:
            handler = 1
        a.release()
    """)
    assert facts["mid"] == frozenset({"a"})
    assert facts["handler"] == frozenset()


def test_while_true_has_no_false_edge():
    # `while True` only exits via break; code after the loop sees the
    # break-path state, not a phantom fall-through from the header.
    _, _, _, facts = _lock_facts("""
    def f(cond):
        a.acquire()
        while True:
            if cond:
                a.release()
                break
        after = 1
    """)
    assert facts["after"] == frozenset()


# ---------------------------------------------------------------- solver
def test_conditional_acquire_loop_converges():
    # The classic lattice stress: a loop that acquires on one path and
    # releases on another.  The worklist must reach a fixed point (this
    # test hanging IS the failure mode) and the must-set degrades to empty
    # rather than oscillating.
    cfg, analysis, in_facts, facts = _lock_facts("""
    def f(items, cond):
        for it in items:
            if cond:
                a.acquire()
            else:
                a.release()
            probe = 1
        done = 1
    """)
    assert facts["probe"] == frozenset()
    assert facts["done"] == frozenset()


def test_reaching_defs_kills_and_joins():
    fn = ast.parse(textwrap.dedent("""
    def f(cond):
        x = 1
        if cond:
            x = 2
        y = x
    """)).body[0]
    cfg = build_cfg(fn)
    analysis = ReachingDefs(frozenset({"x"}), params_line=fn.lineno)
    in_facts = solve(cfg, analysis)
    at_y = None
    for event, fact in iter_event_facts(cfg, analysis, in_facts):
        if isinstance(event, ast.Assign) and isinstance(
                event.targets[0], ast.Name) and event.targets[0].id == "y":
            at_y = fact
    # The entry placeholder is killed by `x = 1`; both real definitions
    # reach the read.
    lines = sorted(line for name, line in at_y)
    assert lines == [3, 5]


# ----------------------------------------------------------------- VMT119
ABBA = {
    "pkg/shared.py": """
    import threading
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    """,
    "pkg/one.py": """
    from pkg.shared import lock_a, lock_b

    def ab():
        with lock_a:
            with lock_b:
                return 1
    """,
    "pkg/two.py": """
    from pkg.shared import lock_a, lock_b

    def ba():
        with lock_b:
            with lock_a:
                return 2
    """,
}


def _findings(sources):
    return analyze_project(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        library_roots=("pkg", "vilbert_multitask_tpu"))


def test_vmt119_cross_module_abba_with_both_witness_chains():
    hits = [f for f in _findings(ABBA) if f.rule == "VMT119"]
    assert len(hits) == 1
    f = hits[0]
    # BOTH conflicting orders must be reported as witness chains.
    assert len(f.flows) == 2
    chain_paths = {step["path"] for chain in f.flows for step in chain}
    assert {"pkg/one.py", "pkg/two.py"} <= chain_paths
    assert all("line" in step and "message" in step
               for chain in f.flows for step in chain)
    assert "lock-order inversion" in f.message
    assert "deadlock" in f.message


def test_vmt119_same_order_everywhere_is_clean():
    clean = dict(ABBA)
    clean["pkg/two.py"] = """
    from pkg.shared import lock_a, lock_b

    def also_ab():
        with lock_a:
            with lock_b:
                return 2
    """
    assert not [f for f in _findings(clean) if f.rule == "VMT119"]


def test_vmt119_one_way_class_lock_pair_is_clean():
    # The engine/runtime.py shape in miniature: _fallback may be held when
    # taking _compile, never the reverse.  Acyclic → silent.
    src = {
        "pkg/eng.py": """
        import threading

        class Eng:
            def __init__(self):
                self._fallback = threading.Lock()
                self._compile = threading.Lock()

            def dispatch(self):
                with self._fallback:
                    with self._compile:
                        return 1

            def warm(self):
                with self._compile:
                    return 2
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT119"]


def test_vmt119_composed_through_call_chain():
    # The inversion only exists through a call: taker holds A and calls a
    # helper that takes B, while another function orders them B then A.
    src = {
        "pkg/mod.py": """
        import threading
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def helper():
            with lock_b:
                return 1

        def holds_a():
            with lock_a:
                return helper()

        def other():
            with lock_b:
                with lock_a:
                    return 2
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT119"]
    assert len(hits) == 1
    assert len(hits[0].flows) == 2
    # The composed chain walks through the helper call.
    joined = " ".join(step["message"]
                      for chain in hits[0].flows for step in chain)
    assert "helper" in joined


def test_vmt119_regression_real_engine_runtime_not_flagged():
    # Ground truth: engine/runtime.py's _fallback_lock → _compile_lock
    # ordering is one-way by design.  The detector must stay silent on it.
    path = os.path.join(REPO, "vilbert_multitask_tpu", "engine",
                        "runtime.py")
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    findings = analyze_project(
        {"vilbert_multitask_tpu/engine/runtime.py": src})
    assert not [f for f in findings if f.rule == "VMT119"]


# ----------------------------------------------------------------- VMT120
def test_vmt120_wait_holding_foreign_lock_fires():
    src = {
        "pkg/w.py": """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()

            def bad(self):
                with self._lock:
                    with self._cond:
                        self._cond.wait()
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT120"]
    assert len(hits) == 1
    assert "W._lock" in hits[0].message


def test_vmt120_wait_under_own_condition_is_clean():
    src = {
        "pkg/w.py": """
        import threading

        class W:
            def __init__(self):
                self._cond = threading.Condition()

            def fine(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait()
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT120"]


def test_vmt120_composed_wait_through_helper_call():
    # The pool.rolling_swap shape: the caller holds a lock across a call
    # to a helper that blocks on a condition wait.
    src = {
        "pkg/p.py": """
        import threading

        class P:
            def __init__(self):
                self._swap = threading.Lock()
                self._cond = threading.Condition()

            def _wait_ready(self):
                with self._cond:
                    self._cond.wait()

            def swap(self):
                with self._swap:
                    self._wait_ready()
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT120"]
    assert len(hits) == 1
    assert "P._swap" in hits[0].message
    assert "_wait_ready" in hits[0].message


def test_vmt120_queue_get_nonblocking_is_clean():
    src = {
        "pkg/q.py": """
        import threading
        import queue

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def drain(self):
                with self._lock:
                    return self._q.get(block=False)
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT120"]


# ----------------------------------------------------------------- VMT121
def test_vmt121_captured_local_rebound_across_jit_calls():
    src = {
        "pkg/j.py": """
        import jax

        def run(xs):
            scale = 1.0
            f = jax.jit(lambda x: x * scale)
            out = []
            for x in xs:
                out.append(f(x))
                scale = scale + 1.0
            return out
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT121"]
    assert len(hits) == 1
    assert "scale" in hits[0].message
    assert "stale" in hits[0].message


def test_vmt121_single_definition_capture_is_clean():
    src = {
        "pkg/j.py": """
        import jax

        def run(xs):
            scale = 1.0
            f = jax.jit(lambda x: x * scale)
            return [f(x) for x in xs]
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT121"]


def test_vmt121_traced_self_read_rebound_elsewhere():
    src = {
        "pkg/m.py": """
        import jax

        class M:
            def __init__(self):
                self.temperature = 1.0

            def set_temperature(self, t):
                self.temperature = t

            @jax.jit
            def forward(self, x):
                return x / self.temperature
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT121"]
    assert len(hits) == 1
    assert "temperature" in hits[0].message
    assert "set_temperature" in hits[0].message


def test_vmt121_init_only_self_state_is_clean():
    src = {
        "pkg/m.py": """
        import jax

        class M:
            def __init__(self):
                self.scale = 2.0

            @jax.jit
            def forward(self, x):
                return x * self.scale
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT121"]


# ----------------------------------------------------------------- VMT122
KNOBS = {
    "pkg/config.py": """
    class ServingConfig:
        knob_used: int = 1
        knob_dead: int = 2
    """,
    "pkg/app.py": """
    def go(cfg):
        s = cfg.serving
        return s.knob_used
    """,
}


def test_vmt122_dead_knob_flagged_at_declaration():
    hits = [f for f in _findings(KNOBS) if f.rule == "VMT122"]
    assert len(hits) == 1
    assert hits[0].path == "pkg/config.py"
    assert "knob_dead" in hits[0].message


def test_vmt122_typo_read_flagged_with_suggestion():
    src = dict(KNOBS)
    src["pkg/app.py"] = """
    def go(cfg):
        s = cfg.serving
        return s.knob_used + s.knob_usedd + s.knob_dead
    """
    hits = [f for f in _findings(src) if f.rule == "VMT122"]
    assert len(hits) == 1
    assert hits[0].path == "pkg/app.py"
    assert "knob_usedd" in hits[0].message
    assert "knob_used" in hits[0].message  # did-you-mean suggestion


def test_vmt122_all_knobs_read_is_clean():
    src = dict(KNOBS)
    src["pkg/app.py"] = """
    def go(cfg):
        s = cfg.serving
        return s.knob_used + s.knob_dead
    """
    assert not [f for f in _findings(src) if f.rule == "VMT122"]


def test_vmt122_reads_through_annotated_param_and_getattr():
    src = {
        "pkg/config.py": """
        class EngineConfig:
            rows: int = 4
            opt_flag: bool = False
        """,
        "pkg/use.py": """
        from pkg.config import EngineConfig

        def plan(ecfg: EngineConfig):
            return ecfg.rows + int(getattr(ecfg, "opt_flag", 0))
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT122"]


# ----------------------------------------------------------------- VMT123
def test_vmt123_dead_instrument_flagged_at_registration():
    src = {
        "pkg/metrics.py": """
        ALIVE = REGISTRY.counter("vmt_alive_total", "incremented below")
        DEAD = REGISTRY.gauge("vmt_dead_gauge", "never touched again")

        def tick():
            ALIVE.inc()
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT123"]
    assert len(hits) == 1
    assert hits[0].path == "pkg/metrics.py"
    assert "vmt_dead_gauge" in hits[0].message


def test_vmt123_typo_read_flagged_with_suggestion():
    src = {
        "pkg/metrics.py": """
        JOBS = REGISTRY.counter("vmt_jobs_total", "jobs")

        def tick():
            JOBS.inc()
        """,
        # vmtlint: disable-next-line=VMT123  (the typo under test, verbatim)
        "pkg/read.py": """
        def snapshot(snap):
            return snap.get("vmt_job_total", 0)
        """,
    }
    hits = [f for f in _findings(src) if f.rule == "VMT123"]
    assert len(hits) == 1
    assert hits[0].path == "pkg/read.py"
    assert "vmt_job_total" in hits[0].message  # vmtlint: disable=VMT123
    assert "vmt_jobs_total" in hits[0].message  # did-you-mean suggestion


def test_vmt123_exposition_suffixes_and_derived_rates_are_reads():
    # _bucket/_sum/_count normalize to the histogram; the Sampler's
    # derived *_per_s key normalizes to its *_total counter — and a
    # name-string reference anywhere counts as keeping it alive.
    src = {
        "pkg/metrics.py": """
        REGISTRY.histogram("vmt_lat_ms", "latency")
        REGISTRY.counter("vmt_jobs_total", "jobs")
        """,
        "pkg/read.py": """
        def asserts(text, series):
            assert "vmt_lat_ms_bucket{" in text
            assert "vmt_lat_ms_count" in text
            return series["vmt_jobs_per_s"]
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT123"]


def test_vmt123_chained_registration_and_foreign_strings_are_clean():
    src = {
        "pkg/metrics.py": """
        import tempfile

        def hit():
            REGISTRY.counter("vmt_hits_total", "get-or-create idiom").inc()
            # Foreign vmt_ strings (paths, native symbols) are not reads.
            return tempfile.mkdtemp(prefix="vmt_demo_scratch")
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT123"]


def test_vmt123_cross_module_handle_use_marks_alive():
    src = {
        "pkg/metrics.py": """
        SHED = REGISTRY.counter("vmt_shed_jobs_total", "sheds")
        """,
        "pkg/worker.py": """
        from pkg.metrics import SHED

        def drop():
            SHED.inc()
        """,
    }
    assert not [f for f in _findings(src) if f.rule == "VMT123"]


# -------------------------------------------------------- --changed mode
def test_import_closure_reverse_and_forward():
    sources = {
        "pkg/shared.py": "X = 1\n",
        "pkg/leaf.py": "from pkg.shared import X\n",
        "pkg/importer.py": "import pkg.leaf\n",
        "pkg/unrelated.py": "Y = 2\n",
    }
    closure = import_closure(sources, {"pkg/leaf.py"})
    assert closure == {"pkg/shared.py", "pkg/leaf.py", "pkg/importer.py"}


def _scratch_repo(root):
    """A git repo with one cross-module ABBA inversion and enough filler
    modules that the changed-closure scan is measurably cheaper than the
    full scan."""
    os.makedirs(os.path.join(root, "pkg"))
    with open(os.path.join(root, "pyproject.toml"), "w") as fh:
        fh.write('[tool.vmtlint]\npaths = ["pkg"]\n'
                 'library_roots = ["pkg"]\n')
    open(os.path.join(root, "pkg", "__init__.py"), "w").close()
    filler = textwrap.dedent("""
        import threading

        class Box{i}:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def add(self, x):
                with self._lock:
                    self.items.append(x)

            def snapshot(self):
                with self._lock:
                    return list(self.items)

        def helper_{i}(n):
            box = Box{i}()
            for k in range(n):
                box.add(k * {i})
            return box.snapshot()
        """)
    for i in range(40):
        with open(os.path.join(root, "pkg", f"filler{i:02d}.py"),
                  "w") as fh:
            fh.write(filler.format(i=i))
    with open(os.path.join(root, "pkg", "leaf.py"), "w") as fh:
        fh.write(textwrap.dedent("""
            import threading
            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        return 1
            """))
    with open(os.path.join(root, "pkg", "importer.py"), "w") as fh:
        fh.write(textwrap.dedent("""
            from pkg.leaf import lock_a, lock_b

            def ba():
                with lock_b:
                    with lock_a:
                        return 2
            """))

    def git(*argv):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *argv],
            cwd=root, check=True, capture_output=True)

    git("init", "-q")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    # The single-file diff: touch leaf.py.
    with open(os.path.join(root, "pkg", "leaf.py"), "a") as fh:
        fh.write("\nTOUCHED = True\n")


def test_changed_scan_parity_and_speed(tmp_path, monkeypatch, capsys):
    _scratch_repo(str(tmp_path))
    monkeypatch.chdir(tmp_path)

    t0 = time.perf_counter()
    cli_main(["--format", "json"])
    t_full = time.perf_counter() - t0
    full = json.loads(capsys.readouterr().out)

    t0 = time.perf_counter()
    cli_main(["--format", "json", "--changed"])
    t_changed = time.perf_counter() - t0
    changed = json.loads(capsys.readouterr().out)

    # The closure of a leaf.py diff is leaf + its importer (+ __init__),
    # not the 40 filler modules.
    assert changed["files_scanned"] < 6
    assert full["files_scanned"] >= 42

    # Identical findings for the changed closure: the ABBA inversion (and
    # anything else in those files) must survive the subset scan exactly.
    closure_paths = {"pkg/leaf.py", "pkg/importer.py"}

    def key(f):
        return (f["rule"], f["path"], f["line"], f["message"])

    full_in_closure = sorted(
        key(f) for f in full["findings"] if f["path"] in closure_paths)
    changed_in_closure = sorted(
        key(f) for f in changed["findings"] if f["path"] in closure_paths)
    assert full_in_closure == changed_in_closure
    assert any(f["rule"] == "VMT119" for f in changed["findings"])

    # Acceptance bar: the subset scan finishes in <25% of the full-scan
    # wall time on a single-file diff.
    assert t_changed < 0.25 * t_full, (t_changed, t_full)


def test_changed_scan_falls_back_when_closure_is_large(tmp_path,
                                                       monkeypatch,
                                                       capsys):
    _scratch_repo(str(tmp_path))
    # Touch a module every filler imports → closure exceeds half the
    # project → the CLI must fall back to a full scan rather than scan a
    # misleading majority-subset.
    with open(os.path.join(str(tmp_path), "pkg", "core.py"), "w") as fh:
        fh.write("SHARED = 1\n")
    for i in range(40):
        path = os.path.join(str(tmp_path), "pkg", f"filler{i:02d}.py")
        with open(path, "a") as fh:
            fh.write("\nfrom pkg.core import SHARED\n")
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    "add", "-A"], cwd=str(tmp_path), check=True,
                   capture_output=True)
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    "commit", "-q", "-m", "wire core"], cwd=str(tmp_path),
                   check=True, capture_output=True)
    with open(os.path.join(str(tmp_path), "pkg", "core.py"), "a") as fh:
        fh.write("MORE = 2\n")
    monkeypatch.chdir(tmp_path)
    cli_main(["--format", "json", "--changed"])
    out = capsys.readouterr()
    data = json.loads(out.out)
    assert data["files_scanned"] >= 42  # full scan, not the subset


# ------------------------------------------------------------------ SARIF
def test_sarif_emits_both_witness_chains_as_codeflows():
    from vilbert_multitask_tpu.analysis.report import render_sarif

    hits = [f for f in _findings(ABBA) if f.rule == "VMT119"]
    doc = json.loads(render_sarif(hits, [], [], files_scanned=3))
    results = doc["runs"][0]["results"]
    assert len(results) == 1
    flows = results[0]["codeFlows"]
    assert len(flows) == 2
    for flow in flows:
        locs = flow["threadFlows"][0]["locations"]
        assert locs, "each witness chain must carry at least one step"
        for loc in locs:
            phys = loc["location"]["physicalLocation"]
            assert phys["artifactLocation"]["uri"].startswith("pkg/")
            assert phys["region"]["startLine"] >= 1
            assert loc["location"]["message"]["text"]
