"""obs/ unit tests: tracer semantics (nesting, cross-thread resumption,
ring eviction), the shared percentile, Prometheus exposition format, the
Chrome-trace schema, and the disabled-mode overhead guard."""

import json
import threading
import time

import pytest

from vilbert_multitask_tpu.obs import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    Tracer,
    chrome_trace,
    log_buckets,
    new_trace_id,
    percentile,
    render_prometheus,
)


# ------------------------------------------------------------------ tracer
def test_span_nesting_and_parenting():
    tr = Tracer()
    with tr.span("outer", task_id=4) as outer:
        with tr.span("inner"):
            pass
        with tr.span("inner2"):
            pass
    spans = {s.name: s for s in tr.spans()}
    assert set(spans) == {"outer", "inner", "inner2"}
    assert spans["outer"].parent_id is None
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["inner2"].parent_id == spans["outer"].span_id
    # all three share the root's minted trace id
    assert {s.trace_id for s in spans.values()} == {spans["outer"].trace_id}
    assert spans["outer"].attrs == {"task_id": 4}
    assert spans["inner"].dur_s <= spans["outer"].dur_s


def test_sibling_roots_get_distinct_traces():
    tr = Tracer()
    with tr.span("a"):
        pass
    with tr.span("b"):
        pass
    a, b = tr.spans()
    assert a.trace_id != b.trace_id


def test_error_annotation():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("bad input")
    (s,) = tr.spans()
    assert s.attrs["error"] == "ValueError: bad input"


def test_cross_thread_trace_resumption():
    """The serve contract in miniature: a trace id minted on the 'HTTP'
    thread rides in a fake queue job body and is re-entered by a 'worker'
    thread — every span lands in ONE trace."""
    tr = Tracer()
    fake_queue = []

    trace_id = new_trace_id()
    with tr.trace(trace_id):
        with tr.span("http.submit"):
            fake_queue.append({"task_id": "1", "trace_id": trace_id})

    def worker():
        job = fake_queue.pop()
        with tr.trace(job["trace_id"]):
            with tr.span("worker.job"):
                with tr.span("engine.forward"):
                    pass

    t = threading.Thread(target=worker, name="worker-0")
    t.start()
    t.join()

    spans = {s.name: s for s in tr.spans()}
    assert set(spans) == {"http.submit", "worker.job", "engine.forward"}
    assert {s.trace_id for s in spans.values()} == {trace_id}
    # resumption adopts the id but not a cross-thread parent: the worker's
    # root is a root
    assert spans["worker.job"].parent_id is None
    assert spans["engine.forward"].parent_id == spans["worker.job"].span_id
    # and the scope is restored after exit
    assert tr.current_trace_id() is None


def test_ring_eviction_under_concurrent_writers():
    tr = Tracer(max_spans=64)
    n_threads, per_thread = 4, 100

    def writer(k):
        for i in range(per_thread):
            with tr.span(f"w{k}.{i}"):
                pass

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.spans()
    assert len(spans) == 64  # bounded, newest kept
    assert tr.spans(limit=10) == spans[-10:]


def test_record_span_joins_given_trace():
    tr = Tracer()
    tr.record_span("worker.claim", 1.0, 0.25, trace_id="abc123", job_id=7)
    (s,) = tr.spans()
    assert (s.trace_id, s.dur_s, s.attrs["job_id"]) == ("abc123", 0.25, 7)


def test_disabled_mode_overhead_under_5us():
    """Tier-1 guard: instrumentation stays on prod paths because disabling
    the tracer makes span() effectively free."""
    tr = Tracer(enabled=False)
    n = 10_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("hot", task_id=1):
                pass
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 5e-6, f"disabled span() costs {best * 1e6:.2f} us"
    assert tr.spans() == []


def test_observer_sees_spans_and_cannot_break_recording():
    tr = Tracer()
    seen = []
    tr.set_observer(lambda s: (seen.append(s.name),
                               1 / 0))  # observer raises every time
    with tr.span("a"):
        pass
    assert seen == ["a"]
    assert [s.name for s in tr.spans()] == ["a"]  # recording survived


# -------------------------------------------------------------- percentile
def test_percentile_linear_interpolation():
    assert percentile([], 0.5) is None
    assert percentile([7.0], 0.9) == 7.0
    # THE satellite bug: nearest-rank int(p*n) gave p50([1,2]) == 2
    assert percentile([1.0, 2.0], 0.5) == 1.5
    xs = list(range(1, 101))  # 1..100
    assert percentile(xs, 0.0) == 1
    assert percentile(xs, 1.0) == 100
    assert percentile(xs, 0.5) == 50.5
    assert abs(percentile(xs, 0.95) - 95.05) < 1e-9
    # order-independent
    assert percentile(list(reversed(xs)), 0.5) == 50.5


def test_metrics_snapshot_uses_shared_percentile():
    from vilbert_multitask_tpu.serve.metrics import Metrics

    m = Metrics()
    m.record(1, 1.0)
    m.record(1, 2.0)
    snap = m.snapshot()
    assert snap["latency_ms"]["p50"] == 1.5  # was 2.0 pre-fix
    assert snap["by_task"] == {"1": 2}
    m.record_failure(3)
    assert m.snapshot()["failures"] == {"3": 1}


# ------------------------------------------------------------- instruments
def test_counter_gauge_labels():
    c = Counter("jobs_total", labelnames=("state",))
    c.inc(state="ok")
    c.inc(2, state="ok")
    c.inc(state="err")
    assert c.value(state="ok") == 3.0
    g = Gauge("depth")
    g.set(7)
    assert g.value() == 7.0
    with pytest.raises(ValueError):
        c.inc(wrong_label="x")


def test_histogram_buckets_and_reservoir():
    h = Histogram("lat_ms", buckets=(1.0, 10.0, 100.0), reservoir=4)
    for v in (0.5, 5.0, 50.0, 500.0, 5000.0):
        h.observe(v)
    (series,) = h.collect().values()
    # cumulative counts per bound, +Inf last and equal to the total
    assert [c for _, c in series["buckets"]] == [1, 2, 3, 5]
    assert series["count"] == 5
    assert series["sum"] == pytest.approx(5555.5)
    # reservoir is bounded and keeps the newest
    assert h.samples() == [5.0, 50.0, 500.0, 5000.0]
    # boundary semantics match Prometheus le (inclusive upper bound)
    h2 = Histogram("edge", buckets=(1.0, 10.0))
    h2.observe(1.0)
    (s2,) = h2.collect().values()
    assert [c for _, c in s2["buckets"]] == [1, 1, 1]


def test_log_buckets_shape():
    bs = log_buckets()
    assert bs[0] == pytest.approx(0.1)
    assert bs[-1] >= 60_000.0
    assert all(b2 > b1 for b1, b2 in zip(bs, bs[1:]))


def test_registry_type_conflicts():
    reg = Registry()
    c = reg.counter("x_total")
    assert reg.counter("x_total") is c
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labelnames=("a",))


# -------------------------------------------------------------- prometheus
def test_prometheus_exposition_format():
    reg = Registry()
    reg.counter("vmt_jobs_total", "Jobs.", labelnames=("state",)).inc(
        3, state="ok")
    reg.gauge("vmt_depth", "Depth.").set(2)
    h = reg.histogram("vmt_lat_ms", "Latency.", labelnames=("task",),
                      buckets=(1.0, 10.0))
    h.observe(0.5, task="1")
    h.observe(100.0, task="1")
    text = render_prometheus(reg)
    lines = text.splitlines()
    assert "# TYPE vmt_jobs_total counter" in lines
    assert "vmt_jobs_total{state=\"ok\"} 3" in lines
    assert "# TYPE vmt_depth gauge" in lines
    assert "vmt_depth 2" in lines
    assert "# TYPE vmt_lat_ms histogram" in lines
    # cumulative buckets end at +Inf == _count
    assert 'vmt_lat_ms_bucket{task="1",le="1"} 1' in lines
    assert 'vmt_lat_ms_bucket{task="1",le="10"} 1' in lines
    assert 'vmt_lat_ms_bucket{task="1",le="+Inf"} 2' in lines
    assert 'vmt_lat_ms_sum{task="1"} 100.5' in lines
    assert 'vmt_lat_ms_count{task="1"} 2' in lines
    # every non-comment line is `name{labels} value`
    for ln in lines:
        if not ln.startswith("#"):
            assert len(ln.rsplit(" ", 1)) == 2


def test_prometheus_label_escaping():
    reg = Registry()
    reg.counter("c_total", labelnames=("path",)).inc(
        path='a"b\\c\nnext')
    text = render_prometheus(reg)
    assert 'path="a\\"b\\\\c\\nnext"' in text


def test_prometheus_bucket_cumulativity_is_monotone():
    reg = Registry()
    h = reg.histogram("m_ms", buckets=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.5, 3.0, 6.0, 100.0, 0.1, 7.0):
        h.observe(v)
    (series,) = h.collect().values()
    counts = [c for _, c in series["buckets"]]
    assert counts == sorted(counts)
    assert counts[-1] == series["count"]


# ------------------------------------------------------------ chrome trace
def test_chrome_trace_schema():
    tr = Tracer()
    with tr.trace("t" * 16):
        with tr.span("worker.job", task_id=4):
            with tr.span("engine.forward", bucket=8):
                pass
    doc = chrome_trace(tracer=tr)
    # must survive a JSON round trip (what /debug/trace serves)
    doc = json.loads(json.dumps(doc))
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    ms = [e for e in events if e["ph"] == "M"]
    assert len(xs) == 2 and len(ms) == 1  # one thread -> one metadata event
    assert ms[0]["name"] == "thread_name"
    for e in xs:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["args"]["trace_id"] == "t" * 16
    fwd = next(e for e in xs if e["name"] == "engine.forward")
    job = next(e for e in xs if e["name"] == "worker.job")
    assert fwd["args"]["parent_id"] == job["args"]["span_id"]
    # child nests inside the parent on the timeline
    assert fwd["ts"] >= job["ts"]
    assert fwd["ts"] + fwd["dur"] <= job["ts"] + job["dur"] + 1e-3


def test_chrome_trace_limit():
    tr = Tracer()
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    doc = chrome_trace(tracer=tr, limit=3)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["s7", "s8", "s9"]


# ------------------------------------------------- sliding windows (SLIs)
def test_histogram_window_percentile_ages_out():
    """Windowed aggregation is the SLI substrate: old samples must leave
    the window as the (injectable) clock advances — no sleeping."""
    h = Histogram("win_ms", reservoir=64)
    now = [1000.0]
    h.clock = lambda: now[0]
    for _ in range(10):
        h.observe(100.0)           # slow burst at t=1000
    now[0] += 30.0
    for _ in range(10):
        h.observe(1.0)             # fresh fast samples at t=1030
    assert h.window_count(60.0) == 20
    assert h.window_percentile(0.95, 60.0) == pytest.approx(100.0)
    # the slow burst ages past the 60 s window; only fresh samples remain
    now[0] += 45.0
    assert h.window_count(60.0) == 10
    assert h.window_percentile(0.95, 60.0) == pytest.approx(1.0)
    assert h.count() == 20         # the lifetime view is untouched
    assert h.window_sum(60.0) == pytest.approx(10.0)


def test_timeseries_store_ring_and_window():
    from vilbert_multitask_tpu.obs import TimeSeriesStore

    ts = TimeSeriesStore(points=4)
    for i in range(8):
        ts.record("qps", float(i), ts=float(i))
    # bounded ring: only the newest `points` samples survive
    assert ts.points("qps") == [(4.0, 4.0), (5.0, 5.0),
                                (6.0, 6.0), (7.0, 7.0)]
    assert ts.latest("qps") == 7.0
    ts.record_many({"a": 1.0, "b": 2.0}, ts=9.0)
    assert ts.names() == ["a", "b", "qps"]
    assert ts.snapshot()["a"] == [(9.0, 1.0)]


def test_sampler_tick_derives_rates_from_counters():
    from vilbert_multitask_tpu.obs import Sampler, TimeSeriesStore

    store = TimeSeriesStore()
    probe = {"sheds_total": 0.0, "depth": 3.0}
    samp = Sampler(store, lambda: dict(probe), cadence_s=60.0)
    first = samp.tick()
    assert "sheds_per_s" not in first      # no previous sample yet
    probe["sheds_total"] = 30.0
    second = samp.tick()
    assert second["sheds_per_s"] > 0.0     # delta / monotonic dt
    assert "depth_per_s" not in second     # only *_total keys derive rates
    assert "sheds_per_s" in store.names()


def test_sampler_thread_lifecycle_and_probe_errors():
    from vilbert_multitask_tpu.obs import Sampler, TimeSeriesStore

    calls = []

    def probe():
        calls.append(1)
        raise RuntimeError("flaky probe")

    samp = Sampler(TimeSeriesStore(), probe, cadence_s=0.01)
    samp.start()
    samp.start()                            # idempotent
    deadline = time.monotonic() + 5.0
    while not calls and time.monotonic() < deadline:
        time.sleep(0.01)
    samp.stop()
    assert calls                            # probe ran and errors were eaten
    assert not any(t.name == "obs-sampler" for t in threading.enumerate())


# ------------------------------------------------------------ burn rates
def test_slo_page_requires_both_windows_and_decays():
    """The acceptance property: states come from SLIDING windows — a burst
    of old slow samples outside the fast window must not hold a PAGE."""
    from vilbert_multitask_tpu.obs import SloEvaluator, latency_slo

    h = Histogram("slo_fixture_ms", reservoir=256)
    now = [5000.0]
    h.clock = lambda: now[0]
    ev = SloEvaluator([latency_slo("lat", h, 100.0, error_budget=0.05)],
                      fast_window_s=60.0, slow_window_s=600.0)
    # empty windows: burn 0, never a page
    assert ev.states() == {"lat": "ok"}
    # an all-bad burst saturates BOTH windows -> page
    for _ in range(20):
        h.observe(400.0)
    assert ev.states() == {"lat": "page"}
    # 2 minutes later the burst left the fast window: min(fast, slow)
    # gates paging, so the state decays even though slow burn is still hot
    now[0] += 120.0
    (report,) = ev.evaluate()
    assert report["state"] == "ok"
    assert report["burn"]["fast"] == 0.0
    assert report["burn"]["slow"] > 0.0


def test_availability_slo_counts_failures_in_window():
    from vilbert_multitask_tpu.obs import SloEvaluator, availability_slo

    ok_h = Histogram("avail_ok_ms", reservoir=64)
    fail_h = Histogram("avail_fail", reservoir=64)
    now = [100.0]
    ok_h.clock = fail_h.clock = lambda: now[0]
    ev = SloEvaluator(
        [availability_slo("avail", ok_h, fail_h, error_budget=0.02)],
        fast_window_s=60.0, slow_window_s=600.0)
    for _ in range(8):
        ok_h.observe(5.0)
    fail_h.observe(-1.0)
    fail_h.observe(-1.0)
    (report,) = ev.evaluate()
    # 2 failures / 10 events = 20% error rate over a 2% budget: burn 10
    assert report["burn"]["fast"] == pytest.approx(10.0)
    assert report["state"] == "page"


# --------------------------------------------------------- flight recorder
def test_recorder_bundle_binds_trace_and_rotates(tmp_path):
    from vilbert_multitask_tpu import obs

    rec = obs.FlightRecorder(str(tmp_path), max_bundles=2,
                             min_interval_s=0.0,
                             sources={"timeseries": lambda: {"qps": 1},
                                      "bad": lambda: 1 / 0})
    tid = obs.new_trace_id()
    with obs.trace_scope(tid), obs.span("unit.op"):
        pass
    assert rec.trigger("fault_injected", site="worker.intake",
                       trace_id=tid)
    rec.close()
    (path,) = rec.bundles()
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["event"] == "fault_injected"
    assert bundle["detail"]["trace_id"] == tid
    assert tid in {s["trace_id"] for s in bundle["spans"]}
    assert tid in bundle["trace_ids"]
    assert bundle["timeseries"] == {"qps": 1}
    # a broken source loses its own section only, never the bundle
    assert "error" in bundle["bad"]
    # rotation: oldest bundles beyond max_bundles are removed
    rec2 = obs.FlightRecorder(str(tmp_path), max_bundles=2,
                              min_interval_s=0.0)
    for event in ("breaker_open", "drain", "worker_exception"):
        assert rec2.trigger(event)
        time.sleep(0.002)          # distinct ms -> distinct filenames
    rec2.close()
    assert len(rec2.bundles()) == 2
    assert not any(t.name == "flight-recorder"
                   for t in threading.enumerate())


def test_recorder_min_interval_rate_limits(tmp_path):
    from vilbert_multitask_tpu import obs

    rec = obs.FlightRecorder(str(tmp_path), min_interval_s=300.0)
    assert rec.trigger("breaker_open") is True
    assert rec.trigger("breaker_open") is False   # inside the interval
    assert rec.trigger("slo_page") is True        # per-event limiter
    rec.close()


def test_recorder_spike_fires_at_threshold(tmp_path):
    from vilbert_multitask_tpu import obs

    rec = obs.FlightRecorder(str(tmp_path), min_interval_s=0.0)
    fired = [rec.spike("deadline_spike", threshold=3, window_s=60.0)
             for _ in range(3)]
    assert fired == [False, False, True]
    # the window clears on fire: the count restarts
    assert rec.spike("deadline_spike", threshold=3, window_s=60.0) is False
    rec.close()


def test_record_event_routes_to_installed_recorder(tmp_path):
    from vilbert_multitask_tpu import obs

    rec = obs.install_recorder(
        obs.FlightRecorder(str(tmp_path), min_interval_s=0.0))
    try:
        assert obs.active_recorder() is rec
        assert obs.record_event("fault_injected", site="x") is True
    finally:
        obs.clear_recorder()
    assert obs.active_recorder() is None
    assert len(rec.bundles()) == 1
    # with no recorder installed the plane is inert
    assert obs.record_event("fault_injected", site="x") is False
    assert obs.record_spike("deadline_spike") is False


def test_recorder_disabled_mode_overhead_under_5us():
    """Tier-1 guard (mirrors the tracer's): trigger sites live on prod
    paths because an uninstalled recorder costs a global read + compare."""
    from vilbert_multitask_tpu import obs

    assert obs.active_recorder() is None
    n = 10_000
    best_event = best_spike = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            obs.record_event("breaker_open", breaker="b")
        best_event = min(best_event, (time.perf_counter() - t0) / n)
        t0 = time.perf_counter()
        for _ in range(n):
            obs.record_spike("deadline_spike", trace_id="t")
        best_spike = min(best_spike, (time.perf_counter() - t0) / n)
    assert best_event < 5e-6, f"record_event costs {best_event * 1e6:.2f} us"
    assert best_spike < 5e-6, f"record_spike costs {best_spike * 1e6:.2f} us"
