"""Serving-tier tests: queue durability/poison handling, store, hub, and the
submit→enqueue→infer→persist→push path end-to-end with a tiny real engine
(the service-integration strategy from SURVEY.md §4)."""

import json
import http.client
import time
import os
import queue as queue_mod

import pytest

from vilbert_multitask_tpu.serve import (
    ApiServer,
    DurableQueue,
    PushHub,
    ResultStore,
    ServeWorker,
    WebSocketBridge,
    make_job_message,
)


# fixtures (tiny_framework_cfg / features_dir / engine / stack) live in
# tests/conftest.py so the batching/eval tests share them.


# ------------------------------------------------------------------- queue
def test_queue_durability_and_ack(tmp_path):
    path = str(tmp_path / "q.sqlite3")
    q = DurableQueue(path)
    q.publish({"n": 1})
    q.publish({"n": 2})
    # durability: a fresh handle (new "process") sees the jobs
    q2 = DurableQueue(path)
    job = q2.claim()
    assert job.body == {"n": 1} and job.attempts == 1
    q2.ack(job.id)
    assert q2.counts() == {"pending": 1}


def test_queue_poison_dead_letters(tmp_path):
    q = DurableQueue(str(tmp_path / "q.sqlite3"), max_delivery_attempts=2)
    q.publish({"bad": True})
    assert q.nack(q.claim().id) == "pending"  # attempt 1 → retry
    assert q.nack(q.claim().id) == "dead"  # attempt 2 → dead-letter
    assert q.claim() is None
    assert [j.body for j in q.dead_jobs()] == [{"bad": True}]


def test_queue_crash_loop_dead_letters_at_claim(tmp_path):
    """A job whose worker dies before nack() must still dead-letter once
    attempts are exhausted (claim-side enforcement)."""
    q = DurableQueue(str(tmp_path / "q.sqlite3"), max_delivery_attempts=2,
                     visibility_timeout_s=0.0)
    q.publish({"crash": True})
    assert q.claim() is not None  # attempt 1; "worker crashes" (no ack/nack)
    assert q.claim() is not None  # attempt 2 via expired claim
    assert q.claim() is None  # attempts exhausted → dead, not redelivered
    assert [j.body for j in q.dead_jobs()] == [{"crash": True}]


def test_queue_claim_exclude_and_release(tmp_path):
    q = DurableQueue(str(tmp_path / "q.sqlite3"))
    a = q.publish({"n": "a"})
    q.publish({"n": "b"})
    job = q.claim(exclude=[a])
    assert job.body == {"n": "b"}
    q.release(job.id)  # un-claim without charging the attempt
    again = q.claim(exclude=[a])
    assert again.id == job.id and again.attempts == 1


def test_queue_visibility_timeout(tmp_path):
    q = DurableQueue(str(tmp_path / "q.sqlite3"), visibility_timeout_s=0.0)
    q.publish({"n": 1})
    first = q.claim()
    # claim expired immediately → redelivered to the "next worker"
    second = q.claim()
    assert second is not None and second.id == first.id
    assert second.attempts == 2


# ------------------------------------------------------------------- store
def test_result_store_catalog_and_qa(tmp_path):
    store = ResultStore(str(tmp_path / "r.sqlite3"))
    tasks = store.list_tasks()
    assert {t["unique_id"] for t in tasks} == {1, 2, 4, 7, 11, 12, 13, 15, 16}
    qa_id = store.create_question(1, "what is this", ["img_a.jpg"], "sock1")
    store.save_answer(qa_id, {"answers": [{"answer": "cat"}]})
    row = store.get_question(qa_id)
    assert row["answer_text"]["answers"][0]["answer"] == "cat"
    assert store.recent()[0]["id"] == qa_id


# --------------------------------------------------------------------- hub
def test_push_hub_groups():
    hub = PushHub(max_queued=2)
    q1 = hub.subscribe("s1")
    q2 = hub.subscribe("s1")
    other = hub.subscribe("s2")
    assert hub.publish("s1", {"terminal": "hi"}) == 2
    assert q1.get_nowait() == {"terminal": "hi"}
    assert q2.get_nowait() == {"terminal": "hi"}
    with pytest.raises(queue_mod.Empty):
        other.get_nowait()
    # overflow drops oldest, keeps newest
    hub.publish("s1", {"n": 1})
    hub.publish("s1", {"n": 2})
    hub.publish("s1", {"n": 3})
    assert [q1.get_nowait()["n"] for _ in range(2)] == [2, 3]
    hub.unsubscribe("s1", q1)
    assert hub.publish("s1", {"n": 4}) == 1


# ------------------------------------------------------------ worker e2e
def test_worker_end_to_end_vqa(stack):
    s, hub, q, store, worker = stack
    sub = hub.subscribe("sockA")
    q.publish(make_job_message(["img_a.jpg"], "what is this", 1, "sockA"))
    assert worker.step() == "acked"
    assert q.counts() == {}
    frames = []
    while True:
        try:
            frames.append(sub.get_nowait())
        except queue_mod.Empty:
            break
    result_frames = [f for f in frames if "result" in f]
    assert len(result_frames) == 1
    res = result_frames[0]["result"]
    assert res["task_id"] == 1 and len(res["answers"]) == 3
    row = store.recent()[0]
    assert row["answer_text"]["answers"] == res["answers"]


def test_worker_poison_job_dead_letters(stack):
    s, hub, q, store, worker = stack
    before = len(store.recent(100))
    q.publish(make_job_message(["missing_img.jpg"], "q", 1, "sockB"))
    outcomes = [worker.step() for _ in range(s.max_delivery_attempts)]
    assert outcomes[:-1] == ["requeued"] * (s.max_delivery_attempts - 1)
    assert outcomes[-1] == "dead"
    assert worker.step() is None  # not redelivered
    # redelivered attempts reuse one audit row, not one per attempt
    assert len(store.recent(100)) == before + 1


def test_worker_grounding_draws_boxes(stack, tmp_path):
    from PIL import Image

    s, hub, q, store, worker = stack
    img_path = str(tmp_path / "img_a.jpg")  # key 'img_a' hits the store
    Image.new("RGB", (100, 100), (128, 128, 128)).save(img_path)
    q.publish(make_job_message([img_path], "the left thing", 11, "sockC"))
    assert worker.step() == "acked"
    row = store.recent()[0]
    assert row["task_id"] == 11
    assert len(row["answer_images"]) == 3
    assert all(os.path.exists(p) for p in row["answer_images"])


def test_worker_nlvr2_and_retrieval(stack):
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg", "img_b.jpg"], "both same", 12,
                               "sockD"))
    q.publish(make_job_message(["img_a.jpg", "img_b.jpg"], "a caption", 7,
                               "sockD"))
    assert worker.step() == "acked"
    assert worker.step() == "acked"
    rows = store.recent(2)
    kinds = {r["task_id"]: r["answer_text"]["kind"] for r in rows}
    assert kinds == {12: "binary", 7: "ranking"}


def test_metrics_recorded_and_served(stack):
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "what", 1, "mm"))
    q.publish(make_job_message(["nope.jpg"], "bad", 1, "mm"))
    worker.step_batch()
    snap = worker.metrics.snapshot()
    assert snap["requests"] == 1 and snap["by_task"] == {"1": 1}
    assert snap["failures"] == {"1": 1}
    assert snap["latency_ms"]["p50"] is not None

    api = ApiServer(q, store, hub, s, metrics=worker.metrics)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/metrics")
        m = json.loads(conn.getresponse().read())
        assert m["requests"] == 1 and "queue" in m
    finally:
        api.stop()


# ---------------------------------------------------------------- http api
def test_http_api_roundtrip(stack):
    s, hub, q, store, worker = stack
    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/")
        root = json.loads(conn.getresponse().read())
        assert len(root["tasks"]) == 9 and root["socket_id"]

        conn.request("GET", "/get_task_details/1/")
        task = json.loads(conn.getresponse().read())
        assert task["name"] == "VQA"

        body = json.dumps({
            "task_id": 1, "socket_id": "sockH", "question": "WHAT Is This",
            "image_list": ["img_a.jpg"],
        })
        conn.request("POST", "/", body=body,
                     headers={"Content-Type": "application/json"})
        resp = json.loads(conn.getresponse().read())
        assert resp["task"] == "VQA"
        job = q.claim()
        assert job.body["question"] == "what is this"  # lowercased (views.py:27)
        q.ack(job.id)

        # image-count gating (worker.py:256-263 semantics)
        conn.request("POST", "/", body=json.dumps({
            "task_id": 12, "socket_id": "x", "question": "q",
            "image_list": ["a.jpg"],
        }), headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 400

        # multipart upload
        boundary = "XBOUND"
        part = (f"--{boundary}\r\n"
                'Content-Disposition: form-data; name="file"; '
                'filename="pic.jpg"\r\n'
                "Content-Type: image/jpeg\r\n\r\n").encode() + b"JPGDATA" + \
            f"\r\n--{boundary}--\r\n".encode()
        conn.request("POST", "/upload_image/", body=part, headers={
            "Content-Type": f"multipart/form-data; boundary={boundary}"})
        up = json.loads(conn.getresponse().read())
        assert len(up["file_paths"]) == 1
        assert open(up["file_paths"][0], "rb").read() == b"JPGDATA"

        conn.request("GET", "/healthz")
        assert json.loads(conn.getresponse().read())["ok"] is True

        # media traversal: absolute and dot-dot paths must be rejected
        os.makedirs(s.media_root, exist_ok=True)
        with open(os.path.join(s.media_root, "ok.txt"), "w") as f:
            f.write("fine")
        for bad in ("/media//etc/passwd", "/media/../../etc/passwd"):
            conn.request("GET", bad)
            assert conn.getresponse().status in (403, 404), bad
        conn.request("GET", "/media/ok.txt")
        resp = conn.getresponse()
        assert resp.status == 200 and resp.read() == b"fine"
    finally:
        api.stop()


# ----------------------------------------------------- attention retrieval
def test_attention_maps_requested_per_job(stack):
    """collect_attention in the job message → per-bridge [CLS]→regions
    summary in the result payload (reference worker.py:288 capability,
    surfaced per request instead of computed-and-dropped)."""
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "what is this", 1, "sockAT",
                               collect_attention=True))
    # batched path must route the flagged job solo, not pack it
    assert worker.step_batch() == 1
    row = store.recent()[0]
    attn = row["answer_text"]["attention"]
    n_regions = worker.engine.cfg.engine.max_regions
    assert attn["n_bridges"] == len(
        worker.engine.cfg.model.v_biattention_id)
    for bridge in attn["bridge_cls_to_regions"]:
        assert len(bridge) == n_regions
        assert abs(sum(bridge) - 1.0) < 1e-2  # a softmax row

    # without the flag no attention payload is attached
    q.publish(make_job_message(["img_a.jpg"], "what is this", 1, "sockAT"))
    worker.step()
    assert "attention" not in store.recent()[0]["answer_text"]


def test_full_attention_maps_end_to_end(stack):
    """VERDICT r2 #8: collect_attention="full" persists the COMPLETE
    per-bridge per-head maps and serves them back through the API."""
    import numpy as np

    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "what is this", 1, "sockFA",
                               collect_attention="full"))
    assert worker.step_batch() == 1  # full jobs route solo, like summary
    row = store.recent()[0]
    attn = row["answer_text"]["attention"]
    assert attn["bridge_cls_to_regions"]  # summary still present
    qa_id = attn["qa_id"]
    assert attn["full_map_url"] == f"/attention/{qa_id}"

    # The npz holds both directions of every bridge, all heads, padded dims.
    npz_path = os.path.join(s.media_root, "attention", f"qa_{qa_id}.npz")
    cfg = worker.engine.cfg
    n_bridges = len(cfg.model.v_biattention_id)
    heads = cfg.model.bi_num_attention_heads
    nt, nv = cfg.engine.max_text_len + 1, cfg.engine.max_regions
    with np.load(npz_path) as z:
        assert len(z.files) == 2 * n_bridges
        assert z["bridge0_t2v"].shape == (heads, nt, nv)
        assert z["bridge0_v2t"].shape == (heads, nv, nt)

    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", f"/attention/{qa_id}")
        body = json.loads(conn.getresponse().read())
        assert body["heads"] == "mean" and len(body["bridges"]) == n_bridges
        mat = body["bridges"][0]["t2v"]
        assert len(mat) == nt and len(mat[0]) == nv
        assert abs(sum(mat[0]) - 1.0) < 1e-2  # head-avg of softmax rows

        conn.request("GET", f"/attention/{qa_id}?heads=all")
        full = json.loads(conn.getresponse().read())
        assert len(full["bridges"][0]["t2v"]) == heads

        conn.request("GET", f"/media/attention/qa_{qa_id}.npz")
        raw = conn.getresponse()
        assert raw.status == 200 and len(raw.read()) > 100

        conn.request("GET", "/attention/999999")
        assert conn.getresponse().status == 404
    finally:
        api.stop()


# ------------------------------------------------------------------- admin
def test_admin_browse_endpoints(stack):
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "admin probe", 1, "sockAD"))
    worker.step()
    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/admin/tasks")
        tasks = json.loads(conn.getresponse().read())["tasks"]
        assert {t["unique_id"] for t in tasks} >= {1, 12, 7}

        conn.request("GET", "/admin/questionanswer?limit=1")
        rows = json.loads(conn.getresponse().read())["rows"]
        assert len(rows) == 1
        assert rows[0]["input_text"] == "admin probe"
        # socket_id is the websocket-stream credential: must be redacted
        assert "socket_id" not in rows[0]

        # limit is clamped: negative means "no limit" to sqlite — reject it
        conn.request("GET", "/admin/questionanswer?limit=-1")
        assert len(json.loads(conn.getresponse().read())["rows"]) >= 1
    finally:
        api.stop()


def test_admin_edit_roundtrip(stack, tmp_path):
    """Write surface of the admin (reference demo/admin.py:11-34): edit a
    Tasks row and a QA answer over POST, get the change back on browse, and
    keep the hand-edit across a store re-open (the boot reseed must leave
    edited rows alone — Django admin edits persist across restarts)."""
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "edit probe", 1, "sockED"))
    worker.step()
    qa_id = store.recent(limit=1)[0]["id"]
    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)

        def post(path, payload):
            conn.request("POST", path, body=json.dumps(payload),
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, json.loads(r.read())

        st, body = post("/admin/tasks/1", {"name": "VQA (edited)",
                                           "num_of_images_max": 3})
        assert st == 200 and body["row"]["name"] == "VQA (edited)"
        conn.request("GET", "/admin/tasks")
        tasks = {t["unique_id"]: t
                 for t in json.loads(conn.getresponse().read())["tasks"]}
        assert tasks[1]["name"] == "VQA (edited)"
        assert tasks[1]["num_of_images_max"] == 3

        st, body = post(f"/admin/questionanswer/{qa_id}",
                        {"answer_text": {"answers": [{"answer": "fixed"}]},
                         "input_text": "edited question"})
        assert st == 200
        assert body["row"]["input_text"] == "edited question"
        assert body["row"]["answer_text"]["answers"][0]["answer"] == "fixed"
        assert "socket_id" not in body["row"]  # same scrub as browse

        # Rejections: unknown field, ill-typed value, missing row — all
        # bounce whole, nothing half-applies.
        assert post("/admin/tasks/1", {"unique_id": 9})[0] == 400
        assert post("/admin/tasks/1", {"num_of_images": "three"})[0] == 400
        # inverted gating range would make the task unselectable forever
        assert post("/admin/tasks/1", {"num_of_images_min": 5,
                                       "num_of_images_max": 1})[0] == 400
        assert post("/admin/tasks/1", {"num_of_images_min": 9})[0] == 400
        assert post("/admin/tasks/999", {"name": "x"})[0] == 404
        assert post(f"/admin/questionanswer/{qa_id}",
                    {"socket_id": "steal"})[0] == 400
        assert post("/admin/questionanswer/999999",
                    {"input_text": "x"})[0] == 404
    finally:
        api.stop()

    # Persistence across boots: re-opening the store reseeds the catalog
    # from TASK_REGISTRY but must not clobber the edited row.
    reopened = ResultStore(store.path)
    t1 = reopened.get_task(1)
    assert t1["name"] == "VQA (edited)"
    assert t1["num_of_images_max"] == 3
    assert reopened.get_task(15)["name"] != "VQA (edited)"  # others reseeded


def test_two_workers_one_queue_each_job_decoded_once(stack):
    """VERDICT r4 #8: the reference's RabbitMQ gave multi-consumer claim
    exclusivity for free (worker.py:661-673); the embedded queue must too.
    Two ServeWorkers drain one sqlite queue concurrently — every job is
    processed EXACTLY once (claim row-lock exclusivity), nothing is lost,
    and the drained queue is empty."""
    import threading
    from collections import Counter

    from vilbert_multitask_tpu.serve import ServeWorker

    s, hub, q, store, worker_a = stack
    worker_b = ServeWorker(worker_a.engine, q, store, hub, s)
    n_jobs = 24
    for i in range(n_jobs):
        q.publish(make_job_message(
            ["img_a.jpg", "img_b.jpg"][i % 2:i % 2 + 1],
            f"contended question {i}", 1, f"sockC{i}"))

    processed: Counter = Counter()
    lock = threading.Lock()
    errors = []

    def instrument(worker):
        inner = worker.process_job

        def wrapped(job):
            with lock:
                processed[job.id] += 1
            return inner(job)

        worker.process_job = wrapped

    instrument(worker_a)
    instrument(worker_b)

    def drain(worker):
        try:
            # step() returns None when a claim comes up empty; two Nones in
            # a row after others finish means drained.
            misses = 0
            while misses < 2:
                if worker.step() is None:
                    misses += 1
                else:
                    misses = 0
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    threads = [threading.Thread(target=drain, args=(w,))
               for w in (worker_a, worker_b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert not errors, errors
    assert len(processed) == n_jobs, "jobs lost or phantom ids claimed"
    assert set(processed.values()) == {1}, (
        f"double-processed jobs: "
        f"{[j for j, c in processed.items() if c > 1]}")
    assert q.counts() == {}  # all acked — nothing pending/inflight/dead
    texts = {r["input_text"] for r in store.recent(limit=n_jobs * 2)
             if r["input_text"].startswith("contended")}
    assert len(texts) == n_jobs  # one result row per job


def test_visibility_timeout_hands_job_to_second_worker(stack):
    """A worker that claims and dies (no ack) must not strand the job: after
    the visibility timeout the OTHER worker's claim sweeps it back and
    processes it (attempt 2)."""
    import dataclasses as dc

    from vilbert_multitask_tpu.serve import DurableQueue, ServeWorker

    s, hub, q_orig, store, worker_a = stack
    q = DurableQueue(q_orig.path + ".vt", visibility_timeout_s=0.0,
                     max_delivery_attempts=3)
    worker_b = ServeWorker(worker_a.engine, q, store, hub, dc.replace(s))
    q.publish(make_job_message(["img_a.jpg"], "handoff probe", 1, "sockVT"))
    crashed = q.claim()  # "worker A" claims, then crashes before ack
    assert crashed is not None and crashed.attempts == 1
    assert worker_b.step() is not None  # B sweeps the expired claim
    assert q.counts() == {}
    row = next(r for r in store.recent(limit=5)
               if r["input_text"] == "handoff probe")
    assert row["answer_text"]["kind"] == "labels"


def test_admin_edit_token_gate(stack):
    """ADVICE r4 #1: with ServingConfig.admin_token set, POST /admin/* needs
    the bearer header (the reference admin sits behind Django auth); browse
    GETs stay open, and the worker token does NOT unlock the admin surface."""
    import dataclasses as dc

    s, hub, q, store, worker = stack
    s = dc.replace(s, admin_token="sesame", worker_token="other")
    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)

        def post(path, payload, token=None):
            headers = {"Content-Type": "application/json"}
            if token:
                headers["Authorization"] = f"Bearer {token}"
            conn.request("POST", path, body=json.dumps(payload),
                         headers=headers)
            r = conn.getresponse()
            return r.status, json.loads(r.read())

        assert post("/admin/tasks/1", {"name": "x"})[0] == 401
        assert post("/admin/tasks/1", {"name": "x"}, token="wrong")[0] == 401
        assert post("/admin/tasks/1", {"name": "x"}, token="other")[0] == 401
        st, body = post("/admin/tasks/1", {"name": "gated edit"},
                        token="sesame")
        assert st == 200 and body["row"]["name"] == "gated edit"
        conn.request("GET", "/admin/tasks")  # browse stays open
        assert conn.getresponse().status == 200
    finally:
        api.stop()


# ---------------------------------------------------------------- frontend
def test_frontend_served_to_browsers(stack):
    """GET / with a browser Accept header returns the single-page app; API
    clients keep the JSON contract; /config carries the websocket port and
    per-task min/max image counts that drive the dropdown gating."""
    s, hub, q, store, worker = stack
    api = ApiServer(q, store, hub, s)
    api.ws_port = 12345
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/", headers={"Accept": "text/html,*/*"})
        resp = conn.getresponse()
        html = resp.read().decode()
        assert resp.status == 200
        assert "text/html" in resp.getheader("Content-Type", "")
        # the load-bearing UI pieces are present
        for needle in ("GW_RE", "updateGating", "renderGrounding",
                       "WebSocket", "upload_image"):
            assert needle in html, needle

        conn.request("GET", "/", headers={"Accept": "application/json"})
        assert "tasks" in json.loads(conn.getresponse().read())

        conn.request("GET", "/config")
        cfg = json.loads(conn.getresponse().read())
        assert cfg["ws_port"] == 12345
        by_id = {t["unique_id"]: t for t in cfg["tasks"]}
        assert by_id[12]["num_of_images_min"] == 2  # NLVR2 pair
        assert by_id[7]["num_of_images_max"] == 10  # retrieval
        assert by_id[1]["num_of_images_max"] == 1  # VQA single image
    finally:
        api.stop()


def test_admin_console_served_to_browsers(stack):
    """GET /admin with a browser Accept header returns the admin console
    page (the reference's Django admin UI surface); API clients get an
    endpoint index."""
    s, hub, q, store, worker = stack
    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/admin", headers={"Accept": "text/html,*/*"})
        resp = conn.getresponse()
        html = resp.read().decode()
        assert resp.status == 200
        for needle in ("/admin/tasks", "/admin/questionanswer", "taskRow",
                       "num_of_images_min"):
            assert needle in html, needle

        conn.request("GET", "/admin",
                     headers={"Accept": "application/json"})
        idx = json.loads(conn.getresponse().read())
        assert "POST /admin/tasks/<id>" in idx["endpoints"]
    finally:
        api.stop()


def test_healthz_reports_boot_info(stack):
    """VERDICT r2 #3: init/warmup timings + kernel path must be observable
    at /healthz, fed live by ServeApp.warm()."""
    s, hub, q, store, worker = stack
    boot = {}
    api = ApiServer(q, store, hub, s, boot_info=boot)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/healthz")
        before = json.loads(conn.getresponse().read())
        assert before["ok"] is True and before["boot"] == {}
        # ServeApp mutates the shared dict as boot stages finish.
        boot.update(engine_init_s=1.2, warmup_s=3.4, buckets=[1, 2],
                    pallas=True, kernel_fallback=False)
        conn.request("GET", "/healthz")
        after = json.loads(conn.getresponse().read())
        assert after["boot"]["warmup_s"] == 3.4
        assert after["boot"]["pallas"] is True
    finally:
        api.stop()


def test_parallel_warmup_compiles_all_buckets(tiny_framework_cfg, engine):
    """Concurrent warmup must land every bucket in the compile cache and
    stay serving-correct afterwards. (Uses the shared session engine —
    already-compiled buckets make this a thread-pool correctness test, not
    a recompile marathon.)"""
    engine.warmup(parallel=True)
    for b in tiny_framework_cfg.engine.image_buckets:
        # single-device serving runs the per-row program (engine._forward_rows)
        assert ("rows", b, False, engine._model_gen) in engine._compiled
    assert not engine.kernel_fallback


# ------------------------------------------------------- mesh-aware binary
def test_serveapp_serves_through_mesh(tiny_framework_cfg, features_dir,
                                      tmp_path):
    """The serving binary itself (not just the engine library) must build the
    dp mesh when >1 device is visible and serve a job through it — the
    round-1 gap where ServeApp ignored its MeshConfig."""
    import dataclasses

    import jax

    from vilbert_multitask_tpu.serve.app import ServeApp

    assert jax.device_count() >= 8  # conftest virtual mesh
    cfg = dataclasses.replace(
        tiny_framework_cfg,
        serving=dataclasses.replace(
            tiny_framework_cfg.serving,
            queue_db_path=str(tmp_path / "q.sqlite3"),
            results_db_path=str(tmp_path / "r.sqlite3"),
            media_root=str(tmp_path / "media"),
        ))
    app = ServeApp(cfg, feature_root=features_dir)
    assert app.engine.mesh is not None
    assert app.engine.mesh.shape["dp"] == jax.device_count()

    app.queue.publish(
        make_job_message(["img_a.jpg", "img_b.jpg"], "a caption", 7, "sockM"))
    assert app.worker.step() == "acked"
    row = app.store.recent()[0]
    assert row["answer_text"]["kind"] == "ranking"
    assert len(row["answer_text"]["ranking"]) == 2


# --------------------------------------------------------------- websocket
def test_websocket_bridge_delivers(stack):
    pytest.importorskip("websockets")
    from websockets.sync.client import connect

    s, hub, q, store, worker = stack
    bridge = WebSocketBridge(hub, "127.0.0.1", 0)
    # port 0 → pick free port; websockets.serve supports it, read back below
    bridge.start()
    try:
        with connect(f"ws://127.0.0.1:{bridge.bound_port}/chat/") as ws:
            ws.send("sockWS")
            import time

            deadline = time.time() + 5
            while hub.publish("sockWS", {"info": "hello"}) == 0:
                if time.time() > deadline:
                    pytest.fail("subscriber never registered")
                time.sleep(0.02)
            frame = json.loads(ws.recv(timeout=5))
            assert frame == {"info": "hello"}
    finally:
        bridge.stop()


def test_worker_grounding_survives_unrenderable_source(stack, tmp_path):
    """A grounding job whose path is a feature file (store-resolvable but
    not a decodable image) must still ack with the box answer — only the
    drawn overlay is skipped (render is best-effort)."""
    s, hub, q, store, worker = stack
    src = str(tmp_path / "img_a.npy")  # store key 'img_a', but NOT an image
    with open(src, "wb") as f:
        f.write(b"\x93NUMPY not really")
    q.publish(make_job_message([src], "the left thing", 11, "sockD"))
    assert worker.step() == "acked"
    row = store.recent()[0]
    assert row["task_id"] == 11 and len(row["answer_text"]["boxes"]) == 3
    assert row["answer_images"] == []
    assert "result_images" not in row["answer_text"]


def test_device_cache_misses_when_feature_file_changes(stack, features_dir):
    """Replacing a feature file on disk must be a device-cache MISS: cache
    keys are content identities (path+mtime+size, FeatureStore.identity),
    never the raw client-supplied image key."""
    import time as _time

    import numpy as np

    from vilbert_multitask_tpu.features.pipeline import RegionFeatures
    from vilbert_multitask_tpu.features.store import save_reference_npy

    s, hub, q, store, worker = stack
    eng = worker.engine
    q.publish(make_job_message(["img_a.jpg"], "what is this", 1, "sockE"))
    assert worker.step() == "acked"
    keys_before = [k for k in eng._input_cache]
    assert keys_before, "first request must populate the device cache"

    # rewrite img_a's features (different content, bumped mtime)
    rng = np.random.RandomState(9)
    feat_dim = eng.cfg.model.v_feature_size
    boxes = rng.uniform(10, 200, size=(5, 4)).astype(np.float32)
    boxes[:, 2:] = boxes[:, :2] + 15
    path = os.path.join(features_dir, "img_a.npy")
    _time.sleep(0.01)  # ensure mtime_ns moves even on coarse clocks
    save_reference_npy(
        path, RegionFeatures(rng.randn(5, feat_dim).astype(np.float32),
                             boxes, 640, 480), "img_a")
    q.publish(make_job_message(["img_a.jpg"], "what is this", 1, "sockE"))
    assert worker.step() == "acked"
    new_keys = [k for k in eng._input_cache if k not in keys_before]
    assert new_keys, "changed file content must mint a NEW cache key"


# ----------------------------------------------------------- observability
def test_end_to_end_single_trace(stack):
    """The ISSUE-2 acceptance path: one HTTP-submitted request yields ONE
    correlated trace (a single trace_id) spanning submit → queue claim →
    worker → engine stages → push, retrievable as valid Chrome-trace JSON
    from /debug/trace."""
    from vilbert_multitask_tpu import obs

    s, hub, q, store, worker = stack
    api = ApiServer(q, store, hub, s, metrics=worker.metrics)
    port = api.start()
    obs.default_tracer().clear()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("POST", "/", body=json.dumps({
            "task_id": 1, "socket_id": "sockT", "question": "what is this",
            "image_list": ["img_a.jpg"],
        }), headers={"Content-Type": "application/json"})
        resp = json.loads(conn.getresponse().read())
        trace_id = resp["trace_id"]
        assert trace_id and resp["job_id"]

        assert worker.step() == "acked"  # claims + runs on this thread

        conn.request("GET", "/debug/trace")
        doc = json.loads(conn.getresponse().read())
    finally:
        api.stop()

    events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], e)
    # every tier of the request pipeline reported in
    for name in ("http.submit", "worker.claim", "worker.job",
                 "worker.intake", "engine.features", "engine.tokenize",
                 "worker.infer", "engine.forward", "engine.decode",
                 "worker.persist", "worker.push"):
        assert name in by_name, f"missing span {name}: {sorted(by_name)}"
    # ... and all under the ONE trace id minted at submit
    correlated = {e["name"] for e in events
                  if e["args"]["trace_id"] == trace_id}
    assert {"http.submit", "worker.claim", "worker.job", "worker.intake",
            "worker.infer", "engine.forward", "engine.decode",
            "worker.persist", "worker.push"} <= correlated
    # parenting: engine.forward sits under worker.infer under worker.job
    fwd = by_name["engine.forward"]
    infer = by_name["worker.infer"]
    assert fwd["args"]["parent_id"] == infer["args"]["span_id"]
    assert infer["args"]["parent_id"] == by_name["worker.job"]["args"][
        "span_id"]


def test_metrics_prometheus_exposition(stack):
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "what", 1, "sockP"))
    worker.step_batch()
    q.publish(make_job_message(["img_b.jpg"], "held back", 1, "sockP"))

    api = ApiServer(
        q, store, hub, s, metrics=worker.metrics,
        stats_fn=lambda: {"input_cache": worker.engine.input_cache_stats})
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/metrics?format=prometheus")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith(
            "text/plain; version=0.0.4")
        text = resp.read().decode()

        # JSON mode still serves on the same path
        conn.request("GET", "/metrics")
        assert "latency_ms" in json.loads(conn.getresponse().read())
    finally:
        api.stop()

    lines = text.splitlines()
    # parseable exposition: every sample line is `name{labels} value`
    for ln in lines:
        if ln and not ln.startswith("#"):
            name_part, value = ln.rsplit(" ", 1)
            float(value)
            assert name_part
    # queue-depth gauges from DurableQueue.counts()
    assert 'vmt_queue_jobs{state="pending"} 1' in lines
    assert 'vmt_queue_jobs{state="inflight"} 0' in lines
    assert 'vmt_queue_jobs{state="dead"} 0' in lines
    # engine cache stats rode through stats_fn
    assert any(ln.startswith('vmt_input_cache{key="hits"}') for ln in lines)
    # per-task stage histograms (the span->histogram observer bridge)
    assert any(ln.startswith(
        'vmt_span_ms_bucket{name="engine.forward",task="1"') for ln in lines)
    # the request-latency histogram (Metrics) is exposed too
    assert any(ln.startswith('request_latency_ms_bucket{task="1"')
               for ln in lines)


def test_debug_profile_endpoints(stack, tmp_path, monkeypatch):
    calls = []
    from vilbert_multitask_tpu.serve import metrics as metrics_mod

    monkeypatch.setattr(metrics_mod, "start_device_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(metrics_mod, "stop_device_trace",
                        lambda: calls.append(("stop",)))

    s, hub, q, store, worker = stack
    api = ApiServer(q, store, hub, s)
    port = api.start()
    log_dir = str(tmp_path / "prof")
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("POST", "/debug/profile/start",
                     body=json.dumps({"log_dir": log_dir}),
                     headers={"Content-Type": "application/json"})
        r1 = conn.getresponse()
        ok1 = json.loads(r1.read())
        assert r1.status == 200 and ok1 == {"ok": True, "log_dir": log_dir}
        # double-start refuses (jax supports one trace at a time)
        conn.request("POST", "/debug/profile/start", body="{}")
        r2 = conn.getresponse()
        assert r2.status == 409 and not json.loads(r2.read())["ok"]
        conn.request("POST", "/debug/profile/stop", body="")
        r3 = conn.getresponse()
        assert r3.status == 200 and json.loads(r3.read())["ok"]
        # stop with nothing running refuses too
        conn.request("POST", "/debug/profile/stop", body="")
        assert conn.getresponse().status == 409
    finally:
        api.stop()
    assert calls == [("start", log_dir), ("stop",)]


# --------------------------------------------------------- live SLO plane
def _fake_clock_slos(target_ms=100.0):
    """An evaluator over a fake-clock histogram: tests age samples by
    advancing `now`, never by sleeping."""
    from vilbert_multitask_tpu import obs

    h = obs.Histogram("slo_endpoint_fixture_ms", reservoir=256)
    now = [10_000.0]
    h.clock = lambda: now[0]
    ev = obs.SloEvaluator(
        [obs.latency_slo("e2e_latency", h, target_ms, error_budget=0.05)],
        fast_window_s=60.0, slow_window_s=600.0)
    return h, now, ev


def test_debug_slo_states_ride_sliding_windows(stack):
    """Acceptance: /debug/slo burn states come from SLIDING windows — a
    burst of old slow samples outside the window must not hold a PAGE."""
    s, hub, q, store, worker = stack
    h, now, ev = _fake_clock_slos()
    api = ApiServer(q, store, hub, s, slos=ev)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        for _ in range(30):
            h.observe(400.0)            # all-bad burst right now
        conn.request("GET", "/debug/slo")
        paged = json.loads(conn.getresponse().read())
        assert paged["enabled"] is True
        assert paged["worst"] == "page"
        (rep,) = paged["slos"]
        assert rep["slo"] == "e2e_latency" and rep["state"] == "page"
        assert rep["burn"]["fast"] >= 4.0 and rep["burn"]["slow"] >= 4.0
        # the same burst, aged past both windows: PAGE must not stick
        now[0] += 1200.0
        conn.request("GET", "/debug/slo")
        decayed = json.loads(conn.getresponse().read())
        assert decayed["worst"] == "ok"
        (rep2,) = decayed["slos"]
        assert rep2["state"] == "ok"
        assert rep2["burn"] == {"fast": 0.0, "slow": 0.0}
    finally:
        api.stop()


def test_healthz_readiness_gates_on_boot_phase_and_slo_page(stack):
    """/healthz is a real readiness probe now: 503 while booting, 503
    while any SLO pages, 200 once both clear — with the evidence in the
    body for the operator who got paged."""
    s, hub, q, store, worker = stack
    h, now, ev = _fake_clock_slos()
    boot = {"phase": "booting"}
    api = ApiServer(q, store, hub, s, boot_info=boot, slos=ev)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 503
        assert body["ok"] is False and body["reason"] == "booting"
        assert "queue" in body and "breakers" in body

        boot["phase"] = "ready"
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["ok"] is True

        for _ in range(30):
            h.observe(400.0)            # page the latency SLO
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 503
        assert body["reason"] == "slo_page:e2e_latency"
        assert body["slo"] == {"e2e_latency": "page"}

        now[0] += 1200.0                # the incident ages out
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
    finally:
        api.stop()


def test_debug_timeseries_serves_sampled_window(stack):
    from vilbert_multitask_tpu import obs

    s, hub, q, store, worker = stack
    ts = obs.TimeSeriesStore(points=16)
    ts.record("queue_pending", 3.0)
    ts.record("worker_inflight", 1.0)
    api = ApiServer(q, store, hub, s, timeseries=ts)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/debug/timeseries")
        body = json.loads(conn.getresponse().read())
        assert body["enabled"] is True
        assert set(body["series"]) == {"queue_pending", "worker_inflight"}
        ((_, v),) = body["series"]["queue_pending"]
        assert v == 3.0
        # windowed form parses its query parameter
        conn.request("GET", "/debug/timeseries?window_s=60")
        assert json.loads(conn.getresponse().read())["enabled"] is True
    finally:
        api.stop()


def test_cost_attribution_end_to_end(stack, tmp_path):
    """One HTTP-submitted job rides the whole attribution plane: stage
    charges land on its JobCost, /debug/costs groups by tenant, the
    trace store keeps it, /debug/autopsy waterfalls it, and the
    OpenMetrics exposition links the latency bucket to its trace id."""
    from vilbert_multitask_tpu import obs

    s, hub, q, store, worker = stack
    tracestore = obs.TraceStore(str(tmp_path / "spine.db"), "test-ident")
    attrib = obs.CostAttributor(
        on_finish=lambda cost: tracestore.offer(
            cost, obs.default_tracer().spans()))
    api = ApiServer(q, store, hub, s, metrics=worker.metrics,
                    attrib=attrib, tracestore=tracestore)
    port = api.start()
    obs.set_attributor(attrib)
    obs.default_tracer().clear()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("POST", "/", body=json.dumps({
            "task_id": 1, "socket_id": "sockC", "question": "what is this",
            "image_list": ["img_a.jpg"], "tenant": "acme",
        }), headers={"Content-Type": "application/json"})
        trace_id = json.loads(conn.getresponse().read())["trace_id"]

        assert worker.step_batch() == 1  # claim → forward → push

        cost = attrib.get(trace_id)
        assert cost is not None and cost.verdict == "ok"
        assert cost.tenant == "acme" and cost.task == "1"
        assert cost.device_s > 0 and cost.stages["forward"] > 0
        for stage in ("queue_wait", "intake", "decode", "push"):
            assert stage in cost.stages, f"stage {stage} never charged"
        assert attrib.conservation()["ratio"] == 1.0

        conn.request("GET", "/debug/costs?by=tenant")
        costs = json.loads(conn.getresponse().read())
        assert costs["enabled"] is True
        assert costs["groups"]["acme"]["jobs"] == 1
        assert costs["groups"]["acme"]["verdicts"] == {"ok": 1}

        conn.request("GET", "/debug/traces?verdict=slow&task=1")
        traces = json.loads(conn.getresponse().read())
        assert trace_id in {t["trace_id"] for t in traces["traces"]}
        assert traces["stats"]["kept"] == 1

        conn.request("GET", f"/debug/autopsy?trace_id={trace_id}")
        autopsy = json.loads(conn.getresponse().read())
        assert autopsy["verdict"] == "ok"
        waterfall = {w["stage"]: w["ms"] for w in autopsy["waterfall"]}
        assert waterfall["forward"] > 0
        assert autopsy["total_ms"] == pytest.approx(
            sum(waterfall.values()), abs=0.01)

        conn.request("GET", "/metrics?format=openmetrics")
        resp = conn.getresponse()
        assert "openmetrics-text" in resp.getheader("Content-Type")
        text = resp.read().decode()
        assert text.endswith("# EOF\n")
        assert f'# {{trace_id="{trace_id}"}}' in text
    finally:
        obs.set_attributor(None)
        api.stop()


def test_serveapp_start_exposes_build_info_uptime_and_recorder(
        tiny_framework_cfg, features_dir, tmp_path):
    """ServeApp.start() must publish vmt_build_info + vmt_uptime_seconds,
    flip /healthz to ready, install the flight recorder, and stop() must
    tear all of it down (the conftest thread guard enforces the joins)."""
    import dataclasses

    from vilbert_multitask_tpu import obs
    from vilbert_multitask_tpu.serve.app import ServeApp

    cfg = dataclasses.replace(
        tiny_framework_cfg,
        serving=dataclasses.replace(
            tiny_framework_cfg.serving,
            queue_db_path=str(tmp_path / "q.sqlite3"),
            results_db_path=str(tmp_path / "r.sqlite3"),
            media_root=str(tmp_path / "media"),
            ws_port=0, sampler_cadence_s=0.05,
        ))
    app = ServeApp(cfg, feature_root=features_dir)
    assert app.boot_info["phase"] == "booting"
    app.start(worker=False)
    try:
        assert obs.active_recorder() is app.recorder
        conn = http.client.HTTPConnection("127.0.0.1", app.http_port,
                                          timeout=5)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        assert resp.status == 200 and health["boot"]["phase"] == "ready"
        assert health["boot"]["config_fingerprint"] == app.fingerprint

        conn.request("GET", "/metrics?format=prometheus")
        text = conn.getresponse().read().decode()
        (info_line,) = [ln for ln in text.splitlines()
                        if ln.startswith("vmt_build_info{")]
        assert f'config_fingerprint="{app.fingerprint}"' in info_line
        assert 'backend="cpu"' in info_line
        assert float(info_line.rsplit(" ", 1)[1]) == 1.0
        # Default identity labels (Registry.set_default_labels, stamped by
        # ServeApp.start) ride every exposition sample.
        assert any(ln.startswith("vmt_uptime_seconds{")
                   and f'instance="{app.identity.ident}"' in ln
                   for ln in text.splitlines())

        # the background sampler feeds the time-series store
        deadline = time.monotonic() + 10.0
        while not app.timeseries.names() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert "queue_pending" in app.timeseries.names()
        assert "slo_worst" in app.timeseries.names()
    finally:
        app.stop()
    assert obs.active_recorder() is None
