"""Compile-surface manifest suite: discovery, determinism, drift
detection, the CLI gates (``surface --check``, ``--prune-baseline
--check``), the rename/delete-aware ``--changed`` subset — and the
runtime↔manifest contract: a TINY engine booted on CPU must never
compile a key the committed COMPILE_SURFACE.json doesn't enumerate."""

import ast
import json
import os
import subprocess
import textwrap

import pytest

from vilbert_multitask_tpu.analysis import surface as surf
from vilbert_multitask_tpu.analysis.cli import (
    _changed_subset,
    _parse_name_status,
    main as cli_main,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, surf.MANIFEST_NAME)


def _library_sources():
    out = {}
    lib = os.path.join(REPO, "vilbert_multitask_tpu")
    for dirpath, dirnames, filenames in os.walk(lib):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, REPO).replace(os.sep, "/")
            with open(full, "r", encoding="utf-8") as f:
                out[rel] = f.read()
    return out


@pytest.fixture(scope="module")
def fresh_surface():
    return surf.build_surface(surf.load_project(_library_sources()))


# ---------------------------------------------------------------- manifest
def test_surface_enumerates_full_key_universe(fresh_surface):
    dims = fresh_surface["dimensions"]
    families = [p["family"] for p in dims["program_families"]]
    assert families == ["batched", "rows"]
    assert dims["buckets"]["values"] == [1, 2, 4, 8, 10, 16, 32]
    assert dims["param_dtypes"]["values"] == ["float32", "bfloat16",
                                              "int8"]
    assert dims["fused_modes"]["values"] == [True, False]
    assert dims["collect_attention"]["values"] == [False, True]
    assert [t["id"] for t in dims["topologies"]] == ["dp-1.tp1.sp1"]
    # 2 families × 7 buckets × 3 dtypes × 2 fused × 1 topo × 2 attn
    assert fresh_surface["record_count"] == 168
    assert len(fresh_surface["records"]) == 168
    keys = [r["key"] for r in fresh_surface["records"]]
    assert len(set(keys)) == 168  # unique and total


def test_surface_static_origins_are_bounded(fresh_surface):
    """Every value reaching a compile-key parameter must be bounded
    (bucketized / knob / literal) — an unbounded origin here is the
    compile-cache blowup VMT124 exists to catch."""
    progs = fresh_surface["dimensions"]["program_families"]
    total = 0
    for prog in progs:
        for entries in prog["static_origins"].values():
            for e in entries:
                total += 1
                assert e["bounded"], e
    assert total > 0  # the analysis actually found dispatch sites


def test_surface_witnesses_anchor_in_real_files(fresh_surface):
    dims = fresh_surface["dimensions"]
    seen = 0
    for dim in ("buckets", "param_dtypes", "fused_modes",
                "collect_attention"):
        for w in dims[dim]["witnesses"]:
            seen += 1
            assert os.path.exists(os.path.join(REPO, w["path"])), w
            assert w["line"] >= 1
    assert seen >= 6


def test_surface_build_is_deterministic():
    sources = _library_sources()
    a = surf.render_surface(surf.build_surface(surf.load_project(sources)))
    b = surf.render_surface(surf.build_surface(surf.load_project(sources)))
    assert a == b


def test_committed_manifest_matches_tree(fresh_surface):
    """The acceptance gate: COMPILE_SURFACE.json is committed and clean
    against the tree it describes."""
    assert os.path.exists(MANIFEST), (
        "COMPILE_SURFACE.json not committed — run `python -m "
        "vilbert_multitask_tpu.analysis surface`")
    with open(MANIFEST, "r", encoding="utf-8") as f:
        committed = json.load(f)
    assert surf.diff_surface(committed, fresh_surface) == []


def test_diff_surface_reports_dimension_drift(fresh_surface):
    mutated = json.loads(json.dumps(fresh_surface))
    mutated["dimensions"]["buckets"]["values"].append(64)
    msgs = surf.diff_surface(mutated, fresh_surface)
    assert any("buckets" in m for m in msgs)
    missing = surf.diff_surface(None, fresh_surface)
    assert missing and "missing" in missing[0]


def test_discover_programs_on_fixture_idiom():
    src = textwrap.dedent('''
        import jax
        from functools import partial

        class Eng:
            def _build(self, bucket, flag):
                key = ("demo", bucket, flag, self._gen)
                if key in self._compiled:
                    return self._compiled[key]

                @partial(jax.jit, static_argnames=("flag",))
                def fwd(params, batch, flag=flag):
                    return batch

                self._compiled[key] = fwd
                return fwd
    ''')
    project = surf.load_project({"pkg/eng.py": src})
    progs = surf.discover_programs(project)
    assert len(progs) == 1
    assert progs[0].family == "demo"
    assert progs[0].builder == "pkg.eng:Eng._build"
    assert progs[0].key_params == ("bucket", "flag")
    assert progs[0].static_args == ("flag",)


def test_surface_sarif_renders_codeflows(fresh_surface):
    doc = json.loads(surf.render_surface_sarif(fresh_surface))
    results = doc["runs"][0]["results"]
    assert len(results) == 2
    for r in results:
        assert r["codeFlows"]
        loc = r["codeFlows"][0]["threadFlows"][0]["locations"][0]
        assert loc["location"]["physicalLocation"]["artifactLocation"][
            "uri"].endswith(".py")


def test_surface_check_cli_clean(monkeypatch):
    monkeypatch.chdir(REPO)
    assert cli_main(["surface", "--check"]) == 0


def test_surface_check_cli_flags_drift(monkeypatch, tmp_path):
    monkeypatch.chdir(REPO)
    with open(MANIFEST, "r", encoding="utf-8") as f:
        d = json.load(f)
    d["dimensions"]["param_dtypes"]["values"] = ["float32"]
    drifted = tmp_path / "drifted.json"
    drifted.write_text(json.dumps(d))
    assert cli_main(["surface", "--check", "--out", str(drifted)]) == 1


# ---------------------------------------------------- --changed name-status
def test_parse_name_status_rename_delete_modify():
    out = ("M\tpkg/mod.py\n"
           "A\tpkg/new.py\n"
           "D\tpkg/dead.py\n"
           "R087\tpkg/old.py\tpkg/moved.py\n"
           "C075\tpkg/src.py\tpkg/copy.py\n")
    changed, removed = _parse_name_status(out)
    assert changed == {"pkg/mod.py", "pkg/new.py", "pkg/moved.py",
                       "pkg/copy.py"}
    assert removed == {"pkg/dead.py", "pkg/old.py"}


def _git(cwd, *args):
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    *args], cwd=cwd, check=True, capture_output=True)


@pytest.fixture()
def git_repo(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text("VALUE = 1\n")
    (pkg / "b.py").write_text("import pkg.a\n\nX = pkg.a.VALUE\n")
    for name in ("c", "d", "e", "f"):
        (pkg / f"{name}.py").write_text(f"{name.upper()} = 0\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    return tmp_path


def test_changed_subset_follows_rename(git_repo):
    _git(git_repo, "mv", "pkg/a.py", "pkg/a2.py")
    result = _changed_subset([str(git_repo / "pkg")], str(git_repo),
                             (), "HEAD")
    assert result is not None
    subset, removed = result
    rels = {os.path.relpath(p, str(git_repo)).replace(os.sep, "/")
            for p in subset}
    # The rename target is scanned, and so is the module that imported
    # the old name — its cross-module findings may have shifted.
    assert "pkg/a2.py" in rels
    assert "pkg/b.py" in rels
    assert removed == {"pkg/a.py"}


def test_changed_subset_deletion_rescans_importers(git_repo):
    _git(git_repo, "rm", "-q", "pkg/a.py")
    result = _changed_subset([str(git_repo / "pkg")], str(git_repo),
                             (), "HEAD")
    assert result is not None
    subset, removed = result
    rels = {os.path.relpath(p, str(git_repo)).replace(os.sep, "/")
            for p in subset}
    assert "pkg/b.py" in rels
    assert removed == {"pkg/a.py"}


def test_changed_subset_untouched_repo_full_scan(git_repo):
    assert _changed_subset([str(git_repo / "pkg")], str(git_repo),
                           (), "HEAD") is None


# ------------------------------------------------- baseline staleness gates
PYPROJECT = textwrap.dedent('''
    [tool.vmtlint]
    paths = ["pkg"]
    baseline = "baseline.json"
''')


def _baseline_entry(fingerprint, path):
    return {"fingerprint": fingerprint, "rule": fingerprint.split(":")[0],
            "name": "x", "path": path, "line": 1, "content": "x",
            "justification": "test"}


@pytest.fixture()
def lint_repo(tmp_path):
    (tmp_path / "pyproject.toml").write_text(PYPROJECT)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("VALUE = 1\n")
    return tmp_path


def test_prune_check_fails_on_stale_entry(lint_repo, monkeypatch):
    (lint_repo / "baseline.json").write_text(json.dumps({
        "version": 1,
        "entries": [_baseline_entry("VMT105:pkg/mod.py:deadbeef0000",
                                    "pkg/mod.py")]}))
    monkeypatch.chdir(lint_repo)
    assert cli_main(["--prune-baseline", "--check"]) == 1


def test_prune_check_fails_on_deleted_file_entry(lint_repo, monkeypatch):
    """The satellite-1 bug class: a baseline entry anchored in a file
    that no longer exists must go stale on a full scan, not linger as a
    dead suppression."""
    (lint_repo / "baseline.json").write_text(json.dumps({
        "version": 1,
        "entries": [_baseline_entry("VMT105:pkg/gone.py:deadbeef0000",
                                    "pkg/gone.py")]}))
    monkeypatch.chdir(lint_repo)
    assert cli_main(["--prune-baseline", "--check"]) == 1


def test_prune_rewrites_then_check_clean(lint_repo, monkeypatch):
    (lint_repo / "baseline.json").write_text(json.dumps({
        "version": 1,
        "entries": [_baseline_entry("VMT105:pkg/gone.py:deadbeef0000",
                                    "pkg/gone.py")]}))
    monkeypatch.chdir(lint_repo)
    assert cli_main(["--prune-baseline"]) == 0
    data = json.loads((lint_repo / "baseline.json").read_text())
    assert data["entries"] == []
    assert cli_main(["--prune-baseline", "--check"]) == 0


def test_prune_check_clean_on_real_repo(monkeypatch):
    monkeypatch.chdir(REPO)
    assert cli_main(["--prune-baseline", "--check"]) == 0


# -------------------------------------------------- runtime↔manifest contract
def test_engine_compiled_keys_covered_by_manifest(tiny_config):
    """Boot the TINY engine on CPU, exercise warmup/run/run_many, and
    assert every key the engine actually compiled maps onto a committed
    manifest record — the drift test that keeps the manifest honest."""
    from vilbert_multitask_tpu.config import EngineConfig, FrameworkConfig
    from vilbert_multitask_tpu.engine import InferenceEngine
    from tests.test_engine import make_regions

    cfg = FrameworkConfig(
        model=tiny_config,
        engine=EngineConfig(
            compute_dtype="float32", max_regions=11,
            use_pallas_coattention=False,
            use_pallas_self_attention=False))
    eng = InferenceEngine(cfg, seed=0)
    eng.warmup(buckets=(1, 2), parallel=False)
    regions = make_regions(2, feat_dim=tiny_config.v_feature_size)
    _, result = eng.run(eng.prepare(1, "what is on the table",
                                    regions[:1]))
    assert result
    many = eng.run_many([eng.prepare(1, "a dog", regions[:1]),
                         eng.prepare(1, "a cat", regions[1:])])
    assert len(many) == 2

    with open(MANIFEST, "r", encoding="utf-8") as f:
        manifest = json.load(f)
    record_keys = {r["key"] for r in manifest["records"]}
    families = {p["family"]
                for p in manifest["dimensions"]["program_families"]}
    topo = manifest["dimensions"]["topologies"][0]["id"]
    param_dtype = cfg.engine.param_dtype
    fused = cfg.engine.fused_task_heads

    assert eng._compiled, "engine compiled nothing — test exercised no path"
    for key in eng._compiled:
        family, bucket, attn, gen = key
        assert family in families, key
        mapped = surf.record_key_for_engine(
            family, bucket, param_dtype, fused, topo, attn)
        assert mapped in record_keys, (
            f"engine compiled {key} but the manifest has no record "
            f"{mapped} — regenerate COMPILE_SURFACE.json")
        # model_gen is a process-local version counter, not a key-universe
        # dimension; no kernel fallback happened on this CPU boot.
        assert gen == 0
