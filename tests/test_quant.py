"""Per-channel int8 weight quantization (vilbert_multitask_tpu/quant.py):
the storage mode behind ``EngineConfig.param_dtype="int8"``. The contract
under test: a symmetric per-output-channel scheme whose round-trip error is
bounded by half a quantization step per channel, pytree-transparent pairs
(plain dicts — Orbax/device_put/tree_map all work untouched), idempotent
tree quantization (the restore → load_params double-cast), and the byte
halving vs bf16 the roofline work banks on."""

import numpy as np
import pytest

from vilbert_multitask_tpu import quant
from vilbert_multitask_tpu.engine.flops import param_tree_bytes
from vilbert_multitask_tpu.parallel import sharding as shd


def _tree(seed=0):
    """Checkpoint-shaped host sample: matrices + an embedding table (both
    quantize) and vector/scalar leaves (must pass through untouched)."""
    r = np.random.RandomState(seed)
    return {
        "dense": {"kernel": r.randn(64, 32).astype(np.float32) * 0.07,
                  "bias": r.randn(32).astype(np.float32)},
        "qkv": {"kernel": r.randn(8, 64, 32).astype(np.float32)},
        "embed": {"embedding": r.randn(1037, 48).astype(np.float32)},
        "norm": {"scale": np.ones(32, np.float32)},
    }


# Quantization step is amax/127; symmetric rounding error is half a step.
@pytest.mark.parametrize("shape", [(64, 32), (8, 64, 32), (1037, 48)])
def test_round_trip_error_bounded_per_channel(shape):
    x = (np.random.RandomState(hash(shape) % 2**31)
         .randn(*shape).astype(np.float32))
    back = quant.dequantize_leaf(quant.quantize_leaf(x), np.float32)
    amax = np.max(np.abs(x), axis=tuple(range(x.ndim - 1)))
    assert np.all(np.abs(back - x) <= amax / 254.0 + 1e-7)


def test_zero_channel_guard():
    """An all-zero output channel must round-trip to exact zeros (scale
    falls back to 1.0, never 0/0)."""
    x = np.random.RandomState(3).randn(16, 8).astype(np.float32)
    x[:, 5] = 0.0
    pair = quant.quantize_leaf(x)
    assert float(pair[quant.QSCALE][5]) == 1.0
    back = quant.dequantize_leaf(pair, np.float32)
    assert np.all(back[:, 5] == 0.0)


def test_quantize_tree_leaves_vectors_floating():
    q = quant.quantize_tree(_tree())
    assert quant.is_quantized_leaf(q["dense"]["kernel"])
    assert quant.is_quantized_leaf(q["embed"]["embedding"])
    assert q["dense"]["bias"].dtype == np.float32  # ndim<2: untouched
    assert q["norm"]["scale"].dtype == np.float32
    assert q["qkv"]["kernel"][quant.QVALUES].dtype == np.int8
    # Scales are per-LAST-axis channels, f32.
    assert q["qkv"]["kernel"][quant.QSCALE].shape == (32,)
    assert q["qkv"]["kernel"][quant.QSCALE].dtype == np.float32
    assert quant.tree_is_quantized(q) and not quant.tree_is_quantized(_tree())


def test_quantize_tree_is_idempotent():
    """restore_params(dtype="int8") → engine.load_params re-casts the tree;
    the second pass must be the identity, not a double quantization."""
    q1 = quant.quantize_tree(_tree())
    q2 = quant.quantize_tree(q1)
    assert np.array_equal(q1["dense"]["kernel"][quant.QVALUES],
                          q2["dense"]["kernel"][quant.QVALUES])
    assert np.array_equal(q1["embed"]["embedding"][quant.QSCALE],
                          q2["embed"]["embedding"][quant.QSCALE])


def test_dequantize_tree_expands_pairs_and_casts_rest():
    q = quant.quantize_tree(_tree())
    wide = quant.dequantize_tree(q, np.float32)
    assert wide["dense"]["kernel"].shape == (64, 32)
    assert wide["dense"]["kernel"].dtype == np.float32
    assert wide["dense"]["bias"].dtype == np.float32
    assert not quant.tree_is_quantized(wide)


def test_int8_tree_bytes_near_quarter_of_f32():
    """The roofline claim: int8 storage reads ~¼ the HBM bytes of f32 (the
    f32 scale vectors and untouched bias/LN leaves cost a few points)."""
    t = _tree()
    ratio = param_tree_bytes(quant.quantize_tree(t)) / param_tree_bytes(t)
    assert 0.25 <= ratio < 0.35, ratio


def test_cast_floating_int8_mode_and_rejection():
    """parallel/sharding.cast_floating is the ONE storage-cast seam: "int8"
    routes to the quantizer, other integer dtypes are a config error, and
    float casts pass quantized pairs through rather than casting the int8
    values to float."""
    t = _tree()
    q = shd.cast_floating(t, "int8")
    assert quant.tree_is_quantized(q)
    again = shd.cast_floating(q, "int8")  # the double-cast seam
    assert np.array_equal(q["dense"]["kernel"][quant.QVALUES],
                          again["dense"]["kernel"][quant.QVALUES])
    still = shd.cast_floating(q, "bfloat16")
    assert still["dense"]["kernel"][quant.QVALUES].dtype == np.int8
    with pytest.raises(ValueError):
        shd.cast_floating(t, "int32")


def test_spec_for_replicates_scale_vectors():
    """Sharding rules match on the path with the pair suffix stripped, so a
    kernel's int8 values shard like the kernel did and its (last_dim,)
    scale vector falls through to replication."""
    import jax

    from vilbert_multitask_tpu.config import MeshConfig
    from vilbert_multitask_tpu.parallel import build_mesh
    from vilbert_multitask_tpu.parallel.sharding import param_specs

    q = quant.quantize_tree({
        "bert": {"encoder": {"t_layer_0": {"ffn": {"output": {
            "kernel": np.zeros((64, 32), np.float32)}}}}}})
    mesh = build_mesh(MeshConfig(dp=jax.device_count(), tp=1))
    specs = param_specs(q, mesh)
    pair = specs["bert"]["encoder"]["t_layer_0"]["ffn"]["output"]["kernel"]
    assert tuple(pair[quant.QVALUES]) in (("tp", None), ())
    assert tuple(pair[quant.QSCALE]) == ()


def test_quantize_under_jit_matches_host():
    """The device path (_place_params jits quantize_tree so eager scalar
    constants never become implicit transfers) must agree bit-for-bit with
    the host numpy path on the same values."""
    import jax
    import jax.numpy as jnp

    t = _tree(9)
    host = quant.quantize_tree(t)
    dev = jax.jit(quant.quantize_tree)(
        jax.tree_util.tree_map(jnp.asarray, t))
    np.testing.assert_array_equal(
        host["dense"]["kernel"][quant.QVALUES],
        np.asarray(dev["dense"]["kernel"][quant.QVALUES]))
    np.testing.assert_allclose(
        host["embed"]["embedding"][quant.QSCALE],
        np.asarray(dev["embed"]["embedding"][quant.QSCALE]), rtol=1e-6)
