"""vmtlint fixture suite: every rule proven to trigger AND to stay quiet.

Each rule gets a positive snippet (the hazard, minimally) and a negative
(the correct idiom it must not flag) — the negative matters as much as
the positive: a lint that cries wolf gets disabled. Plus the suppression
comment, baseline round-trip, config parsing, and CLI exit codes.
"""

import json
import textwrap

import pytest

from vilbert_multitask_tpu.analysis import baseline as bl
from vilbert_multitask_tpu.analysis.cli import main as cli_main
from vilbert_multitask_tpu.analysis.config import parse_toml_tables
from vilbert_multitask_tpu.analysis.core import analyze_source

LIB = "vilbert_multitask_tpu/fake.py"  # library-rooted path for library_only


def rules_hit(src: str, path: str = LIB):
    return {f.rule for f in analyze_source(textwrap.dedent(src), path)}


def findings(src: str, path: str = LIB):
    return analyze_source(textwrap.dedent(src), path)


# ----------------------------------------------------------------- VMT101
def test_host_transfer_in_jit_triggers():
    src = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def f(x):
        return jnp.sum(np.asarray(x))
    """
    assert "VMT101" in rules_hit(src)


def test_host_transfer_item_in_jit_wrapped_fn_triggers():
    # The wrap-by-name form (jax.jit(g)) must mark g's body too.
    src = """
    import jax

    def g(x):
        return x.item()

    run = jax.jit(g)
    """
    assert "VMT101" in rules_hit(src)


def test_host_math_on_static_shapes_is_clean():
    # The kernel idiom: shape dims are concrete Python ints under tracing,
    # and static_argnames params are too — float(np.sqrt(D)) is fine.
    src = """
    import functools
    import jax
    import numpy as np

    @functools.partial(jax.jit, static_argnames=("block",))
    def f(x, block=8):
        B, D = x.shape
        scale = 1.0 / float(np.sqrt(D))
        n = min(block, D)
        return x * scale * n
    """
    assert "VMT101" not in rules_hit(src)


def test_numpy_outside_jit_is_clean():
    src = """
    import numpy as np

    def host_prep(x):
        return np.asarray(x, np.float32)
    """
    assert "VMT101" not in rules_hit(src)


# ----------------------------------------------------------------- VMT102
def test_jit_in_loop_triggers():
    src = """
    import jax

    def sweep(xs):
        out = []
        for x in xs:
            out.append(jax.jit(lambda a: a + 1)(x))
        return out
    """
    assert "VMT102" in rules_hit(src)


def test_jit_hoisted_out_of_loop_is_clean():
    src = """
    import jax

    def sweep(xs):
        f = jax.jit(lambda a: a + 1)
        return [f(x) for x in xs]
    """
    assert "VMT102" not in rules_hit(src)


def test_unhashable_static_literal_triggers():
    src = """
    import jax

    def g(x, sizes):
        return x

    f = jax.jit(g, static_argnums=(1,))

    def call(x):
        return f(x, [1, 2])
    """
    assert "VMT102" in rules_hit(src)


def test_hashable_static_tuple_is_clean():
    src = """
    import jax

    def g(x, sizes):
        return x

    f = jax.jit(g, static_argnums=(1,))

    def call(x):
        return f(x, (1, 2))
    """
    assert "VMT102" not in rules_hit(src)


# ----------------------------------------------------------------- VMT103
def test_donated_buffer_read_after_call_triggers():
    src = """
    import jax

    def g(state):
        return state

    step = jax.jit(g, donate_argnums=(0,))

    def train(state):
        new = step(state)
        return state.mean()
    """
    assert "VMT103" in rules_hit(src)


def test_donation_without_rebind_in_loop_triggers():
    src = """
    import jax

    def g(state):
        return state

    step = jax.jit(g, donate_argnums=(0,))

    def train(state, n):
        for _ in range(n):
            loss = step(state)
        return loss
    """
    assert "VMT103" in rules_hit(src)


def test_donation_with_rebind_is_clean():
    src = """
    import jax

    def g(state):
        return state

    step = jax.jit(g, donate_argnums=(0,))

    def train(state, n):
        for _ in range(n):
            state = step(state)
        return state
    """
    assert "VMT103" not in rules_hit(src)


# ----------------------------------------------------------------- VMT104
def test_unblocked_timed_dispatch_triggers():
    src = """
    import time
    import jax.numpy as jnp

    def bench(x):
        t0 = time.perf_counter()
        y = jnp.dot(x, x)
        return time.perf_counter() - t0
    """
    assert "VMT104" in rules_hit(src)


def test_blocked_timed_dispatch_is_clean():
    src = """
    import time
    import jax
    import jax.numpy as jnp

    def bench(x):
        t0 = time.perf_counter()
        y = jax.block_until_ready(jnp.dot(x, x))
        return time.perf_counter() - t0
    """
    assert "VMT104" not in rules_hit(src)


def test_timed_host_only_span_is_clean():
    # jax.devices()/config are blocking host calls — timing backend init
    # is legitimate and must not be flagged.
    src = """
    import time
    import jax

    def boot():
        t0 = time.perf_counter()
        dev = jax.devices()[0]
        return time.perf_counter() - t0
    """
    assert "VMT104" not in rules_hit(src)


def test_submit_stamp_after_io_triggers():
    # The exact serve_soak.py:148 bug shape (negative latency samples).
    src = """
    import time

    def soak(conn, jobs, submitted):
        for q in jobs:
            conn.request("POST", "/", body=q)
            resp = conn.getresponse()
            submitted[q] = time.perf_counter()
    """
    assert "VMT104" in rules_hit(src)


def test_submit_stamp_before_io_is_clean():
    src = """
    import time

    def soak(conn, jobs, submitted):
        for q in jobs:
            t_submit = time.perf_counter()
            conn.request("POST", "/", body=q)
            resp = conn.getresponse()
            submitted[q] = t_submit
    """
    assert "VMT104" not in rules_hit(src)


# ----------------------------------------------------------------- VMT105
def test_stray_print_in_library_triggers():
    src = """
    def helper(x):
        print("debug", x)
        return x
    """
    assert "VMT105" in rules_hit(src)


def test_breakpoint_and_debug_print_trigger():
    src = """
    import jax

    def helper(x):
        breakpoint()
        jax.debug.print("x={}", x)
        return x
    """
    hits = [f for f in findings(src) if f.rule == "VMT105"]
    assert len(hits) == 2


def test_print_in_main_or_stderr_or_script_is_clean():
    src = """
    import sys

    def helper(msg):
        print(msg, file=sys.stderr)

    def main():
        print("usage: ...")

    if __name__ == "__main__":
        print("running")
        main()
    """
    assert "VMT105" not in rules_hit(src)
    # scripts are outside library_roots: even a bare print is exempt.
    assert "VMT105" not in rules_hit(
        "def helper():\n    print('x')\n", path="scripts/tool.py")


# ----------------------------------------------------------------- VMT106
def test_sqlite_conn_on_self_without_lock_triggers():
    src = """
    import sqlite3

    class Store:
        def __init__(self, path):
            self.conn = sqlite3.connect(path)
    """
    assert "VMT106" in rules_hit(src)


def test_check_same_thread_false_triggers():
    src = """
    import sqlite3

    def open_db(path):
        return sqlite3.connect(path, check_same_thread=False)
    """
    assert "VMT106" in rules_hit(src)


def test_connection_per_call_and_locked_class_are_clean():
    src = """
    import sqlite3
    import threading

    class PerCall:
        def _conn(self):
            return sqlite3.connect("db.sqlite3", timeout=30.0)

    class Locked:
        def __init__(self, path):
            self._lock = threading.Lock()
            self.conn = sqlite3.connect(path)
    """
    assert "VMT106" not in rules_hit(src)


# ----------------------------------------------------------------- VMT107
def test_swallowed_exception_triggers():
    src = """
    def drain(q):
        while True:
            try:
                q.pop()
            except Exception:
                continue
    """
    assert "VMT107" in rules_hit(src)


def test_narrow_except_and_del_teardown_are_clean():
    src = """
    class F:
        def read(self):
            try:
                return self._f.read()
            except OSError:
                pass

        def __del__(self):
            try:
                self._f.close()
            except Exception:
                pass
    """
    assert "VMT107" not in rules_hit(src)


def test_pass_with_working_continuation_is_clean():
    # CFG-aware half of the rule: `pass` is an acceptable degrade when
    # the code after the handler still does real work on that path.
    src = """
    def snapshot(self):
        snap = {"ok": True}
        try:
            snap["stats"] = self._stats()
        except Exception:
            pass
        self._json(200, snap)
    """
    assert "VMT107" not in rules_hit(src)


def test_pass_at_function_end_still_fires():
    # No continuation does any work after the swallow -> still a
    # swallowed exception, CFG or not.
    src = """
    def fire_and_forget(self, evt):
        try:
            self._emit(evt)
        except Exception:
            pass
    """
    assert "VMT107" in rules_hit(src)


# ----------------------------------------------------------------- VMT108
def test_module_numpy_mutation_triggers():
    src = """
    import numpy as np

    COUNTS = np.zeros(8)

    def bump(i):
        COUNTS[i] += 1
    """
    assert "VMT108" in rules_hit(src)


def test_local_numpy_mutation_is_clean():
    src = """
    import numpy as np

    def bump(i):
        counts = np.zeros(8)
        counts[i] += 1
        return counts
    """
    assert "VMT108" not in rules_hit(src)


# ----------------------------------------------------------------- VMT109
def test_walltime_duration_triggers():
    src = """
    import time

    def handler():
        t0 = time.time()
        work()
        return time.time() - t0
    """
    assert "VMT109" in rules_hit(src)


def test_walltime_attribute_anchor_triggers():
    # self._started = time.time() in one method, subtracted in another.
    src = """
    import time

    class M:
        def __init__(self):
            self._started = time.time()

        def uptime(self):
            return time.time() - self._started
    """
    assert "VMT109" in rules_hit(src)


def test_perf_counter_duration_is_clean():
    src = """
    import time

    def handler():
        t0 = time.perf_counter()
        work()
        return time.perf_counter() - t0
    """
    assert "VMT109" not in rules_hit(src)


def test_walltime_timestamp_without_subtraction_is_clean():
    # Stamping an event with wall-clock time is the legitimate use.
    src = """
    import time

    def stamp(job):
        job["submitted_at"] = time.time()
        return job
    """
    assert "VMT109" not in rules_hit(src)


def test_walltime_anchor_is_function_scoped():
    # A name assigned from time.time() in one function must not taint the
    # same name in another function.
    src = """
    import time

    def a():
        t0 = time.time()
        return t0

    def b():
        t0 = 1.0
        return 2.0 - t0
    """
    assert "VMT109" not in rules_hit(src)


def test_walltime_duration_suppressible():
    src = """
    import time

    def deadline_left(stamp):
        return 30.0 - (time.time() - stamp)  # vmtlint: disable=VMT109
    """
    assert "VMT109" not in rules_hit(src)


# ----------------------------------------------------------------- VMT114
def test_naked_retry_loop_triggers():
    # The exact shape serve/remote.py used to hand-roll: unbounded loop,
    # catch, deterministic exponential sleep — lockstep retries forever.
    src = """
    import time

    def fetch(url):
        attempt = 0
        while True:
            try:
                return get(url)
            except ConnectionError:
                time.sleep(0.5 * (2 ** attempt))
                attempt += 1
    """
    assert "VMT114" in rules_hit(src)


def test_naked_retry_loop_constant_sleep_triggers():
    src = """
    import time

    def poll():
        while 1:
            try:
                return read()
            except OSError:
                time.sleep(1.0)
    """
    assert "VMT114" in rules_hit(src)


def test_bounded_retry_with_jitter_is_clean():
    src = """
    import random
    import time

    def fetch(url):
        for attempt in range(5):
            try:
                return get(url)
            except ConnectionError:
                time.sleep(random.uniform(0, 0.5 * (2 ** attempt)))
    """
    assert "VMT114" not in rules_hit(src)


def test_unbounded_loop_with_jittered_sleep_is_clean():
    # Jitter alone desynchronizes the herd; the rule targets the compound
    # hazard (the attempt bound is RetryPolicy's job to add).
    src = """
    import time

    def watch(policy):
        while True:
            try:
                return claim()
            except ConnectionError:
                time.sleep(policy.backoff_s(0))
    """
    assert "VMT114" not in rules_hit(src)


def test_poll_loop_with_exit_condition_is_clean():
    # run_forever's shape: a real exit condition makes it a poll loop,
    # not a retry loop.
    src = """
    import time

    def run_forever(stop):
        while not stop.is_set():
            try:
                step()
            except ValueError:
                pass
            time.sleep(0.05)
    """
    assert "VMT114" not in rules_hit(src)


def test_sleep_in_nested_bounded_loop_is_clean():
    # The sleep belongs to the bounded inner for-loop, not the outer
    # while True service loop.
    src = """
    import time

    def service():
        while True:
            try:
                work()
            except ValueError:
                pass
            for _ in range(3):
                time.sleep(0.1)
    """
    assert "VMT114" not in rules_hit(src)


# ----------------------------------------------------------------- VMT115
OBS = "vilbert_multitask_tpu/obs/fake.py"  # on the telemetry plane


def test_unbounded_instance_buffer_on_obs_plane_triggers():
    src = """
    class Collector:
        def __init__(self):
            self.events = []

        def record(self, e):
            self.events.append(e)
    """
    assert "VMT115" in rules_hit(src, path=OBS)


def test_unbounded_module_buffer_on_obs_plane_triggers():
    src = """
    FRAMES = []

    def push(frame):
        FRAMES.append(frame)
    """
    assert "VMT115" in rules_hit(src, path=OBS)


def test_maxlen_less_deque_on_obs_plane_triggers():
    src = """
    from collections import deque

    class Collector:
        def __init__(self):
            self.ring = deque()

        def record(self, e):
            self.ring.append(e)
    """
    assert "VMT115" in rules_hit(src, path=OBS)


def test_bounded_deque_is_clean():
    src = """
    from collections import deque

    class Collector:
        def __init__(self):
            self.ring = deque(maxlen=256)

        def record(self, e):
            self.ring.append(e)
    """
    assert "VMT115" not in rules_hit(src, path=OBS)


def test_buffer_with_removal_is_clean():
    # The span-stack shape: pushed and popped — bounded by its usage.
    src = """
    class Stack:
        def __init__(self):
            self.stack = []

        def push(self, s):
            self.stack.append(s)

        def done(self):
            self.stack.pop()
    """
    assert "VMT115" not in rules_hit(src, path=OBS)


def test_len_guarded_reservoir_is_clean():
    # The reservoir idiom: growth gated on a capacity check.
    src = """
    class Reservoir:
        def __init__(self, cap):
            self.cap = cap
            self.samples = []

        def observe(self, v):
            if len(self.samples) < self.cap:
                self.samples.append(v)
    """
    assert "VMT115" not in rules_hit(src, path=OBS)


def test_import_time_table_building_is_clean():
    # Module-level accretion at import is static data, not per-event growth.
    src = """
    ROWS = []
    for i in range(4):
        ROWS.append(i)
    """
    assert "VMT115" not in rules_hit(src, path=OBS)


def test_unbounded_buffer_off_obs_plane_is_clean():
    # The rule is scoped to the telemetry planes; elsewhere other rules own
    # memory discipline.
    src = """
    class Collector:
        def __init__(self):
            self.events = []

        def record(self, e):
            self.events.append(e)
    """
    assert "VMT115" not in rules_hit(src)


# ----------------------------------------------------------------- VMT117
SERVE = "vilbert_multitask_tpu/serve/fake.py"  # on the serving plane


def test_replica_handle_stored_on_self_triggers():
    # The affinity pin: a checked-out handle surviving on the instance —
    # the pool can drain/swap/kill that replica and this engine reference
    # never hears about it.
    src = """
    class Dispatcher:
        def __init__(self, pool):
            self.pool = pool
            self.rep = pool.checkout()

        def dispatch(self, batch):
            return self.rep.engine.run_many(batch)
    """
    assert "VMT117" in rules_hit(src, path=SERVE)


def test_checkout_without_checkin_or_return_triggers():
    # The slot leak: checkout with no checkin and no handoff — the
    # replica's inflight budget never recovers.
    src = """
    def fire(pool, batch):
        rep = pool.checkout()
        return rep.engine.run_many(batch)
    """
    assert "VMT117" in rules_hit(src, path=SERVE)


def test_checkout_checkin_pair_is_clean():
    src = """
    def fire(pool, batch):
        rep = pool.checkout()
        try:
            out = rep.engine.run_many(batch)
        except Exception as e:
            pool.checkin(rep, ok=False, error=e)
            raise
        pool.checkin(rep, ok=True)
        return out
    """
    assert "VMT117" not in rules_hit(src, path=SERVE)


def test_seam_forwarding_helper_returning_handle_is_clean():
    # A helper may hand the handle to its caller (who owns the checkin) —
    # the scheduler's drain-aware checkout wrapper is this shape.
    src = """
    def checkout_with_drain(pool, stop):
        while not stop.is_set():
            try:
                return pool.checkout(timeout_s=0.05)
            except LookupError:
                continue
        raise LookupError("draining")
    """
    assert "VMT117" not in rules_hit(src, path=SERVE)


def test_replica_affinity_off_serve_plane_is_clean():
    # Scoped to serve/: bench/eval harnesses may hold an engine directly.
    src = """
    class Harness:
        def __init__(self, pool):
            self.rep = pool.checkout()
    """
    assert "VMT117" not in rules_hit(src)


def test_pool_module_itself_is_exempt():
    # pool.py implements the seam — its internals checkout/checkin across
    # method boundaries by design.
    src = """
    def run(self, req):
        rep = self.checkout()
        return rep.engine.run(req)
    """
    assert "VMT117" not in rules_hit(
        src, path="vilbert_multitask_tpu/serve/pool.py")


# ----------------------------------------------------------------- VMT118
def test_dequant_tree_outside_jit_triggers():
    # The footprint refund: widening the whole int8 tree eagerly on the
    # serve/boot plane recreates the fat copy int8 storage removed.
    src = """
    from vilbert_multitask_tpu import quant

    def boot(params, dtype):
        return quant.dequantize_tree(params, dtype)
    """
    assert "VMT118" in rules_hit(src)


def test_handrolled_dequant_outside_jit_triggers():
    src = """
    import jax.numpy as jnp

    def widen(pair):
        return pair["int8"].astype(jnp.float32) * pair["scale"]
    """
    assert "VMT118" in rules_hit(src)


def test_dequant_inside_jit_body_is_clean():
    # The serving contract: dequant fuses into the consuming matmul
    # inside the compiled program (engine/runtime.py _apply_heads).
    src = """
    import jax
    from vilbert_multitask_tpu import quant

    @jax.jit
    def fwd(params, batch):
        params = quant.dequantize_tree(params, "bfloat16")
        return params
    """
    assert "VMT118" not in rules_hit(src)


def test_dequant_in_method_referenced_from_jit_is_clean():
    # The bound-alias closure (engine = self; engine._apply_heads(...))
    # defeats the call graph; name-reference inside a jit body must count
    # as traced — this is runtime.py's actual shape.
    src = """
    from functools import partial

    import jax
    from vilbert_multitask_tpu import quant

    class Engine:
        def _apply_heads(self, params, batch):
            return quant.dequantize_tree(params, "bfloat16")

        def _forward(self):
            engine = self

            @jax.jit
            def fwd(params, batch):
                return engine._apply_heads(params, batch)

            return fwd
    """
    assert "VMT118" not in rules_hit(src)


def test_quant_module_itself_is_exempt():
    # dequantize_tree's own implementation calls dequantize_leaf per pair.
    src = """
    def dequantize_tree(params, dtype):
        return dequantize_leaf(params, dtype)
    """
    assert "VMT118" not in rules_hit(
        src, path="vilbert_multitask_tpu/quant.py")


# ----------------------------------------------------------------- VMT136
def test_exemplar_observe_with_request_derived_label_triggers():
    # An exemplar-carrying observe() whose label is derived from request
    # data mints one exemplar-bearing series per distinct value.
    src = """
    from vilbert_multitask_tpu import obs

    HIST = obs.REGISTRY.histogram("lat_ms", "latency", (1.0, 10.0))

    def record(rows, latency_ms, trace_id):
        n = len(rows)
        HIST.observe(latency_ms, exemplar_trace_id=trace_id, rows=n)
    """
    fs = [f for f in findings(src) if f.rule == "VMT136"]
    assert len(fs) == 1
    f = fs[0]
    assert "label `rows`" in f.message
    assert "bounded vocabulary" in f.message
    assert f.flows and f.flows[0][-1]["message"].startswith(
        "flows into label `rows`")


def test_exemplar_observe_with_param_label_triggers():
    src = """
    from vilbert_multitask_tpu import obs

    HIST = obs.REGISTRY.histogram("lat_ms", "latency", (1.0, 10.0))

    def record(latency_ms, trace_id, tenant):
        HIST.observe(latency_ms, exemplar_trace_id=trace_id, tenant=tenant)
    """
    assert "VMT136" in rules_hit(src)


def test_exemplar_observe_with_bounded_labels_is_clean():
    # Literal labels, and task ids routed through str() on the way to the
    # label (metrics.Metrics.record's actual shape), stay clean: the task
    # registry bounds the vocabulary, not the request.
    src = """
    from vilbert_multitask_tpu import obs

    HIST = obs.REGISTRY.histogram("lat_ms", "latency", (1.0, 10.0))

    def record(task_id, latency_ms, trace_id):
        HIST.observe(latency_ms, exemplar_trace_id=trace_id,
                     stage="forward", task=str(task_id))
    """
    assert "VMT136" not in rules_hit(src)


def test_exemplarless_observe_with_param_label_is_clean():
    # Without an exemplar the observe() is ordinary label traffic; other
    # rules own plain cardinality, VMT136 only guards exemplar slots.
    src = """
    from vilbert_multitask_tpu import obs

    HIST = obs.REGISTRY.histogram("lat_ms", "latency", (1.0, 10.0))

    def record(latency_ms, tenant):
        HIST.observe(latency_ms, tenant=tenant)
    """
    assert "VMT136" not in rules_hit(src)


# ----------------------------------------------- suppressions and baseline
def test_inline_suppression_by_id_name_and_next_line():
    base = """
    def helper(x):
        print("a")  # vmtlint: disable=VMT105
        print("b")  # vmtlint: disable=stray-print
        # vmtlint: disable-next-line=all
        print("c")
        print("d")
    """
    hits = [f for f in findings(base) if f.rule == "VMT105"]
    assert len(hits) == 1 and hits[0].content.startswith('print("d")')


def test_baseline_round_trip(tmp_path):
    src = "def helper():\n    print('x')\n"
    fs = analyze_source(src, LIB)
    assert fs
    path = str(tmp_path / "baseline.json")
    bl.write_baseline(path, fs, justification="legacy diagnostic")
    loaded = bl.load_baseline(path)
    new, old, stale = bl.split_baselined(analyze_source(src, LIB), loaded)
    assert not new and len(old) == len(fs) and not stale
    # Editing the flagged line invalidates the entry: the finding comes
    # back as new and the old entry reports stale.
    edited = "def helper():\n    print('x', 'y')\n"
    new2, old2, stale2 = bl.split_baselined(
        analyze_source(edited, LIB), loaded)
    assert new2 and not old2 and stale2


def test_baseline_rejects_malformed(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(ValueError):
        bl.load_baseline(str(p))


# ----------------------------------------------------------- config + CLI
def test_toml_subset_parser():
    text = textwrap.dedent("""
    [project]
    name = "x"  # comment

    [tool.vmtlint]
    paths = ["a", "b.py"]
    exclude = [
        "tests/fixtures",
        "gen",
    ]
    baseline = "base.json"
    fail_on = "warning"

    [tool.vmtlint.severity]
    VMT105 = "error"
    """)
    tables = parse_toml_tables(text)
    lint = tables["tool.vmtlint"]
    assert lint["paths"] == ["a", "b.py"]
    assert lint["exclude"] == ["tests/fixtures", "gen"]
    assert lint["baseline"] == "base.json"
    assert tables["tool.vmtlint.severity"]["VMT105"] == "error"


@pytest.fixture()
def lint_repo(tmp_path, monkeypatch):
    """A throwaway repo root: pyproject + one file per severity class."""
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
    [tool.vmtlint]
    paths = ["pkg"]
    library_roots = ["pkg"]
    baseline = "baseline.json"
    """))
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(textwrap.dedent("""
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        return np.asarray(x)

    def helper(x):
        print(x)
    """))
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_cli_exit_codes_and_json(lint_repo, capsys):
    assert cli_main([]) == 1  # error-severity finding present
    out = capsys.readouterr().out
    assert "VMT101" in out and "VMT105" in out

    assert cli_main(["--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["counts"]["error"] == 1 and doc["counts"]["warning"] == 1

    # Baseline everything -> clean non-strict AND strict runs.
    assert cli_main(["--write-baseline", "baseline.json"]) == 0
    capsys.readouterr()
    assert cli_main([]) == 0
    assert cli_main(["--strict"]) == 0

    # Fix the error; its baseline entry is now stale: non-strict passes,
    # strict demands the dead entry be removed.
    (lint_repo / "pkg" / "bad.py").write_text(
        "def helper(x):\n    print(x)  # vmtlint: disable=VMT105\n")
    capsys.readouterr()
    assert cli_main([]) == 0
    assert cli_main(["--strict"]) == 1


def test_cli_warning_only_gates_strict(tmp_path, monkeypatch, capsys):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.vmtlint]\npaths = [\"pkg\"]\nlibrary_roots = [\"pkg\"]\n")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "w.py").write_text("def h(x):\n    print(x)\n")
    monkeypatch.chdir(tmp_path)
    assert cli_main([]) == 0  # warnings don't fail the default gate
    assert cli_main(["--strict"]) == 1
    capsys.readouterr()


def test_cli_missing_path_is_usage_error(lint_repo, capsys):
    assert cli_main(["no/such/dir"]) == 2
    capsys.readouterr()


def test_syntax_error_reports_vmt000(tmp_path, monkeypatch, capsys):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.vmtlint]\npaths = [\"pkg\"]\n")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def f(:\n")
    monkeypatch.chdir(tmp_path)
    assert cli_main([]) == 1
    assert "VMT000" in capsys.readouterr().out
