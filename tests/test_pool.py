"""Replica-pool tests: health-gated routing, breaker-driven degradation and
recovery, kill→failover with exactly-one-terminal, rolling checkpoint swaps
under live load, poison-job quarantine, and crash-recovery redelivery.

Most tests run against fake engines (the pool only needs the dispatch
surface: run/run_many/warmup/live_stats plus the ``killed`` flag contract
from engine/runtime.py); the failover and crash-recovery integration tests
wrap the shared tiny real engine so the full worker pipeline runs.
"""

import dataclasses
import threading
import time

import pytest

from vilbert_multitask_tpu.config import ServingConfig
from vilbert_multitask_tpu.resilience import ReplicaKilled
from vilbert_multitask_tpu.serve import (
    DurableQueue,
    NoReadyReplica,
    PushHub,
    ReplicaPool,
    ResultStore,
    ServeWorker,
    make_job_message,
)
from vilbert_multitask_tpu.serve.pool import (
    STATE_DEAD,
    STATE_DEGRADED,
    STATE_READY,
)


class FakeEngine:
    """The dispatch surface the pool programs against, nothing else."""

    def __init__(self, service_s=0.0, fail_with=None):
        self.killed = False
        self.service_s = service_s
        self.fail_with = fail_with  # exception instance raised per call
        self.calls = 0
        self.loads = 0

    def _dispatch(self):
        if self.killed:
            raise ReplicaKilled("replica killed (chaos)")
        if self.fail_with is not None:
            raise self.fail_with
        if self.service_s:
            time.sleep(self.service_s)  # GIL-releasing, like a device wait
        self.calls += 1

    def run(self, req, **kwargs):
        self._dispatch()
        return ("ok", req)

    def run_many(self, reqs, on_result=None, **kwargs):
        self._dispatch()
        return [("ok", r) for r in reqs]

    def warmup(self, buckets=None, parallel=None):
        pass

    def live_stats(self):
        return {"fake_calls": float(self.calls)}

    def load_params(self, params):
        self.loads += 1


def make_pool(n=2, serving=None, **serving_overrides):
    serving = serving or ServingConfig(**serving_overrides)
    pool = ReplicaPool([FakeEngine() for _ in range(n)], serving=serving)
    pool.mark_ready()
    return pool


# ---------------------------------------------------------------- routing
def test_routing_skips_non_ready_replicas():
    pool = make_pool(3, pool_checkout_timeout_s=0.2)
    # r1 never becomes admissible while draining/booting-like.
    pool.replicas[1].state = "draining"
    names = set()
    for _ in range(6):
        rep = pool.checkout()
        names.add(rep.name)
        pool.checkin(rep, ok=True)
    assert names == {"r0", "r2"}


def test_checkout_is_least_loaded_and_caps_inflight():
    pool = make_pool(2, pool_max_inflight_per_replica=1,
                     pool_checkout_timeout_s=0.05)
    a = pool.checkout()
    b = pool.checkout()
    assert {a.name, b.name} == {"r0", "r1"}  # spread, not pile-up
    with pytest.raises(NoReadyReplica):  # both at the inflight cap
        pool.checkout(timeout_s=0.05)
    pool.checkin(a, ok=True)
    assert pool.checkout().name == a.name  # freed slot is admissible again
    pool.checkin(a, ok=True)
    pool.checkin(b, ok=True)


def test_checkout_times_out_when_nothing_ready():
    serving = ServingConfig()
    pool = ReplicaPool([FakeEngine()], serving=serving)  # still booting
    with pytest.raises(NoReadyReplica):
        pool.checkout(timeout_s=0.05)


# ------------------------------------------------- breaker-gated health
def test_breaker_open_degrades_then_half_open_probe_recovers():
    pool = make_pool(2, pool_breaker_failure_threshold=2,
                     pool_breaker_window_s=30.0,
                     pool_breaker_reset_timeout_s=0.05,
                     pool_checkout_timeout_s=0.5)
    flaky = pool.replicas[0]
    flaky.engine.fail_with = RuntimeError("transient device loss")
    # Drive failures onto r0 specifically (checkout is least-loaded, so
    # dispatching through run() could land either side).
    for _ in range(2):
        rep = pool.checkout()
        while rep.name != "r0":
            pool.checkin(rep, ok=True)
            rep = pool.checkout()
        pool.checkin(rep, ok=False, error=RuntimeError("boom"))
    assert flaky.state == STATE_DEGRADED
    assert flaky.breaker.state == "open"
    # While open, checkout never routes to the degraded replica.
    for _ in range(4):
        rep = pool.checkout()
        assert rep.name == "r1"
        pool.checkin(rep, ok=True)
    # After the reset timeout the breaker half-opens: the next checkout IS
    # the recovery probe, and its success flips the replica back to ready.
    flaky.engine.fail_with = None
    deadline = time.monotonic() + 2.0
    while flaky.breaker.state != "half_open":
        assert time.monotonic() < deadline, "breaker never half-opened"
        time.sleep(0.01)
    out = pool.run("probe-req")
    assert out[0] == "ok"
    assert flaky.state == STATE_READY
    assert flaky.breaker.state == "closed"


def test_kill_is_silent_until_dispatch_then_fails_over():
    """kill() must NOT un-route the replica — the next dispatch has to hit
    the corpse and fail over, like a real silent hardware loss."""
    from vilbert_multitask_tpu.serve.pool import ReplicaFailover

    pool = make_pool(2, pool_checkout_timeout_s=0.5)
    pool.kill("r0")
    assert pool.replicas[0].state == STATE_READY  # not discovered yet
    failovers = 0
    served = 0
    for i in range(6):
        try:
            pool.run(i)
            served += 1
        except ReplicaFailover as e:
            assert e.replica == "r0"
            failovers += 1
    assert failovers == 1  # exactly one dispatch died discovering the kill
    assert served == 5
    assert pool.replicas[0].state == STATE_DEAD
    assert pool.replicas[1].engine.calls == 5


def test_probe_discovers_kill_without_dispatch():
    pool = make_pool(2)
    pool.kill("r1")
    sample = pool.probe()
    assert pool.replicas[1].state == STATE_DEAD
    assert sample["replica_r1_state"] == 5.0
    assert sample["pool_dead_replicas"] == 1.0
    assert sample["pool_ready_replicas"] == 1.0
    # /healthz payload: the dead replica is visible per-replica.
    info = {r["name"]: r for r in pool.replicas_info()}
    assert info["r1"]["state"] == STATE_DEAD


# ----------------------------------------------------------- rolling swap
def test_rolling_swap_updates_all_replicas_never_zero_ready():
    pool = make_pool(2, pool_swap_drain_timeout_s=2.0)
    ready_during_load = []

    def load(engine):
        ready_during_load.append(pool.ready_count())
        engine.load_params({"v": 2})

    report = pool.rolling_swap(load)
    assert [r["name"] for r in report["replicas"]] == ["r0", "r1"]
    assert report["min_ready_seen"] >= 1
    assert all(n >= 1 for n in ready_during_load)
    assert all(r.engine.loads == 1 for r in pool.replicas)
    assert all(r.swaps == 1 for r in pool.replicas)
    assert pool.ready_count() == 2


def test_rolling_swap_skips_dead_replicas():
    pool = make_pool(3, pool_swap_drain_timeout_s=2.0,
                     pool_checkout_timeout_s=0.5)
    pool.kill("r1")
    pool.probe()  # discover the corpse
    report = pool.rolling_swap(lambda eng: eng.load_params({}))
    assert report["skipped"] == ["r1"]
    assert [r["name"] for r in report["replicas"]] == ["r0", "r2"]


def test_rolling_swap_under_live_load_loses_no_requests():
    """The acceptance invariant: swap while dispatches are in flight — every
    request completes (no NoReadyReplica, no failure) and at least one
    replica stays ready throughout."""
    pool = make_pool(2, serving=ServingConfig(
        pool_checkout_timeout_s=10.0, pool_swap_drain_timeout_s=10.0))
    for rep in pool.replicas:
        rep.engine.service_s = 0.002
    stop = threading.Event()
    outcomes = {"ok": 0, "errors": []}
    lock = threading.Lock()

    def hammer():
        while not stop.is_set():
            try:
                pool.run("req")
            except Exception as e:  # noqa: BLE001 — the assertion target
                with lock:
                    outcomes["errors"].append(repr(e))
                return
            with lock:
                outcomes["ok"] += 1

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # load established before the swap starts
    report = pool.rolling_swap(lambda eng: eng.load_params({"v": 2}))
    time.sleep(0.05)  # and keeps flowing after
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert outcomes["errors"] == []
    assert outcomes["ok"] > 0
    assert report["min_ready_seen"] >= 1
    assert all(r.swaps == 1 for r in pool.replicas)
    assert pool.ready_count() == 2


# ------------------------------------------------------ poison quarantine
def test_delivery_count_dead_letters_released_jobs(tmp_path):
    """release() charges no attempt — delivery_count must still bound a job
    that fails over forever (the reference's redeliver-forever loop)."""
    q = DurableQueue(str(tmp_path / "q.sqlite3"), max_deliveries=2)
    q.publish({"poison": True})
    for _ in range(2):
        job = q.claim()
        assert job is not None
        q.release(job.id)  # failover path: no attempt charged
    assert q.claim() is None  # quarantined despite attempts == 0
    dead = q.dead_jobs()
    assert len(dead) == 1 and dead[0].body == {"poison": True}
    assert dead[0].attempts == 0 and dead[0].deliveries == 2


def test_poison_quarantine_notifies_client_exactly_once(tmp_path):
    serving = ServingConfig()
    hub = PushHub()
    sub = hub.subscribe("sockP")
    q = DurableQueue(str(tmp_path / "q.sqlite3"), max_deliveries=1)
    store = ResultStore(str(tmp_path / "r.sqlite3"))
    worker_a = ServeWorker(FakeEngine(), q, store, hub, serving)
    worker_b = ServeWorker(FakeEngine(), q, store, hub, serving)
    q.publish(make_job_message(["img_a.jpg"], "poison?", 1, "sockP"))
    q.release(q.claim().id)  # one delivery burned via failover
    assert q.claim() is None  # sweep quarantines it
    # Both workers poll; the dead_notified column hands the terminal frame
    # to exactly one of them.
    worker_a._notify_dead_letters()
    worker_b._notify_dead_letters()
    frames = []
    while not sub.empty():
        frames.append(sub.get_nowait())
    dead_frames = [f for f in frames if f.get("dead_letter")]
    assert len(dead_frames) == 1
    assert "delivered 1 times" in dead_frames[0]["terminal"]
    assert dead_frames[0]["question"] == "poison?"


def test_abandon_inflight_stamps_replica_provenance(tmp_path):
    serving = ServingConfig()
    hub = PushHub()
    sub = hub.subscribe("sockD")
    q = DurableQueue(str(tmp_path / "q.sqlite3"))
    store = ResultStore(str(tmp_path / "r.sqlite3"))
    eng = FakeEngine()
    eng.replica_id = "r7"
    worker = ServeWorker(eng, q, store, hub, serving)
    q.publish(make_job_message(["img_a.jpg"], "q", 1, "sockD"))
    assert worker._claim() is not None
    assert worker.abandon_inflight() == 1
    frame = sub.get_nowait()
    assert frame["requeued"] is True
    assert frame["abandoned_by"] == "r7"
    # Released, not charged: the job is claimable again at attempt 1.
    again = q.claim()
    assert again is not None and again.attempts == 1


# ------------------------------------- integration: worker over the pool
class WrapEngine:
    """A killable replica that delegates real inference to the shared tiny
    engine — so the full worker pipeline (intake → batch forward → persist
    → push) runs while chaos stays per-replica."""

    def __init__(self, host, name):
        self._host = host
        self.replica_id = name
        self.killed = False
        self.cfg = host.cfg
        self.calls = 0

    def _gate(self):
        if self.killed:
            raise ReplicaKilled(f"replica {self.replica_id} killed (chaos)")

    def run(self, req, **kwargs):
        self._gate()
        self.calls += 1
        return self._host.run(req, **kwargs)

    def run_many(self, reqs, on_result=None, **kwargs):
        self._gate()
        self.calls += 1
        return self._host.run_many(reqs, on_result=on_result, **kwargs)

    def prepare(self, *args, **kwargs):
        return self._host.prepare(*args, **kwargs)

    def prepare_from_store(self, *args, **kwargs):
        return self._host.prepare_from_store(*args, **kwargs)

    def chunk_plan(self, *args, **kwargs):
        return self._host.chunk_plan(*args, **kwargs)

    def decode(self, *args, **kwargs):
        return self._host.decode(*args, **kwargs)

    def warmup(self, buckets=None, parallel=None):
        pass

    def live_stats(self):
        return {}

    @property
    def input_cache_stats(self):
        return self._host.input_cache_stats

    @property
    def stage_times(self):
        return self._host.stage_times

    @property
    def mesh(self):
        return self._host.mesh


@pytest.fixture()
def pool_stack(tiny_framework_cfg, engine, tmp_path):
    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        media_root=str(tmp_path / "media"),
        pool_replicas=2,
        pool_checkout_timeout_s=2.0,
    )
    pool = ReplicaPool(
        [WrapEngine(engine, "r0"), WrapEngine(engine, "r1")], serving=s)
    pool.mark_ready()
    hub = PushHub()
    q = DurableQueue(s.queue_db_path,
                     max_delivery_attempts=s.max_delivery_attempts,
                     max_deliveries=s.queue_max_deliveries)
    store = ResultStore(s.results_db_path)
    worker = ServeWorker(pool, q, store, hub, s)
    return s, hub, q, store, worker, pool


def _drain_frames(sub):
    frames = []
    while not sub.empty():
        frames.append(sub.get_nowait())
    return frames


def test_replica_kill_fails_over_with_exactly_one_terminal(pool_stack):
    """The chaos acceptance path: a batch lands on a silently-killed
    replica, every member is released (no attempt charged), redelivery runs
    them on the survivor, and each job ends with exactly one result."""
    s, hub, q, store, worker, pool = pool_stack
    subs = {f"sock{i}": hub.subscribe(f"sock{i}") for i in range(2)}
    for i in range(2):
        q.publish(make_job_message(["img_a.jpg"], f"q{i}", 1, f"sock{i}"))
    pool.kill("r0")
    # Batches pin to one replica; least-loaded checkout sends the first
    # batch to the corpse → ReplicaFailover → release (attempt un-charged).
    deadline = time.monotonic() + 60.0
    while q.counts() and time.monotonic() < deadline:
        worker.step_batch()
    assert q.counts() == {}, "jobs left behind after failover"
    for name, sub in subs.items():
        frames = _drain_frames(sub)
        results = [f for f in frames if "result" in f]
        assert len(results) == 1, (name, frames)  # exactly-one-terminal
        requeued = [f for f in frames if f.get("requeued")]
        assert all(f["replica"] == "r0" for f in requeued)
    assert pool.replicas[0].state == STATE_DEAD
    assert pool.replicas[1].engine.calls >= 1
    assert pool.replicas[0].failovers >= 1
    # No delivery attempt was charged for the failed-over landing.
    info = {r["name"]: r for r in pool.replicas_info()}
    assert info["r0"]["failures"] >= 1


def test_crash_recovery_via_visibility_timeout(tiny_framework_cfg, engine,
                                               tmp_path):
    """Worker A claims mid-batch and dies before ack; the visibility
    timeout redelivers to worker B, which completes each job exactly
    once."""
    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        media_root=str(tmp_path / "media"),
    )
    hub = PushHub()
    sub = hub.subscribe("sockC")
    q = DurableQueue(s.queue_db_path, visibility_timeout_s=0.05)
    store = ResultStore(s.results_db_path)
    for i in range(2):
        q.publish(make_job_message(["img_a.jpg"], f"q{i}", 1, "sockC"))
    # Worker A: claims both jobs "mid-batch", then the process dies — no
    # ack, no nack, no release.
    assert q.claim() is not None
    assert q.claim() is not None
    assert q.claim() is None  # nothing deliverable while claims are live
    time.sleep(0.06)  # visibility timeout lapses
    worker_b = ServeWorker(engine, q, store, hub, s)
    deadline = time.monotonic() + 60.0
    while q.counts() and time.monotonic() < deadline:
        worker_b.step_batch()
    assert q.counts() == {}
    frames = _drain_frames(sub)
    results = [f for f in frames if "result" in f]
    assert len(results) == 2  # one terminal per job, despite redelivery
    questions = {f["result"]["question"] for f in results}
    assert questions == {"q0", "q1"}


# ------------------------------------------------- retire (scale-in path)
def test_retire_unnamed_picks_least_loaded_ready():
    pool = make_pool(3)
    # r1 is busiest, r2 has history; r0 is the cheapest to drain.
    pool.replicas[1].inflight = 2
    pool.replicas[2].dispatches = 5
    info = pool.retire_replica()
    assert info["name"] == "r0"
    assert [r.name for r in pool.replicas] == ["r1", "r2"]


def test_retire_withdraws_state_gauge_and_healthz_block():
    from vilbert_multitask_tpu import obs

    pool = make_pool(2)
    pool.probe()  # publish both series
    assert obs.REPLICA_STATE.value(replica="r1") is not None
    pool.retire_replica("r1")
    # No ghost replica: the gauge series is gone and stays gone through
    # the next probe sweep (which only walks surviving replicas).
    assert obs.REPLICA_STATE.value(replica="r1") is None
    pool.probe()
    assert obs.REPLICA_STATE.value(replica="r1") is None
    assert [r["name"] for r in pool.replicas_info()] == ["r0"]


def test_retire_refuses_below_min_replicas():
    pool = make_pool(2, autoscale_min_replicas=2)
    with pytest.raises(ValueError, match="autoscale_min_replicas"):
        pool.retire_replica()
    assert len(pool.replicas) == 2


def test_retire_refuses_last_ready_replica():
    pool = make_pool(2)
    pool.replicas[1].state = STATE_DEGRADED
    with pytest.raises(ValueError, match="last READY"):
        pool.retire_replica("r0")
    assert len(pool.replicas) == 2


def test_retire_waits_for_inflight_drain():
    pool = make_pool(2, pool_checkout_timeout_s=1.0)
    rep = pool.checkout()  # one dispatch in flight on some replica
    victim = rep.name
    done = []

    def finish():
        time.sleep(0.1)
        pool.checkin(rep, ok=True)
        done.append(True)

    threading.Thread(target=finish, daemon=True).start()
    info = pool.retire_replica(victim, drain_timeout_s=5.0)
    assert done  # the retire blocked until the in-flight call finished
    assert info["name"] == victim
    assert victim not in {r.name for r in pool.replicas}


def test_retire_drain_timeout_restores_replica():
    pool = make_pool(2)
    rep = pool.replicas[0]
    rep.inflight = 1  # a dispatch that never finishes
    with pytest.raises(TimeoutError):
        pool.retire_replica("r0", drain_timeout_s=0.1)
    # Abandoned retirement, not a stranded replica: back in rotation.
    assert rep.state == STATE_READY
    assert len(pool.replicas) == 2


def test_add_then_retire_roundtrip_keeps_pool_consistent():
    pool = make_pool(1)
    pool.add_replica(FakeEngine(), warm=True)
    assert pool.ready_count() == 2
    info = pool.retire_replica()
    assert pool.ready_count() == 1
    assert info["name"] not in {r.name for r in pool.replicas}
