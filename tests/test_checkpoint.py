"""Checkpoint conversion tests.

The real ``pytorch_model_9.bin`` is not vendored (neither in the reference —
SURVEY.md §0), so fidelity is proven structurally: the torch↔flax name map
must cover every param leaf of the model, and converting a synthesized torch
state dict back and forth must be lossless bit-for-bit.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vilbert_multitask_tpu.checkpoint import (
    build_name_map,
    convert_torch_state_dict,
    load_torch_checkpoint,
    restore_params,
    save_params,
    to_torch_state_dict,
)
from vilbert_multitask_tpu.config import ViLBertConfig
from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks


def _init_params(cfg):
    model = ViLBertForVLTasks(cfg, dtype=jnp.float32)
    B, Nt, Nv = 2, 8, 5
    return model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((B, Nt), jnp.int32),
        jnp.zeros((B, Nv, cfg.v_feature_size), jnp.float32),
        jnp.zeros((B, Nv, 5), jnp.float32),
        jnp.zeros((B, Nt), jnp.int32),
        jnp.ones((B, Nt), jnp.int32),
        jnp.ones((B, Nv), jnp.int32),
        None,
        jnp.ones((B, 1), jnp.int32),
        deterministic=True,
    )["params"]


@pytest.fixture(scope="module")
def tiny_cfg():
    return ViLBertConfig().tiny()


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return _init_params(tiny_cfg)


def _flat_paths(tree, prefix=()):
    for k, v in tree.items():
        if isinstance(v, dict):
            yield from _flat_paths(v, prefix + (k,))
        else:
            yield prefix + (k,), v


def test_name_map_covers_every_param_leaf(tiny_cfg, tiny_params):
    mapped = {path for path, _ in build_name_map(tiny_cfg)}
    actual = {p for p, _ in _flat_paths(tiny_params)}
    missing = actual - mapped
    extra = mapped - actual
    assert not missing, f"param leaves without torch mapping: {sorted(missing)[:8]}"
    assert not extra, f"mapped paths not in the model: {sorted(extra)[:8]}"


def test_torch_roundtrip_lossless(tiny_cfg, tiny_params):
    sd = to_torch_state_dict(tiny_params, tiny_cfg)
    report = {}
    back = convert_torch_state_dict(sd, tiny_cfg, strict=True, report=report)
    flat_a = dict(_flat_paths(tiny_params))
    flat_b = dict(_flat_paths(back))
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(np.asarray(flat_a[k]), flat_b[k],
                                      err_msg=str(k))
    # The only torch key not consumed on the way back is the tied decoder.
    assert report["unmapped"] == ["cls.predictions.decoder.weight"]
    assert report["missing"] == []


def test_convert_int8_quantizes_after_f32_pack(tiny_cfg, tiny_params):
    """dtype="int8" conversion packs every leaf at full f32 precision FIRST
    and only then quantizes the finished tree — so dequantizing lands
    within half a quantization step of the f32 conversion everywhere (a
    raw ``np.asarray(x, "int8")`` leaf cast would truncate real weights to
    garbage)."""
    from vilbert_multitask_tpu import quant

    sd = to_torch_state_dict(tiny_params, tiny_cfg)
    q = convert_torch_state_dict(sd, tiny_cfg, dtype="int8")
    assert quant.tree_is_quantized(q)
    back = quant.dequantize_tree(q, np.float32)
    flat_a = dict(_flat_paths(tiny_params))
    flat_b = dict(_flat_paths(back))
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        a = np.asarray(flat_a[k], np.float32)
        if a.ndim >= 2:
            amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)))
            assert np.all(np.abs(flat_b[k] - a) <= amax / 254.0 + 1e-7), k
        else:  # vectors stay full precision, bit-exact
            np.testing.assert_array_equal(a, flat_b[k], err_msg=str(k))
    with pytest.raises(ValueError):
        convert_torch_state_dict(sd, tiny_cfg, dtype="int16")


def test_converted_params_run_and_match(tiny_cfg, tiny_params):
    """Converted tree drives the model to the same logits as the original."""
    model = ViLBertForVLTasks(tiny_cfg, dtype=jnp.float32)
    back = convert_torch_state_dict(
        to_torch_state_dict(tiny_params, tiny_cfg), tiny_cfg)
    B, Nt, Nv = 2, 8, 5
    rng = np.random.default_rng(1)
    args = (
        jnp.asarray(rng.integers(0, tiny_cfg.vocab_size, (B, Nt)), jnp.int32),
        jnp.asarray(rng.normal(size=(B, Nv, tiny_cfg.v_feature_size)),
                    jnp.float32),
        jnp.asarray(rng.random((B, Nv, 5)), jnp.float32),
        jnp.zeros((B, Nt), jnp.int32),
        jnp.ones((B, Nt), jnp.int32),
        jnp.ones((B, Nv), jnp.int32),
        None,
        jnp.ones((B, 1), jnp.int32),
    )
    out_a = model.apply({"params": tiny_params}, *args, deterministic=True)
    out_b = model.apply({"params": back}, *args, deterministic=True)
    np.testing.assert_allclose(out_a.vil_prediction, out_b.vil_prediction,
                               atol=1e-6)
    np.testing.assert_allclose(out_a.vision_logit, out_b.vision_logit,
                               atol=1e-6)


def test_load_real_torch_bin(tmp_path, tiny_cfg, tiny_params):
    """End-to-end through an actual torch-serialized .bin file."""
    torch = pytest.importorskip("torch")
    sd = {k: torch.from_numpy(np.asarray(v))
          for k, v in to_torch_state_dict(tiny_params, tiny_cfg).items()}
    # the reference checkpoint carries DataParallel-style 'module.' prefixes
    sd = {f"module.{k}": v for k, v in sd.items()}
    path = os.path.join(tmp_path, "pytorch_model_9.bin")
    torch.save(sd, path)
    params = load_torch_checkpoint(path, tiny_cfg)
    flat_a = dict(_flat_paths(tiny_params))
    for k, v in _flat_paths(params):
        np.testing.assert_array_equal(np.asarray(flat_a[k]), v, err_msg=str(k))


def test_orbax_roundtrip(tmp_path, tiny_params):
    path = os.path.join(tmp_path, "ckpt")
    save_params(path, tiny_params)
    restored = restore_params(path)
    flat_a = dict(_flat_paths(tiny_params))
    flat_b = dict(_flat_paths(restored))
    assert flat_a.keys() == flat_b.keys()
    for k in flat_a:
        np.testing.assert_array_equal(np.asarray(flat_a[k]), flat_b[k])
