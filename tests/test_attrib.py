"""Cost-attribution plane unit tests: amortization math (double-entry
conservation, mid-batch failure waste), windowed aggregates, the
module-plane disabled-mode overhead guard, tail-sampling keep policy and
durability of the trace store, histogram exemplars, and the OpenMetrics
golden rendering."""

import json
import time

from vilbert_multitask_tpu.obs import (
    OPENMETRICS_CONTENT_TYPE,
    CostAttributor,
    JobCost,
    Registry,
    Tracer,
    TraceStore,
    get_attributor,
    job_batch,
    job_begin,
    job_charge,
    job_finish,
    render_openmetrics,
    set_attributor,
)


# ----------------------------------------------------------- attrib math
def test_stage_charges_accumulate_and_negative_clamps():
    a = CostAttributor()
    a.begin("t1", job_id=7, task="vqa", tenant="acme")
    a.charge("t1", "intake", 0.010)
    a.charge("t1", "intake", 0.005)
    a.charge("t1", "decode", -3.0)  # clock skew never goes negative
    cost = a.finish("t1", "ok")
    assert cost is not None
    assert cost.job_id == 7 and cost.task == "vqa" and cost.tenant == "acme"
    assert cost.stages["intake"] == 15.0
    assert cost.stages["decode"] == 0.0
    assert cost.verdict == "ok"
    assert cost.total_ms() == 15.0
    # Closed records stay readable from the done ring.
    assert a.get("t1") is cost
    # Unknown stages/traces are inert, not errors.
    a.charge("nope", "intake", 1.0)
    assert a.finish("nope", "ok") is None


def test_batch_amortization_mixed_rows_conserves_exactly():
    a = CostAttributor()
    a.begin("big", task="vqa")
    a.begin("small", task="retrieval")
    a.charge_batch(2.0, [("big", 3), ("small", 1)], batch_rows=4,
                   bucket=4, replica="rep0")
    big, small = a.get("big"), a.get("small")
    assert big.device_s == 1.5 and small.device_s == 0.5
    assert big.stages["forward"] == 1500.0
    assert big.bucket == "4" and big.replica == "rep0"
    assert big.member_rows == 3 and big.batch_rows == 4
    # Every member streamed: the two ledgers agree exactly.
    cons = a.conservation()
    assert cons == {"busy_s": 2.0, "attributed_s": 2.0, "ratio": 1.0}


def test_mid_batch_failure_charges_streamed_only():
    a = CostAttributor()
    a.begin("ok1", task="vqa")
    a.begin("dead1", task="vqa")
    # Only the streamed member is listed; the dead one's share stays on
    # the busy ledger as visible waste.
    a.charge_batch(1.0, [("ok1", 1)], batch_rows=4)
    assert a.get("ok1").device_s == 0.25
    assert a.get("dead1").device_s == 0.0
    cons = a.conservation()
    assert cons["busy_s"] == 1.0 and cons["attributed_s"] == 0.25
    assert cons["ratio"] == 0.25


def test_empty_ledgers_report_ratio_one():
    # No dispatches yet must read as "conserved", not divide-by-zero.
    assert CostAttributor().conservation()["ratio"] == 1.0


def test_window_groups_by_tenant_and_task():
    a = CostAttributor()
    for tid, task, tenant, verdict in (
            ("a", "vqa", "acme", "ok"), ("b", "vqa", "acme", "ok"),
            ("c", "retrieval", "zed", "dead_letter")):
        a.begin(tid, task=task, tenant=tenant)
        a.charge(tid, "intake", 0.001)
        a.finish(tid, verdict)
    by_tenant = a.window(by="tenant")
    assert by_tenant["by"] == "tenant"
    assert by_tenant["groups"]["acme"]["jobs"] == 2
    assert by_tenant["groups"]["zed"]["verdicts"] == {"dead_letter": 1}
    by_task = a.window(by="task")
    assert by_task["groups"]["vqa"]["stage_ms"]["intake"] == 2.0
    assert "conservation" in by_task
    # A window in the future excludes everything already finished.
    assert a.window(window_s=-60.0)["groups"] == {}


def test_open_records_bounded_oldest_evicted():
    a = CostAttributor(max_open=2)
    a.begin("t1")
    a.begin("t2")
    a.begin("t3")  # evicts t1
    assert a.get("t1") is None
    assert a.get("t2") is not None and a.get("t3") is not None


def test_on_finish_hook_errors_never_break_finish():
    def boom(cost):
        raise RuntimeError("store down")
    a = CostAttributor(on_finish=boom)
    a.begin("t1", task="vqa")
    assert a.finish("t1", "ok") is not None
    assert a.finished == 1


# ------------------------------------------------------- module-level plane
def test_module_plane_routes_to_installed_attributor():
    a = CostAttributor()
    set_attributor(a)
    try:
        assert get_attributor() is a
        job_begin("t1", job_id=1, task="vqa", tenant="acme")
        job_charge("t1", "intake", 0.002)
        job_batch(1.0, [("t1", 2)], batch_rows=2, bucket=2)
        job_finish("t1", "ok")
    finally:
        set_attributor(None)
    (cost,) = a.completed()
    assert cost.stages["intake"] == 2.0 and cost.device_s == 1.0


def test_attrib_disabled_mode_overhead_under_5us():
    """The job_* helpers are a single None-check when attribution is off —
    same tier-1 guard as the tracer/recorder disabled modes."""
    set_attributor(None)
    n = 10_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            job_begin("t", task="vqa")
            job_charge("t", "intake", 0.001)
            job_finish("t", "ok")
        best = min(best, (time.perf_counter() - t0) / (3 * n))
    assert best < 5e-6, f"disabled job_* call costs {best * 1e6:.2f} us"


# ------------------------------------------------------------- trace store
class _Rng:
    """Deterministic sampler: pops scripted values."""

    def __init__(self, values):
        self.values = list(values)

    def random(self):
        return self.values.pop(0)


def _cost(trace_id, *, task="vqa", tenant="acme", verdict="ok", ms=10.0):
    c = JobCost(trace_id=trace_id, task=task, tenant=tenant,
                verdict=verdict)
    c.stages["forward"] = ms
    c.finished_unix = time.time()
    return c


def test_keep_policy_verdict_pinned_topk_sampled(tmp_path):
    store = TraceStore(str(tmp_path / "spine.db"), "w0", keep_top_k=1,
                       sample_rate=0.5, rng=_Rng([0.9, 0.1]))
    # 1) non-ok verdicts always keep
    assert store.offer(_cost("t-dead", verdict="dead_letter")) == "verdict"
    # 2) pinned SLO offenders keep even when the sampler would drop them
    store.pin(["t-pin"])
    assert store.offer(_cost("t-pin", ms=1.0)) == "pinned"
    # 3) first ok completion seeds the per-task top-K
    assert store.offer(_cost("t-slow", ms=50.0)) == "slow"
    # 4) faster than the slot floor + rng 0.9 >= 0.5 -> dropped
    assert store.offer(_cost("t-fast", ms=2.0)) is None
    # 5) faster + rng 0.1 < 0.5 -> p-sampled
    assert store.offer(_cost("t-luck", ms=2.0)) == "sampled"
    # 6) slower than the floor displaces the top-K slot
    assert store.offer(_cost("t-slower", ms=80.0)) == "slow"
    assert store.stats()["offered"] == 6 and store.stats()["kept"] == 5
    assert store.stats()["tail_kept_frac"] == round(5 / 6, 4)


def test_flush_persists_retention_trims_and_survives_reopen(tmp_path):
    path = str(tmp_path / "spine.db")
    store = TraceStore(path, "w0", retention_s=3600.0)
    tr = Tracer()
    with tr.span("forward"):
        pass
    (span,) = tr.spans()
    cost = _cost(span.trace_id, verdict="dead_letter", ms=25.0)
    assert store.offer(cost, tr.spans()) == "verdict"
    assert store.stats()["pending"] == 1
    assert store.flush() == 1
    assert store.stats()["pending"] == 0

    row = store.get(span.trace_id)
    assert row["verdict"] == "dead_letter" and row["ident"] == "w0"
    assert row["cost"]["total_ms"] == 25.0
    assert [s["name"] for s in row["spans"]] == ["forward"]

    # Durable across process restarts: a fresh handle reads the row.
    reopened = TraceStore(path, "w1")
    assert reopened.get(span.trace_id)["ident"] == "w0"

    # Retention: a zero-retention flush trims everything already stored.
    expiring = TraceStore(path, "w1", retention_s=0.0)
    expiring.flush()
    assert store.get(span.trace_id) is None


def test_offer_filters_spans_to_the_job_trace(tmp_path):
    store = TraceStore(str(tmp_path / "spine.db"), "w0")
    tr = Tracer()
    with tr.span("mine"):
        pass
    with tr.span("other-jobs"):
        pass
    mine, other = tr.spans()
    store.offer(_cost(mine.trace_id, verdict="deadline"), tr.spans())
    store.flush()
    row = store.get(mine.trace_id)
    assert [s["name"] for s in row["spans"]] == ["mine"]
    assert other.trace_id != mine.trace_id


def test_list_scope_local_vs_fleet_and_filters(tmp_path):
    path = str(tmp_path / "spine.db")
    w0 = TraceStore(path, "w0")
    w1 = TraceStore(path, "w1")
    w0.offer(_cost("t-w0", task="vqa", verdict="dead_letter"))
    w1.offer(_cost("t-w1", task="retrieval", tenant="zed",
                   verdict="deadline"))
    w0.flush()
    w1.flush()
    # Fleet scope reads every ident on disk (dead peers included — the
    # span-retention contract); local restricts to this process.
    assert {r["ident"] for r in w0.list(scope="fleet")} == {"w0", "w1"}
    assert {r["ident"] for r in w0.list(scope="local")} == {"w0"}
    assert [r["trace_id"] for r in w0.list(task="retrieval")] == ["t-w1"]
    assert [r["trace_id"] for r in w0.list(tenant="zed")] == ["t-w1"]
    assert [r["trace_id"]
            for r in w0.list(verdict="dead_letter")] == ["t-w0"]


def test_list_verdict_slow_matches_keep_reason(tmp_path):
    store = TraceStore(str(tmp_path / "spine.db"), "w0", keep_top_k=1)
    assert store.offer(_cost("t-slow", ms=90.0)) == "slow"
    store.flush()
    (row,) = store.list(verdict="slow")
    assert row["trace_id"] == "t-slow"
    assert row["keep_reason"] == "slow" and row["verdict"] == "ok"


# ---------------------------------------------------- exemplars + openmetrics
def test_histogram_exemplars_newest_wins_and_slowest():
    reg = Registry()
    hist = reg.histogram("lat_ms", "latency", ("task",),
                         buckets=(10.0, 100.0))
    hist.observe(5.0, exemplar_trace_id="aaa", task="vqa")
    hist.observe(7.0, exemplar_trace_id="bbb", task="vqa")  # same bucket
    hist.observe(50.0, exemplar_trace_id="ccc", task="vqa")
    hist.observe(3.0, task="vqa")  # exemplar-less: slot untouched
    ex = hist.collect_exemplars()[("vqa",)]
    assert ex[0][:2] == (7.0, "bbb")  # newest wins within the bucket
    assert ex[1][:2] == (50.0, "ccc")
    assert hist.slowest_exemplars(2) == [(50.0, "ccc"), (7.0, "bbb")]


def test_openmetrics_golden():
    reg = Registry()
    c = reg.counter("vmt_jobs_total", "Jobs.", ("task",))
    c.inc(3, task="vqa")
    hist = reg.histogram("lat_ms", "latency", ("task",), buckets=(10.0,))
    hist.observe(5.0, exemplar_trace_id="abc123", task="vqa")
    text = render_openmetrics(reg)
    lines = text.splitlines()
    # Counter family drops _total; the sample line keeps it.
    assert "# TYPE vmt_jobs counter" in lines
    assert 'vmt_jobs_total{task="vqa"} 3' in lines
    # Bucket line carries its exemplar: # {trace_id="..."} value ts
    (bucket_line,) = [l for l in lines if l.startswith("lat_ms_bucket")
                      and 'le="10"' in l]
    assert '# {trace_id="abc123"} 5' in bucket_line
    assert 'lat_ms_sum{task="vqa"} 5' in lines
    assert 'lat_ms_count{task="vqa"} 1' in lines
    # Spec terminator + the content type the handler advertises.
    assert text.endswith("# EOF\n")
    assert "openmetrics-text" in OPENMETRICS_CONTENT_TYPE


def test_openmetrics_histogram_without_exemplar_has_plain_buckets():
    reg = Registry()
    hist = reg.histogram("lat_ms", "latency", buckets=(10.0,))
    hist.observe(5.0)
    text = render_openmetrics(reg)
    bucket_lines = [l for l in text.splitlines()
                    if l.startswith("lat_ms_bucket")]
    assert bucket_lines and all("#" not in l for l in bucket_lines)


def test_job_cost_as_dict_round_trips_json():
    cost = _cost("t1", ms=12.5)
    doc = json.loads(json.dumps(cost.as_dict()))
    assert doc["trace_id"] == "t1" and doc["total_ms"] == 12.5
    assert doc["stages"] == {"forward": 12.5}
