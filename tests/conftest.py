"""Test harness: force an 8-device virtual CPU mesh so sharding tests run
anywhere (the standard JAX fake-backend trick; see SURVEY.md §4).

Note: this environment's sitecustomize registers a TPU PJRT plugin in every
Python process; selecting it costs a ~2-minute remote handshake. Tests must
never touch it, so we pin the platform to CPU *before any backend init* —
``jax.config.update`` works post-import as long as ``jax.devices()`` hasn't
been called yet, and XLA_FLAGS is read at first backend init.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402

# ------------------------------------------------------- fast/slow profiles
# The full suite is compile-bound (~40-75 min on this 1-core box) — slow
# enough that nobody runs it mid-edit, which is how regressions slip in
# (VERDICT r4 weak-5). The compile-heaviest tests (>= ~20 s measured call
# time, mostly mesh-sharded trainer loops and multi-bucket warmups) carry
# the ``slow`` marker so
#
#     pytest -m "not slow"     # fast profile, <10 min — the edit loop
#     pytest                   # full suite — round boundaries / CI
#
# Centralized here (not per-file decorators) so the list is one reviewable
# block; the collection hook FAILS if an entry stops matching a collected
# test, so a rename can't silently un-slow anything. Parametrized tests
# match on the base name.
SLOW_TESTS = {
    # trainer loops (optimizer steps × jit compiles, some mesh-sharded)
    "test_multitask_smoke_trains_all_heads",
    "test_checkpoint_resume_is_bit_exact",
    "test_mesh_checkpoint_resume_is_bit_exact",
    "test_mesh_sharded_training_loop",
    "test_cli_main_synthetic_smoke",
    "test_pretrain_jsonl_captions",
    "test_loss_decreases_on_fixed_batch",
    "test_retrieval_jsonl_group_layout",
    "test_trainer_aborts_on_divergence",
    "test_pretrain_head_trains",
    "test_checkpoint_retention",
    "test_eval_hook_scores_on_serving_path",
    # train-step unit suites that grad-compile the full model
    "test_dryrun_multichip_entry",
    "test_sharded_train_step_on_mesh",
    "test_loss_decreases_over_steps",
    "test_remat_matches_plain_gradients",
    # engine/serving paths that compile several buckets or a mesh twin
    "test_mesh_sharded_run_many_matches_single_device",
    "test_mesh_sharded_engine_matches_single_device",
    "test_transfer_dtype_follows_compute_dtype",
    "test_bf16_param_storage_decode_parity",
    "test_int8_param_storage_decode_parity",
    "test_fused_heads_match_per_head_decode_on_mixed_chunk",
    "test_device_input_cache_lru_eviction",
    "test_warmup_falls_back_to_xla_when_kernel_rejected",
    "test_input_cache_stats_counts",
    "test_parallel_warmup_compiles_all_buckets",
    "test_serveapp_serves_through_mesh",
    "test_throughput_bucket_chunking",
    # end-to-end flows with their own engines/converters
    "test_onboard_end_to_end",
    "test_fallback_store_feeds_vilbert_forward",
    "test_model_runs_sequence_parallel_and_matches_dense",
    "test_golden_scores_are_falsifiable",
    "test_golden_scores_exact",
    "test_full_serving_config_parity",  # also marked inline (280M params)
    # bench machinery that spawns subprocess children / XLA cost analyses
    "test_probe_skipped_in_tiny_mode",
    "test_dead_backend_probes_then_structured_failure",
    "test_dead_on_arrival_window_fast_fails_with_pointer",
    "test_flops_estimate_vs_xla_cost_analysis",
}


_COLLECT_ERRORS = []


def pytest_collectreport(report):
    if report.failed:
        _COLLECT_ERRORS.append(report.nodeid)


def pytest_collection_modifyitems(config, items):
    seen = set()
    for item in items:
        base = item.name.split("[")[0]
        if base in SLOW_TESTS:
            seen.add(base)
            item.add_marker(pytest.mark.slow)
    # Only enforce inventory on full, error-free collections: a -k/path-
    # filtered run legitimately collects a subset, and a file that failed
    # to collect already reports its own error — asserting here would bury
    # that real failure under a bogus "renamed?" INTERNALERROR.
    if (not _COLLECT_ERRORS
            and config.args in ([], ["tests"], ["tests/"])
            and len(items) > 150):
        missing = SLOW_TESTS - seen
        assert not missing, (
            f"SLOW_TESTS entries match no collected test (renamed?): "
            f"{sorted(missing)}")


# ------------------------------------------------- transfer-guard sanitizer
# Dynamic twin of vmtlint's VMT101 (host-transfer-in-jit): the engine and
# model unit tests run under ``jax.transfer_guard("disallow")``, so any
# IMPLICIT host↔device transfer — a numpy array silently re-uploaded per
# call, a Python scalar materialized mid-eager-forward — fails the test
# instead of becoming round 2's 23.7 s p50. Explicit transfers
# (``jax.device_put``, ``jnp.asarray``, ``np.asarray(device_array)``) stay
# legal under "disallow"; that is exactly the contract the engine code is
# held to. Session fixtures (the shared ``engine``) are built before the
# function-scoped guard activates, so one-time boot transfers are exempt —
# engines constructed inside a test body run fully guarded.
TRANSFER_GUARDED_MODULES = {"test_engine", "test_model_shapes"}


@pytest.fixture(autouse=True)
def _no_implicit_transfers(request):
    if request.module.__name__.rpartition(".")[2] \
            not in TRANSFER_GUARDED_MODULES:
        yield
        return
    with jax.transfer_guard("disallow"):
        yield


# ------------------------------------------------------ obs thread hygiene
@pytest.fixture(autouse=True)
def _no_leaked_project_threads():
    """Every thread a test spawns must be accounted for when it ends:
    the sampler and flight-recorder writer joined (stop()/close()
    contract — leaking either keeps sampling freed state under every
    later test), any other non-daemon thread joined, and any *named*
    daemon thread registered with the obs watchdog (a crash-guarded
    loop announces itself; an anonymous stdlib helper gets a pass)."""
    import threading

    before = {id(t) for t in threading.enumerate()}
    yield
    from vilbert_multitask_tpu import obs

    # Default/stdlib naming schemes: unnamed threads, pool workers, and
    # asyncio helpers — not project loops, not watchdog material.
    stdlib_names = ("MainThread", "Thread-", "ThreadPoolExecutor",
                    "asyncio_", "Dummy-")
    wd = obs.watchdog()
    leaked = []
    for t in threading.enumerate():
        if id(t) in before or not t.is_alive():
            continue
        if t.name in (obs.SAMPLER_THREAD_NAME,
                      obs.RECORDER_THREAD_NAME):
            leaked.append(f"{t.name} (stop()/close() must join it)")
        elif not t.daemon:
            leaked.append(f"{t.name} (non-daemon thread never joined)")
        elif not t.name.startswith(stdlib_names) \
                and not wd.is_known_thread(t.name):
            leaked.append(f"{t.name} (named daemon thread unknown to "
                          f"the watchdog registry — run its loop under "
                          f"obs.crash_guard or join it)")
    assert not leaked, (
        f"project threads leaked by this test: {leaked}")


@pytest.fixture(scope="session")
def tiny_config():
    from vilbert_multitask_tpu.config import ViLBertConfig

    return ViLBertConfig().tiny()


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)


# --------------------------------------------------------- serving fixtures
@pytest.fixture(scope="session")
def tiny_framework_cfg(tmp_path_factory):
    from vilbert_multitask_tpu.config import (
        EngineConfig,
        FrameworkConfig,
        ServingConfig,
        ViLBertConfig,
    )

    root = tmp_path_factory.mktemp("serve_state")
    return FrameworkConfig(
        model=ViLBertConfig().tiny(),
        engine=EngineConfig(
            max_text_len=12, max_regions=9, num_features=8,
            image_buckets=(1, 2, 4, 8), compute_dtype="float32",
            # Keep the serving fixtures on the image buckets alone: the
            # default 16/32-row throughput buckets would add two more
            # compiles to every batching test. Their behavior has a
            # dedicated test (test_batching.py::test_throughput_bucket_chunking).
            throughput_buckets=None,
            # XLA attention here: these fixtures exercise the serving tiers,
            # not the kernel, and interpret-mode Pallas makes CPU forwards
            # ~10x slower. Kernel coverage lives in test_pallas_coattention.
            use_pallas_coattention=False, use_pallas_self_attention=False,
        ),
        serving=ServingConfig(
            queue_db_path=str(root / "queue.sqlite3"),
            results_db_path=str(root / "results.sqlite3"),
            media_root=str(root / "media"),
            http_port=0,
        ),
    )


@pytest.fixture(scope="session")
def features_dir(tmp_path_factory, tiny_framework_cfg):
    import numpy as np

    from vilbert_multitask_tpu.features.pipeline import RegionFeatures
    from vilbert_multitask_tpu.features.store import save_reference_npy

    d = tmp_path_factory.mktemp("features")
    nrng = np.random.default_rng(0)
    dim = tiny_framework_cfg.model.v_feature_size
    for name in ("img_a", "img_b"):
        boxes = np.array([[10, 10, 60, 60], [30, 20, 90, 80],
                          [5, 40, 50, 95]], np.float32)
        region = RegionFeatures(
            features=nrng.normal(size=(3, dim)).astype(np.float32),
            boxes=boxes, image_width=100, image_height=100)
        save_reference_npy(str(d / f"{name}.npy"), region, name)
    return str(d)


@pytest.fixture(scope="session")
def engine(tiny_framework_cfg, features_dir):
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine
    from vilbert_multitask_tpu.features.store import FeatureStore

    return InferenceEngine(tiny_framework_cfg,
                           feature_store=FeatureStore(features_dir))


@pytest.fixture()
def stack(tiny_framework_cfg, engine, tmp_path):
    import dataclasses

    from vilbert_multitask_tpu.serve import (
        DurableQueue,
        PushHub,
        ResultStore,
        ServeWorker,
    )

    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        media_root=str(tmp_path / "media"),
    )
    hub = PushHub()
    q = DurableQueue(s.queue_db_path,
                     max_delivery_attempts=s.max_delivery_attempts)
    store = ResultStore(s.results_db_path)
    worker = ServeWorker(engine, q, store, hub, s)
    return s, hub, q, store, worker
