"""Test harness: force an 8-device virtual CPU mesh so sharding tests run
anywhere (the standard JAX fake-backend trick; see SURVEY.md §4).

Note: this environment's sitecustomize registers a TPU PJRT plugin in every
Python process; selecting it costs a ~2-minute remote handshake. Tests must
never touch it, so we pin the platform to CPU *before any backend init* —
``jax.config.update`` works post-import as long as ``jax.devices()`` hasn't
been called yet, and XLA_FLAGS is read at first backend init.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_config():
    from vilbert_multitask_tpu.config import ViLBertConfig

    return ViLBertConfig().tiny()


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)


# --------------------------------------------------------- serving fixtures
@pytest.fixture(scope="session")
def tiny_framework_cfg(tmp_path_factory):
    from vilbert_multitask_tpu.config import (
        EngineConfig,
        FrameworkConfig,
        ServingConfig,
        ViLBertConfig,
    )

    root = tmp_path_factory.mktemp("serve_state")
    return FrameworkConfig(
        model=ViLBertConfig().tiny(),
        engine=EngineConfig(
            max_text_len=12, max_regions=9, num_features=8,
            image_buckets=(1, 2, 4, 8), compute_dtype="float32",
            # Keep the serving fixtures on the image buckets alone: the
            # default 16/32-row throughput buckets would add two more
            # compiles to every batching test. Their behavior has a
            # dedicated test (test_batching.py::test_throughput_bucket_chunking).
            throughput_buckets=None,
            # XLA attention here: these fixtures exercise the serving tiers,
            # not the kernel, and interpret-mode Pallas makes CPU forwards
            # ~10x slower. Kernel coverage lives in test_pallas_coattention.
            use_pallas_coattention=False, use_pallas_self_attention=False,
        ),
        serving=ServingConfig(
            queue_db_path=str(root / "queue.sqlite3"),
            results_db_path=str(root / "results.sqlite3"),
            media_root=str(root / "media"),
            http_port=0,
        ),
    )


@pytest.fixture(scope="session")
def features_dir(tmp_path_factory, tiny_framework_cfg):
    import numpy as np

    from vilbert_multitask_tpu.features.pipeline import RegionFeatures
    from vilbert_multitask_tpu.features.store import save_reference_npy

    d = tmp_path_factory.mktemp("features")
    nrng = np.random.default_rng(0)
    dim = tiny_framework_cfg.model.v_feature_size
    for name in ("img_a", "img_b"):
        boxes = np.array([[10, 10, 60, 60], [30, 20, 90, 80],
                          [5, 40, 50, 95]], np.float32)
        region = RegionFeatures(
            features=nrng.normal(size=(3, dim)).astype(np.float32),
            boxes=boxes, image_width=100, image_height=100)
        save_reference_npy(str(d / f"{name}.npy"), region, name)
    return str(d)


@pytest.fixture(scope="session")
def engine(tiny_framework_cfg, features_dir):
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine
    from vilbert_multitask_tpu.features.store import FeatureStore

    return InferenceEngine(tiny_framework_cfg,
                           feature_store=FeatureStore(features_dir))


@pytest.fixture()
def stack(tiny_framework_cfg, engine, tmp_path):
    import dataclasses

    from vilbert_multitask_tpu.serve import (
        DurableQueue,
        PushHub,
        ResultStore,
        ServeWorker,
    )

    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        media_root=str(tmp_path / "media"),
    )
    hub = PushHub()
    q = DurableQueue(s.queue_db_path,
                     max_delivery_attempts=s.max_delivery_attempts)
    store = ResultStore(s.results_db_path)
    worker = ServeWorker(engine, q, store, hub, s)
    return s, hub, q, store, worker
