"""Test harness: force an 8-device virtual CPU mesh so sharding tests run
anywhere (the standard JAX fake-backend trick; see SURVEY.md §4)."""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_config():
    from vilbert_multitask_tpu.config import ViLBertConfig

    return ViLBertConfig().tiny()


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)
