"""Test harness: force an 8-device virtual CPU mesh so sharding tests run
anywhere (the standard JAX fake-backend trick; see SURVEY.md §4).

Note: this environment's sitecustomize registers a TPU PJRT plugin in every
Python process; selecting it costs a ~2-minute remote handshake. Tests must
never touch it, so we pin the platform to CPU *before any backend init* —
``jax.config.update`` works post-import as long as ``jax.devices()`` hasn't
been called yet, and XLA_FLAGS is read at first backend init.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_config():
    from vilbert_multitask_tpu.config import ViLBertConfig

    return ViLBertConfig().tiny()


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)
