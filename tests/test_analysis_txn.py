"""Transaction tier suite: the SQL statement model, connection-scope
recovery, VMT128-131 hazard/clean pairs, and the durable-state manifest
(TXN_SURFACE.json) — discovery, determinism, drift detection, and the
byte-for-byte committed-manifest gate CI runs via ``txn --check``.

Rule fixtures are multi-module dicts through ``analyze_project`` (the
scopes resolve their connection factory through the ProjectGraph, so a
single-module scan would miss the cross-file shape the real stores use).
"""

import ast
import copy
import json
import os
import textwrap

import pytest

from vilbert_multitask_tpu.analysis import analyze_project
from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.graph import ProjectGraph
from vilbert_multitask_tpu.analysis import txn as txn_mod
from vilbert_multitask_tpu.analysis.sql import statements_from_call
from vilbert_multitask_tpu.analysis.txn import (
    build_txn_surface,
    diff_txn_surface,
    render_txn_surface,
    render_txn_surface_sarif,
    txn_flow,
)
from vilbert_multitask_tpu.analysis.txnrules import SqlSchemaDrift

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, txn_mod.MANIFEST_NAME)


def project(sources):
    ctxs = []
    for path in sorted(sources):
        src = textwrap.dedent(sources[path])
        ctxs.append(ModuleContext(path, src, ast.parse(src)))
    graph = ProjectGraph(ctxs)
    for c in ctxs:
        c.project = graph
    return graph


def findings(sources):
    return analyze_project(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        library_roots=("pkg", "vilbert_multitask_tpu"))


def rules_hit(sources):
    return {f.rule for f in findings(sources)}


def _library_sources():
    out = {}
    lib = os.path.join(REPO, "vilbert_multitask_tpu")
    for dirpath, dirnames, filenames in os.walk(lib):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, REPO).replace(os.sep, "/")
            with open(full, "r", encoding="utf-8") as f:
                out[rel] = f.read()
    return out


@pytest.fixture(scope="module")
def repo_flow():
    srcs = {p: s for p, s in _library_sources().items()}
    graph = project(srcs)
    return txn_flow(graph)


@pytest.fixture(scope="module")
def fresh_surface():
    graph = project(_library_sources())
    return build_txn_surface(graph)


# The seeded hazard: the pre-fix nack() shape — SELECT feeding a
# dependent write on the same table under the deferred default.
_DEFERRED_RMW = {
    "pkg/store.py": """
    import sqlite3

    class Store:
        def _conn(self):
            conn = sqlite3.connect(self.path, timeout=30.0)
            return conn

        def nack(self, job_id):
            with self._conn() as c:
                row = c.execute(
                    "SELECT attempts FROM jobs WHERE id=?", (job_id,)
                ).fetchone()
                if row is None:
                    return "gone"
                status = "dead" if row[0] >= 3 else "pending"
                c.execute(
                    "UPDATE jobs SET status=? WHERE id=?",
                    (status, job_id),
                )
                return status
    """,
}


# ------------------------------------------------------------- SQL model
def _statements(src, method="execute"):
    """All SqlStatements of the first ``.{method}(`` call in ``src``."""
    graph = project({"pkg/m.py": src})
    ctx = graph.modules["pkg.m"].ctx
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == method):
            return statements_from_call(ctx, node)
    raise AssertionError("no execute call in fixture")


def test_sql_model_classifies_select_with_guards():
    sts = _statements("""
    def read(c, qn):
        return c.execute(
            "SELECT id, body FROM jobs WHERE queue=? AND status='pending' "
            "ORDER BY id LIMIT 1", (qn,)).fetchone()
    """)
    (st,) = sts
    assert st.kind == "select" and st.tables == ("jobs",)
    assert st.where_literals.get("status") == "pending"
    assert "id" in st.order_by and st.has_limit
    assert not st.spliced


def test_sql_model_expands_fstring_not_in_splice():
    # The claim() shape: a runtime-length placeholder list spliced into
    # the WHERE — the statement must still parse, marked spliced.
    sts = _statements("""
    def claim(c, qn, exclude):
        not_in = (
            " AND id NOT IN ({})".format(",".join("?" * len(exclude)))
            if exclude else ""
        )
        return c.execute(
            "SELECT id FROM jobs "
            f"WHERE queue=? AND status='pending'{not_in} "
            "ORDER BY id LIMIT 1", (qn, *exclude)).fetchone()
    """)
    assert all(st.kind == "select" and st.has_limit for st in sts)
    assert any(st.spliced for st in sts)
    assert all(st.where_literals.get("status") == "pending" for st in sts)


def test_sql_model_expands_covarying_migration_loop():
    sts = _statements("""
    def migrate(c):
        for col, decl in (("a", "INTEGER"), ("b", "TEXT"),
                          ("edited", "INTEGER DEFAULT 0")):
            c.execute(f"ALTER TABLE tasks ADD COLUMN {col} {decl}")
    """)
    assert [st.kind for st in sts] == ["alter_table"] * 3
    assert {st.schema_columns[0][0] for st in sts} == {"a", "b", "edited"}


def test_sql_model_splits_executescript():
    sts = _statements("""
    def boot(c):
        c.executescript(\"\"\"
            CREATE TABLE IF NOT EXISTS a (x INTEGER PRIMARY KEY);
            CREATE TABLE IF NOT EXISTS b (y TEXT);
            CREATE INDEX IF NOT EXISTS b_y ON b (y);
        \"\"\")
    """, method="executescript")
    assert [st.kind for st in sts] == ["create_table", "create_table",
                                      "create_index"]
    assert [st.tables[0] for st in sts] == ["a", "b", "b"]


def test_sql_model_maps_set_params_to_placeholder_index():
    (st,) = _statements("""
    def touch(c, s, t, i):
        c.execute("UPDATE jobs SET status=?, claimed_at=? WHERE id=?",
                  (s, t, i))
    """)
    assert st.set_params == {"status": 0, "claimed_at": 1}
    assert "id" in st.where_columns


# ------------------------------------------------------- scope recovery
def test_scopes_resolve_factory_through_project_graph():
    graph = project(_DEFERRED_RMW)
    flow = txn_flow(graph)
    assert flow.factories == {"_conn"}
    (scope,) = flow.scopes
    assert scope.kind == "with" and scope.mode == "deferred"
    assert len(scope.sites) == 2


def test_direct_sqlite_connect_with_is_a_scope():
    graph = project({"pkg/m.py": """
    import sqlite3

    def count(path):
        with sqlite3.connect(path) as c:
            return c.execute("SELECT COUNT(*) FROM jobs").fetchone()
    """})
    (scope,) = txn_flow(graph).scopes
    assert scope.factory == "sqlite3.connect" and scope.mode == "deferred"


def test_explicit_begin_immediate_flips_scope_mode():
    srcs = copy.deepcopy(_DEFERRED_RMW)
    srcs["pkg/store.py"] = srcs["pkg/store.py"].replace(
        'row = c.execute(',
        'c.execute("BEGIN IMMEDIATE")\n'
        '                row = c.execute(')
    (scope,) = txn_flow(project(srcs)).scopes
    assert scope.mode == "immediate"


# ---------------------------------------------------------------- VMT128
def test_vmt128_fires_on_deferred_rmw_with_witness_chain():
    fs = [f for f in findings(_DEFERRED_RMW) if f.rule == "VMT128"]
    (f,) = fs
    assert f.severity == "error"
    assert "jobs" in f.message and "BEGIN IMMEDIATE" in f.message
    (chain,) = f.flows
    assert len(chain) >= 2
    assert "SELECT" in chain[0]["message"]
    assert "UPDATE" in chain[-1]["message"]


def test_vmt128_quiet_on_begin_immediate_twin():
    srcs = copy.deepcopy(_DEFERRED_RMW)
    srcs["pkg/store.py"] = srcs["pkg/store.py"].replace(
        'row = c.execute(',
        'c.execute("BEGIN IMMEDIATE")\n'
        '                row = c.execute(')
    assert "VMT128" not in rules_hit(srcs)


def test_vmt128_quiet_on_independent_write():
    # Same scope, same table, but the write neither consumes the read's
    # result nor sits behind a guard on it — no RMW dependency.
    assert "VMT128" not in rules_hit({"pkg/m.py": """
    import sqlite3

    def tick(path, now):
        with sqlite3.connect(path) as c:
            rows = c.execute("SELECT id FROM jobs").fetchall()
            c.execute("UPDATE jobs SET claimed_at=?", (now,))
            return rows
    """})


# ---------------------------------------------------------------- VMT129
_MIGRATION = {
    "pkg/db.py": """
    import sqlite3

    def boot(path):
        with sqlite3.connect(path) as c:
            c.execute("CREATE TABLE IF NOT EXISTS tasks "
                      "(id INTEGER PRIMARY KEY, name TEXT)")
            cols = {r[1] for r in c.execute("PRAGMA table_info(tasks)")}
            if "edited" not in cols:
                c.execute("ALTER TABLE tasks ADD COLUMN "
                          "edited INTEGER DEFAULT 0")
            c.execute("INSERT INTO tasks (id, name) VALUES (?, ?)",
                      (1, "seed"))
    """,
}


def test_vmt129_fires_on_split_migration():
    fs = [f for f in findings(_MIGRATION) if f.rule == "VMT129"]
    (f,) = fs
    assert f.severity == "error" and "tasks" in f.message


def test_vmt129_quiet_under_explicit_txn_and_across_tables():
    srcs = copy.deepcopy(_MIGRATION)
    srcs["pkg/db.py"] = srcs["pkg/db.py"].replace(
        'c.execute("CREATE TABLE',
        'c.execute("BEGIN IMMEDIATE")\n'
        '            c.execute("CREATE TABLE')
    assert "VMT129" not in rules_hit(srcs)
    # Unrelated tables in one scope are independent autocommits: fine.
    assert "VMT129" not in rules_hit({"pkg/m.py": """
    import sqlite3

    def boot(path):
        with sqlite3.connect(path) as c:
            c.execute("CREATE TABLE IF NOT EXISTS a (x INTEGER)")
            c.execute("CREATE TABLE IF NOT EXISTS b (y INTEGER)")
    """})


# ---------------------------------------------------------------- VMT130
_SCHEMA_PROJ = {
    "pkg/db.py": """
    import sqlite3

    def boot(path):
        with sqlite3.connect(path) as c:
            c.execute("BEGIN IMMEDIATE")
            c.execute("CREATE TABLE IF NOT EXISTS jobs "
                      "(id INTEGER PRIMARY KEY, status TEXT, "
                      "attempts INTEGER)")
            c.execute("ALTER TABLE jobs ADD COLUMN claimed_by TEXT")

    def read(path):
        with sqlite3.connect(path) as c:
            return c.execute(
                "SELECT id, status, attempts, claimed_by FROM jobs"
            ).fetchall()
    """,
}


def test_vmt130_models_migrated_columns():
    # claimed_by only exists via the ALTER migration; querying it is
    # clean, and nothing else drifts.
    assert "VMT130" not in rules_hit(_SCHEMA_PROJ)


def test_vmt130_unknown_column_with_did_you_mean():
    srcs = copy.deepcopy(_SCHEMA_PROJ)
    srcs["pkg/db.py"] = srcs["pkg/db.py"].replace(
        "SELECT id, status, attempts, claimed_by",
        "SELECT id, statuz, attempts, claimed_by")
    fs = [f for f in findings(srcs) if f.rule == "VMT130"]
    (unknown,) = [f for f in fs if "statuz" in f.message]
    assert "status" in unknown.message  # did-you-mean
    # ...and the orphaned declaration now reads nowhere: dead direction.
    assert any("never read" in f.message for f in fs)


def test_vmt130_dead_column_needs_whole_project_scan():
    srcs = copy.deepcopy(_SCHEMA_PROJ)
    srcs["pkg/db.py"] = srcs["pkg/db.py"].replace(
        "SELECT id, status, attempts, claimed_by", "SELECT id, attempts")
    dead = [f for f in findings(srcs) if f.rule == "VMT130"]
    assert len(dead) == 2  # status and claimed_by now unread
    assert all("never read" in f.message for f in dead)
    # --changed subset scans can't prove project-wide absence: the
    # partial_scan degradation VMT122 pioneered applies here too.
    rule = SqlSchemaDrift()
    rule.partial_scan = True
    graph = project(srcs)
    ctx = graph.modules["pkg.db"].ctx
    assert list(rule.check(ctx)) == []


# ---------------------------------------------------------------- VMT131
def test_vmt131_fires_on_unordered_claim_and_quiet_with_order_by():
    claim = {"pkg/q.py": """
    import sqlite3

    def claim(path, now):
        with sqlite3.connect(path) as c:
            c.execute("BEGIN IMMEDIATE")
            row = c.execute(
                "SELECT id FROM jobs WHERE status='pending' LIMIT 1"
            ).fetchone()
            if row is None:
                return None
            c.execute("UPDATE jobs SET status='inflight', claimed_at=? "
                      "WHERE id=?", (now, row[0]))
            return row[0]
    """}
    assert "VMT131" in rules_hit(claim)
    ordered = {"pkg/q.py": claim["pkg/q.py"].replace(
        "LIMIT 1", "ORDER BY id LIMIT 1")}
    assert "VMT131" not in rules_hit(ordered)


# ------------------------------------------------------ the real stores
def test_repo_stores_carry_no_txn_hazards(repo_flow):
    # The seeded bugs are fixed in-tree: every RMW scope takes the write
    # lock and the boot migrations are single transactions.
    assert repo_flow.rmw == []
    assert repo_flow.multi_write == []
    assert repo_flow.claims == []
    # The one accepted drift is baselined (fleet_instruments.updated_unix).
    assert [(d["kind"], d["path"]) for d in repo_flow.drift] == [
        ("dead", "vilbert_multitask_tpu/obs/fleet.py")]


def test_repo_rmw_scopes_are_immediate(repo_flow):
    modes = {s.function.split(":", 1)[1]: s.mode for s in repo_flow.scopes}
    for fn in ("DurableQueue.nack", "DurableQueue.claim",
               "DurableQueue.pop_dead_letters", "DurableQueue.__init__",
               "ResultStore.__init__", "ResultStore.create_question"):
        assert modes[fn] == "immediate", (fn, modes[fn])


# ---------------------------------------------------------------- manifest
def test_surface_models_migrated_jobs_schema(fresh_surface):
    jobs = fresh_surface["tables"]["jobs"]
    by_name = {c["name"]: c for c in jobs["columns"]}
    assert {"id", "queue", "body", "status", "attempts", "claimed_at",
            "created_at", "delivery_count", "dead_notified",
            "claimed_by"} == set(by_name)
    assert by_name["status"]["origin"] == "create"
    for col in ("delivery_count", "dead_notified", "claimed_by"):
        assert by_name[col]["origin"] == "alter"


def test_surface_recovers_status_state_machine(fresh_surface):
    status = fresh_surface["state_machines"]["jobs"]["status"]
    assert status["initial"] == "pending"
    assert status["values"] == ["dead", "inflight", "pending"]
    edges = {(t.get("from"), t["to"]) for t in status["transitions"]}
    assert {("pending", "inflight"), ("inflight", "pending"),
            ("pending", "dead")} <= edges
    notified = fresh_surface["state_machines"]["jobs"]["dead_notified"]
    assert ("0", "1") in {(t.get("from"), t["to"])
                          for t in notified["transitions"]}


def test_surface_is_deterministic():
    a = render_txn_surface(build_txn_surface(project(_library_sources())))
    b = render_txn_surface(build_txn_surface(project(_library_sources())))
    assert a == b


def test_committed_manifest_matches_tree_byte_for_byte(fresh_surface):
    with open(MANIFEST, "r", encoding="utf-8") as f:
        committed = f.read()
    assert committed == render_txn_surface(fresh_surface), (
        "TXN_SURFACE.json drifted — regenerate with `python -m "
        "vilbert_multitask_tpu.analysis txn` and commit")


def test_diff_reports_schema_and_site_drift(fresh_surface):
    assert diff_txn_surface(None, fresh_surface)  # missing manifest
    mutated = copy.deepcopy(fresh_surface)
    mutated["tables"]["jobs"]["columns"].pop()
    msgs = diff_txn_surface(mutated, fresh_surface)
    assert any("jobs" in m for m in msgs)
    mutated = copy.deepcopy(fresh_surface)
    mutated["txn_sites"][0]["mode"] = "autocommit"
    assert any("transaction sites" in m
               for m in diff_txn_surface(mutated, fresh_surface))


def test_sarif_rendering_carries_site_flows(fresh_surface):
    doc = json.loads(render_txn_surface_sarif(fresh_surface))
    results = doc["runs"][0]["results"]
    assert len(results) >= fresh_surface["counts"]["txn_sites"]
    assert any(r["ruleId"] == "TXN-STATE-MACHINE" for r in results)
    for r in results:
        assert r["codeFlows"][0]["threadFlows"][0]["locations"]


def test_txn_check_gate_is_clean(monkeypatch):
    from vilbert_multitask_tpu.analysis.cli import main as cli_main

    monkeypatch.chdir(REPO)
    assert cli_main(["txn", "--check"]) == 0
