"""Engine tests: every served task type end-to-end on a tiny model (CPU),
bucket padding invariance, and mesh-sharded execution on the virtual
8-device mesh (SURVEY.md §4 device-test strategy)."""

import dataclasses

import numpy as np
import pytest

from vilbert_multitask_tpu.config import (
    EngineConfig,
    FrameworkConfig,
    MeshConfig,
    TASK_REGISTRY,
)
from vilbert_multitask_tpu.engine import InferenceEngine
from vilbert_multitask_tpu.features.pipeline import RegionFeatures
from vilbert_multitask_tpu.parallel import build_mesh, param_specs


def make_regions(n, num_boxes=7, feat_dim=32, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        boxes = rng.uniform(0, 200, size=(num_boxes, 4)).astype(np.float32)
        boxes[:, 2:] = boxes[:, :2] + 10 + boxes[:, 2:] * 0.3
        out.append(
            RegionFeatures(
                features=rng.randn(num_boxes, feat_dim).astype(np.float32),
                boxes=np.clip(boxes, 0, 640),
                image_width=640,
                image_height=480,
            )
        )
    return out


def _cpu_engine_cfg(**kw):
    """XLA attention for CPU engine tests (kernel coverage lives in
    test_pallas_coattention; interpret-mode Pallas is ~10x slower here)."""
    kw.setdefault("use_pallas_coattention", False)
    kw.setdefault("use_pallas_self_attention", False)
    return EngineConfig(compute_dtype="float32", **kw)


@pytest.fixture(scope="module")
def engine(tiny_config):
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=_cpu_engine_cfg(max_regions=11),
    )
    return InferenceEngine(cfg, seed=0)


def test_params_device_resident(engine):
    """BENCH_r02 regression: every param leaf must live on a device as a
    jax.Array after engine boot — host-numpy leaves silently re-upload the
    full tree on every jitted forward (the 23.7 s p50 of round 2). This is
    the JAX equivalent of the reference's one-time ``model.cuda(0)``
    (worker.py:534-536)."""
    import jax

    leaves = jax.tree_util.tree_leaves(engine.params)
    assert leaves
    for leaf in leaves:
        assert isinstance(leaf, jax.Array), type(leaf)
        assert not isinstance(leaf, np.ndarray)
        assert len(leaf.devices()) >= 1


def test_engine_device_pins_host_params(tiny_config):
    """Passing a host-numpy tree (the checkpoint-restore shape) must still
    yield device-resident params — the upload happens once, at boot."""
    import jax

    cfg = FrameworkConfig(
        model=tiny_config,
        engine=_cpu_engine_cfg(max_regions=11),
    )
    donor = InferenceEngine(cfg, seed=0)
    host_tree = jax.tree_util.tree_map(
        lambda x: np.asarray(x), donor.params)
    eng = InferenceEngine(cfg, params=host_tree)
    for leaf in jax.tree_util.tree_leaves(eng.params):
        assert isinstance(leaf, jax.Array) and not isinstance(leaf, np.ndarray)


def test_warmup_falls_back_to_xla_when_kernel_rejected(tiny_config,
                                                       monkeypatch):
    """Pallas is default-on; if Mosaic rejects the kernel on some backend,
    warmup() must degrade the engine to XLA attention and keep serving —
    for EVERY consumer (ServeApp, evals, bench), not just the benchmark."""
    from vilbert_multitask_tpu.ops import coattention

    def boom(*a, **k):
        raise RuntimeError("Mosaic rejected the kernel (simulated)")

    cfg = FrameworkConfig(
        model=tiny_config,
        engine=EngineConfig(compute_dtype="float32", max_regions=11),
    )
    # Construction must never compile the kernel (init runs through an XLA
    # twin), so the engine builds fine even where Mosaic would reject it...
    monkeypatch.setattr(coattention, "flash_cross_attention", boom)
    eng = InferenceEngine(cfg, seed=0)
    assert eng.pallas_enabled and not eng.kernel_fallback
    # ...and ANY first forward degrades — here a live request on an un-warmed
    # engine (the evals-harness / --no-warmup path), not just warmup().
    regions = make_regions(1, feat_dim=cfg.model.v_feature_size)
    _, result = eng.run(eng.prepare(1, "what is the man holding", regions))
    assert result.answers
    assert eng.kernel_fallback
    assert not eng.pallas_enabled  # rebuilt model runs XLA attention
    eng.warmup(buckets=(1, 2))  # further compiles stay on the XLA path


def test_vocab_overflow_fails_at_boot(tiny_config, caplog):
    """VERDICT r2 #7: a vocab bigger than the embedding table must fail at
    boot (on TPU an OOB gather clamps silently); a much-wider table warns."""
    import logging

    # Overflow: table with fewer rows than the committed 1,037-token vocab.
    # (The check runs before param init, so the failure is immediate.)
    small = dataclasses.replace(tiny_config, vocab_size=512)
    with pytest.raises(ValueError, match="index out of the embedding"):
        InferenceEngine(FrameworkConfig(
            model=small, engine=_cpu_engine_cfg(max_regions=11)))
    # Dead-weight gap: big table over the small vocab → warning, not error.
    # params={} skips the (slow, irrelevant) random init compile.
    wide = dataclasses.replace(tiny_config, vocab_size=30522)
    with caplog.at_level(logging.WARNING):
        InferenceEngine(FrameworkConfig(
            model=wide, engine=_cpu_engine_cfg(max_regions=11)), params={})
    assert any("dead weight" in r.message for r in caplog.records)


def test_engine_defaults_to_committed_assets(engine):
    """No tokenizer/label args → the committed vocab + reference-layout
    pickles load by default (never the in-memory demo vocab)."""
    assert engine.tokenizer.cls_id == 101  # bert-base-uncased layout
    assert len(engine.tokenizer.vocab) > 1000
    assert engine.labels.get("vqa")[0] == "yes"  # from the committed pickle
    assert len(engine.labels.get("vqa")) == 3129


TASK_QUESTIONS = {
    1: "what is the man holding",
    2: "what color is the car",
    15: "is the bowl right of the mug",
    4: "which object can you eat",
    11: "the woman in the red coat",
    16: "q: is it a person? a: no q: is it red? a: yes",
    13: "two dogs are playing in the snow",
    12: "both images contain two wolves",
    7: "a man riding a horse on the beach",
}


@pytest.mark.parametrize("task_id", sorted(TASK_REGISTRY))
def test_all_tasks_end_to_end(engine, task_id):
    spec = TASK_REGISTRY[task_id]
    n = spec.min_images
    regions = make_regions(n, feat_dim=engine.cfg.model.v_feature_size)
    req = engine.prepare(task_id, TASK_QUESTIONS[task_id], regions)
    _, result = engine.run(req)
    assert result.task_id == task_id
    assert result.kind == spec.decode
    if spec.decode in ("labels", "binary", "trinary"):
        assert len(result.answers) == min(
            spec.top_k, {"binary": 2, "trinary": 3}.get(spec.decode, spec.top_k)
        )
        confs = [a["confidence"] for a in result.answers]
        assert confs == sorted(confs, reverse=True)
        assert all(0.0 <= c <= 1.0 for c in confs)
    elif spec.decode == "grounding":
        assert len(result.boxes) == spec.top_k
        for b in result.boxes:
            x1, y1, x2, y2 = b["box_xyxy"]
            assert 0 <= x1 <= 640 and 0 <= y2 <= 480 or b["is_global"]
    elif spec.decode == "ranking":
        assert len(result.ranking) == n
        assert [r["rank"] for r in result.ranking] == list(range(1, n + 1))


def test_retrieval_bucket_padding_invariance(engine):
    """3 candidates pad to the 4-bucket; scores of real rows must match an
    unpadded 2-candidate run row-for-row (pad rows never leak into decode)."""
    feat_dim = engine.cfg.model.v_feature_size
    regions = make_regions(3, feat_dim=feat_dim, seed=1)
    req3 = engine.prepare(7, "a dog on a beach", regions)
    assert req3.bucket == 4 and req3.n_images == 3
    _, res3 = engine.run(req3)
    assert len(res3.ranking) == 3

    req2 = engine.prepare(7, "a dog on a beach", regions[:2])
    assert req2.bucket == 2
    _, res2 = engine.run(req2)
    score3 = {r["image"]: r["score"] for r in res3.ranking}
    score2 = {r["image"]: r["score"] for r in res2.ranking}
    for k, v in score2.items():
        assert score3[k] == pytest.approx(v, abs=1e-4)


def test_nlvr2_requires_two_images(engine):
    regions = make_regions(1, feat_dim=engine.cfg.model.v_feature_size)
    with pytest.raises(ValueError, match="task 12"):
        engine.prepare(12, "both images", regions)


def test_guesswhat_dialog_reformat_changes_tokens(engine):
    """Task 16 reformats Q/A dialogs (fixing the reference's dead code,
    SURVEY.md §2.4) — its ids must differ from the raw-encoded query."""
    regions = make_regions(1, feat_dim=engine.cfg.model.v_feature_size)
    q = "q: is it a person? a: no"
    req16 = engine.prepare(16, q, regions)
    req11 = engine.prepare(11, q, regions)
    assert not np.array_equal(req16.text.input_ids, req11.text.input_ids)


def test_mesh_sharded_engine_matches_single_device(tiny_config):
    """dp×tp sharded run (virtual 8-device mesh) must reproduce the
    single-device logits — XLA collectives only change placement."""
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=_cpu_engine_cfg(max_regions=11),
        mesh=MeshConfig(dp=4, tp=2),
    )
    base = InferenceEngine(cfg, seed=3)
    mesh = build_mesh(cfg.mesh)
    sharded = InferenceEngine(cfg, seed=3, mesh=mesh)

    regions = make_regions(2, feat_dim=cfg.model.v_feature_size, seed=5)
    req_a = base.prepare(12, "both images contain wolves", regions)
    req_b = sharded.prepare(12, "both images contain wolves", regions)
    out_a, res_a = base.run(req_a)
    out_b, res_b = sharded.run(req_b)
    np.testing.assert_allclose(
        np.asarray(out_a.vil_binary_prediction),
        np.asarray(out_b.vil_binary_prediction), atol=1e-4,
    )
    assert [a["answer"] for a in res_a.answers] == [
        a["answer"] for a in res_b.answers
    ]


def test_mesh_sharded_run_many_matches_single_device(tiny_config):
    """The batched path's mesh branch (_dispatch_many packs a whole-chunk
    device_put with batch shardings) must reproduce single-device decodes
    for a mixed single/multi-image backlog."""
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=_cpu_engine_cfg(max_regions=11, image_buckets=(1, 2, 4),
                               throughput_buckets=(8,)),
        mesh=MeshConfig(dp=4, tp=2),
    )
    base = InferenceEngine(cfg, seed=3)
    sharded = InferenceEngine(cfg, seed=3, mesh=build_mesh(cfg.mesh))

    regions = make_regions(4, feat_dim=cfg.model.v_feature_size, seed=5)
    backlog = [
        (1, "what is the man holding", 1),
        (12, "both images contain wolves", 2),
        (7, "a red car parked outside", 4),
        (15, "is the bowl right of the mug", 1),
        (12, "both show dogs", 2),
    ]
    res_a = base.run_many([base.prepare(t, q, regions[:n])
                           for t, q, n in backlog])
    res_b = sharded.run_many([sharded.prepare(t, q, regions[:n])
                              for t, q, n in backlog])
    assert [r.kind for r in res_a] == [r.kind for r in res_b]
    for a, b in zip(res_a, res_b):
        if a.answers is not None:
            assert [x["answer"] for x in a.answers] == \
                [x["answer"] for x in b.answers]
        if a.ranking is not None:
            assert [x["image"] for x in a.ranking] == \
                [x["image"] for x in b.ranking]


def test_partition_rules_shard_big_matmuls(tiny_config):
    """TP rules must actually shard the FFN/QKV kernels when dims divide."""
    cfg = FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(),
        mesh=MeshConfig(dp=4, tp=2),
    )
    eng = InferenceEngine(cfg, seed=0)
    mesh = build_mesh(cfg.mesh)
    specs = param_specs(eng.params, mesh)
    qkv = specs["bert"]["encoder"]["t_layer_0"]["attention"]["qkv"]["kernel"]
    assert tuple(qkv) == (None, "tp")
    ffn_out = specs["bert"]["encoder"]["t_layer_0"]["ffn"]["output"]["kernel"]
    assert tuple(ffn_out) == ("tp", None)
    norm = specs["bert"]["encoder"]["t_layer_0"]["ffn"]["norm"]["scale"]
    assert tuple(norm) == ()


def test_device_input_cache_hit_and_parity(engine):
    """cache_keys pins the region row in a slab slot after the first run; a
    repeat request resolves to the SAME slot (no re-upload) and decodes
    identically to an uncached run."""
    regions = make_regions(1, feat_dim=engine.cfg.model.v_feature_size, seed=3)
    cached = engine.prepare(1, "what is on the table", regions,
                            cache_keys=["imgA"])
    plain = engine.prepare(1, "what is on the table", regions)
    assert cached.cache_keys == ["imgA"] and plain.cache_keys is None

    _, r1 = engine.run(cached)
    slot = engine._input_cache["imgA"]
    assert slot != 0  # slot 0 is the permanent pad row, never a cache entry
    hits_before = engine.input_cache_stats["hits"]
    _, r2 = engine.run(cached)
    assert engine._input_cache["imgA"] == slot  # LRU hit, same slab slot
    assert engine.input_cache_stats["hits"] > hits_before
    _, r_plain = engine.run(plain)
    a1 = [a["confidence"] for a in r1.answers]
    assert a1 == [a["confidence"] for a in r2.answers]
    assert a1 == pytest.approx(
        [a["confidence"] for a in r_plain.answers], abs=1e-6)


def test_device_input_cache_lru_eviction(tiny_config):
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=_cpu_engine_cfg(max_regions=11, device_input_cache_entries=1),
    )
    eng = InferenceEngine(cfg, seed=0)
    regions = make_regions(1, feat_dim=tiny_config.v_feature_size)
    for key in ("a", "b"):
        eng.run(eng.prepare(1, "q", regions, cache_keys=[key]))
    assert list(eng._input_cache) == ["b"]  # "a" evicted

    # entries=0 disables the cache entirely (no key ever recorded)
    cfg0 = FrameworkConfig(
        model=tiny_config,
        engine=_cpu_engine_cfg(max_regions=11, device_input_cache_entries=0),
    )
    eng0 = InferenceEngine(cfg0, seed=0)
    req = eng0.prepare(1, "q", regions, cache_keys=["a"])
    assert req.cache_keys is None


def test_run_many_uses_device_cache_and_matches_solo(engine):
    """The batched path rides the same row cache as solo serving, and its
    per-row decodes match run() row-for-row."""
    feat_dim = engine.cfg.model.v_feature_size
    r_a = make_regions(1, feat_dim=feat_dim, seed=11)
    r_b = make_regions(1, feat_dim=feat_dim, seed=12)
    reqs = [engine.prepare(1, "what is this", r_a, cache_keys=["many_a"]),
            engine.prepare(15, "is it red", r_b, cache_keys=["many_b"]),
            engine.prepare(1, "what is this", r_a, cache_keys=["many_a"])]
    results = engine.run_many(reqs)
    assert {"many_a", "many_b"} <= set(engine._input_cache)
    solo = [engine.run(r)[1] for r in reqs]
    for batched, s in zip(results, solo):
        assert ([a["confidence"] for a in batched.answers]
                == pytest.approx([a["confidence"] for a in s.answers],
                                 abs=1e-5))


def test_retrieval_pads_with_shared_device_row(engine):
    """Bucket padding resolves to slab slot 0 — the permanent device-resident
    pad row (no per-request pad upload, ever) — and padded requests still
    decode all real rows."""
    import jax

    feat_dim = engine.cfg.model.v_feature_size
    regions = make_regions(3, feat_dim=feat_dim, seed=13)
    req = engine.prepare(7, "a dog on a beach", regions,
                         cache_keys=["p0", "p1", "p2"])
    assert req.bucket == 4 and req.n_images == 3
    slab, slots = engine._pack_rows(engine._request_rows(req), req.bucket)
    assert slots.shape == (4,) and slots[3] == 0  # pad row = slab slot 0
    assert all(s != 0 for s in slots[:3])  # real rows never alias the pad
    assert all(isinstance(v, jax.Array) for v in slab.values())
    # Slot 0 carries the canonical pad content: zero features, mask[0]=1.
    assert float(jax.device_get(slab["features"])[0].sum()) == 0.0
    assert int(jax.device_get(slab["image_mask"])[0][0]) == 1
    _, res = engine.run(req)
    assert len(res.ranking) == 3


def test_rows_dispatch_leaf_count_is_constant(engine, monkeypatch):
    """O(1)-leaf regression: the rows program's per-dispatch argument tree
    (slab + pack) must have the SAME leaf count at bucket 1 and bucket 4 —
    3 slab tensors + 5 pack tensors, never 3×bucket image leaves. A leaf
    count that scales with bucket size is the round-5 per-dispatch
    marshalling cost (bench.py ``manyarg_exec_ms``) creeping back in."""
    import jax

    counts = {}
    real = engine._call_forward

    def spy(bucket, collect_attention, *args, **kw):
        counts[bucket] = len(jax.tree_util.tree_leaves(args))
        return real(bucket, collect_attention, *args, **kw)

    monkeypatch.setattr(engine, "_call_forward", spy)
    feat_dim = engine.cfg.model.v_feature_size
    engine.run(engine.prepare(1, "what is this",
                              make_regions(1, feat_dim=feat_dim, seed=21)))
    engine.run(engine.prepare(7, "a dog on a beach",
                              make_regions(3, feat_dim=feat_dim, seed=22)))
    assert counts[1] == counts[4] == 8, counts


def test_bf16_param_storage_decode_parity(tiny_config):
    """EngineConfig.param_dtype="bfloat16" halves served-weight HBM; decodes
    must stay within bf16 rounding of the f32 engine for EVERY decode
    family's head — the parity gate on the serving storage mode."""
    import jax
    import jax.numpy as jnp

    eng32 = InferenceEngine(FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(max_regions=11)), seed=0)
    host = jax.device_get(eng32.params)  # f32 masters, checkpoint-shaped
    engbf = InferenceEngine(FrameworkConfig(
        model=tiny_config,
        engine=dataclasses.replace(_cpu_engine_cfg(max_regions=11),
                                   param_dtype="bfloat16"),
    ), params=host)
    for leaf in jax.tree_util.tree_leaves(engbf.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, leaf.dtype

    feat_dim = tiny_config.v_feature_size
    for task_id, spec in sorted(TASK_REGISTRY.items()):
        regions = make_regions(spec.min_images, feat_dim=feat_dim,
                               seed=40 + task_id)
        question = spec.placeholder or "what is in the picture"
        out32, res32 = eng32.run(eng32.prepare(task_id, question, regions))
        outbf, resbf = engbf.run(engbf.prepare(task_id, question, regions))
        head32 = np.asarray(
            jax.device_get(getattr(out32, spec.head)), np.float32)
        headbf = np.asarray(
            jax.device_get(getattr(outbf, spec.head)), np.float32)
        np.testing.assert_allclose(
            headbf, head32, rtol=0.1, atol=0.05,
            err_msg=f"task {task_id} ({spec.name}) head {spec.head}")
        assert resbf.task_id == res32.task_id == task_id
        assert type(resbf) is type(res32)


def test_int8_param_storage_decode_parity(tiny_config):
    """EngineConfig.param_dtype="int8" quarters served-weight HBM; with
    in-program dequant fused before each matmul, every decode family's
    head must stay within per-channel quantization noise of the f32
    engine. Tolerances are bumped over the bf16 gate — int8 carries ~3 bits
    less mantissa than bf16 through a 12-layer trunk."""
    import jax
    import jax.numpy as jnp

    from vilbert_multitask_tpu import quant
    from vilbert_multitask_tpu.engine.flops import param_tree_bytes

    eng32 = InferenceEngine(FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(max_regions=11)), seed=0)
    host = jax.device_get(eng32.params)  # f32 masters, checkpoint-shaped
    engq = InferenceEngine(FrameworkConfig(
        model=tiny_config,
        engine=dataclasses.replace(_cpu_engine_cfg(max_regions=11),
                                   param_dtype="int8"),
    ), params=host)
    assert quant.tree_is_quantized(engq.params)
    for leaf in jax.tree_util.tree_leaves(engq.params):
        assert leaf.dtype in (jnp.int8, jnp.float32), leaf.dtype
    # The roofline claim: int8 storage reads ~0.3× the f32 bytes (scales
    # and untouched vector leaves keep it off the exact quarter).
    ratio = param_tree_bytes(engq.params) / param_tree_bytes(eng32.params)
    assert ratio < 0.35, ratio

    feat_dim = tiny_config.v_feature_size
    for task_id, spec in sorted(TASK_REGISTRY.items()):
        regions = make_regions(spec.min_images, feat_dim=feat_dim,
                               seed=40 + task_id)
        question = spec.placeholder or "what is in the picture"
        out32, res32 = eng32.run(eng32.prepare(task_id, question, regions))
        outq, resq = engq.run(engq.prepare(task_id, question, regions))
        head32 = np.asarray(
            jax.device_get(getattr(out32, spec.head)), np.float32)
        headq = np.asarray(
            jax.device_get(getattr(outq, spec.head)), np.float32)
        np.testing.assert_allclose(
            headq, head32, rtol=0.15, atol=0.15,
            err_msg=f"task {task_id} ({spec.name}) head {spec.head}")
        assert resq.task_id == res32.task_id == task_id
        assert type(resq) is type(res32)


def test_fused_heads_match_per_head_decode_on_mixed_chunk(tiny_config):
    """The fused decode-head program (one batched slab matmul + in-program
    gather by task id) must decode a mixed-task run_many chunk to the same
    answers as the per-head path (fused_task_heads=False) on the SAME
    weights — answer order exact, confidences to f32 noise."""
    import jax

    fused = InferenceEngine(FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(max_regions=11)), seed=3)
    assert fused.head_slabs is not None
    host = jax.device_get(fused.params)
    perhead = InferenceEngine(FrameworkConfig(
        model=tiny_config,
        engine=dataclasses.replace(_cpu_engine_cfg(max_regions=11),
                                   fused_task_heads=False),
    ), params=host)
    assert perhead.head_slabs is None

    regions = make_regions(4, feat_dim=tiny_config.v_feature_size, seed=5)
    backlog = [
        (1, "what is the man holding", 1),   # VQA labels
        (12, "both images contain wolves", 2),  # NLVR2 pair
        (7, "a red car parked outside", 4),  # retrieval ranking
        (15, "is the bowl right of the mug", 1),  # GQA labels
        (13, "a person entailed by a premise", 1),  # SNLI-VE trinary
        (4, "which hand holds the phone", 1),  # Visual7W grounding
    ]
    res_a = fused.run_many([fused.prepare(t, q, regions[:n])
                            for t, q, n in backlog])
    res_b = perhead.run_many([perhead.prepare(t, q, regions[:n])
                              for t, q, n in backlog])
    assert [r.kind for r in res_a] == [r.kind for r in res_b]
    for a, b in zip(res_a, res_b):
        if a.answers is not None:
            assert [x["answer"] for x in a.answers] == \
                [x["answer"] for x in b.answers]
            np.testing.assert_allclose(
                [x["confidence"] for x in a.answers],
                [x["confidence"] for x in b.answers], rtol=1e-4, atol=1e-6)
        if a.ranking is not None:
            assert [x["image"] for x in a.ranking] == \
                [x["image"] for x in b.ranking]
        if a.boxes is not None:
            np.testing.assert_allclose(
                [x["score"] for x in a.boxes],
                [x["score"] for x in b.boxes], rtol=1e-4, atol=1e-6)


def test_swap_requantizes_f32_checkpoint(tiny_config):
    """POST /admin/swap regression: load_params on an int8 engine must
    RE-QUANTIZE an incoming f32 host tree (restore_params ships f32 when
    the checkpoint predates the storage mode) — and republish the fused
    head slabs against the new tree atomically. A swap that silently
    serves the fat tree defeats the storage mode without failing."""
    import jax
    import jax.numpy as jnp

    from vilbert_multitask_tpu import quant

    eng32 = InferenceEngine(FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(max_regions=11)), seed=0)
    host = jax.device_get(eng32.params)
    engq = InferenceEngine(FrameworkConfig(
        model=tiny_config,
        engine=dataclasses.replace(_cpu_engine_cfg(max_regions=11),
                                   param_dtype="int8"),
    ), params=host)
    slabs_before = engq.head_slabs

    bumped = jax.tree_util.tree_map(lambda x: x * 1.01, host)
    engq.load_params(bumped)  # the rolling_swap load_fn path
    assert quant.tree_is_quantized(engq.params)
    for leaf in jax.tree_util.tree_leaves(engq.params):
        assert leaf.dtype in (jnp.int8, jnp.float32), leaf.dtype
    # Slabs republished against the swapped tree, and quantized kernels
    # stay quantized through the swap.
    assert engq.head_slabs is not slabs_before
    assert quant.is_quantized_leaf(engq.head_slabs["label_d1_kernel"])
    # An already-quantized tree round-trips through load_params untouched
    # (the idempotent double-cast on the restore path).
    requant = jax.device_get(engq.params)
    engq.load_params(requant)
    assert quant.tree_is_quantized(engq.params)
    regions = make_regions(1, feat_dim=tiny_config.v_feature_size, seed=9)
    _, res = engq.run(engq.prepare(1, "what is this", regions))
    assert res.task_id == 1


def test_transfer_dtype_follows_compute_dtype(tiny_config):
    """bf16 engines ship features as bf16 (half the host→device payload;
    bit-identical because the model casts at its first dense layer); f32
    engines — every golden-fixture test — keep f32 features untouched."""
    import jax.numpy as jnp

    f32 = InferenceEngine(FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(max_regions=11)), seed=0)
    regions = make_regions(1, feat_dim=tiny_config.v_feature_size)
    assert f32.prepare(1, "q", regions).features.dtype == np.float32

    bf = InferenceEngine(FrameworkConfig(
        model=tiny_config,
        engine=dataclasses.replace(
            _cpu_engine_cfg(max_regions=11), compute_dtype="bfloat16"),
    ), seed=0)
    req = bf.prepare(1, "q", regions)
    assert req.features.dtype == jnp.bfloat16
    # warmup and live requests must hit the SAME compiled program: the
    # dummy batch ships the transfer dtype too (a dtype mismatch means a
    # silent recompile on the first live request of every bucket).
    for eng in (f32, bf):
        assert (eng._dummy_batch(1)["features"].dtype
                == eng.prepare(1, "q", regions).features.dtype)
    _, result = bf.run(req)  # bf16 inputs flow through the forward + decode
    assert result.task_id == 1


def test_input_cache_stats_counts(tiny_config):
    eng = InferenceEngine(FrameworkConfig(
        model=tiny_config, engine=_cpu_engine_cfg(max_regions=11)), seed=0)
    regions = make_regions(1, feat_dim=tiny_config.v_feature_size)
    assert eng.input_cache_stats == {"entries": 0, "hits": 0, "misses": 0}
    req = eng.prepare(1, "q", regions, cache_keys=["statA"])
    eng.run(req)
    eng.run(req)
    s = eng.input_cache_stats
    assert s["entries"] == 1 and s["misses"] == 1 and s["hits"] >= 1
