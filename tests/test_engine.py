"""Engine tests: every served task type end-to-end on a tiny model (CPU),
bucket padding invariance, and mesh-sharded execution on the virtual
8-device mesh (SURVEY.md §4 device-test strategy)."""

import dataclasses

import numpy as np
import pytest

from vilbert_multitask_tpu.config import (
    EngineConfig,
    FrameworkConfig,
    MeshConfig,
    TASK_REGISTRY,
)
from vilbert_multitask_tpu.engine import InferenceEngine
from vilbert_multitask_tpu.features.pipeline import RegionFeatures
from vilbert_multitask_tpu.parallel import build_mesh, param_specs


def make_regions(n, num_boxes=7, feat_dim=32, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        boxes = rng.uniform(0, 200, size=(num_boxes, 4)).astype(np.float32)
        boxes[:, 2:] = boxes[:, :2] + 10 + boxes[:, 2:] * 0.3
        out.append(
            RegionFeatures(
                features=rng.randn(num_boxes, feat_dim).astype(np.float32),
                boxes=np.clip(boxes, 0, 640),
                image_width=640,
                image_height=480,
            )
        )
    return out


@pytest.fixture(scope="module")
def engine(tiny_config):
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=EngineConfig(compute_dtype="float32", max_regions=11),
    )
    return InferenceEngine(cfg, seed=0)


def test_engine_defaults_to_committed_assets(engine):
    """No tokenizer/label args → the committed vocab + reference-layout
    pickles load by default (never the in-memory demo vocab)."""
    assert engine.tokenizer.cls_id == 101  # bert-base-uncased layout
    assert len(engine.tokenizer.vocab) > 1000
    assert engine.labels.get("vqa")[0] == "yes"  # from the committed pickle
    assert len(engine.labels.get("vqa")) == 3129


TASK_QUESTIONS = {
    1: "what is the man holding",
    2: "what color is the car",
    15: "is the bowl right of the mug",
    4: "which object can you eat",
    11: "the woman in the red coat",
    16: "q: is it a person? a: no q: is it red? a: yes",
    13: "two dogs are playing in the snow",
    12: "both images contain two wolves",
    7: "a man riding a horse on the beach",
}


@pytest.mark.parametrize("task_id", sorted(TASK_REGISTRY))
def test_all_tasks_end_to_end(engine, task_id):
    spec = TASK_REGISTRY[task_id]
    n = spec.min_images
    regions = make_regions(n, feat_dim=engine.cfg.model.v_feature_size)
    req = engine.prepare(task_id, TASK_QUESTIONS[task_id], regions)
    _, result = engine.run(req)
    assert result.task_id == task_id
    assert result.kind == spec.decode
    if spec.decode in ("labels", "binary", "trinary"):
        assert len(result.answers) == min(
            spec.top_k, {"binary": 2, "trinary": 3}.get(spec.decode, spec.top_k)
        )
        confs = [a["confidence"] for a in result.answers]
        assert confs == sorted(confs, reverse=True)
        assert all(0.0 <= c <= 1.0 for c in confs)
    elif spec.decode == "grounding":
        assert len(result.boxes) == spec.top_k
        for b in result.boxes:
            x1, y1, x2, y2 = b["box_xyxy"]
            assert 0 <= x1 <= 640 and 0 <= y2 <= 480 or b["is_global"]
    elif spec.decode == "ranking":
        assert len(result.ranking) == n
        assert [r["rank"] for r in result.ranking] == list(range(1, n + 1))


def test_retrieval_bucket_padding_invariance(engine):
    """3 candidates pad to the 4-bucket; scores of real rows must match an
    unpadded 2-candidate run row-for-row (pad rows never leak into decode)."""
    feat_dim = engine.cfg.model.v_feature_size
    regions = make_regions(3, feat_dim=feat_dim, seed=1)
    req3 = engine.prepare(7, "a dog on a beach", regions)
    assert req3.bucket == 4 and req3.n_images == 3
    _, res3 = engine.run(req3)
    assert len(res3.ranking) == 3

    req2 = engine.prepare(7, "a dog on a beach", regions[:2])
    assert req2.bucket == 2
    _, res2 = engine.run(req2)
    score3 = {r["image"]: r["score"] for r in res3.ranking}
    score2 = {r["image"]: r["score"] for r in res2.ranking}
    for k, v in score2.items():
        assert score3[k] == pytest.approx(v, abs=1e-4)


def test_nlvr2_requires_two_images(engine):
    regions = make_regions(1, feat_dim=engine.cfg.model.v_feature_size)
    with pytest.raises(ValueError, match="task 12"):
        engine.prepare(12, "both images", regions)


def test_guesswhat_dialog_reformat_changes_tokens(engine):
    """Task 16 reformats Q/A dialogs (fixing the reference's dead code,
    SURVEY.md §2.4) — its ids must differ from the raw-encoded query."""
    regions = make_regions(1, feat_dim=engine.cfg.model.v_feature_size)
    q = "q: is it a person? a: no"
    req16 = engine.prepare(16, q, regions)
    req11 = engine.prepare(11, q, regions)
    assert not np.array_equal(req16.text.input_ids, req11.text.input_ids)


def test_mesh_sharded_engine_matches_single_device(tiny_config):
    """dp×tp sharded run (virtual 8-device mesh) must reproduce the
    single-device logits — XLA collectives only change placement."""
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=EngineConfig(compute_dtype="float32", max_regions=11),
        mesh=MeshConfig(dp=4, tp=2),
    )
    base = InferenceEngine(cfg, seed=3)
    mesh = build_mesh(cfg.mesh)
    sharded = InferenceEngine(cfg, seed=3, mesh=mesh)

    regions = make_regions(2, feat_dim=cfg.model.v_feature_size, seed=5)
    req_a = base.prepare(12, "both images contain wolves", regions)
    req_b = sharded.prepare(12, "both images contain wolves", regions)
    out_a, res_a = base.run(req_a)
    out_b, res_b = sharded.run(req_b)
    np.testing.assert_allclose(
        np.asarray(out_a.vil_binary_prediction),
        np.asarray(out_b.vil_binary_prediction), atol=1e-4,
    )
    assert [a["answer"] for a in res_a.answers] == [
        a["answer"] for a in res_b.answers
    ]


def test_partition_rules_shard_big_matmuls(tiny_config):
    """TP rules must actually shard the FFN/QKV kernels when dims divide."""
    cfg = FrameworkConfig(
        model=tiny_config, engine=EngineConfig(compute_dtype="float32"),
        mesh=MeshConfig(dp=4, tp=2),
    )
    eng = InferenceEngine(cfg, seed=0)
    mesh = build_mesh(cfg.mesh)
    specs = param_specs(eng.params, mesh)
    qkv = specs["bert"]["encoder"]["t_layer_0"]["attention"]["qkv"]["kernel"]
    assert tuple(qkv) == (None, "tp")
    ffn_out = specs["bert"]["encoder"]["t_layer_0"]["ffn"]["output"]["kernel"]
    assert tuple(ffn_out) == ("tp", None)
    norm = specs["bert"]["encoder"]["t_layer_0"]["ffn"]["norm"]["scale"]
    assert tuple(norm) == ()
