"""Shape/contract tests for the two-stream model against the reference
10-tuple contract (reference worker.py:287-289)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks


def make_inputs(cfg, batch=2, n_text=9, n_regions=7, seed=0):
    # Every tensor goes through an EXPLICIT same-dtype jnp.asarray: this
    # module runs under the conftest transfer-guard fixture, where an
    # implicit upload fails — and that includes bare jnp.ones (its scalar
    # fill transfers per call) AND jnp.asarray with a *converting* dtype
    # (the eager convert_element_type re-enters the implicit path), so the
    # dtype casts happen host-side in numpy.
    rng = np.random.RandomState(seed)
    return dict(
        input_ids=jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch, n_text)).astype(np.int32)),
        features=jnp.asarray(
            rng.randn(batch, n_regions, cfg.v_feature_size).astype(np.float32)
        ),
        spatials=jnp.asarray(
            rng.rand(batch, n_regions, 5).astype(np.float32)),
        segment_ids=jnp.asarray(np.zeros((batch, n_text), np.int32)),
        input_mask=jnp.asarray(
            (np.arange(n_text)[None, :] < rng.randint(3, n_text, (batch, 1))).astype(
                np.int32
            )
        ),
        image_mask=jnp.asarray(np.ones((batch, n_regions), np.int32)),
        task_ids=jnp.asarray(np.ones((batch, 1), np.int32)),
    )


def jit_apply(model, params, inputs, rngs=None, **static_kw):
    """Forward under jit — the production path (the engine jits every
    forward) and the transfer-guard-clean one: eager ``model.apply``
    materializes its Python scalar constants host-side per op, which the
    conftest ``transfer_guard("disallow")`` fixture rightly rejects."""
    if rngs is None:
        fn = jax.jit(lambda p, i: model.apply(p, **i, **static_kw))
        return fn(params, inputs)
    fn = jax.jit(lambda p, i, r: model.apply(p, **i, rngs=r, **static_kw))
    return fn(params, inputs, rngs)


@pytest.fixture(scope="module")
def model_and_params(tiny_config, rng):
    model = ViLBertForVLTasks(tiny_config)
    inputs = make_inputs(tiny_config)
    params = model.init(rng, **inputs)
    return model, params, inputs


def test_output_shapes(tiny_config, model_and_params):
    model, params, inputs = model_and_params
    cfg = tiny_config
    B, Nt = inputs["input_ids"].shape
    Nv = inputs["features"].shape[1]
    out = jit_apply(model, params, inputs, output_all_attention_masks=True)

    assert out.vil_prediction.shape == (B, cfg.num_labels)
    assert out.vil_prediction_gqa.shape == (B, cfg.gqa_num_labels)
    assert out.vil_logit.shape == (B, 1)
    assert out.vil_binary_prediction.shape == (B // 2, 2)
    assert out.vil_tri_prediction.shape == (B, 3)
    assert out.vision_prediction.shape == (B, Nv, cfg.v_target_size)
    assert out.vision_logit.shape == (B, Nv, 1)
    # task token extends the text sequence by one
    assert out.linguisic_prediction.shape == (B, Nt + 1, cfg.vocab_size)
    assert out.linguisic_logit.shape == (B, Nt + 1, 1)
    # one (text→image, image→text) pair per connection layer
    assert len(out.attn_data_list) == cfg.num_connection_layers
    t2v, v2t = out.attn_data_list[0]
    assert t2v.shape == (B, cfg.bi_num_attention_heads, Nt + 1, Nv)
    assert v2t.shape == (B, cfg.bi_num_attention_heads, Nv, Nt + 1)
    # 10-tuple ordering is stable
    tup = out.to_tuple()
    assert len(tup) == 10
    assert tup[0] is out.vil_prediction and tup[-1] is out.attn_data_list


def test_deterministic_and_finite(model_and_params):
    model, params, inputs = model_and_params
    out1 = jit_apply(model, params, inputs)
    out2 = jit_apply(model, params, inputs)
    np.testing.assert_array_equal(out1.vil_prediction, out2.vil_prediction)
    for leaf in [out1.vil_prediction, out1.vision_logit, out1.linguisic_prediction]:
        assert np.isfinite(np.asarray(leaf)).all()


def test_image_mask_penalty(model_and_params):
    """Masked-out regions must be unselectable by the grounding decode."""
    model, params, inputs = model_and_params
    masked = dict(inputs)
    image_mask = np.asarray(masked["image_mask"]).copy()
    image_mask[:, -2:] = 0
    masked["image_mask"] = jnp.asarray(image_mask)
    out = jit_apply(model, params, masked)
    logits = np.asarray(out.vision_logit)[..., 0]
    assert (logits[:, -2:] < -9000).all()
    assert (logits[:, :-2] > -9000).all()


def test_odd_batch_skips_binary_head(tiny_config, rng):
    model = ViLBertForVLTasks(tiny_config)
    inputs = make_inputs(tiny_config, batch=3)
    params = jax.jit(model.init)(rng, **make_inputs(tiny_config, batch=2))
    out = jit_apply(model, params, inputs)
    assert out.vil_binary_prediction is None


def test_dropout_rng_training_mode(tiny_config, rng):
    model = ViLBertForVLTasks(tiny_config)
    inputs = make_inputs(tiny_config)
    params = jax.jit(model.init)(rng, **inputs)
    # Keys derive from the device-resident session key: PRNGKey(int) would
    # implicitly upload its seed scalar, which the guard fixture forbids.
    k1, k2 = jax.random.split(rng)
    d1 = jit_apply(model, params, inputs, deterministic=False,
                   rngs={"dropout": k1})
    d2 = jit_apply(model, params, inputs, deterministic=False,
                   rngs={"dropout": k2})
    assert not np.allclose(d1.vil_prediction, d2.vil_prediction)


def test_config_json_roundtrip(tmp_path):
    """from_json_file loads the reference config format: a full round trip
    (to_json -> file -> from_json_file) reproduces every field, unknown
    keys are ignored (reference JSONs carry torch-only fields), and the
    json list form of the biattention ids maps back to the typed tuple
    semantics."""
    import dataclasses
    import json as _json

    from vilbert_multitask_tpu.config import ViLBertConfig

    cfg = ViLBertConfig().tiny(hidden_size=96, num_attention_heads=8)
    p = tmp_path / "bert_config.json"
    raw = _json.loads(cfg.to_json())
    raw["torch_only_field"] = {"ignored": True}  # unknown keys tolerated
    p.write_text(_json.dumps(raw))
    back = ViLBertConfig.from_json_file(str(p))
    a, b = dataclasses.asdict(cfg), dataclasses.asdict(back)
    a["v_biattention_id"] = list(a["v_biattention_id"])
    a["t_biattention_id"] = list(a["t_biattention_id"])
    b["v_biattention_id"] = list(b["v_biattention_id"])
    b["t_biattention_id"] = list(b["t_biattention_id"])
    assert a == b
    assert back.hidden_size == 96
