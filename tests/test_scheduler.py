"""Continuous-batching scheduler: window policy, EDF packing, drain, chaos.

The pure pieces (``fire_decision``/``select_batch``/``adapt_window``) test
with fabricated items and explicit clocks — no threads, no sleeps. The
integration tests run the real three-stage data plane over the ``stack``
fixture and assert the serving invariants the scheduler must preserve:
every job one terminal state, clean drain on stop, nothing lost.
"""

import queue as queue_mod
import threading
import time

import pytest

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.resilience import Deadline
from vilbert_multitask_tpu.serve.queue import make_job_message
from vilbert_multitask_tpu.serve.scheduler import (
    ContinuousScheduler,
    ReadyItem,
    adapt_window,
    fire_decision,
    select_batch,
)


class _Req:
    """Stands in for PreparedRequest: only n_images matters to packing."""

    def __init__(self, n_images=1):
        self.n_images = n_images


def _item(n_images=1, deadline=None, enq_t=0.0, solo=False):
    return ReadyItem(None, 1, None if solo else _Req(n_images), 0.0,
                     deadline, enq_t, solo=solo)


# ------------------------------------------------------------ window policy
def test_fire_when_bucket_full():
    fire, wait = fire_decision(
        100.0, rows=8, oldest_enq_t=100.0, nearest_expiry=float("inf"),
        max_rows=8, window_s=0.05, near_deadline_s=0.25)
    assert fire and wait == 0.0


def test_fire_when_window_elapsed():
    fire, wait = fire_decision(
        100.051, rows=1, oldest_enq_t=100.0, nearest_expiry=float("inf"),
        max_rows=8, window_s=0.05, near_deadline_s=0.25)
    assert fire


def test_fire_when_member_near_deadline():
    # 0.1 s of slack < 0.25 s near-deadline bar: the EDF front must not
    # wait out the rest of the window.
    fire, wait = fire_decision(
        100.0, rows=1, oldest_enq_t=99.99, nearest_expiry=100.1,
        max_rows=8, window_s=0.05, near_deadline_s=0.25)
    assert fire


def test_wait_is_bounded_by_window_and_deadline():
    # Neither condition met: wait until whichever comes first — the window
    # closing (0.04 s away) or the nearest deadline entering the
    # near-deadline band (10 - 0.25 s away).
    fire, wait = fire_decision(
        100.01, rows=1, oldest_enq_t=100.0, nearest_expiry=110.0,
        max_rows=8, window_s=0.05, near_deadline_s=0.25)
    assert not fire
    assert wait == pytest.approx(0.04)
    # ...and the deadline band bounds it when nearer than the window.
    fire, wait = fire_decision(
        100.01, rows=1, oldest_enq_t=100.0, nearest_expiry=100.27,
        max_rows=8, window_s=0.05, near_deadline_s=0.25)
    assert not fire
    assert wait == pytest.approx(0.01)


def test_adapt_window_aimd_bounds():
    assert adapt_window(0.01, 1.0, lo=0.002, hi=0.05) == 0.02  # full: x2
    assert adapt_window(0.04, 1.0, lo=0.002, hi=0.05) == 0.05  # capped
    assert adapt_window(0.01, 0.5, lo=0.002, hi=0.05) == 0.005  # partial: /2
    assert adapt_window(0.003, 0.1, lo=0.002, hi=0.05) == 0.002  # floored


# -------------------------------------------------------------- EDF packing
def test_select_batch_orders_by_deadline():
    loose = _item(deadline=Deadline(1000.0))
    tight = _item(deadline=Deadline(50.0))
    none = _item(deadline=None)  # budgetless packs last
    batch, expired, rest = select_batch([none, loose, tight],
                                        time.perf_counter(), max_rows=8)
    assert batch == [tight, loose, none]
    assert expired == [] and rest == []


def test_select_batch_sheds_expired_and_respects_row_budget():
    dead = _item(deadline=Deadline(0.001))
    live = [_item(n_images=4, deadline=Deadline(1000.0 + i))
            for i in range(3)]
    now = time.perf_counter() + 1.0  # dead's budget is long gone
    batch, expired, rest = select_batch([live[2], dead, live[0], live[1]],
                                        now, max_rows=8)
    assert expired == [dead]
    # Row budget stops charging at 8: two 4-row members pack, the third
    # stays ready for the next fire.
    assert batch == [live[0], live[1]]
    assert rest == [live[2]]


def test_solo_items_pack_into_the_fire_order():
    solo = _item(deadline=Deadline(10.0), solo=True)
    packed = _item(deadline=Deadline(1000.0))
    batch, expired, rest = select_batch([packed, solo],
                                        time.perf_counter(), max_rows=8)
    assert batch == [solo, packed]  # EDF puts the tight solo first


# ------------------------------------------------- dispatcher (fake clock)
def test_next_batch_fires_on_elapsed_window_with_injected_clock(stack):
    s, hub, q, store, worker = stack
    now = [100.0]
    sched = ContinuousScheduler(worker, clock=lambda: now[0])
    win0 = sched._window_s
    sched._ready.extend([_item(enq_t=100.0, deadline=None),
                         _item(enq_t=100.0, deadline=None)])
    now[0] = 100.0 + win0 + 1e-4  # oldest member waited out the window
    batch, expired = sched._next_batch()
    assert len(batch) == 2 and not expired
    # Partial fill (2 of 8 rows) shrinks the window, floored at the min.
    assert sched._window_s == s.sched_window_min_s


def test_next_batch_grows_window_after_full_bucket(stack):
    s, hub, q, store, worker = stack
    now = [100.0]
    sched = ContinuousScheduler(worker, clock=lambda: now[0])
    win0 = sched._window_s
    max_rows = worker.engine.cfg.engine.max_batch_rows()
    sched._ready.extend(_item(enq_t=100.0) for _ in range(max_rows))
    batch, expired = sched._next_batch()  # bucket full: fires at once
    assert len(batch) == max_rows
    assert sched._window_s == min(win0 * 2, s.sched_window_max_s)


# --------------------------------------------------------------- integration
def _start(worker, stop):
    t = threading.Thread(
        target=worker.run_forever,
        kwargs={"poll_interval_s": 0.01, "stop_event": stop}, daemon=True)
    t.start()
    return t


def _drain_frames(sub):
    frames = []
    while True:
        try:
            frames.append(sub.get_nowait())
        except queue_mod.Empty:
            return frames


def test_scheduler_serves_mixed_burst_end_to_end(stack):
    s, hub, q, store, worker = stack
    assert s.sched_enabled  # run_forever must route through the scheduler
    sub = hub.subscribe("sched-e2e")
    burst = [(1, ["img_a.jpg"]), (12, ["img_a.jpg", "img_b.jpg"]),
             (7, ["img_a.jpg", "img_b.jpg"])]
    n = 12
    batches_before = obs.BATCHES_DISPATCHED.value()
    for i in range(n):
        task_id, imgs = burst[i % len(burst)]
        q.publish(make_job_message(
            imgs, f"sched q {i}", task_id, "sched-e2e",
            deadline=Deadline(60.0).to_wire(), published_unix=time.time()))
    stop = threading.Event()
    t = _start(worker, stop)
    results = 0
    deadline_t = time.monotonic() + 120
    while results < n and time.monotonic() < deadline_t:
        try:
            frame = sub.get(timeout=30)
        except queue_mod.Empty:
            break
        if "result" in frame:
            results += 1
    stop.set()
    t.join(timeout=30)
    assert not t.is_alive()
    assert results == n
    assert q.counts() == {}  # every job acked, nothing pending/dead
    assert worker.inflight_count() == 0
    assert worker.scheduler is None  # run_forever cleaned up after itself
    # The burst actually went through batched dispatches, and fills were
    # sampled per chunk.
    assert obs.BATCHES_DISPATCHED.value() > batches_before
    assert obs.BATCH_FILL.all_samples()


def test_scheduler_drain_on_stop_releases_cleanly(stack):
    """SIGTERM contract: in-flight batches finish, ready jobs release back
    to pending (requeued notice, no attempt charged), nothing is lost."""
    s, hub, q, store, worker = stack
    sub = hub.subscribe("sched-drain")
    n = 8
    for i in range(n):
        q.publish(make_job_message(["img_a.jpg"], f"drain q {i}", 1,
                                   "sched-drain",
                                   deadline=Deadline(60.0).to_wire()))
    stop = threading.Event()
    t = _start(worker, stop)
    # Stop as soon as the first result lands: some jobs are mid-pipeline.
    deadline_t = time.monotonic() + 120
    while time.monotonic() < deadline_t:
        try:
            if "result" in sub.get(timeout=30):
                break
        except queue_mod.Empty:
            break
    stop.set()
    t.join(timeout=30)
    assert not t.is_alive()
    frames = _drain_frames(sub)
    done = 1 + sum(1 for f in frames if "result" in f)
    counts = q.counts()
    # Every job is exactly one of: completed (acked) or back in pending —
    # never stuck inflight, never dead-lettered by the drain.
    assert counts.get("inflight", 0) == 0
    assert counts.get("dead", 0) == 0
    assert done + counts.get("pending", 0) == n
    assert worker.inflight_count() == 0
    # Released ready jobs told their client (requeued, not lost) and
    # charged no delivery attempt (release, not nack).
    requeued = [f for f in frames if f.get("requeued")]
    if counts.get("pending", 0):
        assert requeued or done + len(requeued) <= n


def test_scheduler_chaos_exactly_one_terminal(stack):
    """The soak's --chaos invariant at unit scale: under injected intake
    errors and dispatch delays, every job still reaches EXACTLY one
    terminal state (result, dead-letter error, or deadline push)."""
    from vilbert_multitask_tpu.resilience import (
        FaultPlan,
        FaultRule,
        clear_plan,
        install_plan,
    )

    s, hub, q, store, worker = stack
    sub = hub.subscribe("sched-chaos")
    n = 10
    install_plan(FaultPlan(7, [
        FaultRule("worker.intake", "error", rate=0.3),
        FaultRule("engine.dispatch", "delay", rate=0.3, delay_s=0.02),
    ]))
    try:
        for i in range(n):
            q.publish(make_job_message(
                ["img_a.jpg"], f"chaos q {i}", 1, "sched-chaos",
                deadline=Deadline(60.0).to_wire()))
        stop = threading.Event()
        t = _start(worker, stop)
        terminals = {}
        dups = []
        deadline_t = time.monotonic() + 120
        while len(terminals) < n and time.monotonic() < deadline_t:
            try:
                frame = sub.get(timeout=30)
            except queue_mod.Empty:
                break
            if "result" in frame:
                state, qq = "result", frame["result"]["question"]
            elif frame.get("deadline_exceeded"):
                state, qq = "deadline", frame.get("question", "")
            elif "error" in frame:
                state, qq = "dead", frame.get("question", "")
            else:
                continue
            if qq in terminals:
                dups.append((qq, state))
            else:
                terminals[qq] = state
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive()
    finally:
        clear_plan()
    assert len(terminals) == n, f"lost jobs: {sorted(terminals)}"
    assert not dups, f"duplicate terminal states: {dups}"
    assert q.counts().get("inflight", 0) == 0
    assert worker.inflight_count() == 0
