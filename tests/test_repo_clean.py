"""Tier-1 gate: the repo's own code passes its own static analyzer.

Runs vmtlint over the configured scan set (``[tool.vmtlint]`` in
pyproject.toml: the library, bench.py, scripts/) and fails on any finding
that is not grandfathered in vmtlint_baseline.json — so a PR that
introduces a host transfer inside jit, a jit-in-loop recompile, a
donated-buffer reuse, or an unblocked timed dispatch fails fast CI, not
a TPU window. Pure AST work: no jax import, runs in well under a second.
"""

import os

from vilbert_multitask_tpu.analysis import baseline as bl
from vilbert_multitask_tpu.analysis.config import load_config
from vilbert_multitask_tpu.analysis.core import analyze_paths
from vilbert_multitask_tpu.analysis.rules import default_rules

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scan():
    cfg, root = load_config(REPO_ROOT)
    assert root == REPO_ROOT, "pyproject.toml with [tool.vmtlint] not found"
    paths = [os.path.join(root, p) for p in cfg.paths]
    findings = analyze_paths(paths, root=root,
                             rules=default_rules(cfg.severity,
                                                 cfg.rule_paths),
                             exclude=cfg.exclude,
                             library_roots=cfg.library_roots,
                             layers=cfg.layers)
    baseline = {}
    if cfg.baseline:
        baseline = bl.load_baseline(os.path.join(root, cfg.baseline))
    return bl.split_baselined(findings, baseline), baseline


def test_repo_has_no_unbaselined_findings():
    (new, _baselined, _stale), _ = _scan()
    assert not new, "vmtlint findings (fix or baseline with justification):\n" \
        + "\n".join(f"  {f.path}:{f.line}: {f.rule} {f.message}"
                    for f in new)


def test_baseline_has_no_stale_entries():
    # Debt that got paid must leave the ledger: a fixed finding's entry is
    # dead weight that would mask a regression at the same fingerprint.
    (_new, _baselined, stale), baseline = _scan()
    assert not stale, "stale baseline entries (remove from " \
        "vmtlint_baseline.json):\n" + "\n".join(
            f"  {fp} ({baseline[fp].get('path')})" for fp in stale)


def test_scan_set_covers_obs_and_vmt109_is_active():
    # The obs/ package must sit inside the configured scan set (it lives
    # under the library root, so no separate path entry is needed) and the
    # wall-clock-duration rule must be registered — otherwise the "obs code
    # is lint-clean" guarantee silently stops meaning anything. VMT115
    # (unbounded-obs-buffer) is scoped to obs/serve paths: it only bites
    # while those paths stay in the scan set, so it is asserted here too.
    cfg, root = load_config(REPO_ROOT)
    obs_dir = os.path.join(root, "vilbert_multitask_tpu", "obs")
    assert os.path.isdir(obs_dir)
    assert any(obs_dir.startswith(os.path.join(root, p)) for p in cfg.paths)
    assert {"VMT109", "VMT115"} <= {r.id for r in default_rules()}


def test_debug_surface_is_wired():
    # The live-health endpoints are load-bearing (check.sh's SLO smoke and
    # the readiness probe poll them); a refactor that drops a route from
    # the dispatch table must fail tier-1, not an incident. Source-level
    # assertion: no server boot, stays jax-free and sub-second.
    api_src = open(os.path.join(
        REPO_ROOT, "vilbert_multitask_tpu", "serve", "http_api.py")).read()
    for route in ("/healthz", "/metrics", "/debug/slo", "/debug/timeseries",
                  "/debug/trace", "/debug/costs", "/debug/traces",
                  "/debug/autopsy", "/debug/autoscale"):
        assert f'"{route}"' in api_src, f"route {route} left the http api"


def test_baseline_entries_carry_justification():
    _, baseline = _scan()
    missing = [fp for fp, e in baseline.items()
               if not str(e.get("justification", "")).strip()]
    assert not missing, f"baseline entries lack a justification: {missing}"


def test_whole_program_rules_active_and_scan_covers_tests():
    # The project-graph rule family must stay registered, the layering
    # contracts declared, and tests/ inside the scan set — otherwise the
    # "whole repo is race/layer clean" guarantee quietly narrows.
    cfg, _root = load_config(REPO_ROOT)
    ids = {r.id for r in default_rules()}
    assert {"VMT110", "VMT111", "VMT112",
            "VMT119", "VMT120", "VMT121", "VMT122", "VMT123",
            "VMT124", "VMT125", "VMT126", "VMT127",
            "VMT128", "VMT129", "VMT130", "VMT131",
            "VMT132", "VMT133", "VMT134", "VMT135", "VMT136",
            "VMT137", "VMT138", "VMT139", "VMT140"} <= ids
    assert cfg.layers, "[tool.vmtlint.layers] contracts disappeared"
    assert any(p == "tests" or p.startswith("tests/") for p in cfg.paths)


def test_layer_contracts_protect_the_analysis_package():
    # analysis/ is the tool itself: it must stay importable without jax
    # (tier-1 lint gating runs before any backend exists). The contract is
    # only as good as its presence in config.
    cfg, _root = load_config(REPO_ROOT)
    assert ("vilbert_multitask_tpu.analysis", "jax") in [
        tuple(c) for c in cfg.layers]
