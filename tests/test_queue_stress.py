"""Cross-process claim safety: the runtime twin of VMT128.

Two REAL OS processes (subprocess, own sqlite connections) hammer
claim/nack/release/ack on one WAL queue file. The static tier proves
every read-modify-write takes BEGIN IMMEDIATE; this test is the dynamic
witness ROADMAP item 3(a) needs before the multi-process soak lands:

- no double-claim: every (job, delivery_count) pair is claimed exactly
  once fleet-wide — two processes handed the same delivery would mean
  the claim SELECT→UPDATE pair wasn't atomic;
- no lost attempts update: the attempt balance at each delivery matches
  the charge/un-charge ledger (claim +1, release -1, nack +0) exactly,
  which a lost nack/release write would skew;
- exactly one terminal per job, and the queue drains to empty.

Throughput lands in PERF_LEDGER.jsonl as ``txn.stress`` so cross-process
claim rate has a tracked baseline.
"""

import os
import subprocess
import sys
import time
from collections import defaultdict

from vilbert_multitask_tpu.obs.ledger import append_entry
from vilbert_multitask_tpu.serve.queue import DurableQueue

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

JOBS = 12

# Each job's scripted life across the fleet, keyed by delivery_count:
# claim #1 -> nack (requeue, attempt stays charged),
# claim #2 -> release (attempt un-charged),
# claim #3 -> ack (terminal). Attempt balance: 1, 2->1, 2.
_WORKER_SRC = r"""
import os, sys, time
from vilbert_multitask_tpu.serve.queue import DurableQueue

db, ident, go_path = sys.argv[1], sys.argv[2], sys.argv[3]
q = DurableQueue(db, max_delivery_attempts=100, max_deliveries=100,
                 visibility_timeout_s=300.0)
print("READY", flush=True)
while not os.path.exists(go_path):
    time.sleep(0.002)
idle = 0
while idle < 40:  # ~200ms with nothing claimable => fleet is drained
    job = q.claim(claimed_by=ident)
    if job is None:
        idle += 1
        time.sleep(0.005)
        continue
    idle = 0
    if job.deliveries == 1:
        action = "nack:" + q.nack(job.id)
    elif job.deliveries == 2:
        q.release(job.id)
        action = "release"
    else:
        q.ack(job.id)
        action = "ack"
    print(f"EV {job.id} {job.deliveries} {job.attempts} {action}",
          flush=True)
"""


def _spawn_worker(db, ident, go_path):
    proc = subprocess.Popen(
        [sys.executable, "-c", _WORKER_SRC, db, ident, go_path],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.stdout.readline().strip() == "READY"
    return proc


def test_two_process_claim_nack_release_ack_exactly_once(tmp_path):
    db = str(tmp_path / "queue.sqlite3")
    go_path = str(tmp_path / "go")
    q = DurableQueue(db, max_delivery_attempts=100, max_deliveries=100,
                     visibility_timeout_s=300.0)
    job_ids = [q.publish({"n": n}) for n in range(JOBS)]

    workers = [_spawn_worker(db, f"stress:{i}", go_path) for i in (0, 1)]
    t0 = time.monotonic()
    with open(go_path, "w") as f:
        f.write("go")
    outs = []
    for proc in workers:
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, err
        outs.append(out)
    elapsed = time.monotonic() - t0

    events = defaultdict(list)  # job id -> [(deliveries, attempts, action)]
    per_worker = []
    for out in outs:
        mine = 0
        for line in out.splitlines():
            if not line.startswith("EV "):
                continue
            _, jid, deliveries, attempts, action = line.split()
            events[int(jid)].append((int(deliveries), int(attempts), action))
            mine += 1
        per_worker.append(mine)

    assert sorted(events) == sorted(job_ids)
    total_claims = sum(per_worker)
    assert total_claims == 3 * JOBS
    # 36 contended claims: a worker that never won a single one would mean
    # the other held the write lock for the whole run.
    assert all(n > 0 for n in per_worker), per_worker

    for jid, evs in events.items():
        evs.sort()  # delivery_count is the fleet-wide claim order
        # No double-claim, no lost delivery: deliveries 1,2,3 exactly once.
        assert [d for d, _, _ in evs] == [1, 2, 3], (jid, evs)
        # No lost attempts update: +1 claim, -1 release, +0 nack.
        assert [a for _, a, _ in evs] == [1, 2, 2], (jid, evs)
        assert [act for _, _, act in evs] == \
            ["nack:pending", "release", "ack"], (jid, evs)

    # Exactly one terminal each: every acked row is gone, nothing lingers.
    assert q.counts() == {}

    append_entry("txn.stress", {
        "claims_per_s": round(total_claims / elapsed, 2),
        "jobs": JOBS,
        "processes": len(workers),
    }, extra={"verdict": "pass"})
