"""Autoscaler decision-policy tests: pure functions, fake clock, no pool.

The control step (:func:`serve.autoscale.decide`) maps (policy, state,
inputs, now) to a decision record with no real clocks, sleeps, or
sockets — so every policy property is testable as arithmetic: hysteresis
band no-ops, cooldown suppression after each action, breaker-flap and
poison-rate gating, min/max clamps, and the slow sustained-slack
scale-in. A thin ``FakePool`` covers the :class:`Autoscaler` plumbing
(tick → decide → actuate → decision ring) without booting anything.
"""

import dataclasses

from vilbert_multitask_tpu.config import ServingConfig
from vilbert_multitask_tpu.serve.autoscale import (
    ACTION_HOLD,
    ACTION_SCALE_IN,
    ACTION_SCALE_OUT,
    Autoscaler,
    AutoscaleInputs,
    AutoscalePolicy,
    ControllerState,
    classify,
    decide,
)


def make_policy(**overrides) -> AutoscalePolicy:
    base = dict(autoscale_enabled=True, autoscale_min_replicas=1,
                autoscale_max_replicas=4,
                autoscale_target_queue_wait_p95_ms=100.0,
                autoscale_burn_threshold=1.0,
                autoscale_band_high=1.2, autoscale_band_low=0.5,
                autoscale_breach_ticks=3, autoscale_slack_ticks=6,
                autoscale_cooldown_out_s=10.0, autoscale_cooldown_in_s=30.0,
                autoscale_max_poison_rate_per_s=0.5)
    base.update(overrides)
    return AutoscalePolicy(ServingConfig(**base))


BREACH = AutoscaleInputs(queue_wait_p95_ms=500.0, live_replicas=2,
                         ready_replicas=2)
SLACK = AutoscaleInputs(queue_wait_p95_ms=10.0, live_replicas=2,
                        ready_replicas=2)


def run_ticks(policy, state, inputs, n, t0=0.0, dt=1.0):
    """n decide steps with a fake advancing clock; returns the last."""
    d = None
    for i in range(n):
        d = decide(policy, state, inputs, t0 + i * dt)
    return d


# ------------------------------------------------------------- classify
def test_classify_hysteresis_band():
    p = make_policy()  # target 100, band 50..120
    assert classify(p, AutoscaleInputs(queue_wait_p95_ms=500.0)) == "breach"
    assert classify(p, AutoscaleInputs(queue_wait_p95_ms=121.0)) == "breach"
    assert classify(p, AutoscaleInputs(queue_wait_p95_ms=10.0)) == "slack"
    # Inside the dead zone: neither direction accumulates.
    assert classify(p, AutoscaleInputs(queue_wait_p95_ms=80.0)) == "in_band"
    assert classify(p, AutoscaleInputs(queue_wait_p95_ms=119.0)) == "in_band"


def test_classify_empty_window_is_slack():
    # No claims in the window (idle trough, cold start): no traffic needs
    # no extra capacity.
    p = make_policy()
    assert classify(p, AutoscaleInputs(queue_wait_p95_ms=None)) == "slack"


def test_classify_burn_needs_both_windows():
    p = make_policy()
    fast_only = AutoscaleInputs(queue_wait_p95_ms=80.0, burn_fast=5.0,
                                burn_slow=0.2)
    assert classify(p, fast_only) == "in_band"  # a blip, not a breach
    both = AutoscaleInputs(queue_wait_p95_ms=80.0, burn_fast=5.0,
                           burn_slow=2.0)
    assert classify(p, both) == "breach"
    # Burn on both windows also blocks the slack side.
    calm_queue = AutoscaleInputs(queue_wait_p95_ms=10.0, burn_fast=5.0,
                                 burn_slow=2.0)
    assert classify(p, calm_queue) == "breach"


# ------------------------------------------------------- sustain windows
def test_hysteresis_band_never_scales():
    p, st = make_policy(), ControllerState()
    mid = AutoscaleInputs(queue_wait_p95_ms=80.0, live_replicas=2)
    for i in range(50):
        assert decide(p, st, mid, float(i))["action"] == ACTION_HOLD
    assert st.breach_ticks == 0 and st.slack_ticks == 0


def test_scale_out_requires_sustained_breach():
    p, st = make_policy(autoscale_breach_ticks=3), ControllerState()
    assert decide(p, st, BREACH, 0.0)["reason"] == "breach_building"
    assert decide(p, st, BREACH, 1.0)["reason"] == "breach_building"
    d = decide(p, st, BREACH, 2.0)
    assert d["action"] == ACTION_SCALE_OUT
    assert d["reason"] == "sustained_breach"
    assert d["target_replicas"] == 3  # live 2 -> 3


def test_breach_counter_resets_on_calm_tick():
    p, st = make_policy(autoscale_breach_ticks=3), ControllerState()
    decide(p, st, BREACH, 0.0)
    decide(p, st, BREACH, 1.0)
    mid = dataclasses.replace(BREACH, queue_wait_p95_ms=80.0)
    decide(p, st, mid, 2.0)  # in-band tick breaks the streak
    assert st.breach_ticks == 0
    assert decide(p, st, BREACH, 3.0)["action"] == ACTION_HOLD


def test_scale_in_requires_sustained_slack_across_slow_window():
    p, st = make_policy(autoscale_slack_ticks=6), ControllerState()
    for i in range(5):
        d = decide(p, st, SLACK, float(i))
        assert d["action"] == ACTION_HOLD
        assert d["reason"] == "slack_building"
    d = decide(p, st, SLACK, 5.0)
    assert d["action"] == ACTION_SCALE_IN
    assert d["reason"] == "sustained_slack"
    assert d["target_replicas"] == 1


# ------------------------------------------------------------- cooldowns
def test_cooldown_suppresses_second_scale_out():
    p, st = make_policy(autoscale_breach_ticks=1,
                        autoscale_cooldown_out_s=10.0), ControllerState()
    assert decide(p, st, BREACH, 0.0)["action"] == ACTION_SCALE_OUT
    d = decide(p, st, BREACH, 1.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "cooldown_out"
    assert d["cooldown"]["out_active"]
    # The clock, not the tick count, ends the cooldown.
    assert decide(p, st, BREACH, 10.5)["action"] == ACTION_SCALE_OUT


def test_cooldown_suppresses_scale_in_after_scale_out():
    # Freshly added capacity immediately makes the queue look calm; the
    # scale-in cooldown is what stops add-retire thrash.
    p = make_policy(autoscale_breach_ticks=1, autoscale_slack_ticks=1,
                    autoscale_cooldown_in_s=30.0)
    st = ControllerState()
    assert decide(p, st, BREACH, 0.0)["action"] == ACTION_SCALE_OUT
    d = decide(p, st, SLACK, 1.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "cooldown_in"
    assert decide(p, st, SLACK, 31.0)["action"] == ACTION_SCALE_IN


def test_cooldown_suppresses_after_scale_in_too():
    p = make_policy(autoscale_slack_ticks=1, autoscale_cooldown_in_s=30.0,
                    autoscale_min_replicas=1)
    st = ControllerState()
    three = dataclasses.replace(SLACK, live_replicas=3)
    assert decide(p, st, three, 0.0)["action"] == ACTION_SCALE_IN
    d = decide(p, st, three, 1.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "cooldown_in"


# ---------------------------------------------------------- health gates
def test_breaker_flap_gates_scale_out():
    p, st = make_policy(autoscale_breach_ticks=1), ControllerState()
    flapping = dataclasses.replace(BREACH, open_breakers=1)
    d = decide(p, st, flapping, 0.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "breaker_open"
    # The moment the breaker closes, the already-sustained breach fires.
    assert decide(p, st, BREACH, 1.0)["action"] == ACTION_SCALE_OUT


def test_poison_storm_gates_scale_out():
    p = make_policy(autoscale_breach_ticks=1,
                    autoscale_max_poison_rate_per_s=0.5)
    st = ControllerState()
    poisoned = dataclasses.replace(BREACH, poison_rate_per_s=2.0)
    for i in range(10):
        d = decide(p, st, poisoned, float(i))
        assert d["action"] == ACTION_HOLD
        assert d["reason"] == "poison_storm"


def test_poison_storm_gates_scale_in_as_well():
    # Retiring capacity mid-incident is no better than adding it.
    p, st = make_policy(autoscale_slack_ticks=1), ControllerState()
    poisoned = dataclasses.replace(SLACK, poison_rate_per_s=2.0)
    assert decide(p, st, poisoned, 0.0)["reason"] == "poison_storm"


# ------------------------------------------------------------ min / max
def test_max_replicas_clamps_scale_out():
    p, st = make_policy(autoscale_breach_ticks=1,
                        autoscale_max_replicas=2), ControllerState()
    at_max = dataclasses.replace(BREACH, live_replicas=2)
    d = decide(p, st, at_max, 0.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "at_max"
    assert d["target_replicas"] == 2


def test_min_replicas_clamps_scale_in():
    p, st = make_policy(autoscale_slack_ticks=1,
                        autoscale_min_replicas=2), ControllerState()
    at_min = dataclasses.replace(SLACK, live_replicas=2)
    d = decide(p, st, at_min, 0.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "at_min"
    assert d["target_replicas"] == 2


def test_boot_in_progress_defers_second_add():
    p, st = make_policy(autoscale_breach_ticks=1), ControllerState()
    booting = dataclasses.replace(BREACH, booting_replicas=1)
    d = decide(p, st, booting, 0.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "boot_in_progress"


def test_no_engine_factory_blocks_scale_out():
    p, st = make_policy(autoscale_breach_ticks=1), ControllerState()
    orphan = dataclasses.replace(BREACH, can_add=False)
    d = decide(p, st, orphan, 0.0)
    assert d["action"] == ACTION_HOLD and d["reason"] == "no_engine_factory"


# --------------------------------------------------- Autoscaler plumbing
class FakePool:
    """replicas_info/add_replica/retire_replica — all the Autoscaler
    touches."""

    def __init__(self, n=1):
        self.infos = [{"name": f"r{i}", "state": "ready",
                       "breaker": "closed"} for i in range(n)]
        self.added = 0
        self.retired = 0

    def replicas_info(self):
        return [dict(r) for r in self.infos]

    def add_replica(self, engine, warm=True):
        self.added += 1
        info = {"name": f"r{len(self.infos)}", "state": "ready",
                "breaker": "closed"}
        self.infos.append(info)
        return type("R", (), {"name": info["name"], "state": "ready"})()

    def retire_replica(self, name=None):
        self.retired += 1
        info = self.infos.pop()
        return {"name": info["name"], "drain_s": 0.0}


def make_autoscaler(pool, clock, **overrides):
    base = dict(autoscale_enabled=True, autoscale_breach_ticks=2,
                autoscale_slack_ticks=3, autoscale_cooldown_out_s=5.0,
                autoscale_cooldown_in_s=5.0, autoscale_max_replicas=3,
                autoscale_target_queue_wait_p95_ms=100.0)
    base.update(overrides)
    return Autoscaler(pool, ServingConfig(**base),
                      engine_factory=lambda: object(), clock=clock)


def test_tick_scales_out_then_in_with_fake_clock():
    pool = FakePool(1)
    t = [0.0]
    a = make_autoscaler(pool, lambda: t[0])
    # Force the sensor sweep: breach inputs while the clock advances.
    breach = AutoscaleInputs(queue_wait_p95_ms=900.0, live_replicas=1,
                             ready_replicas=1)
    a.observe = lambda now=None: dataclasses.replace(
        breach, live_replicas=len(pool.infos),
        ready_replicas=len(pool.infos))
    for _ in range(2):
        t[0] += 1.0
        a.tick()
    assert pool.added == 1
    assert a.target_replicas == 2
    # Now sustained slack past the cooldown: the pool shrinks back.
    slack = AutoscaleInputs(queue_wait_p95_ms=1.0)
    a.observe = lambda now=None: dataclasses.replace(
        slack, live_replicas=len(pool.infos),
        ready_replicas=len(pool.infos))
    t[0] += 10.0  # clear the cooldown
    for _ in range(3):
        t[0] += 1.0
        a.tick()
    assert pool.retired == 1
    assert a.target_replicas == 1


def test_decision_ring_is_bounded():
    pool = FakePool(1)
    t = [0.0]
    a = make_autoscaler(pool, lambda: t[0],
                        autoscale_decision_history=8)
    a.observe = lambda now=None: AutoscaleInputs(queue_wait_p95_ms=80.0)
    for _ in range(50):
        t[0] += 1.0
        a.tick()
    assert len(a.decisions) == 8  # deque(maxlen=...) — the VMT115 bound


def test_debug_payload_shape():
    pool = FakePool(1)
    t = [0.0]
    a = make_autoscaler(pool, lambda: t[0])
    a.observe = lambda now=None: AutoscaleInputs(queue_wait_p95_ms=80.0)
    t[0] = 1.0
    a.tick()
    body = a.debug_payload(limit=10)
    assert body["enabled"] is True
    assert body["target_replicas"] == 1
    assert body["policy"]["max_replicas"] == 3
    rec = body["decisions"][-1]
    # The debug contract: inputs observed, thresholds, action, cooldown.
    assert rec["action"] == ACTION_HOLD
    assert rec["inputs"]["queue_wait_p95_ms"] == 80.0
    assert rec["thresholds"]["breach_above_ms"] == 120.0
    assert "out_active" in rec["cooldown"]
