"""Ring attention (parallel/ring.py): sequence-parallel EXACT attention.

The contract is exactness, not approximation: rotating KV blocks around
the mesh ring with an online-softmax accumulator must reproduce dense
softmax attention to float tolerance, masks included, for any sp that
divides the sequence. Validated on the virtual 8-device CPU mesh
(conftest pins the platform and forces 8 devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from vilbert_multitask_tpu.ops.attention import (
    mask_to_bias,
    multi_head_attention,
)
from vilbert_multitask_tpu.parallel.ring import make_ring_attention


def _qkv(b=2, nq=16, nk=16, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda *s: jnp.asarray(rng.normal(size=s).astype(np.float32))
    return mk(b, nq, h, d), mk(b, nk, h, d), mk(b, nk, h, d)


def _sp_mesh(sp: int):
    if len(jax.devices()) < sp:
        pytest.skip(f"needs {sp} virtual devices")
    return Mesh(np.asarray(jax.devices()[:sp]).reshape(sp), ("sp",))


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(sp):
    mesh = _sp_mesh(sp)
    q, k, v = _qkv()
    ring = make_ring_attention(mesh)
    got = np.asarray(ring(q, k, v))
    want, _ = multi_head_attention(q, k, v, None, dtype=jnp.float32)
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-5)


def test_ring_respects_kv_mask():
    mesh = _sp_mesh(4)
    q, k, v = _qkv(nq=8, nk=32, seed=3)
    rng = np.random.default_rng(4)
    mask = jnp.asarray((rng.random((2, 32)) > 0.4).astype(np.int32))
    # ensure at least one valid key per row (all-masked rows are undefined
    # for both paths)
    mask = mask.at[:, 0].set(1)
    ring = make_ring_attention(mesh)
    got = np.asarray(ring(q, k, v, mask))
    want, _ = multi_head_attention(q, k, v, mask_to_bias(mask),
                                   dtype=jnp.float32)
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-5)


def test_ring_region_scale_shapes():
    """The long-context case this exists for: a region sequence far past
    the serving bucket (e.g. tiled detections), sharded 8 ways — per-device
    KV is N/8 and the output is still exact."""
    mesh = _sp_mesh(8)
    q, k, v = _qkv(b=1, nq=64, nk=512, h=2, d=16, seed=7)
    ring = make_ring_attention(mesh)
    got = np.asarray(ring(q, k, v))
    want, _ = multi_head_attention(q, k, v, None, dtype=jnp.float32)
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-5)
    assert got.shape == (1, 64, 2, 16)


def test_ring_composes_with_data_parallel():
    """dp×sp mesh: batch shards over dp, sequence over sp — each dp group
    runs its own independent ring, still exact vs dense."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(b=4, nq=16, nk=64, seed=9)
    # Masked on purpose: the bias carries per-ROW mask state, so a
    # mis-sharded bias spec (batch dim not split over dp → groups reading
    # each other's mask rows) would only show up with a non-uniform mask.
    rng = np.random.default_rng(10)
    mask = jnp.asarray((rng.random((4, 64)) > 0.4).astype(np.int32))
    mask = mask.at[:, 0].set(1)
    ring = make_ring_attention(mesh, batch_axis="dp")
    got = np.asarray(ring(q, k, v, mask))
    want, _ = multi_head_attention(q, k, v, mask_to_bias(mask),
                                   dtype=jnp.float32)
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-5)


def test_ring_rejects_indivisible_seq():
    mesh = _sp_mesh(8)
    q, k, v = _qkv(nq=12, nk=12)  # 12 % 8 != 0
    ring = make_ring_attention(mesh)
    with pytest.raises(Exception):
        ring(q, k, v)


def test_ring_rejects_indivisible_batch():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    q, k, v = _qkv(b=3, nq=16, nk=16)  # 3 % dp=2 != 0
    ring = make_ring_attention(mesh, batch_axis="dp")
    with pytest.raises(Exception):
        ring(q, k, v)
