"""Train-step tests: loss decreases, sharded step runs on a virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vilbert_multitask_tpu.config import MeshConfig, ViLBertConfig
from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks
from vilbert_multitask_tpu.parallel import sharding as shd
from vilbert_multitask_tpu.parallel.mesh import build_mesh
from vilbert_multitask_tpu.train import (
    LossConfig,
    create_train_state,
    make_train_step,
    multitask_loss,
    shard_train_state,
)
from vilbert_multitask_tpu.train.step import default_optimizer


def _setup(tp_divisible=False):
    cfg = ViLBertConfig().tiny()
    if tp_divisible:
        cfg = cfg.tiny(
            hidden_size=64, num_attention_heads=4, intermediate_size=128,
            v_hidden_size=64, v_num_attention_heads=4, v_intermediate_size=128,
            bi_hidden_size=64, bi_num_attention_heads=4,
            bi_intermediate_size=128,
        )
    model = ViLBertForVLTasks(cfg, dtype=jnp.float32)
    B, Nt, Nv = 4, 12, 9
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (B, Nt)), jnp.int32),
        "features": jnp.asarray(
            rng.normal(size=(B, Nv, cfg.v_feature_size)), jnp.float32),
        "spatials": jnp.asarray(rng.random((B, Nv, 5)), jnp.float32),
        "segment_ids": jnp.zeros((B, Nt), jnp.int32),
        "input_mask": jnp.ones((B, Nt), jnp.int32),
        "image_mask": jnp.ones((B, Nv), jnp.int32),
        "task_ids": jnp.ones((B, 1), jnp.int32),
        "vqa_target": jnp.asarray(
            rng.random((B, cfg.num_labels)) < 0.1, jnp.float32),
        "tri_label": jnp.asarray(rng.integers(0, 3, (B,)), jnp.int32),
        "binary_label": jnp.asarray(rng.integers(0, 2, (B // 2,)), jnp.int32),
        "grounding_target": jnp.asarray(rng.random((B, Nv)), jnp.float32),
        "mlm_labels": jnp.asarray(
            np.where(rng.random((B, Nt)) < 0.3,
                     rng.integers(0, cfg.vocab_size, (B, Nt)), -1), jnp.int32),
    }
    params = model.init(
        jax.random.PRNGKey(0), batch["input_ids"], batch["features"],
        batch["spatials"], batch["segment_ids"], batch["input_mask"],
        batch["image_mask"], None, batch["task_ids"], deterministic=True,
    )["params"]
    return cfg, model, params, batch


def test_loss_decreases_over_steps():
    cfg, model, params, batch = _setup()
    tx = default_optimizer(learning_rate=1e-3, warmup_steps=1, total_steps=50)
    loss_cfg = LossConfig(heads=("vqa", "tri", "grounding", "binary", "mlm"))
    step = make_train_step(model, tx, loss_cfg, donate=False)
    state = create_train_state(params, tx)
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss/total"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert int(state.step) == 4


def test_all_loss_heads_finite():
    cfg, model, params, batch = _setup()
    out = model.apply(
        {"params": params}, batch["input_ids"], batch["features"],
        batch["spatials"], batch["segment_ids"], batch["input_mask"],
        batch["image_mask"], None, batch["task_ids"], deterministic=True,
    )
    batch = dict(batch)
    batch["gqa_target"] = jnp.zeros((4, cfg.gqa_num_labels), jnp.float32)
    batch["mrm_target"] = jnp.full((4, 9, cfg.v_target_size),
                                   1.0 / cfg.v_target_size, jnp.float32)
    batch["mrm_mask"] = jnp.ones((4, 9), jnp.float32)
    loss_cfg = LossConfig(
        heads=("vqa", "gqa", "binary", "tri", "grounding", "retrieval",
               "mlm", "mrm"),
        retrieval_group_size=2,
    )
    total, metrics = multitask_loss(loss_cfg, out, batch)
    assert np.isfinite(float(total))
    assert len([k for k in metrics if k.startswith("loss/")]) == 9


def test_sharded_train_step_on_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg, model, params, batch = _setup(tp_divisible=True)
    mesh = build_mesh(MeshConfig(tp=2), devices=jax.devices()[:8])
    tx = default_optimizer(warmup_steps=1, total_steps=10)
    loss_cfg = LossConfig(heads=("vqa", "tri"))
    state = shard_train_state(create_train_state(params, tx), mesh)

    # tp rules actually sharded the big matmuls (not everything replicated).
    ffn_kernel = state.params["bert"]["encoder"]["t_layer_0"]["ffn"][
        "intermediate"]["kernel"]
    assert "tp" in str(ffn_kernel.sharding.spec)

    with mesh:
        placed = jax.device_put(batch, shd.batch_shardings(batch, mesh))
        step = make_train_step(model, tx, loss_cfg, donate=False)
        state2, metrics = step(state, placed)
    assert np.isfinite(float(metrics["loss/total"]))
    # Updated params keep their shardings (no silent replication).
    ffn2 = state2.params["bert"]["encoder"]["t_layer_0"]["ffn"][
        "intermediate"]["kernel"]
    assert ffn2.sharding == ffn_kernel.sharding


def test_place_batch_callback_path_matches_device_put():
    """The multi-process placement path (make_array_from_callback slicing a
    host-global batch) must produce arrays identical in value, sharding,
    and train-step result to the single-process device_put path — it's the
    same global batch either way, only shard construction differs."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg, model, params, batch = _setup(tp_divisible=True)
    mesh = build_mesh(MeshConfig(tp=2), devices=jax.devices()[:8])
    with mesh:
        a = shd.place_batch(batch, mesh)
        b = shd.place_batch(batch, mesh, _force_callback=True)
    for leaf_a, leaf_b in zip(jax.tree_util.tree_leaves(a),
                              jax.tree_util.tree_leaves(b)):
        assert leaf_a.sharding == leaf_b.sharding
        np.testing.assert_array_equal(np.asarray(leaf_a),
                                      np.asarray(leaf_b))
    tx = default_optimizer(warmup_steps=1, total_steps=10)
    loss_cfg = LossConfig(heads=("vqa", "tri"))
    state = shard_train_state(create_train_state(params, tx), mesh)
    with mesh:
        step = make_train_step(model, tx, loss_cfg, donate=False)
        _, m_a = step(state, a)
        _, m_b = step(state, b)
    assert float(m_a["loss/total"]) == float(m_b["loss/total"])


def test_remat_matches_plain_gradients():
    """cfg.remat changes memory/FLOPs, never values: same loss, same grads."""
    import dataclasses

    cfg, model, params, batch = _setup()
    cfg_r = dataclasses.replace(cfg, remat=True)
    model_r = ViLBertForVLTasks(cfg_r, dtype=jnp.float32)
    loss_cfg = LossConfig(heads=("vqa", "tri"))

    def loss_fn(m):
        def f(p):
            out = m.apply(
                {"params": p}, batch["input_ids"], batch["features"],
                batch["spatials"], batch["segment_ids"], batch["input_mask"],
                batch["image_mask"], None, batch["task_ids"],
                deterministic=True,
            )
            return multitask_loss(loss_cfg, out, batch)[0]
        return f

    l0, g0 = jax.value_and_grad(loss_fn(model))(params)
    l1, g1 = jax.value_and_grad(loss_fn(model_r))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5),
        g0, g1)


def test_dryrun_multichip_entry():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
