"""AOT executable cache tests (engine/aotcache.py): disk round trip — a
second engine boots entirely from deserialized executables with zero
compiles — plus fingerprint hygiene (a stale/corrupt entry must MISS and
recompile, never poison the boot)."""

import dataclasses
import glob
import os

import numpy as np
import pytest

from vilbert_multitask_tpu.config import EngineConfig, FrameworkConfig
from vilbert_multitask_tpu.engine import aotcache, runtime
from vilbert_multitask_tpu.engine.runtime import InferenceEngine
from vilbert_multitask_tpu.features.pipeline import RegionFeatures


def _regions(n=1, num_boxes=4, feat_dim=32, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        boxes = rng.uniform(0, 100, size=(num_boxes, 4)).astype(np.float32)
        boxes[:, 2:] = boxes[:, :2] + 10
        out.append(RegionFeatures(
            features=rng.randn(num_boxes, feat_dim).astype(np.float32),
            boxes=boxes, image_width=320, image_height=240))
    return out


def _cfg(tiny_config, aot_dir, **kw):
    """One-bucket engine: warmup is exactly one compiled program, so the
    hit/compile accounting below has no slack to hide in."""
    knobs = dict(
        max_text_len=8, max_regions=5, num_features=4,
        image_buckets=(1,), throughput_buckets=None,
        device_input_cache_entries=2, compute_dtype="float32",
        use_pallas_coattention=False, use_pallas_self_attention=False,
        aot_cache_dir=str(aot_dir))
    knobs.update(kw)
    return FrameworkConfig(model=tiny_config, engine=EngineConfig(**knobs))


def _total_compiles() -> float:
    return sum(runtime._COMPILES.collect().values())


def test_record_key_matches_manifest_grammar():
    key = aotcache.record_key("rows", 8, "bfloat16", True, "dp-1.tp1.sp1",
                              False)
    assert key == "rows/b8/bfloat16/fused/dp-1.tp1.sp1/plain"
    assert aotcache.entry_filename(key).endswith(aotcache.ENTRY_SUFFIX)
    assert "/" not in aotcache.entry_filename(key)


def test_fingerprint_discriminates(tiny_config):
    cfg = FrameworkConfig(model=tiny_config)
    fp = aotcache.compile_fingerprint(cfg)
    # model_gen folds into the hash (a degraded engine must not share
    # entries with the pristine one), and any compile-relevant knob flip
    # lands in a different cache generation.
    assert aotcache.fingerprint_hash(fp) != aotcache.fingerprint_hash(
        fp, model_gen=1)
    other = dataclasses.replace(
        cfg, engine=dataclasses.replace(cfg.engine, param_dtype="bfloat16"))
    assert (aotcache.fingerprint_hash(aotcache.compile_fingerprint(other))
            != aotcache.fingerprint_hash(fp))
    # Non-compile knobs (paths, warmup parallelism) must NOT split caches.
    same = dataclasses.replace(
        cfg, engine=dataclasses.replace(cfg.engine, vocab_path="elsewhere",
                                        parallel_warmup=False))
    assert (aotcache.fingerprint_hash(aotcache.compile_fingerprint(same))
            == aotcache.fingerprint_hash(fp))


def test_round_trip_zero_compiles(tiny_config, tmp_path):
    aot_dir = tmp_path / "aot"
    cfg = _cfg(tiny_config, aot_dir)

    cold = InferenceEngine(cfg, seed=0)
    cold.warmup()
    stats = cold.live_stats()
    assert stats["engine_aot_compiled"] == 1.0
    assert stats["engine_aot_hits"] == 0.0
    assert cold._aot.entry_count(cold._model_gen) == 1
    assert stats.get("engine_boot_compile_s", 0.0) > 0.0
    _, ref = cold.run(cold.prepare(1, "what is this", _regions()))

    # Fresh engine, same dir: every warmup program deserializes — the
    # fast-boot contract is ZERO traces/compiles for manifest-covered
    # programs (ISSUE acceptance).
    before = _total_compiles()
    warm = InferenceEngine(cfg, params=cold.params, seed=0)
    assert warm.boot_from_cache() is True
    stats = warm.live_stats()
    assert stats["engine_aot_hits"] == 1.0
    assert stats["engine_aot_compiled"] == 0.0
    assert stats["engine_aot_fallbacks"] == 0.0
    assert stats.get("engine_boot_cache_load_s", 0.0) > 0.0
    assert _total_compiles() == before
    # The deserialized executable must SERVE, same numbers as the compiled
    # one (shared params → identical logits path).
    _, out = warm.run(warm.prepare(1, "what is this", _regions()))
    assert out.task_id == ref.task_id
    assert ([a["answer"] for a in out.answers]
            == [a["answer"] for a in ref.answers])
    np.testing.assert_allclose([a["confidence"] for a in out.answers],
                               [a["confidence"] for a in ref.answers],
                               rtol=1e-5)
    assert warm.live_stats()["engine_aot_fallbacks"] == 0.0
    assert _total_compiles() == before


def test_corrupt_entry_misses_and_recompiles(tiny_config, tmp_path):
    aot_dir = tmp_path / "aot"
    cfg = _cfg(tiny_config, aot_dir)
    cold = InferenceEngine(cfg, seed=0)
    cold.warmup()
    (entry,) = glob.glob(
        os.path.join(str(aot_dir), "**", "*" + aotcache.ENTRY_SUFFIX),
        recursive=True)
    with open(entry, "wb") as f:
        f.write(b"not a pickled executable")

    # A poisoned entry must cost a recompile, never a broken engine:
    # load fails -> miss -> compile -> the entry is rewritten healthy.
    warm = InferenceEngine(cfg, params=cold.params, seed=0)
    assert warm.boot_from_cache() is False
    warm.warmup()
    stats = warm.live_stats()
    assert stats["engine_aot_compiled"] == 1.0
    _, out = warm.run(warm.prepare(1, "what is this", _regions()))
    assert out.answers

    rewarmed = InferenceEngine(cfg, params=cold.params, seed=0)
    assert rewarmed.boot_from_cache() is True


def test_stale_fingerprint_misses(tiny_config, tmp_path):
    """Same cache dir, different compile-relevant config: the entry must
    MISS on fingerprint, not deserialize into a wrong-shape executable."""
    aot_dir = tmp_path / "aot"
    cold = InferenceEngine(_cfg(tiny_config, aot_dir), seed=0)
    cold.warmup()
    changed = _cfg(tiny_config, aot_dir, max_regions=7)
    other = InferenceEngine(changed, seed=0)
    assert other.boot_from_cache() is False
