"""Remote worker mode: a worker drains the queue over HTTP (VERDICT r2 #6).

The reference's broker is a network service (demo/sender.py:12-15), so web
tier and GPU worker deploy on separate hosts. These tests stand up the real
ApiServer over an ephemeral port and drive a real ServeWorker whose queue/
store/hub are the HTTP shims from serve/remote.py — the full job pipeline
(claim → intake → forward → persist → push → ack) crossing a real socket.
"""

import dataclasses
import json
import urllib.error
import urllib.request

import pytest

from vilbert_multitask_tpu.serve import (
    DurableQueue,
    PushHub,
    ResultStore,
    ServeWorker,
)
from vilbert_multitask_tpu.serve.http_api import ApiServer
from vilbert_multitask_tpu.serve.remote import (
    RemoteHub,
    RemoteQueue,
    RemoteStore,
    WorkerApiClient,
    build_remote_worker,
)


@pytest.fixture()
def web_host(tiny_framework_cfg, tmp_path):
    """The web-tier half: queue + store + hub behind a live ApiServer."""
    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        media_root=str(tmp_path / "media"),
    )
    hub = PushHub()
    q = DurableQueue(s.queue_db_path,
                     max_delivery_attempts=s.max_delivery_attempts)
    store = ResultStore(s.results_db_path)
    api = ApiServer(q, store, hub, s)
    port = api.start()
    yield s, hub, q, store, f"http://127.0.0.1:{port}"
    api.stop()


def _submit(base_url, payload):
    req = urllib.request.Request(
        base_url + "/", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_remote_worker_drains_queue_over_http(web_host, engine):
    s, hub, q, store, url = web_host
    sub = hub.subscribe("sock-remote")
    out = _submit(url, {"task_id": 1, "socket_id": "sock-remote",
                        "question": "What is this?",
                        "image_list": ["img_a"]})
    assert "job_id" in out

    client = WorkerApiClient(url)
    worker = ServeWorker(engine, RemoteQueue(client), RemoteStore(client),
                         RemoteHub(client), s)
    assert worker.step_batch() == 1
    assert q.counts() == {}  # acked over HTTP → gone

    frames = []
    while not sub.empty():
        frames.append(sub.get_nowait())
    results = [f for f in frames if "result" in f]
    assert len(results) == 1
    assert results[0]["result"]["answers"]

    rows = store.recent()
    assert len(rows) == 1 and rows[0]["answer_text"]["answers"]


def test_remote_worker_failure_nacks_to_dead_letter(web_host, engine):
    s, hub, q, store, url = web_host
    # Unknown feature key → intake raises on the worker, every redelivery,
    # until the job dead-letters — all over HTTP.
    _submit(url, {"task_id": 1, "socket_id": "sock-x",
                  "question": "what", "image_list": ["missing_key"]})
    client = WorkerApiClient(url)
    worker = ServeWorker(engine, RemoteQueue(client), RemoteStore(client),
                         RemoteHub(client), s)
    for _ in range(s.max_delivery_attempts + 1):
        worker.step_batch()
    assert q.counts().get("dead", 0) == 1


def test_worker_endpoints_reject_bad_token(tiny_framework_cfg, tmp_path):
    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        worker_token="sekrit",
    )
    hub = PushHub()
    q = DurableQueue(s.queue_db_path)
    store = ResultStore(s.results_db_path)
    api = ApiServer(q, store, hub, s)
    port = api.start()
    url = f"http://127.0.0.1:{port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            WorkerApiClient(url).post("/worker/claim", {})
        assert ei.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            WorkerApiClient(url, token="wrong").post("/worker/claim", {})
        assert ei.value.code == 401
        out = WorkerApiClient(url, token="sekrit").post("/worker/claim", {})
        assert out == {"job": None}
        # Public endpoints stay open: job submission is the browser surface.
        resp = _submit(url, {"task_id": 1, "socket_id": "s",
                             "question": "q", "image_list": ["img_a"]})
        assert "job_id" in resp
    finally:
        api.stop()


def test_build_remote_worker_reuses_engine(web_host, engine):
    _, _, _, _, url = web_host
    w = build_remote_worker(url, engine=engine)
    assert w.engine is engine
    assert isinstance(w.queue, RemoteQueue)


def test_remote_worker_survives_transport_flaps(web_host, engine):
    """Injected transport faults (FaultInjected ⊂ ConnectionError) hit the
    real retry path: the shared RetryPolicy jitters and retries, the job
    still completes exactly once, and the breaker never trips (the flap
    count stays under its threshold)."""
    from vilbert_multitask_tpu.resilience import (
        CircuitBreaker,
        FaultPlan,
        FaultRule,
        RetryBudget,
        RetryPolicy,
        clear_plan,
        install_plan,
    )

    s, hub, q, store, url = web_host
    sub = hub.subscribe("sock-flap")
    _submit(url, {"task_id": 1, "socket_id": "sock-flap",
                  "question": "what is this", "image_list": ["img_a"]})
    client = WorkerApiClient(
        url,
        retry=RetryPolicy(max_attempts=6, base_delay_s=0.001,
                          max_delay_s=0.01,
                          budget=RetryBudget(1e9, 1e9)),
        breaker=CircuitBreaker(name="test.flap", failure_threshold=5,
                               window_s=5.0, reset_timeout_s=0.05))
    worker = ServeWorker(engine, RemoteQueue(client), RemoteStore(client),
                         RemoteHub(client), s)
    plan = install_plan(FaultPlan(3, [
        FaultRule("remote.post", "error", rate=0.4, max_injections=4)]))
    try:
        done = 0
        for _ in range(10):  # a flapped claim reads as "drained" → re-step
            done += worker.step_batch()
            if done:
                break
        assert done == 1
        assert q.counts() == {}
        assert plan.injections().get("remote.post", 0) > 0  # flaps happened
    finally:
        clear_plan()
    frames = []
    while not sub.empty():
        frames.append(sub.get_nowait())
    assert len([f for f in frames if "result" in f]) == 1  # exactly once
