"""The MFU numerator must be real: pin the analytic matmul-FLOP count
(engine/flops.py) against XLA's own cost model for the compiled serving
forward. The analytic count ignores elementwise ops, so it must come in at
or just under XLA's figure — never above it (an overcount would inflate
every MFU number the bench reports)."""

import numpy as np
import pytest

from vilbert_multitask_tpu.config import EngineConfig, FrameworkConfig
from vilbert_multitask_tpu.engine.flops import (
    peak_flops_for,
    serving_forward_flops,
)
from vilbert_multitask_tpu.engine.runtime import InferenceEngine


@pytest.mark.parametrize("batch", [1, 2])
def test_flops_estimate_vs_xla_cost_analysis(tiny_config, batch):
    cfg = FrameworkConfig(
        model=tiny_config,
        engine=EngineConfig(
            compute_dtype="float32", max_regions=11,
            use_pallas_coattention=False, use_pallas_self_attention=False,
        ),
    )
    eng = InferenceEngine(cfg, seed=0)
    d = eng._dummy_batch(batch)
    fwd = eng._forward(batch, False)
    compiled = fwd.lower(eng.params, eng.head_slabs, d).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0]
    xla_flops = float(cost["flops"])
    est = serving_forward_flops(cfg.model, cfg.engine, batch)
    # Lower bound, but a tight one: within 2% above is measurement noise in
    # XLA's model; more than 40% below means a missing term.
    assert est <= xla_flops * 1.02, (est, xla_flops)
    assert est >= 0.6 * xla_flops, (est, xla_flops)


def test_flops_scale_linearly_in_batch(tiny_config):
    e = EngineConfig()
    one = serving_forward_flops(tiny_config, e, 1)
    ten = serving_forward_flops(tiny_config, e, 10)
    assert ten == 10 * one
    # Flagship config sanity: a serving forward is tens of GFLOPs per row.
    from vilbert_multitask_tpu.config import ViLBertConfig

    full = serving_forward_flops(ViLBertConfig(), e, 1)
    assert 10e9 < full < 500e9, full


def test_peak_lookup():
    assert peak_flops_for("TPU v5 lite") == 197e12
    assert peak_flops_for("TPU v4") == 275e12
    assert peak_flops_for("cpu") is None
    assert np.isfinite(peak_flops_for("TPU v6 lite"))


def test_bench_sweep_parse_is_forgiving():
    """A malformed BENCH_SWEEP_ROWS env var must degrade to 'no sweep',
    never raise: the parse runs at bench.py import time, before the
    orchestrator's always-emit-JSON kill trap exists."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        pathlib.Path(__file__).resolve().parents[1] / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._parse_sweep("64,128") == (64, 128)
    assert bench._parse_sweep("") == ()
    assert bench._parse_sweep("64;128") == ()          # wrong separator
    assert bench._parse_sweep("64, oops,0,-3") == (64,)  # junk dropped
