"""MODEL-level ring attention (VERDICT r4 #4).

tests/test_ring_attention.py proves the ring PRIMITIVE exact; these tests
prove the MODEL runs sequence-parallel: a ViLBertForVLTasks built with a
RingContext routes visual-stream self-attention through shard_map/ppermute
over the mesh's sp axis (structurally asserted on the jaxpr), reproduces the
dense model's outputs from the SAME param tree, and stays dense below the
region-count threshold or on non-dividing shapes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vilbert_multitask_tpu.config import MeshConfig, ViLBertConfig
from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks
from vilbert_multitask_tpu.parallel import build_mesh
from vilbert_multitask_tpu.parallel.ring import RingContext

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")

N_REGIONS = 16  # divisible by sp=4, above the test threshold
BATCH = 4  # divisible by dp=2; even for the NLVR2 head


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshConfig(dp=2, tp=1, sp=4))


@pytest.fixture(scope="module")
def cfg():
    # XLA attention (no Pallas interpret-mode slowdown on CPU); the ring
    # path composes with the kernels identically — it replaces the same
    # FusedSelfAttention computation.
    return dataclasses.replace(
        ViLBertConfig().tiny(),
        use_pallas_self_attention=False, use_pallas_coattention=False)


def _inputs(cfg, n_regions=N_REGIONS, batch=BATCH, n_text=9, seed=3):
    rng = np.random.default_rng(seed)
    inp = dict(
        input_ids=jnp.asarray(
            rng.integers(0, cfg.vocab_size, (batch, n_text)), jnp.int32),
        features=jnp.asarray(
            rng.normal(size=(batch, n_regions, cfg.v_feature_size)),
            jnp.float32),
        spatials=jnp.asarray(
            rng.random((batch, n_regions, 5)), jnp.float32),
        segment_ids=jnp.zeros((batch, n_text), jnp.int32),
        input_mask=jnp.ones((batch, n_text), jnp.int32),
        image_mask=jnp.asarray(
            rng.integers(0, 2, (batch, n_regions)) | np.eye(
                1, n_regions, dtype=np.int64)[0], jnp.int32),
        task_ids=jnp.asarray(
            rng.integers(0, cfg.num_task_tokens, (batch, 1)), jnp.int32),
    )
    return inp


def _apply(model, params, inp):
    return model.apply(
        {"params": params}, inp["input_ids"], inp["features"],
        inp["spatials"], inp["segment_ids"], inp["input_mask"],
        inp["image_mask"], None, inp["task_ids"], deterministic=True)


def test_model_runs_sequence_parallel_and_matches_dense(sp_mesh, cfg):
    """Same params, two instances: the ring model must (a) actually shard —
    its jaxpr contains the ring's ppermute collective — and (b) reproduce
    the dense outputs (exact attention, fp32 tolerance)."""
    ctx = RingContext(sp_mesh, sp_axis="sp", batch_axis="dp",
                      min_seq=N_REGIONS)
    dense = ViLBertForVLTasks(cfg, dtype=jnp.float32)
    ring = ViLBertForVLTasks(cfg, ring_v=ctx, dtype=jnp.float32)
    inp = _inputs(cfg)
    params = dense.init(
        jax.random.PRNGKey(0), inp["input_ids"], inp["features"],
        inp["spatials"], inp["segment_ids"], inp["input_mask"],
        inp["image_mask"], None, inp["task_ids"], deterministic=True,
    )["params"]

    jaxpr = str(jax.make_jaxpr(lambda p, i: _apply(ring, p, i))(params, inp))
    assert "ppermute" in jaxpr, "ring model compiled without the collective"
    dense_jaxpr = str(
        jax.make_jaxpr(lambda p, i: _apply(dense, p, i))(params, inp))
    assert "ppermute" not in dense_jaxpr

    out_d = _apply(dense, params, inp)
    out_r = _apply(ring, params, inp)
    for head in ("vil_prediction", "vil_logit", "vision_logit",
                 "vil_binary_prediction", "linguisic_logit"):
        np.testing.assert_allclose(
            np.asarray(getattr(out_r, head)),
            np.asarray(getattr(out_d, head)),
            atol=3e-5, rtol=1e-4, err_msg=f"{head} diverges under sp")


def test_model_ring_works_under_jit(sp_mesh, cfg):
    """The serving/training path jits the forward; shard_map must compose."""
    ctx = RingContext(sp_mesh, sp_axis="sp", batch_axis="dp",
                      min_seq=N_REGIONS)
    ring = ViLBertForVLTasks(cfg, ring_v=ctx, dtype=jnp.float32)
    inp = _inputs(cfg)
    params = ring.init(
        jax.random.PRNGKey(1), inp["input_ids"], inp["features"],
        inp["spatials"], inp["segment_ids"], inp["input_mask"],
        inp["image_mask"], None, inp["task_ids"], deterministic=True,
    )["params"]
    out = jax.jit(lambda p, i: _apply(ring, p, i).vil_prediction)(params, inp)
    assert np.isfinite(np.asarray(out)).all()


def test_model_ring_composes_with_tensor_parallel(cfg):
    """tp×sp mesh: the ring's head axis rides tp (no per-layer all-gather
    of Megatron head-sharded Q/K/V), and the outputs still match dense.
    from_mesh includes head_axis only when tp is real."""
    mesh = build_mesh(MeshConfig(dp=1, tp=2, sp=4))
    ctx = RingContext.from_mesh(mesh, min_seq=N_REGIONS)
    assert ctx is not None and ctx.head_axis == "tp"
    assert ctx.batch_axis is None  # dp=1 → no batch sharding
    dense = ViLBertForVLTasks(cfg, dtype=jnp.float32)
    ring = ViLBertForVLTasks(cfg, ring_v=ctx, dtype=jnp.float32)
    inp = _inputs(cfg, batch=2)
    params = dense.init(
        jax.random.PRNGKey(4), inp["input_ids"], inp["features"],
        inp["spatials"], inp["segment_ids"], inp["input_mask"],
        inp["image_mask"], None, inp["task_ids"], deterministic=True,
    )["params"]
    out_r = jax.jit(lambda p, i: _apply(ring, p, i).vil_prediction)(
        params, inp)
    out_d = _apply(dense, params, inp).vil_prediction
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               atol=3e-5, rtol=1e-4)


def test_threshold_and_divisibility_keep_dense(sp_mesh, cfg):
    """Below min_seq, or when the region count doesn't divide sp, the
    static gate keeps the dense program — no collective in the jaxpr."""
    dense_ctx = RingContext(sp_mesh, sp_axis="sp", batch_axis="dp",
                            min_seq=N_REGIONS * 4)  # threshold above N
    model = ViLBertForVLTasks(cfg, ring_v=dense_ctx, dtype=jnp.float32)
    inp = _inputs(cfg)
    params = model.init(
        jax.random.PRNGKey(0), inp["input_ids"], inp["features"],
        inp["spatials"], inp["segment_ids"], inp["input_mask"],
        inp["image_mask"], None, inp["task_ids"], deterministic=True,
    )["params"]
    jaxpr = str(jax.make_jaxpr(lambda p, i: _apply(model, p, i))(params, inp))
    assert "ppermute" not in jaxpr

    # 15 regions: clears a low threshold but does not divide sp=4.
    ctx = RingContext(sp_mesh, sp_axis="sp", batch_axis="dp", min_seq=8)
    model15 = ViLBertForVLTasks(cfg, ring_v=ctx, dtype=jnp.float32)
    inp15 = _inputs(cfg, n_regions=15)
    jaxpr15 = str(
        jax.make_jaxpr(lambda p, i: _apply(model15, p, i))(params, inp15))
    assert "ppermute" not in jaxpr15
