"""Offline extractor CLI + multi-host init helper tests."""

import os

import numpy as np
import pytest

from vilbert_multitask_tpu.features.extract import (
    extract_one,
    main as extract_main,
    preprocess_image,
)
from vilbert_multitask_tpu.features.store import (
    load_reference_npy,
    load_vlfr,
)
from vilbert_multitask_tpu.parallel import distributed


def _raw_dump(tmp_path, name, n=30, c=6, d=32, w=200, h=150, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.random((n,)) * (w - 40)
    y1 = rng.random((n,)) * (h - 40)
    boxes = np.stack([x1, y1, x1 + 20 + rng.random(n) * 20,
                      y1 + 20 + rng.random(n) * 20], axis=1).astype(np.float32)
    scores = rng.random((n, c)).astype(np.float32)
    scores /= scores.sum(axis=1, keepdims=True)
    path = str(tmp_path / f"{name}.npz")
    np.savez(path, boxes=boxes, cls_scores=scores,
             features=rng.normal(size=(n, d)).astype(np.float32),
             image_width=w, image_height=h)
    return path


def test_extract_one_npy_schema(tmp_path):
    raw = _raw_dump(tmp_path, "img_x")
    out = extract_one(raw, str(tmp_path / "feats"), fmt="npy", num_keep=10)
    assert out.endswith("img_x.npy")
    region = load_reference_npy(out)
    assert region.features.shape[0] == region.num_boxes <= 10
    assert region.boxes.shape == (region.num_boxes, 4)
    assert (region.image_width, region.image_height) == (200, 150)


def test_extract_cli_vlfr_glob(tmp_path):
    for i in range(3):
        _raw_dump(tmp_path, f"img_{i}", seed=i)
    out_dir = str(tmp_path / "feats")
    extract_main(["--raw", str(tmp_path), "--out", out_dir,
                  "--format", "vlfr", "--num-keep", "5"])
    files = sorted(os.listdir(out_dir))
    assert files == ["img_0.vlfr", "img_1.vlfr", "img_2.vlfr"]
    region = load_vlfr(os.path.join(out_dir, "img_0.vlfr"))
    assert region.num_boxes <= 5


def test_extract_selection_matches_jax_path(tmp_path):
    """CLI output boxes = the JAX select_top_regions keep set (ordering and
    membership), regardless of which backend (C++/JAX) actually ran."""
    from vilbert_multitask_tpu.ops import nms as jnms

    raw_path = _raw_dump(tmp_path, "img_p", seed=3)
    raw = np.load(raw_path)
    keep, valid, *_ = (np.asarray(x) for x in jnms.select_top_regions(
        raw["boxes"], raw["cls_scores"], num_keep=8))
    out = extract_one(raw_path, str(tmp_path / "f"), fmt="npy", num_keep=8)
    region = load_reference_npy(out)
    np.testing.assert_array_equal(
        region.boxes, raw["boxes"][keep[: int(valid)]])


def test_preprocess_image_contract():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (300, 400, 3), np.uint8)
    out, scale = preprocess_image(img)
    # short side 300 → target 800 would put long side at 1067 ≤ 1333
    assert scale == pytest.approx(800 / 300)
    assert out.shape == (800, 1067, 3)
    # BGR flip + mean subtraction: channel 0 is original channel 2 minus mean
    assert out.dtype == np.float32
    img2 = rng.integers(0, 255, (200, 2000, 3), np.uint8)
    _, scale2 = preprocess_image(img2)
    assert scale2 == pytest.approx(1333 / 2000)  # long-side clamp


# ------------------------------------------------------------- distributed
def test_distributed_single_process_fallback(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert distributed.initialize() is False  # no coordinator → no-op


def test_distributed_requires_full_args(monkeypatch):
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="num_processes"):
        distributed.initialize(coordinator_address="host:1234")


def test_runtime_info_shape():
    info = distributed.runtime_info()
    assert info["process_count"] == 1
    assert info["global_device_count"] >= 1
    assert info["backend"] == "cpu"
