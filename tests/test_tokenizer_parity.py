"""Tokenizer/label-asset parity tests (VERDICT round 1, item 4).

The genuine bert-base-uncased vocab is not present in this image and cannot
be fetched (zero egress), so exact-id parity against that asset is pinned
two ways instead:

1. ALGORITHM parity: the installed ``transformers`` BertTokenizer (the
   lineage successor of the reference's ``pytorch_transformers`` tokenizer,
   worker.py:42,537-539) is run over the SAME committed vocab file; our
   pure-host implementation must produce identical ids for every fixture
   sentence — basic-tokenization, lower-casing, accent stripping,
   punctuation splits, greedy longest-match WordPiece, [UNK] behavior and
   special-token placement all verified against an independent
   implementation.
2. STABILITY: a committed golden fixture pins the exact ids across rounds.

When the real vocab file is swapped in (EngineConfig.vocab_path), the same
algorithm produces the reference's exact ids — that is what (1) proves.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from vilbert_multitask_tpu import assets
from vilbert_multitask_tpu.engine.labels import LabelMapStore
from vilbert_multitask_tpu.text.wordpiece import FullTokenizer

GOLDEN = pathlib.Path(__file__).parent / "fixtures" / "tokenizer_golden.json"

SENTENCES = [
    "what is the man holding",
    "What COLOR is the CAR?",
    "Is the bowl to the right of the mug?",
    "don't stop, it's fine!!",
    "a café near the résumé drop-off",  # combining accents
    "two dogs (both black) are playing; really?",
    "the qwzx unheard-of contraption",  # forces multi-piece + char fallback
    "q: is it a person? a: no q: is it red? a: yes",
    "  weird \t whitespace \n everywhere  ",
    "12 bananas cost $3.50 at 7-eleven",
    "今天 weather is nice",  # CJK chars split out
    "skateboarding skateboarder skateboards",
]


@pytest.fixture(scope="module")
def tok() -> FullTokenizer:
    return FullTokenizer.from_vocab_file(assets.default_vocab_path())


def test_special_token_ids_match_bert_base(tok):
    """The committed vocab keeps bert-base-uncased's special ids, so the
    checkpoint-visible contract ([CLS]=101 etc.) survives a vocab swap."""
    assert tok.pad_id == 0
    assert tok.vocab["[UNK]"] == 100
    assert tok.cls_id == 101
    assert tok.sep_id == 102
    assert tok.vocab["[MASK]"] == 103


def test_algorithm_parity_vs_transformers(tok):
    transformers = pytest.importorskip("transformers")
    hf = transformers.BertTokenizer(
        vocab_file=assets.default_vocab_path(), do_lower_case=True)
    for s in SENTENCES:
        ours = tok.encode(s)
        theirs = hf.encode(s, add_special_tokens=False)
        assert ours == theirs, f"ids diverge for {s!r}"
        ours_special = tok.add_special_tokens_single_sentence(ours)
        theirs_special = hf.encode(s, add_special_tokens=True)
        assert ours_special == theirs_special, f"specials diverge for {s!r}"
        assert tok.tokenize(s) == hf.tokenize(s), f"tokens diverge for {s!r}"


def test_golden_ids_pinned(tok):
    """Exact ids are pinned across rounds; regenerate deliberately with
    tests/fixtures/regen via this file's __main__."""
    golden = json.loads(GOLDEN.read_text())
    assert list(golden) == SENTENCES, "fixture sentences drifted"
    for s in SENTENCES:
        assert tok.encode(s) == golden[s], f"golden drift for {s!r}"


def test_label_assets_reference_layout():
    """The committed label maps load through the reference's pickle layout
    ({root}/{name}/cache/trainval_label2ans.pkl, worker.py:299,311) with the
    exact head widths (3129 VQA / 1533 GQA)."""
    store = LabelMapStore(root=assets.default_labels_root(),
                          allow_synthetic=False)
    vqa = store.get("vqa")
    gqa = store.get("gqa")
    assert len(vqa) == 3129 and vqa[0] == "yes" and vqa[1] == "no"
    assert len(gqa) == 1533 and gqa[0] == "no"


if __name__ == "__main__":
    t = FullTokenizer.from_vocab_file(assets.default_vocab_path())
    GOLDEN.parent.mkdir(parents=True, exist_ok=True)
    GOLDEN.write_text(json.dumps({s: t.encode(s) for s in SENTENCES},
                                 indent=1))
    print(f"wrote {GOLDEN}")
