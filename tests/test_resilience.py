"""resilience/: policy units (deadline, retry, breaker, admission), the
fault-injection plane, and their serve-tier integration (expired-deadline
terminal push, 429 shed, deadline on the wire, graceful drain)."""

import dataclasses
import http.client
import json
import queue as queue_mod
import time

import pytest

from vilbert_multitask_tpu.resilience import (
    AdmissionController,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    FaultInjected,
    FaultPlan,
    FaultRule,
    RetryBudget,
    RetryPolicy,
    clear_plan,
    fault_point,
    install_plan,
)
from vilbert_multitask_tpu.serve.http_api import ApiServer
from vilbert_multitask_tpu.serve.queue import make_job_message


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    """No test may leak an installed FaultPlan into the rest of tier-1."""
    clear_plan()
    yield
    clear_plan()


def _drain(sub) -> list:
    frames = []
    while True:
        try:
            frames.append(sub.get_nowait())
        except queue_mod.Empty:
            return frames


# ------------------------------------------------------------- deadlines
def test_deadline_monotonic_expiry():
    d = Deadline(0.03)
    assert not d.expired() and d.remaining_s() > 0
    time.sleep(0.04)
    assert d.expired() and d.remaining_s() < 0


def test_deadline_wire_round_trip_preserves_budget():
    d = Deadline(120.0)
    wire = d.to_wire()
    assert set(wire) == {"budget_s", "issued_unix"}
    back = Deadline.from_wire(wire)
    # Re-anchored in (this) process: nearly the full budget remains.
    assert 119.0 < back.remaining_s() <= 120.0


def test_deadline_expired_on_the_wire():
    # Calendar math, not a duration: forging a wire stamp issued in the past.
    wire = {"budget_s": 10.0,
            "issued_unix": time.time() - 60.0}  # vmtlint: disable=VMT109
    assert Deadline.from_wire(wire).expired()


@pytest.mark.parametrize("garbage", [
    None, "nope", 7, {}, {"budget_s": "x", "issued_unix": "y"},
    {"budget_s": 5.0},
])
def test_deadline_from_wire_tolerates_garbage(garbage):
    # Jobs published by pre-deadline clients must keep serving.
    assert Deadline.from_wire(garbage) is None


# --------------------------------------------------------------- retries
def test_retry_backoff_is_full_jitter():
    p = RetryPolicy(max_attempts=9, base_delay_s=0.5, max_delay_s=4.0)
    import random

    rng = random.Random(3)
    for attempt, cap in [(0, 0.5), (1, 1.0), (2, 2.0), (3, 4.0), (6, 4.0)]:
        draws = [p.backoff_s(attempt, rng=rng) for _ in range(50)]
        assert all(0.0 <= d <= cap for d in draws)
        assert len({round(d, 6) for d in draws}) > 10  # actually random


def test_retry_call_retries_then_succeeds():
    calls, sleeps = [], []
    p = RetryPolicy(max_attempts=5, base_delay_s=0.01,
                    budget=RetryBudget(1e9, 1e9))

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("flap")
        return "ok"

    assert p.call(flaky, site="t.flaky", sleep=sleeps.append) == "ok"
    assert len(calls) == 3 and len(sleeps) == 2


def test_retry_call_exhausts_and_raises_last():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                    budget=RetryBudget(1e9, 1e9))
    with pytest.raises(ConnectionError, match="always"):
        p.call(lambda: (_ for _ in ()).throw(ConnectionError("always")),
               site="t.dead", sleep=lambda s: None)


def test_retry_no_retry_propagates_immediately():
    calls = []
    p = RetryPolicy(max_attempts=5, budget=RetryBudget(1e9, 1e9))

    class Fatal(ConnectionError):
        """Deterministic subclass of the retryable class (HTTPError-style)."""

    def fatal():
        calls.append(1)
        raise Fatal("401")

    with pytest.raises(Fatal):
        p.call(fatal, site="t.fatal", retry_on=(ConnectionError,),
               no_retry=(Fatal,), sleep=lambda s: None)
    assert len(calls) == 1  # never retried


def test_retry_budget_stops_the_storm():
    # Empty bucket, zero refill: each caller gets its first attempt and
    # then fails fast instead of sleeping toward a dead dependency.
    budget = RetryBudget(rate_per_s=0.0, capacity=1.0)
    p = RetryPolicy(max_attempts=5, base_delay_s=0.001, budget=budget)
    sleeps = []

    def dead():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        p.call(dead, site="t.budget", sleep=sleeps.append)  # spends the token
    with pytest.raises(ConnectionError):
        p.call(dead, site="t.budget", sleep=sleeps.append)  # budget empty
    assert len(sleeps) == 1


# -------------------------------------------------------------- breakers
class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_trips_after_threshold_in_window():
    clk = FakeClock()
    b = CircuitBreaker(name="t1", failure_threshold=3, window_s=10.0,
                       reset_timeout_s=5.0, clock=clk)
    for _ in range(2):
        b.record_failure()
    assert b.state == "closed"
    b.preflight()  # still admits
    b.record_failure()
    assert b.state == "open"
    with pytest.raises(CircuitOpenError):
        b.preflight()


def test_breaker_sliding_window_prunes_old_failures():
    clk = FakeClock()
    b = CircuitBreaker(name="t2", failure_threshold=3, window_s=10.0,
                       clock=clk)
    b.record_failure()
    b.record_failure()
    clk.t += 11.0  # both age out of the window
    b.record_failure()
    assert b.state == "closed"


def test_breaker_half_open_probe_success_closes():
    clk = FakeClock()
    b = CircuitBreaker(name="t3", failure_threshold=1, window_s=10.0,
                       reset_timeout_s=5.0, clock=clk)
    b.record_failure()
    assert b.state == "open"
    clk.t += 5.0
    assert b.state == "half_open"
    b.preflight()  # the probe slot
    with pytest.raises(CircuitOpenError):
        b.preflight()  # only one probe admitted
    b.record_success()
    assert b.state == "closed"
    b.preflight()  # closed again: calls flow


def test_breaker_half_open_probe_failure_reopens():
    clk = FakeClock()
    b = CircuitBreaker(name="t4", failure_threshold=1, window_s=10.0,
                       reset_timeout_s=5.0, clock=clk)
    b.record_failure()
    clk.t += 5.0
    b.preflight()
    b.record_failure()  # probe failed → re-open, timer restarts
    assert b.state == "open"
    clk.t += 4.9
    assert b.state == "open"
    clk.t += 0.2
    assert b.state == "half_open"


def test_retry_call_respects_breaker():
    clk = FakeClock()
    b = CircuitBreaker(name="t5", failure_threshold=2, window_s=60.0,
                       reset_timeout_s=30.0, clock=clk)
    p = RetryPolicy(max_attempts=10, base_delay_s=0.001,
                    budget=RetryBudget(1e9, 1e9))
    calls = []

    def dead():
        calls.append(1)
        raise ConnectionError("down")

    # Breaker opens after 2 failures; the loop then sheds WITHOUT calling.
    with pytest.raises(CircuitOpenError):
        p.call(dead, site="t.breaker", breaker=b, sleep=lambda s: None)
    assert len(calls) == 2


# -------------------------------------------------------------- admission
def test_admission_sheds_on_depth_and_age():
    a = AdmissionController(max_queue_depth=4, max_queue_age_s=30.0,
                            retry_after_s=7.0)
    assert a.admit(depth=3, oldest_age_s=1.0).admitted
    d = a.admit(depth=4, oldest_age_s=1.0)
    assert (d.admitted, d.reason, d.retry_after_s) == (False, "queue_depth", 7.0)
    d = a.admit(depth=0, oldest_age_s=31.0)
    assert (d.admitted, d.reason) == (False, "queue_age")
    # Empty queue reports no age — admitted.
    assert a.admit(depth=0, oldest_age_s=None).admitted


def test_admission_zero_threshold_disables_signal():
    a = AdmissionController(max_queue_depth=0, max_queue_age_s=0.0)
    assert a.admit(depth=10_000, oldest_age_s=1e6).admitted


# ------------------------------------------------------------ fault plane
def test_fault_plan_same_seed_same_schedule():
    def schedule(seed):
        plan = FaultPlan(seed, [FaultRule("site.x", "error", rate=0.5)])
        return [plan.decide("site.x") is not None for _ in range(200)]

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(8)


def test_fault_plan_sites_are_independent_streams():
    plan = FaultPlan(7, [FaultRule("a", "error", rate=0.5),
                         FaultRule("b", "error", rate=0.5)])
    seq_a = [plan.decide("a") is not None for _ in range(50)]
    # Interleaving calls at another site must not perturb a's stream.
    plan2 = FaultPlan(7, [FaultRule("a", "error", rate=0.5),
                          FaultRule("b", "error", rate=0.5)])
    seq_a2 = []
    for _ in range(50):
        plan2.decide("b")
        seq_a2.append(plan2.decide("a") is not None)
    assert seq_a == seq_a2


def test_fault_plan_kinds_and_caps():
    plan = install_plan(FaultPlan(3, [
        FaultRule("inj.err", "error", rate=1.0, max_injections=2),
        FaultRule("inj.slow", "delay", rate=1.0, delay_s=0.01),
        FaultRule("inj.bad", "corrupt", rate=1.0),
        FaultRule("pfx.*", "error", rate=1.0),
    ]))
    for _ in range(2):
        with pytest.raises(FaultInjected):
            fault_point("inj.err")
    assert fault_point("inj.err", "through") == "through"  # cap reached
    t0 = time.perf_counter()
    assert fault_point("inj.slow", 5) == 5
    assert time.perf_counter() - t0 >= 0.01
    out = fault_point("inj.bad", {"q": "abc", "n": 1})
    assert out["__fault_corrupted__"] and out["q"] == "cba" and out["n"] == 1
    with pytest.raises(FaultInjected):
        fault_point("pfx.anything")  # prefix rule
    assert plan.injections()["inj.err"] == 2
    assert plan.calls()["inj.err"] == 3


def test_fault_injected_is_a_connection_error():
    # Injections must flow through the transport-error handling the serve
    # tiers already have (_NET_ERRORS) — no test-only error paths.
    assert issubclass(FaultInjected, ConnectionError)


def test_disabled_fault_point_passthrough_and_overhead():
    """Tier-1 guard: sites live on production paths unconditionally
    because the disabled plane is one global read (< 5 us per call)."""
    payload = {"x": 1}
    assert fault_point("any.site", payload) is payload
    n = 10_000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            fault_point("hot.site")
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 5e-6, f"disabled fault_point costs {best * 1e6:.2f} us"


# ------------------------------------------------- serve-tier integration
def test_expired_deadline_terminates_without_forward(stack, monkeypatch):
    s, hub, q, store, worker = stack
    sub = hub.subscribe("sockD")
    forwards = []
    monkeypatch.setattr(
        worker.engine, "run_many",
        lambda *a, **k: forwards.append("run_many") or [])
    monkeypatch.setattr(
        worker.engine, "run",
        lambda *a, **k: forwards.append("run") or (None, None))
    # Calendar math, not a duration: a wire stamp issued a minute ago.
    q.publish(make_job_message(
        ["img_a.jpg"], "too late", 1, "sockD",
        deadline={"budget_s": 0.01,
                  "issued_unix": time.time() - 60}))  # vmtlint: disable=VMT109
    assert worker.step_batch() == 1  # terminated = reached a final state
    assert forwards == []  # the engine never dispatched
    assert q.counts() == {}  # acked away, not dead-lettered
    frames = _drain(sub)
    dead = [f for f in frames if f.get("deadline_exceeded")]
    assert len(dead) == 1 and dead[0]["question"] == "too late"


def test_deadline_rides_the_job_body(stack):
    s, hub, q, store, worker = stack
    api = ApiServer(q, store, hub, s)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("POST", "/", body=json.dumps({
            "task_id": 1, "socket_id": "sockW", "question": "q",
            "image_list": ["img_a.jpg"], "deadline_s": 45.0,
        }), headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
    finally:
        api.stop()
    job = q.claim()
    assert job.body["deadline"]["budget_s"] == 45.0
    assert Deadline.from_wire(job.body["deadline"]).remaining_s() > 40.0
    q.ack(job.id)


def test_http_shed_replies_429_with_retry_after(stack):
    s, hub, q, store, worker = stack
    s429 = dataclasses.replace(s, admission_max_queue_depth=2,
                               admission_retry_after_s=3.0)
    api = ApiServer(q, store, hub, s429)
    port = api.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        body = {"task_id": 1, "socket_id": "x", "question": "q",
                "image_list": ["img_a.jpg"]}
        for expect in (200, 200):  # depth 0 → 1 → 2
            conn.request("POST", "/", body=json.dumps(body),
                         headers={"Content-Type": "application/json"})
            assert conn.getresponse().status == expect
        conn.request("POST", "/", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        shed = json.loads(resp.read())
        assert resp.status == 429
        assert resp.getheader("Retry-After") == "3"
        assert shed["reason"] == "queue_depth"
        # The shed shows up in the Prometheus exposition.
        conn.request("GET", "/metrics?format=prometheus")
        text = conn.getresponse().read().decode()
        assert 'vmt_shed_total{reason="queue_depth"}' in text
    finally:
        api.stop()


def test_intake_fault_injection_dead_letters(stack):
    s, hub, q, store, worker = stack
    sub = hub.subscribe("sockF")
    install_plan(FaultPlan(1, [FaultRule("worker.intake", "error")]))
    q.publish(make_job_message(["img_a.jpg"], "doomed", 1, "sockF"))
    for _ in range(s.max_delivery_attempts):
        worker.step_batch()
    assert q.counts() == {"dead": 1}
    dead = [f for f in _drain(sub) if "error" in f]
    assert len(dead) == 1 and "injected fault" in dead[0]["error"]


def test_publish_fault_injection_raises_before_enqueue(stack):
    # queue.publish sits BEFORE the INSERT: an injected transport error
    # must surface to the caller with nothing durably enqueued (the
    # client retries; at-least-once starts only after the row exists).
    s, hub, q, store, worker = stack
    install_plan(FaultPlan(1, [FaultRule("queue.publish", "error")]))
    with pytest.raises(FaultInjected):
        q.publish(make_job_message(["img_a.jpg"], "never lands", 1, "sockQ"))
    assert q.counts() == {}  # no half-published row


def test_push_fault_injection_is_best_effort(stack):
    # push.publish is best-effort by contract: an injected fault on the
    # frame hub drops that frame (returns 0 fanout) instead of raising
    # into the worker's terminal path.
    s, hub, q, store, worker = stack
    sub = hub.subscribe("sockP")
    install_plan(FaultPlan(1, [FaultRule("push.publish", "error")]))
    assert hub.publish("sockP", {"answer": "lost"}) == 0
    assert _drain(sub) == []  # subscriber saw nothing
    clear_plan()
    assert hub.publish("sockP", {"answer": "ok"}) == 1  # plane recovers


# --------------------------------------------------------- graceful drain
def test_drain_stops_claiming_when_stop_set(stack):
    import threading

    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "later", 1, "sockG"))
    stop = threading.Event()
    stop.set()
    assert worker.step_batch(stop_event=stop) == 0
    assert q.counts() == {"pending": 1}  # untouched for the next worker


def test_abandon_inflight_releases_and_notifies(stack):
    s, hub, q, store, worker = stack
    sub = hub.subscribe("sockR")
    q.publish(make_job_message(["img_a.jpg"], "requeue me", 1, "sockR"))
    job = worker._claim()
    assert job is not None and q.counts() == {"inflight": 1}
    assert worker.abandon_inflight() == 1
    assert q.counts() == {"pending": 1}
    frames = [f for f in _drain(sub) if f.get("requeued")]
    assert len(frames) == 1 and frames[0]["question"] == "requeue me"
    # release() charged no delivery attempt: the next claim is attempt 1.
    job2 = q.claim()
    assert job2.attempts == 1
    q.ack(job2.id)
    assert worker.abandon_inflight() == 0  # nothing left in hand
