"""Cross-task micro-batching: one forward serving a mixed-task batch."""

import numpy as np
import pytest

from vilbert_multitask_tpu.serve import make_job_message


def _prep(engine, task_id, question, keys):
    regions = engine.feature_store.get_batch(keys)
    return engine.prepare(task_id, question, regions, keys)


def test_run_many_matches_individual_runs(engine):
    reqs = [
        _prep(engine, 1, "what is this", ["img_a.jpg"]),
        _prep(engine, 15, "is it red", ["img_b.jpg"]),
        _prep(engine, 13, "a dog plays", ["img_a.jpg"]),
        _prep(engine, 11, "the left box", ["img_b.jpg"]),
    ]
    batched = engine.run_many(reqs)
    assert [r.kind for r in batched] == ["labels", "labels", "trinary",
                                        "grounding"]
    for req, got in zip(reqs, batched):
        _, solo = engine.run(req)
        if got.answers is not None:
            assert [a["answer"] for a in got.answers] == \
                [a["answer"] for a in solo.answers]
            np.testing.assert_allclose(
                [a["confidence"] for a in got.answers],
                [a["confidence"] for a in solo.answers], atol=1e-4)
        if got.boxes is not None:
            assert [b["region_index"] for b in got.boxes] == \
                [b["region_index"] for b in solo.boxes]


def test_run_many_batches_multi_image(engine):
    """NLVR2 pairs and retrieval candidate sets ride the batched path
    (round-3 ceiling removed): results must match solo run() exactly, in
    input order, with pair rows staying even-aligned inside chunks."""
    reqs = [
        _prep(engine, 12, "both show dogs", ["img_a.jpg", "img_b.jpg"]),
        _prep(engine, 1, "what is this", ["img_a.jpg"]),
        _prep(engine, 12, "both show cats", ["img_b.jpg", "img_a.jpg"]),
        _prep(engine, 7, "a dog in snow",
              ["img_a.jpg", "img_b.jpg", "img_a.jpg", "img_b.jpg"]),
        _prep(engine, 12, "two wolves", ["img_a.jpg", "img_b.jpg"]),
    ]
    batched = engine.run_many(reqs)
    assert [r.kind for r in batched] == ["binary", "labels", "binary",
                                        "ranking", "binary"]
    for req, got in zip(reqs, batched):
        _, solo = engine.run(req)
        if got.answers is not None:
            assert [a["answer"] for a in got.answers] == \
                [a["answer"] for a in solo.answers], req.spec.task_id
            np.testing.assert_allclose(
                [a["confidence"] for a in got.answers],
                [a["confidence"] for a in solo.answers], atol=1e-4)
        if got.ranking is not None:
            assert [r["image"] for r in got.ranking] == \
                [r["image"] for r in solo.ranking]


def test_run_many_rejects_oversized_request(engine):
    """A request wider than the chunk cannot pack — clear error."""
    reqs = [_prep(engine, 7, "query",
                  ["img_a.jpg", "img_b.jpg"] * 2)]
    with pytest.raises(ValueError, match="exceeds"):
        engine.run_many(reqs, chunk_rows=2)


def test_run_many_empty(engine):
    assert engine.run_many([]) == []


def test_run_many_chunks_beyond_max_bucket(engine):
    """Batches above the largest compiled bucket split, not crash."""
    max_bucket = max(engine.cfg.engine.image_buckets)
    n = max_bucket + 3
    reqs = [
        _prep(engine, 1, f"question {i}", [("img_a.jpg", "img_b.jpg")[i % 2]])
        for i in range(n)
    ]
    results = engine.run_many(reqs)
    assert len(results) == n
    assert all(r.kind == "labels" for r in results)


def test_throughput_bucket_chunking(tiny_framework_cfg, features_dir):
    """run_many chunks at the throughput bucket (not the max image bucket)
    when one is configured, produces the same decodes, and honors the
    chunk_rows override; row_bucket_for folds the extra bucket in."""
    import dataclasses

    from vilbert_multitask_tpu.engine.runtime import InferenceEngine
    from vilbert_multitask_tpu.features.store import FeatureStore

    cfg = dataclasses.replace(
        tiny_framework_cfg,
        engine=dataclasses.replace(
            tiny_framework_cfg.engine,
            image_buckets=(1, 2, 4), throughput_buckets=(8,)),
    )
    assert cfg.engine.row_bucket_for(5) == 8
    assert cfg.engine.bucket_for(4) == 4  # image-axis semantics unchanged
    with pytest.raises(ValueError, match="row bucket"):
        cfg.engine.row_bucket_for(9)

    eng = InferenceEngine(cfg, feature_store=FeatureStore(features_dir))
    reqs = [
        _prep(eng, 1, f"question {i}", [("img_a.jpg", "img_b.jpg")[i % 2]])
        for i in range(6)
    ]
    batched = eng.run_many(reqs)  # one 8-row chunk (6 rows + 2 pad)
    assert len(batched) == 6
    solo_answers = []
    for r in reqs:
        _, s = eng.run(r)
        solo_answers.append([a["answer"] for a in s.answers])
    assert [[a["answer"] for a in b.answers] for b in batched] == solo_answers
    # Override back to the image buckets: two chunks of 4 — identical output.
    chunked = eng.run_many(reqs, chunk_rows=4)
    assert [[a["answer"] for a in b.answers]
            for b in chunked] == solo_answers
    with pytest.raises(ValueError, match="row bucket"):
        eng.run_many(reqs, chunk_rows=16)
    for bad in (0, -4):  # must error, never silently drop requests
        with pytest.raises(ValueError, match=">=1"):
            eng.run_many(reqs, chunk_rows=bad)


def test_chunk_plan_is_run_manys_packing(engine):
    """ADVICE r4 #4: the bench's FLOP accounting consumes engine.chunk_plan/
    padded_rows instead of re-deriving the arithmetic — pin the plan's
    semantics here so a packing change breaks a test, not the artifact.
    Tiny engine: image buckets (1,2,4,8), no throughput buckets → max 8."""
    counts = [1, 2, 1, 4, 2, 1, 1]  # mixed single/pair/quad backlog
    plan = engine.chunk_plan(counts)
    # Mixed-count packing (round 5): evens first (2+4+2 fills a chunk),
    # then the singles share one — 2 dispatches where per-count grouping
    # paid 3.
    assert plan == [[1, 3, 4], [0, 2, 5, 6]]
    assert sorted(i for c in plan for i in c) == list(range(len(counts)))
    for chunk in plan:
        assert sum(counts[i] for i in chunk) <= 8
        # even-count requests lead the chunk AND sit at even row offsets
        # (the binary head pairs rows 2k/2k+1; decode reads offset//2)
        offset, seen_odd = 0, False
        for i in chunk:
            if counts[i] % 2 == 0:
                assert not seen_odd and offset % 2 == 0, (chunk, i)
            else:
                seen_odd = True
            offset += counts[i]
    assert engine.padded_rows(counts) == 8 + 4
    # chunk_rows override changes the plan the same way run_many chunks
    assert engine.chunk_plan([1] * 6, chunk_rows=4) == [[0, 1, 2, 3], [4, 5]]
    assert engine.padded_rows([1] * 6, chunk_rows=4) == 4 + 2
    with pytest.raises(ValueError, match="exceeds"):
        engine.chunk_plan([9])


def test_mixed_count_chunk_decodes_match_solo(engine):
    """Functional proof of the round-5 mixed packer: NLVR2 pairs, a
    retrieval set, and singles packed into SHARED chunks must decode
    identically to one-request-at-a-time runs — pair alignment, ranking
    row spans, and label rows all survive mixed packing."""
    reqs = [
        _prep(engine, 1, "what is it", ["img_a.jpg"]),
        _prep(engine, 12, "both contain dogs", ["img_a.jpg", "img_b.jpg"]),
        _prep(engine, 13, "dogs play", ["img_b.jpg"]),
        _prep(engine, 7, "a dog catching",
              ["img_a.jpg", "img_b.jpg", "img_a.jpg", "img_b.jpg"]),
        _prep(engine, 12, "both contain cats", ["img_b.jpg", "img_a.jpg"]),
        _prep(engine, 15, "is it red", ["img_a.jpg"]),
    ]
    # 1+2+1+4+2+1 = 11 rows over max bucket 8 → two mixed chunks
    plan = engine.chunk_plan([r.n_images for r in reqs])
    assert len(plan) == 2 and any(
        len({reqs[i].n_images for i in c}) > 1 for c in plan)
    batched = engine.run_many(reqs)
    for req, got in zip(reqs, batched):
        _, solo = engine.run(req)
        assert got.kind == solo.kind
        if got.answers is not None:
            assert [a["answer"] for a in got.answers] == \
                [a["answer"] for a in solo.answers], req.spec.task_id
        if got.ranking is not None:
            assert [r["image"] for r in got.ranking] == \
                [r["image"] for r in solo.ranking]


def test_prepare_clips_oversized_feature_files(engine):
    """Feature files with more boxes than the engine's region budget clip to
    the top-N (files are confidence-ordered) instead of erroring."""
    from vilbert_multitask_tpu.features.pipeline import RegionFeatures

    max_regions = engine.cfg.engine.max_regions
    n = max_regions + 20
    rng = np.random.default_rng(5)
    region = RegionFeatures(
        features=rng.normal(
            size=(n, engine.cfg.model.v_feature_size)).astype(np.float32),
        boxes=np.tile(np.array([[1, 1, 50, 50]], np.float32), (n, 1)),
        image_width=100, image_height=100)
    req = engine.prepare(1, "what", [region])
    assert req.features.shape[1] == max_regions
    assert int(req.image_mask[0].sum()) == max_regions  # global + N-1 boxes
    _, result = engine.run(req)
    assert result.kind == "labels"


def test_worker_step_batch_mixed_tasks(stack):
    s, hub, q, store, worker = stack
    before = len(store.recent(100))
    q.publish(make_job_message(["img_a.jpg"], "what", 1, "m1"))
    q.publish(make_job_message(["img_b.jpg"], "where", 15, "m2"))
    q.publish(make_job_message(["img_a.jpg", "img_b.jpg"], "both", 12, "m3"))
    q.publish(make_job_message(["img_b.jpg"], "entails", 13, "m4"))
    assert worker.step_batch(max_jobs=8) == 4
    assert q.counts() == {}
    rows = store.recent(100)
    assert len(rows) == before + 4
    by_task = {r["task_id"]: r for r in rows[:4]}
    assert by_task[12]["answer_text"]["kind"] == "binary"
    assert by_task[1]["answer_text"]["kind"] == "labels"


def test_worker_batches_multi_image_jobs(stack, monkeypatch):
    """NLVR2/retrieval jobs complete through run_many, never the solo
    path: with engine.run() poisoned, a mixed drain must still finish
    every job (round-3's known ceiling — multi-image jobs paid one
    forward each — is gone)."""
    s, hub, q, store, worker = stack

    def _boom(*a, **k):
        raise AssertionError("solo run() must not be used by step_batch")

    monkeypatch.setattr(worker.engine, "run", _boom)
    q.publish(make_job_message(["img_a.jpg", "img_b.jpg"], "both", 12, "b1"))
    q.publish(make_job_message(["img_a.jpg"], "what", 1, "b2"))
    q.publish(make_job_message(
        ["img_a.jpg", "img_b.jpg", "img_a.jpg", "img_b.jpg"],
        "a dog", 7, "b3"))
    assert worker.step_batch() == 3
    assert q.counts() == {}
    rows = store.recent(3)
    kinds = {r["task_id"]: r["answer_text"]["kind"] for r in rows}
    assert kinds == {12: "binary", 1: "labels", 7: "ranking"}


def test_worker_step_batch_poison_isolated(stack):
    """One bad job in a batch must not poison its batchmates."""
    s, hub, q, store, worker = stack
    q.publish(make_job_message(["img_a.jpg"], "ok", 1, "p1"))
    q.publish(make_job_message(["no_such_key.jpg"], "bad", 1, "p2"))
    q.publish(make_job_message(["img_b.jpg"], "ok2", 15, "p3"))
    assert worker.step_batch(max_jobs=8) == 2
    counts = q.counts()
    assert counts.get("pending") == 1  # poison requeued, good ones gone
