"""Outage-resilience contract for the bench orchestrator.

Round 3's driver artifact was ``rc=124, parsed:null``: a dead TPU tunnel ate
full 1800 s attempt timeouts until the driver's outer kill, leaving no
structured evidence (VERDICT r3 §weak-1). These tests simulate that outage
hermetically — a fresh subprocess with the axon hook's env removed and
``JAX_PLATFORMS`` pointed at a platform that cannot exist — and pin the
three defenses bench.py now carries:

1. cheap pre-attempt probes cycle instead of attempt-sized timeouts;
2. the wall budget bounds everything and still yields one JSON line;
3. SIGTERM (what ``timeout`` sends) emits best-so-far JSON before death.
"""

import json
import os
import signal
import subprocess
import sys
import time

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _outage_env(**over):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update(
        # A platform jax can never have: every probe fails in ~2 s with
        # "Unable to initialize backend" — the same error class a dead
        # tunnel raises, at test speed.
        JAX_PLATFORMS="fakeplat",
        BENCH_PROBE_BACKOFF_S="1",
        BENCH_PROBE_TIMEOUT_S="30",
        **over,
    )
    return env


def test_dead_backend_probes_then_structured_failure():
    """A dead backend burns probes, not attempts — and inside a 10-minute
    window the orchestrator still emits one parseable failure line with the
    probe log, well before any attempt-sized timeout could fire."""
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, BENCH],
        env=_outage_env(BENCH_WALL_BUDGET_S="25", BENCH_MIN_ATTEMPT_S="10"),
        capture_output=True, text=True, timeout=600,
    )
    elapsed = time.monotonic() - t0
    assert r.returncode == 1, r.stderr[-2000:]
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith('{"metric"'))
    out = json.loads(line)
    assert out["value"] is None
    assert "backend never came up" in out["error"]
    assert "fakeplat" in out["error"]  # probe diagnostics surfaced
    # ≥3 probe cycles ran (VERDICT r3 done-criterion), no measurement child
    # was ever launched, and the whole thing stayed inside the wall budget
    # plus one probe's worth of slack.
    probes = [ln for ln in r.stderr.splitlines() if "probe rc=" in ln]
    assert len(probes) >= 3, r.stderr[-2000:]
    assert "bench attempt" not in r.stderr
    assert elapsed < 120, elapsed


def test_dead_on_arrival_window_fast_fails_with_pointer():
    """A generous wall budget must NOT buy a wall budget of probes: if no
    probe has EVER succeeded by BENCH_PROBE_WINDOW_S, bench emits partial
    JSON pointing at the newest committed artifact and exits — minutes
    after a dead-on-arrival tunnel, not hours (the round-5 builder spent
    1798 s learning what its first 5 minutes already knew)."""
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, BENCH],
        env=_outage_env(BENCH_WALL_BUDGET_S="600", BENCH_MIN_ATTEMPT_S="10",
                        BENCH_PROBE_WINDOW_S="15"),
        capture_output=True, text=True, timeout=300,
    )
    elapsed = time.monotonic() - t0
    assert r.returncode == 1, r.stderr[-2000:]
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith('{"metric"'))
    payload = json.loads(line)
    assert payload["value"] is None
    assert payload["partial"] is True
    assert "backend dead on arrival" in payload["error"]
    assert "BENCH_PROBE_WINDOW_S=15" in payload["error"]
    # The failure points its reader at the last real measurement, so a dead
    # tunnel can never read as "the engine got slow".
    assert payload["last_known_good"].startswith("BENCH_")
    assert isinstance(payload["last_known_good_p50_ms"], (int, float))
    # Window + a couple of probe cycles of slack — nowhere near the budget.
    assert elapsed < 120, elapsed
    assert "bench attempt" not in r.stderr


def test_sigterm_during_outage_emits_partial_json():
    """``timeout``'s SIGTERM mid-run still leaves structured stdout."""
    proc = subprocess.Popen(
        [sys.executable, BENCH],
        env=_outage_env(BENCH_WALL_BUDGET_S="600", BENCH_MIN_ATTEMPT_S="10"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        time.sleep(8)  # a couple of probe cycles
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
    finally:
        proc.kill()
    line = next(ln for ln in out.splitlines() if ln.startswith('{"metric"'))
    payload = json.loads(line)
    assert payload["value"] is None
    assert payload["partial"] is True
    assert "killed by signal 15" in payload["error"]


def test_probe_skipped_in_tiny_mode():
    """TINY (CPU smoke) mode must not probe: it pins the platform in-process
    and a probe subprocess would pay the axon handshake for nothing."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update(BENCH_TINY="1", BENCH_COMPARE="0", JAX_PLATFORMS="cpu",
               BENCH_WALL_BUDGET_S="600")
    r = subprocess.run(
        [sys.executable, BENCH], env=env,
        capture_output=True, text=True, timeout=570,
    )
    assert "PROBE_OK" not in r.stderr and "probe" not in r.stdout
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith('{"metric"'))
    out = json.loads(line)
    assert isinstance(out["value"], (int, float)), r.stderr[-2000:]
    # The roofline block ships on every headline, TINY included: the
    # analytic batch knee and the per-row weight-read cost next to the
    # param_bytes they derive from.
    assert out["knee_rows"] >= 1
    assert out["weight_bytes_per_row"] > 0
    assert out["param_bytes"] > 0
