"""Checkpoint-conversion oracle tests (VERDICT round 1, item 3).

Round 1 only proved the converter's name map is self-inverse, which cannot
catch a wrong convention. Here an INDEPENDENT torch implementation of the
upstream layout (tests/torch_oracle.py) provides golden logits: random torch
weights → state_dict → convert → Flax forward must reproduce every head. A
deliberately transposed kernel or a swapped bi-attention direction breaks
these tests (proved below).
"""

from __future__ import annotations

import pathlib

import numpy as np
import pytest

from vilbert_multitask_tpu.checkpoint.convert import (
    build_name_map,
    convert_torch_state_dict,
)
from vilbert_multitask_tpu.config import ViLBertConfig

torch = pytest.importorskip("torch")

from tests.torch_oracle import TorchViLBertOracle  # noqa: E402

KEYS_FILE = (pathlib.Path(__file__).resolve().parents[1]
             / "vilbert_multitask_tpu" / "checkpoint"
             / "upstream_keys_bert_base_6layer_6conect.txt")

# Everything runs in float64: the clean-conversion parity error is then
# ~1e-12, so even perturbation signals attenuated 1000x by the random-weight
# trunk (measured ~1e-5 at the heads for a transposed layer-0 kernel) sit
# orders of magnitude above the pass tolerance — the tests discriminate.
ATOL = 1e-9
PERTURB_MIN = 1e-6


def _tiny_cfg() -> ViLBertConfig:
    return ViLBertConfig().tiny()


def _random_oracle(cfg, seed=0):
    torch.manual_seed(seed)
    oracle = TorchViLBertOracle(cfg).double()
    with torch.no_grad():
        for p in oracle.parameters():
            p.uniform_(-0.35, 0.35)
    oracle.eval()
    return oracle


def _inputs(cfg, batch=2, n_text=9, n_regions=7, seed=1):
    rng = np.random.default_rng(seed)
    input_ids = rng.integers(0, cfg.vocab_size, (batch, n_text))
    segment_ids = np.zeros((batch, n_text), np.int64)
    input_mask = np.ones((batch, n_text), np.int64)
    input_mask[:, -2:] = 0  # exercise the text mask path
    image_mask = np.ones((batch, n_regions), np.int64)
    image_mask[:, -3:] = 0  # and the region mask path
    features = rng.normal(size=(batch, n_regions, cfg.v_feature_size))
    spatials = rng.random((batch, n_regions, 5))
    task_ids = rng.integers(0, cfg.num_task_tokens, (batch, 1))
    return dict(input_ids=input_ids.astype(np.int64),
                features=features.astype(np.float64),
                spatials=spatials.astype(np.float64),
                segment_ids=segment_ids, input_mask=input_mask,
                image_mask=image_mask, task_ids=task_ids.astype(np.int64))


def _torch_forward(oracle, inp):
    with torch.no_grad():
        out = oracle(*(torch.from_numpy(inp[k]) for k in (
            "input_ids", "features", "spatials", "segment_ids",
            "input_mask", "image_mask", "task_ids")))
    return {k: (v.numpy() if v is not None else None) for k, v in out.items()}


def _numpy_state_dict(oracle):
    return {k: v.detach().numpy().copy()
            for k, v in oracle.state_dict().items()}


def _flax_forward(cfg, params, inp):
    import jax

    from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks

    with jax.enable_x64(True):
        import jax.numpy as jnp

        model = ViLBertForVLTasks(cfg, dtype=jnp.float64)
        out = model.apply(
            {"params": params},
            jnp.asarray(inp["input_ids"], jnp.int32),
            jnp.asarray(inp["features"], jnp.float64),
            jnp.asarray(inp["spatials"], jnp.float64),
            jnp.asarray(inp["segment_ids"], jnp.int32),
            jnp.asarray(inp["input_mask"], jnp.int32),
            jnp.asarray(inp["image_mask"], jnp.int32),
            None,
            jnp.asarray(inp["task_ids"], jnp.int32),
            deterministic=True,
            compute_pretraining_heads=True,
        )
    return jax.tree_util.tree_map(lambda x: np.asarray(x), out)


HEADS = ("vil_prediction", "vil_prediction_gqa", "vil_logit",
         "vil_binary_prediction", "vil_tri_prediction", "vision_prediction",
         "vision_logit", "linguisic_prediction", "linguisic_logit")


def test_golden_logits_every_head():
    """Converted torch weights reproduce the oracle's logits head-by-head."""
    cfg = _tiny_cfg()
    oracle = _random_oracle(cfg)
    inp = _inputs(cfg)
    golden = _torch_forward(oracle, inp)
    params = convert_torch_state_dict(_numpy_state_dict(oracle), cfg,
                                      dtype=np.float64)
    got = _flax_forward(cfg, params, inp)
    for head in HEADS:
        g, f = golden[head], getattr(got, head)
        assert g.shape == f.shape, head
        np.testing.assert_allclose(
            f, g, atol=ATOL, rtol=1e-7,
            err_msg=f"head {head} diverges after conversion")


def test_transposed_kernel_breaks_parity():
    """Falsifiability: one transposed square kernel must break the test."""
    cfg = _tiny_cfg()
    oracle = _random_oracle(cfg)
    inp = _inputs(cfg)
    golden = _torch_forward(oracle, inp)
    sd = _numpy_state_dict(oracle)
    key = "bert.encoder.layer.0.attention.self.query.weight"
    sd[key] = np.ascontiguousarray(sd[key].T)  # square: shape-legal, wrong
    params = convert_torch_state_dict(sd, cfg, dtype=np.float64)
    got = _flax_forward(cfg, params, inp)
    diff = np.abs(got.vil_prediction - golden["vil_prediction"]).max()
    assert diff > PERTURB_MIN, "transposed kernel went undetected"


def test_swapped_bridge_direction_breaks_parity():
    """Falsifiability: swapping the biattention *1/*2 families must break it.

    This is the exact failure VERDICT round 1 called unfalsifiable: a
    converter that mapped text_attends_image from (query1,key2,value2)
    instead of (query2,key1,value1) would produce a structurally valid tree
    with wrong numerics whenever the two streams have equal widths.
    """
    # Equal stream widths so the swap is shape-legal (the silent case).
    cfg = ViLBertConfig().tiny(hidden_size=32, num_attention_heads=4,
                               intermediate_size=32)
    oracle = _random_oracle(cfg)
    inp = _inputs(cfg)
    golden = _torch_forward(oracle, inp)
    sd = _numpy_state_dict(oracle)
    for i in range(cfg.num_connection_layers):
        base = f"bert.encoder.c_layer.{i}.biattention"
        for name in ("query", "key", "value"):
            for suffix in ("weight", "bias"):
                a, b = f"{base}.{name}1.{suffix}", f"{base}.{name}2.{suffix}"
                sd[a], sd[b] = sd[b], sd[a]
    params = convert_torch_state_dict(sd, cfg, dtype=np.float64)
    got = _flax_forward(cfg, params, inp)
    diff = np.abs(got.vil_prediction - golden["vil_prediction"]).max()
    assert diff > PERTURB_MIN, "swapped bridge direction went undetected"


def test_upstream_key_inventory_pinned():
    """The oracle's full-config state_dict == the vendored key inventory, and
    the converter's name map covers every key except the tied decoder table
    (reconstructed from the embedding, convert.py to_torch_state_dict)."""
    cfg = ViLBertConfig()  # full serving config
    with torch.device("meta"):
        oracle = TorchViLBertOracle(cfg)
    keys = set(oracle.state_dict().keys())
    vendored = set(KEYS_FILE.read_text().split())
    assert keys == vendored, (
        f"oracle/state-dict drift: +{sorted(keys - vendored)[:5]} "
        f"-{sorted(vendored - keys)[:5]}")

    mapped: set = set()
    for _flax_path, (torch_keys, _p, _u) in build_name_map(cfg):
        mapped.update(torch_keys)
    assert mapped <= keys, f"map targets ghost keys: {sorted(mapped - keys)[:5]}"
    unmapped = keys - mapped
    assert unmapped == {"cls.predictions.decoder.weight"}, (
        f"converter silently drops: {sorted(unmapped)[:8]}")
