"""Checkpoint-conversion oracle tests (VERDICT round 1, item 3).

Round 1 only proved the converter's name map is self-inverse, which cannot
catch a wrong convention. Here an INDEPENDENT torch implementation of the
upstream layout (tests/torch_oracle.py) provides golden logits: random torch
weights → state_dict → convert → Flax forward must reproduce every head. A
deliberately transposed kernel or a swapped bi-attention direction breaks
these tests (proved below).
"""

from __future__ import annotations

import pathlib

import numpy as np
import pytest

from vilbert_multitask_tpu.checkpoint.convert import (
    build_name_map,
    convert_torch_state_dict,
)
from vilbert_multitask_tpu.config import ViLBertConfig

torch = pytest.importorskip("torch")

from tests.torch_oracle import (  # noqa: E402
    TorchViLBertOracle,
    flax_forward as _flax_forward,
    numpy_state_dict as _numpy_state_dict,
    oracle_inputs,
    random_oracle as _random_oracle,
    torch_forward as _torch_forward,
)

KEYS_FILE = (pathlib.Path(__file__).resolve().parents[1]
             / "vilbert_multitask_tpu" / "checkpoint"
             / "upstream_keys_bert_base_6layer_6conect.txt")

# Everything runs in float64: the clean-conversion parity error is then
# ~1e-12, so even perturbation signals attenuated 1000x by the random-weight
# trunk (measured ~1e-5 at the heads for a transposed layer-0 kernel) sit
# orders of magnitude above the pass tolerance — the tests discriminate.
ATOL = 1e-9
PERTURB_MIN = 1e-6


def _tiny_cfg() -> ViLBertConfig:
    return ViLBertConfig().tiny()


_inputs = oracle_inputs


HEADS = ("vil_prediction", "vil_prediction_gqa", "vil_logit",
         "vil_binary_prediction", "vil_tri_prediction", "vision_prediction",
         "vision_logit", "linguisic_prediction", "linguisic_logit")


def test_golden_logits_every_head():
    """Converted torch weights reproduce the oracle's logits head-by-head."""
    cfg = _tiny_cfg()
    oracle = _random_oracle(cfg)
    inp = _inputs(cfg)
    golden = _torch_forward(oracle, inp)
    params = convert_torch_state_dict(_numpy_state_dict(oracle), cfg,
                                      dtype=np.float64)
    got = _flax_forward(cfg, params, inp)
    for head in HEADS:
        g, f = golden[head], getattr(got, head)
        assert g.shape == f.shape, head
        np.testing.assert_allclose(
            f, g, atol=ATOL, rtol=1e-7,
            err_msg=f"head {head} diverges after conversion")


def test_transposed_kernel_breaks_parity():
    """Falsifiability: one transposed square kernel must break the test."""
    cfg = _tiny_cfg()
    oracle = _random_oracle(cfg)
    inp = _inputs(cfg)
    golden = _torch_forward(oracle, inp)
    sd = _numpy_state_dict(oracle)
    key = "bert.encoder.layer.0.attention.self.query.weight"
    sd[key] = np.ascontiguousarray(sd[key].T)  # square: shape-legal, wrong
    params = convert_torch_state_dict(sd, cfg, dtype=np.float64)
    got = _flax_forward(cfg, params, inp)
    diff = np.abs(got.vil_prediction - golden["vil_prediction"]).max()
    assert diff > PERTURB_MIN, "transposed kernel went undetected"


def test_swapped_bridge_direction_breaks_parity():
    """Falsifiability: swapping the biattention *1/*2 families must break it.

    This is the exact failure VERDICT round 1 called unfalsifiable: a
    converter that mapped text_attends_image from (query1,key2,value2)
    instead of (query2,key1,value1) would produce a structurally valid tree
    with wrong numerics whenever the two streams have equal widths.
    """
    # Equal stream widths so the swap is shape-legal (the silent case).
    cfg = ViLBertConfig().tiny(hidden_size=32, num_attention_heads=4,
                               intermediate_size=32)
    oracle = _random_oracle(cfg)
    inp = _inputs(cfg)
    golden = _torch_forward(oracle, inp)
    sd = _numpy_state_dict(oracle)
    for i in range(cfg.num_connection_layers):
        base = f"bert.encoder.c_layer.{i}.biattention"
        for name in ("query", "key", "value"):
            for suffix in ("weight", "bias"):
                a, b = f"{base}.{name}1.{suffix}", f"{base}.{name}2.{suffix}"
                sd[a], sd[b] = sd[b], sd[a]
    params = convert_torch_state_dict(sd, cfg, dtype=np.float64)
    got = _flax_forward(cfg, params, inp)
    diff = np.abs(got.vil_prediction - golden["vil_prediction"]).max()
    assert diff > PERTURB_MIN, "swapped bridge direction went undetected"


@pytest.mark.slow
def test_full_serving_config_parity(tmp_path):
    """End-to-end logit parity at the FULL serving config (280M params).

    The tiny-config golden test above cannot catch a transpose that is only
    shape-legal at serving widths (1024x1024 square kernels, fused-QKV repack
    at 1024-dim, 3129/1533-wide heads) — SURVEY §7 risk (a) at the scale
    where it bites. scripts/parity_full.py is the committed-artifact
    generator (PARITY_FULL.json); this wraps the same run so the proof
    re-executes at round boundaries."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "parity_full",
        pathlib.Path(__file__).resolve().parents[1] / "scripts"
        / "parity_full.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.run(str(tmp_path / "parity.json"))
    assert report["n_params"] > 250_000_000, report["n_params"]
    assert report["passed"], (
        f"full-config conversion parity broke: worst head err "
        f"{report['worst_max_abs_err']:.3e} > {report['atol']:.0e}; "
        f"per-head: { {h: v['max_abs_err'] for h, v in report['heads'].items()} }")


def test_upstream_key_inventory_pinned():
    """The oracle's full-config state_dict == the vendored key inventory, and
    the converter's name map covers every key except the tied decoder table
    (reconstructed from the embedding, convert.py to_torch_state_dict)."""
    cfg = ViLBertConfig()  # full serving config
    with torch.device("meta"):
        oracle = TorchViLBertOracle(cfg)
    keys = set(oracle.state_dict().keys())
    vendored = set(KEYS_FILE.read_text().split())
    assert keys == vendored, (
        f"oracle/state-dict drift: +{sorted(keys - vendored)[:5]} "
        f"-{sorted(vendored - keys)[:5]}")

    mapped: set = set()
    for _flax_path, (torch_keys, _p, _u) in build_name_map(cfg):
        mapped.update(torch_keys)
    assert mapped <= keys, f"map targets ghost keys: {sorted(mapped - keys)[:5]}"
    unmapped = keys - mapped
    assert unmapped == {"cls.predictions.decoder.weight"}, (
        f"converter silently drops: {sorted(unmapped)[:8]}")
