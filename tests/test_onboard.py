"""Rehearse the one-command real-asset onboarding flow (VERDICT r3 #4).

The real deployment assets (pytorch_model_9.bin, the bert-base-uncased
vocab, the answer-vocabulary pickles — reference worker.py:470,537-539,
299-315) don't exist in this image, so the rehearsal uses faithful
stand-ins: a genuinely torch-serialized ``.bin`` (DataParallel-prefixed,
like the published file), the committed synthetic vocab, and a JSON label
map written through LabelMapStore. The test proves a deployer can run ONE
command and get a parity verdict — and that the verdict binds (a wrong
expectation fails).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from vilbert_multitask_tpu import assets
from vilbert_multitask_tpu.checkpoint import onboard
from vilbert_multitask_tpu.checkpoint.convert import to_torch_state_dict
from vilbert_multitask_tpu.config import FrameworkConfig
from vilbert_multitask_tpu.engine.labels import LabelMapStore
from vilbert_multitask_tpu.engine.runtime import InferenceEngine

GOLDEN = os.path.join(os.path.dirname(__file__), "fixtures", "golden")


def _onboard_cfg(vocab_path, labels_root):
    """Exactly the config ``onboard.main(--tiny --cpu)`` builds, so the
    test's expectation engine and the CLI's engine share numerics."""
    cfg = FrameworkConfig()
    cfg = dataclasses.replace(cfg, model=cfg.model.tiny())
    return dataclasses.replace(cfg, engine=dataclasses.replace(
        cfg.engine, vocab_path=vocab_path, labels_root=labels_root,
        compute_dtype="float32", use_pallas_coattention=False,
        use_pallas_self_attention=False))


def test_onboard_end_to_end(tmp_path, capsys):
    torch = pytest.importorskip("torch")

    vocab = assets.default_vocab_path()
    labels_root = str(tmp_path / "labels")
    cfg = _onboard_cfg(vocab, labels_root)
    # Real label FILES (not the synthetic fallback): the rehearsal must
    # walk the same load path the genuine pickles/JSON would.
    store = LabelMapStore(labels_root, allow_synthetic=False)
    store.save_json("vqa", [f"ans_{i}" for i in range(cfg.model.num_labels)])
    store.save_json("gqa", [f"g_{i}"
                            for i in range(cfg.model.gqa_num_labels)])

    # The "published checkpoint" stand-in: a seeded engine's weights,
    # torch-serialized with the DataParallel 'module.' prefixes the real
    # pytorch_model_9.bin carries (reference worker.py:470).
    src = InferenceEngine(cfg, seed=0)
    sd = {f"module.{k}": torch.from_numpy(np.asarray(v))
          for k, v in to_torch_state_dict(src.params, cfg.model).items()}
    bin_path = str(tmp_path / "pytorch_model_9.bin")
    torch.save(sd, bin_path)

    # Expected scores, computed on the source engine through the same
    # harness the CLI uses — what a deployer would paste from the paper.
    from vilbert_multitask_tpu.evals.harness import Evaluator, load_jsonl
    from vilbert_multitask_tpu.features.store import FeatureStore

    src.feature_store = FeatureStore(os.path.join(GOLDEN, "features"))
    vqa_res = Evaluator(src, batch=4).run(
        "vqa", load_jsonl(os.path.join(GOLDEN, "vqa.jsonl")))
    expect_path = str(tmp_path / "expected.json")
    with open(expect_path, "w") as f:
        json.dump({"vqa": {"accuracy": vqa_res["accuracy"]}}, f)

    # Detector stand-in: a torch-serialized Faster R-CNN checkpoint in the
    # detectron {"model": {...}} envelope, built with the same fixture
    # helper the converter tests use (nontrivial BN running stats included).
    from vilbert_multitask_tpu.config import DetectorConfig
    from vilbert_multitask_tpu.detect.model import FasterRCNN
    from tests.test_detect_convert import _synthetic_torch_sd

    import jax

    # Onboarding derives representation_size from the trunk's
    # v_feature_size (like serve/app.py), so the stand-in must match it.
    import dataclasses as dc

    dcfg = dc.replace(DetectorConfig().tiny(),
                      representation_size=cfg.model.v_feature_size)
    det_model = FasterRCNN(dcfg)
    c = dcfg.canvas
    det_params = det_model.init(jax.random.PRNGKey(0),
                                np.zeros((c, c, 3), np.float32),
                                np.asarray([c, c], np.float32))["params"]
    det_bin = str(tmp_path / "detectron_model.pth")
    torch.save({"model": {k: torch.from_numpy(np.array(v))
                          for k, v in _synthetic_torch_sd(
                              dcfg, det_params).items()}}, det_bin)

    out_dir = str(tmp_path / "onboarded")
    argv = ["--torch-bin", bin_path, "--vocab", vocab,
            "--labels", labels_root, "--out", out_dir,
            "--detector-bin", det_bin,
            "--eval", f"vqa={os.path.join(GOLDEN, 'vqa.jsonl')}",
            "--features", os.path.join(GOLDEN, "features"),
            "--expect", expect_path, "--tol", "1e-9",
            "--tiny", "--cpu"]
    rc = onboard.main(argv)
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["ok"] is True
    assert report["steps"]["convert"]["ok"]
    assert report["steps"]["boot"]["vocab_tokens"] > 1000
    assert report["steps"]["parity"]["failures"] == []
    assert report["steps"]["detector"]["n_boxes"] >= 1
    assert os.path.isdir(report["steps"]["detector"]["params_dir"])
    # Smoke answers decoded from the PROVIDED label files, not synthetics.
    assert report["steps"]["smoke"]["tasks"]["1"]["top"].startswith("ans_")
    # Converted params persisted through the production Orbax path.
    assert os.path.isdir(report["steps"]["convert"]["params_dir"])
    assert os.path.exists(os.path.join(out_dir, "report.json"))

    # The verdict must bind: a wrong expectation → rc 1 with the miss
    # named, and an expected task that was never evaluated is a failure
    # too (not a silent pass).
    with open(expect_path, "w") as f:
        json.dump({"vqa": {"accuracy": vqa_res["accuracy"] + 0.25},
                   "gqa": {"accuracy": 0.5}}, f)
    rc = onboard.main(argv)
    assert rc == 1
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["ok"] is False
    fails = report["steps"]["parity"]["failures"]
    assert any("vqa.accuracy" in f for f in fails)
    assert any("gqa" in f and "never evaluated" in f for f in fails)


def test_onboard_rejects_malformed_eval_spec(tmp_path):
    with pytest.raises(SystemExit, match="TASK=DATA"):
        onboard._parse_evals(["vqa:data.jsonl"])


def test_onboard_uncovered_expectation_fails(tmp_path, capsys):
    """An expected task with no matching --eval must fail, not silently
    pass — 'exit 0' claims every expected score was reproduced."""
    expect = tmp_path / "exp.json"
    expect.write_text(json.dumps({"vqa": {"accuracy": 0.5},
                                  "gqa": {"accuracy": 0.5}}))
    with pytest.raises(SystemExit, match="verify nothing"):
        onboard.main(["--torch-bin", "x.bin", "--vocab", "v", "--labels",
                      "l", "--out", str(tmp_path), "--expect", str(expect)])
