"""Pallas flash co-attention vs the XLA reference path.

On CPU the kernel runs in interpreter mode (auto-selected), so these tests
validate the exact blockwise online-softmax math everywhere; on TPU the same
code compiles via Mosaic.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from vilbert_multitask_tpu.config import ViLBertConfig
from vilbert_multitask_tpu.models.vilbert import ViLBertForVLTasks
from vilbert_multitask_tpu.ops.attention import mask_to_bias, multi_head_attention
from vilbert_multitask_tpu.ops.coattention import flash_cross_attention


def _rand_qkv(rng, B, Nq, Nk, H, D):
    return (
        jnp.asarray(rng.normal(size=(B, Nq, H, D)), jnp.float32),
        jnp.asarray(rng.normal(size=(B, Nk, H, D)), jnp.float32),
        jnp.asarray(rng.normal(size=(B, Nk, H, D)), jnp.float32),
    )


def test_matches_xla_reference_serving_shapes():
    """38 text tokens × 101 regions — the exact serving geometry."""
    rng = np.random.default_rng(0)
    B, Nq, Nk, H, D = 2, 38, 101, 8, 128
    q, k, v = _rand_qkv(rng, B, Nq, Nk, H, D)
    mask = jnp.asarray(rng.random((B, Nk)) < 0.9, jnp.int32)
    mask = mask.at[:, 0].set(1)
    bias = mask_to_bias(mask)
    ref, _ = multi_head_attention(q, k, v, bias)
    out = flash_cross_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_blockwise_path_multiple_kv_blocks():
    """Nk spanning several KV tiles exercises the online-softmax recurrence."""
    rng = np.random.default_rng(1)
    B, Nq, Nk, H, D = 1, 16, 300, 2, 64
    q, k, v = _rand_qkv(rng, B, Nq, Nk, H, D)
    mask = jnp.ones((B, Nk), jnp.int32)
    bias = mask_to_bias(mask)
    ref, _ = multi_head_attention(q, k, v, bias)
    out = flash_cross_attention(q, k, v, bias, block_q=8, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_masked_keys_do_not_leak():
    """Fully-masked tail keys must not affect the context at all."""
    rng = np.random.default_rng(2)
    B, Nq, Nk, H, D = 1, 8, 40, 2, 32
    q, k, v = _rand_qkv(rng, B, Nq, Nk, H, D)
    mask = jnp.concatenate(
        [jnp.ones((B, 25), jnp.int32), jnp.zeros((B, 15), jnp.int32)], axis=1)
    out_full = flash_cross_attention(q, k, v, mask_to_bias(mask))
    # Same computation with garbage in the masked tail.
    k2 = k.at[:, 25:].set(1e3)
    v2 = v.at[:, 25:].set(-1e3)
    out_garbage = flash_cross_attention(q, k2, v2, mask_to_bias(mask))
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(out_garbage),
                               atol=1e-5)


def test_model_parity_pallas_vs_xla(tiny_config, rng):
    """Full trunk forward: Pallas co-attention ≡ XLA co-attention."""
    cfg_x = tiny_config
    cfg_p = dataclasses.replace(cfg_x, use_pallas_coattention=True)
    B, Nt, Nv = 2, 10, 7
    nrng = np.random.default_rng(3)
    args = (
        jnp.asarray(nrng.integers(0, cfg_x.vocab_size, (B, Nt)), jnp.int32),
        jnp.asarray(nrng.normal(size=(B, Nv, cfg_x.v_feature_size)),
                    jnp.float32),
        jnp.asarray(nrng.random((B, Nv, 5)), jnp.float32),
        jnp.zeros((B, Nt), jnp.int32),
        jnp.ones((B, Nt), jnp.int32),
        jnp.ones((B, Nv), jnp.int32),
        None,
        jnp.ones((B, 1), jnp.int32),
    )
    model_x = ViLBertForVLTasks(cfg_x, dtype=jnp.float32)
    model_p = ViLBertForVLTasks(cfg_p, dtype=jnp.float32)
    params = model_x.init(rng, *args, deterministic=True)["params"]
    out_x = model_x.apply({"params": params}, *args, deterministic=True)
    out_p = model_p.apply({"params": params}, *args, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_p.vil_prediction),
                               np.asarray(out_x.vil_prediction),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(out_p.vision_logit),
                               np.asarray(out_x.vision_logit),
                               atol=1e-4, rtol=1e-4)


def test_self_attention_pallas_matches_xla(rng):
    """FusedSelfAttention kernel path (head_dim=128) ≡ XLA path."""
    import flax.linen as nn

    from vilbert_multitask_tpu.ops.attention import FusedSelfAttention

    nrng = np.random.default_rng(7)
    B, N, H = 2, 23, 256  # 2 heads × head_dim 128 → kernel-eligible
    x = jnp.asarray(nrng.normal(size=(B, N, H)), jnp.float32)
    mask = jnp.ones((B, N), jnp.int32).at[:, 17:].set(0)
    bias = mask_to_bias(mask)
    mod_x = FusedSelfAttention(hidden_size=H, num_heads=2, use_pallas=False)
    mod_p = FusedSelfAttention(hidden_size=H, num_heads=2, use_pallas=True)
    params = mod_x.init(rng, x, bias)["params"]
    ref, probs = mod_x.apply({"params": params}, x, bias)
    out, none_probs = mod_p.apply({"params": params}, x, bias)
    assert none_probs is None and probs is not None
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_self_attention_kernel_at_visual_stream_geometry(rng):
    """The EXACT serving eligibility claim (config.py): the 1024-wide/8-head
    visual stream (head_dim 128) takes the kernel path at its real length
    (101 regions) and matches XLA; BERT-base text (768/12, head_dim 64)
    must NOT take it (a 64-lane op would waste half the MXU)."""
    from vilbert_multitask_tpu.ops.attention import FusedSelfAttention

    nrng = np.random.default_rng(11)
    B, N, H, heads = 2, 101, 1024, 8  # visual stream, serving geometry
    x = jnp.asarray(nrng.normal(size=(B, N, H)), jnp.float32)
    mask = jnp.ones((B, N), jnp.int32).at[:, 77:].set(0)
    bias = mask_to_bias(mask)
    mod_x = FusedSelfAttention(hidden_size=H, num_heads=heads,
                               use_pallas=False)
    mod_p = FusedSelfAttention(hidden_size=H, num_heads=heads,
                               use_pallas=True)
    params = mod_x.init(rng, x, bias)["params"]
    ref, _ = mod_x.apply({"params": params}, x, bias)
    out, probs = mod_p.apply({"params": params}, x, bias)
    assert probs is None  # proof the kernel path actually ran
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    # Text-stream geometry: head_dim 64 → kernel ineligible, probs returned.
    Ht, ht_heads, Nt = 768, 12, 38
    xt = jnp.asarray(nrng.normal(size=(1, Nt, Ht)), jnp.float32)
    bt = mask_to_bias(jnp.ones((1, Nt), jnp.int32))
    mod_t = FusedSelfAttention(hidden_size=Ht, num_heads=ht_heads,
                               use_pallas=True)
    pt = mod_t.init(rng, xt, bt)["params"]
    _, probs_t = mod_t.apply({"params": pt}, xt, bt)
    assert probs_t is not None  # stayed on XLA as designed


def test_mosaic_compiles_kernel_on_tpu():
    """TPU-only (skips on the CPU-pinned test backend): the kernel must
    COMPILE under Mosaic — interpret=False — and match XLA at the serving
    geometry. bench.py exercises this on hardware every round
    (BENCH_r03: pallas_coattention=true); this pins it as a test artifact
    wherever a chip is visible."""
    import pytest

    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU backend (Mosaic)")
    rng = np.random.default_rng(0)
    B, Nq, Nk, H, D = 2, 38, 101, 8, 128
    q, k, v = _rand_qkv(rng, B, Nq, Nk, H, D)
    bias = mask_to_bias(jnp.ones((B, Nk), jnp.int32))
    ref, _ = multi_head_attention(q, k, v, bias)
    out = flash_cross_attention(q, k, v, bias, interpret=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)  # bf16-class tolerance


def test_pretraining_heads_skippable(tiny_config, rng):
    """compute_pretraining_heads=False drops only the masked-modeling heads."""
    model = ViLBertForVLTasks(tiny_config, dtype=jnp.float32)
    B, Nt, Nv = 2, 8, 5
    args = (
        jnp.zeros((B, Nt), jnp.int32),
        jnp.zeros((B, Nv, tiny_config.v_feature_size), jnp.float32),
        jnp.zeros((B, Nv, 5), jnp.float32),
        jnp.zeros((B, Nt), jnp.int32),
        jnp.ones((B, Nt), jnp.int32),
        jnp.ones((B, Nv), jnp.int32),
        None,
        jnp.ones((B, 1), jnp.int32),
    )
    params = model.init(rng, *args, deterministic=True)["params"]
    full = model.apply({"params": params}, *args, deterministic=True)
    lean = model.apply({"params": params}, *args, deterministic=True,
                       compute_pretraining_heads=False)
    assert lean.linguisic_prediction is None
    assert lean.vision_prediction is None
    assert full.linguisic_prediction is not None
    np.testing.assert_array_equal(np.asarray(lean.vil_prediction),
                                  np.asarray(full.vil_prediction))
    np.testing.assert_array_equal(np.asarray(lean.vision_logit),
                                  np.asarray(full.vision_logit))


def test_attention_maps_still_available_with_pallas_config(tiny_config, rng):
    """The visualization contract (reference worker.py:288) falls back to the
    probs-returning XLA path even when the Pallas flag is on."""
    cfg_p = dataclasses.replace(tiny_config, use_pallas_coattention=True)
    B, Nt, Nv = 1, 6, 5
    args = (
        jnp.zeros((B, Nt), jnp.int32),
        jnp.zeros((B, Nv, cfg_p.v_feature_size), jnp.float32),
        jnp.zeros((B, Nv, 5), jnp.float32),
        jnp.zeros((B, Nt), jnp.int32),
        jnp.ones((B, Nt), jnp.int32),
        jnp.ones((B, Nv), jnp.int32),
        None,
        jnp.ones((B, 1), jnp.int32),
    )
    model = ViLBertForVLTasks(cfg_p, dtype=jnp.float32)
    params = model.init(rng, *args, deterministic=True)["params"]
    out = model.apply({"params": params}, *args, deterministic=True,
                      output_all_attention_masks=True)
    assert len(out.attn_data_list) == cfg_p.num_connection_layers
    assert all(p[0] is not None for p in out.attn_data_list)
