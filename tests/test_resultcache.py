"""Result cache, singleflight coalescing, and the tenant-weighted EDF tier.

The cache/coalescing unit tests drive :class:`ResultCache` directly on a
tmp sqlite file — claim-state transitions, lease takeover, swap
invalidation, exactly-once follower pops. The deficit-scheduler tests
drive ``select_batch`` with fabricated items and explicit clocks, same
style as test_scheduler.py. The integration tests run real submits
through ``ApiServer.submit_job`` + ``ServeWorker`` and assert the
tentpole invariant: N identical concurrent submits → ONE forward, and
exactly one terminal frame per submit even when the leader dead-letters
or expires (seeded FaultPlan, no sleep-based races).
"""

import queue as queue_mod
import threading
import time

import pytest

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.serve.resultcache import (
    ResultCache,
    cache_key,
    canonical_question,
)
from vilbert_multitask_tpu.serve.scheduler import ReadyItem, select_batch


# ---------------------------------------------------------------- cache key
def test_cache_key_canonicalizes_whitespace(tmp_path):
    img = str(tmp_path / "img_a.npy")
    k1 = cache_key(1, [img], "what  is\tthis ", "fp")
    k2 = cache_key(1, [img], "what is this", "fp")
    assert k1 == k2
    assert canonical_question("  a\t b \n") == "a b"


def test_cache_key_separates_task_images_question_fingerprint(tmp_path):
    img = str(tmp_path / "img_a.npy")
    base = cache_key(1, [img], "q", "fp")
    assert cache_key(2, [img], "q", "fp") != base
    assert cache_key(1, [img, img], "q", "fp") != base
    assert cache_key(1, [img], "q2", "fp") != base
    assert cache_key(1, [img], "q", "fp2") != base


def test_cache_key_tracks_file_content_identity(tmp_path):
    """The image component is file+mtime+size (features/store.py identity
    idiom): overwriting the file must rotate the key, a missing file
    degrades to the raw path (still a stable key)."""
    img = tmp_path / "img.npy"
    missing = cache_key(1, [str(img)], "q", "fp")
    assert missing == cache_key(1, [str(img)], "q", "fp")
    img.write_bytes(b"one")
    k1 = cache_key(1, [str(img)], "q", "fp")
    assert k1 != missing
    time.sleep(0.01)  # mtime_ns tick
    img.write_bytes(b"two bytes longer")
    assert cache_key(1, [str(img)], "q", "fp") != k1


# ------------------------------------------------------------ claim machine
@pytest.fixture()
def cache(tmp_path):
    return ResultCache(str(tmp_path / "cache.sqlite3"), fingerprint="fp1")


def test_claim_lead_attach_hit_lifecycle(cache):
    key = cache_key(1, ["a"], "q", cache.fingerprint)
    # First submit leads...
    assert cache.admit(key, socket_id="s0") == ("lead", None)
    cache.set_leader(key, 41)
    # ...identical in-flight submits attach to the leader's job id...
    state, leader = cache.admit(key, socket_id="s1", trace_id="t1")
    assert (state, leader) == ("attach", 41)
    # ...completion makes every later submit a durable hit.
    cache.complete(key, {"answers": [1]})
    state, payload = cache.admit(key, socket_id="s2")
    assert state == "hit" and payload == {"answers": [1]}
    assert cache.stats()["cache_stored_hits"] == 1.0


def test_claim_coalesce_disabled_leads_without_attaching(cache):
    key = cache_key(1, ["a"], "q", cache.fingerprint)
    assert cache.admit(key, socket_id="s0")[0] == "lead"
    # coalesce off: the duplicate runs its own forward (no follower row),
    # but completed results still hit.
    assert cache.admit(key, socket_id="s1", coalesce=False) == \
        ("lead", None)
    assert cache.peek_followers(key) == []
    cache.complete(key, {"v": 2})
    assert cache.admit(key, socket_id="s2", coalesce=False)[0] == "hit"


def test_lease_takeover_rearms_dead_leader(tmp_path):
    """A leader that died without completing must not strand the key:
    past the lease, the next submit takes leadership over."""
    c = ResultCache(str(tmp_path / "c.sqlite3"), fingerprint="fp",
                    lease_s=0.0)
    key = cache_key(1, ["a"], "q", c.fingerprint)
    assert c.admit(key, socket_id="s0")[0] == "lead"
    c.set_leader(key, 7)
    # Lease already expired (lease_s=0): re-arm instead of attaching to
    # the corpse. Earlier followers stay registered for the new leader.
    assert c.admit(key, socket_id="s1")[0] == "lead"


def test_complete_does_not_resurrect_invalidated_row(cache):
    key = cache_key(1, ["a"], "q", cache.fingerprint)
    cache.admit(key, socket_id="s0")
    # Rolling swap lands while the leader is in flight.
    assert cache.invalidate("fp2") == 1
    cache.complete(key, {"stale": True})
    # The old-generation payload must NOT be served under the new gen.
    newkey = cache_key(1, ["a"], "q", cache.fingerprint)
    assert cache.admit(newkey, socket_id="s1")[0] == "lead"
    assert cache.stats()["cache_done_rows"] == 0.0


def test_ttl_expired_entry_leads_again(tmp_path):
    c = ResultCache(str(tmp_path / "c.sqlite3"), fingerprint="fp",
                    ttl_s=0.0)
    key = cache_key(1, ["a"], "q", c.fingerprint)
    c.admit(key, socket_id="s0")
    c.complete(key, {"v": 1})
    # ttl 0: the done row is stale on arrival — dropped, fresh lead.
    assert c.admit(key, socket_id="s1")[0] == "lead"


def test_pop_followers_is_destructive_peek_is_not(cache):
    key = cache_key(1, ["a"], "q", cache.fingerprint)
    cache.admit(key, socket_id="s0")
    cache.admit(key, socket_id="s1", trace_id="t1", tenant="gold")
    cache.admit(key, socket_id="s2", trace_id="t2")
    peeked = cache.peek_followers(key)
    assert [f.socket_id for f in peeked] == ["s1", "s2"]
    assert peeked[0].tenant == "gold" and peeked[0].trace_id == "t1"
    popped = cache.pop_followers(key)
    assert [f.socket_id for f in popped] == ["s1", "s2"]
    # Exactly-once: a racing second terminal pops an empty registry.
    assert cache.pop_followers(key) == []


def test_invalidate_drops_only_other_generations(cache):
    k_old = cache_key(1, ["a"], "q", cache.fingerprint)
    cache.admit(k_old, socket_id="s0")
    cache.complete(k_old, {"v": 1})
    dropped = cache.invalidate("fp2")
    assert dropped == 1 and cache.fingerprint == "fp2"
    k_new = cache_key(1, ["a"], "q", "fp2")
    cache.admit(k_new, socket_id="s1")
    cache.complete(k_new, {"v": 2})
    # Same fingerprint: nothing to drop.
    assert cache.invalidate("fp2") == 0
    assert cache.admit(k_new, socket_id="s2")[0] == "hit"


def test_abandon_lets_next_submit_retry(cache):
    key = cache_key(1, ["a"], "q", cache.fingerprint)
    cache.admit(key, socket_id="s0")
    cache.abandon(key)
    assert cache.admit(key, socket_id="s1")[0] == "lead"


def test_capacity_trim_keeps_newest(tmp_path):
    c = ResultCache(str(tmp_path / "c.sqlite3"), fingerprint="fp",
                    max_rows=2)
    keys = [cache_key(1, ["a"], f"q{i}", "fp") for i in range(4)]
    for k in keys:
        c.admit(k, socket_id="s")
        c.complete(k, {"k": k})
    assert c.stats()["cache_done_rows"] == 2.0
    # Newest survive, oldest evicted back to a miss.
    assert c.admit(keys[-1], socket_id="s")[0] == "hit"
    assert c.admit(keys[0], socket_id="s")[0] == "lead"


# ------------------------------------------------- tenant-weighted packing
def _Req(n):
    class R:
        n_images = n
    return R()


def _titem(tenant, expiry=None, enq_t=0.0, n=1):
    from vilbert_multitask_tpu.resilience import Deadline

    dl = None
    if expiry is not None:
        dl = Deadline(1.0)
        dl._expires_perf = expiry  # explicit clock, test_scheduler.py style
    return ReadyItem(None, 1, _Req(n), 0.0, dl, enq_t, tenant=tenant)


def test_select_batch_without_deficits_is_pure_edf():
    items = [_titem("a", expiry=103.0), _titem("b", expiry=101.0),
             _titem("a", expiry=102.0)]
    batch, expired, rest = select_batch(items, now=100.0, max_rows=2)
    assert [i.deadline.expires_at() for i in batch] == [101.0, 102.0]
    assert not expired and len(rest) == 1


def test_select_batch_weighted_deficit_shares():
    """3:1 weights → a 4-row fire packs 3 of gold's jobs and 1 of
    bronze's, even with every deadline equal."""
    items = [_titem("gold") for _ in range(8)] \
        + [_titem("bronze") for _ in range(8)]
    deficits = {}
    batch, _, rest = select_batch(
        items, now=100.0, max_rows=4, deficits=deficits,
        weights={"gold": 3.0, "bronze": 1.0})
    packed = [i.tenant for i in batch]
    assert packed.count("gold") == 3 and packed.count("bronze") == 1
    assert len(rest) == 12


def test_select_batch_deficit_carries_over_to_starved_tenant():
    """An underweighted tenant's unspent credit accumulates: it cannot be
    starved forever by a heavier tenant's backlog."""
    deficits = {}
    weights = {"gold": 7.0, "bronze": 1.0}
    served = {"gold": 0, "bronze": 0}
    items = [_titem("gold") for _ in range(64)] \
        + [_titem("bronze") for _ in range(8)]
    for _ in range(8):
        batch, _, items = select_batch(
            items, now=100.0, max_rows=4, deficits=deficits,
            weights=weights)
        for it in batch:
            served[it.tenant] += 1
    assert served["bronze"] >= 2  # 1/8 of 32 rows, credit-carried
    assert served["gold"] > served["bronze"]


def test_select_batch_marks_passed_over_items_deferred():
    items = [_titem("gold") for _ in range(4)] \
        + [_titem("bronze") for _ in range(4)]
    batch, _, rest = select_batch(
        items, now=100.0, max_rows=2, deficits={},
        weights={"gold": 1.0, "bronze": 1.0})
    assert all(i.deferred for i in rest)
    assert not any(i.deferred for i in batch)


def test_select_batch_drained_tenant_resets_deficit():
    """Cardinality bound: a tenant whose backlog fully drains leaves the
    deficit map (no unbounded per-tenant state, no banked credit)."""
    deficits = {}
    items = [_titem("gold"), _titem("bronze")]
    batch, _, rest = select_batch(
        items, now=100.0, max_rows=4, deficits=deficits,
        weights={"gold": 1.0, "bronze": 1.0})
    assert len(batch) == 2 and not rest
    assert deficits == {}


def test_select_batch_expired_still_shed_first():
    items = [_titem("gold", expiry=99.0), _titem("gold", expiry=200.0)]
    batch, expired, rest = select_batch(
        items, now=100.0, max_rows=4, deficits={}, weights={})
    assert len(expired) == 1 and expired[0].deadline.expires_at() == 99.0
    assert len(batch) == 1 and not rest


# ----------------------------------------------------- end-to-end coalesce
@pytest.fixture()
def coalesce_stack(tiny_framework_cfg, engine, tmp_path):
    """stack fixture + the duplicate-traffic tier wired through, the way
    ServeApp composes it (one sqlite for queue + cache)."""
    import dataclasses

    from vilbert_multitask_tpu.serve import (
        DurableQueue,
        PushHub,
        ResultStore,
        ServeWorker,
    )
    from vilbert_multitask_tpu.serve.http_api import ApiServer

    s = dataclasses.replace(
        tiny_framework_cfg.serving,
        queue_db_path=str(tmp_path / "q.sqlite3"),
        results_db_path=str(tmp_path / "r.sqlite3"),
        media_root=str(tmp_path / "media"),
    )
    hub = PushHub()
    q = DurableQueue(s.queue_db_path,
                     max_delivery_attempts=s.max_delivery_attempts)
    store = ResultStore(s.results_db_path)
    cache = ResultCache(s.queue_db_path, fingerprint="test-gen0",
                        lease_s=60.0)
    worker = ServeWorker(engine, q, store, hub, s, cache=cache)
    api = ApiServer(q, store, hub, s, cache=cache)
    return s, hub, q, store, worker, api, cache


def _submit_n(api, hub, n, question="what is this", image="img_a.jpg"):
    """N identical concurrent submits from N sockets; returns the per-
    socket subscriptions and the api responses in socket order."""
    subs = [hub.subscribe(f"co-{i}") for i in range(n)]
    results: list = [None] * n

    def _go(i):
        results[i] = api.submit_job({
            "task_id": 1, "socket_id": f"co-{i}", "question": question,
            "image_list": [image], "tenant": "gold" if i % 2 else "bronze",
        })

    threads = [threading.Thread(target=_go, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert all(code == 200 for code, _ in results), results
    return subs, [body for _, body in results]


def _terminals(sub):
    """Drain one socket's frames; return its terminal frames."""
    out = []
    while True:
        try:
            frame = sub.get_nowait()
        except queue_mod.Empty:
            return out
        if ("result" in frame or "error" in frame
                or frame.get("deadline_exceeded")
                or frame.get("dead_letter")):
            out.append(frame)


def test_concurrent_identical_submits_one_forward_one_terminal_each(
        coalesce_stack):
    s, hub, q, store, worker, api, cache = coalesce_stack
    subs, bodies = _submit_n(api, hub, 4)
    markers = sorted(b.get("cache") for b in bodies)
    # Exactly one submit led (published the one real job); the other
    # three attached to its job id.
    assert markers == ["coalesced", "coalesced", "coalesced", "miss"]
    leader_id = next(b["job_id"] for b in bodies
                     if b.get("cache") == "miss")
    # A follower that attached before the leader's publish stamped the
    # job id reports job_id null — fan-out is keyed on the cache key, so
    # its terminal still closes. No follower may name a DIFFERENT job.
    assert all(b["job_id"] in (leader_id, None) for b in bodies)
    assert q.counts()["pending"] == 1  # ONE forward for four submits
    worker.step_batch()
    for sub in subs:
        terms = _terminals(sub)
        assert len(terms) == 1 and "result" in terms[0]
    # The write-through makes submit five a durable hit, result inline.
    code, body = api.submit_job({
        "task_id": 1, "socket_id": "late", "question": "what is this",
        "image_list": ["img_a.jpg"]})
    assert code == 200 and body["cache"] == "hit"
    assert body["result"]["question"] == "what is this"
    assert q.counts().get("pending", 0) == 0


def test_leader_dead_letter_fans_exactly_one_terminal_per_submit(
        coalesce_stack):
    """Satellite chaos proof, dead-letter arm: a seeded FaultPlan kills
    every intake of the leader job until the queue quarantines it — all
    N submits must still close with exactly one (error) terminal."""
    from vilbert_multitask_tpu.resilience import (
        FaultPlan,
        FaultRule,
        clear_plan,
        install_plan,
    )

    s, hub, q, store, worker, api, cache = coalesce_stack
    subs, bodies = _submit_n(api, hub, 3, question="doomed leader")
    assert sorted(b.get("cache") for b in bodies) == \
        ["coalesced", "coalesced", "miss"]
    install_plan(FaultPlan(11, [
        FaultRule("worker.intake", "error", rate=1.0, max_injections=32),
    ]))
    try:
        for _ in range(s.max_delivery_attempts + 1):
            worker.step_batch()
    finally:
        clear_plan()
    assert q.counts()["dead"] == 1
    for sub in subs:
        terms = _terminals(sub)
        assert len(terms) == 1, terms
        assert "error" in terms[0]
    # The singleflight claim dropped with the corpse: a retry submit
    # republishes instead of attaching to the dead job.
    code, body = api.submit_job({
        "task_id": 1, "socket_id": "retry", "question": "doomed leader",
        "image_list": ["img_a.jpg"]})
    assert code == 200 and body["cache"] == "miss"


def test_leader_expiry_fans_exactly_one_terminal_per_submit(
        coalesce_stack):
    """Deadline arm: the leader expires before dispatch — every follower
    hears the deadline push, exactly once."""
    s, hub, q, store, worker, api, cache = coalesce_stack
    subs, bodies = _submit_n(api, hub, 3, question="too late")
    assert sorted(b.get("cache") for b in bodies) == \
        ["coalesced", "coalesced", "miss"]
    job = q.claim()
    worker._expire_job(job)
    for sub in subs:
        terms = _terminals(sub)
        assert len(terms) == 1, terms
        assert terms[0].get("deadline_exceeded")
    # tenant_budget sheds classify separately in vmt_shed_total.
    before = obs.SHED_COUNTER.value(reason="tenant_budget")
    subs2, _ = _submit_n(api, hub, 1, question="qos shed")
    worker._expire_job(q.claim(), reason="tenant_budget")
    assert obs.SHED_COUNTER.value(reason="tenant_budget") == before + 1
    assert _terminals(subs2[0])[0].get("deadline_exceeded")


def test_attention_submits_bypass_the_cache(coalesce_stack):
    """Per-request attention payloads are per-submit state: they must
    never be served from (or stored into) the shared cache."""
    s, hub, q, store, worker, api, cache = coalesce_stack
    body = {"task_id": 1, "socket_id": "att", "question": "maps please",
            "image_list": ["img_a.jpg"], "collect_attention": True}
    code, b1 = api.submit_job(dict(body))
    code2, b2 = api.submit_job(dict(body))
    assert code == code2 == 200
    assert "cache" not in b1 and "cache" not in b2
    assert q.counts()["pending"] == 2  # no dedup across attention jobs
