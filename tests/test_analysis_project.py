"""Whole-program vmtlint suite: the project graph and every rule that
needs it.

Same contract as test_analysis.py — each rule proves it fires on the
minimal hazard AND stays quiet on the correct twin — but the fixtures
here are multi-module dicts fed to ``analyze_project``, because the
hazards only exist across files: a numpy helper traced from a jit in
another module, a donating function that escapes through an import, a
thread started in one method racing a field written in another.
"""

import ast
import json
import textwrap

import pytest

from vilbert_multitask_tpu.analysis import ProjectGraph, analyze_project
from vilbert_multitask_tpu.analysis.cli import main as cli_main
from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.graph import module_name_for


def project(sources, layers=()):
    """Build a ProjectGraph from {rel_path: source} (dedented)."""
    ctxs = []
    for path in sorted(sources):
        src = textwrap.dedent(sources[path])
        ctxs.append(ModuleContext(path, src, ast.parse(src)))
    graph = ProjectGraph(ctxs, layers=layers)
    for c in ctxs:
        c.project = graph
    return graph


def findings(sources, layers=()):
    return analyze_project(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        library_roots=("pkg", "vilbert_multitask_tpu"), layers=layers)


def rules_hit(sources, layers=()):
    return {(f.rule, f.path) for f in findings(sources, layers=layers)}


# ------------------------------------------------------------ graph builder
def test_module_name_for_paths():
    assert module_name_for("pkg/sub/mod.py") == "pkg.sub.mod"
    assert module_name_for("pkg/__init__.py") == "pkg"
    assert module_name_for("pkg/sub/__init__.py") == "pkg.sub"
    assert module_name_for("script.py") == "script"


def test_resolve_through_aliased_import():
    g = project({
        "pkg/a.py": """
        def f():
            return 1
        """,
        "pkg/b.py": """
        from pkg.a import f as renamed
        import pkg.a as amod
        """,
    })
    b = g.modules["pkg.b"]
    assert b.refs["renamed"] == "pkg.a.f"
    mod, sym = g.resolve_symbol("pkg.a.f")
    assert mod.name == "pkg.a" and sym == "f"
    mod, sym = g.resolve_symbol(b.refs["amod"])
    assert mod.name == "pkg.a" and sym == ""


def test_resolve_chases_package_reexport():
    # from pkg import f  → pkg/__init__.py → pkg/impl.py, two hops.
    g = project({
        "pkg/__init__.py": """
        from pkg.impl import f
        """,
        "pkg/impl.py": """
        def f():
            return 1
        """,
        "app.py": """
        from pkg import f
        """,
    })
    app = g.modules["app"]
    mod, sym = g.resolve_symbol(app.refs["f"])
    assert mod.name == "pkg.impl" and sym == "f"


def test_relative_imports_resolve():
    g = project({
        "pkg/__init__.py": "",
        "pkg/sub/__init__.py": "",
        "pkg/sub/a.py": """
        from . import b
        from .b import f
        from ..top import g
        """,
        "pkg/sub/b.py": """
        def f():
            return 1
        """,
        "pkg/top.py": """
        def g():
            return 2
        """,
    })
    a = g.modules["pkg.sub.a"]
    assert a.refs["f"] == "pkg.sub.b.f"
    assert a.refs["g"] == "pkg.top.g"
    mod, sym = g.resolve_symbol(a.refs["f"])
    assert mod.name == "pkg.sub.b" and sym == "f"


def test_import_cycle_resolution_terminates():
    # a re-exports from b, b re-exports from a: chasing the phantom name
    # must return None, not recurse forever.
    g = project({
        "pkg/a.py": """
        from pkg.b import ghost
        """,
        "pkg/b.py": """
        from pkg.a import ghost
        """,
    })
    assert g.resolve_symbol("pkg.a.ghost") is None
    assert g.resolve_symbol("pkg.b.ghost") is None


# ---------------------------------------------- interprocedural VMT101/103
def test_vmt101_fires_in_helper_called_from_jit_across_modules():
    hits = rules_hit({
        "pkg/helpers.py": """
        import numpy as np

        def to_host(x):
            return np.asarray(x)
        """,
        "pkg/model.py": """
        import jax

        from pkg.helpers import to_host

        @jax.jit
        def step(x):
            return to_host(x) + 1
        """,
    })
    # The finding lands in the helper's file — that's where the fix goes.
    assert ("VMT101", "pkg/helpers.py") in hits


def test_vmt101_quiet_when_helper_only_called_eagerly():
    hits = rules_hit({
        "pkg/helpers.py": """
        import numpy as np

        def to_host(x):
            return np.asarray(x)
        """,
        "pkg/model.py": """
        from pkg.helpers import to_host

        def eager_path(x):
            return to_host(x)
        """,
    })
    assert not {r for r, _ in hits} & {"VMT101"}


def test_vmt103_donated_buffer_escapes_through_import():
    hits = rules_hit({
        "pkg/steps.py": """
        import jax

        def _step(state, batch):
            return state

        train_step = jax.jit(_step, donate_argnums=(0,))
        """,
        "pkg/loop.py": """
        from pkg.steps import train_step

        def run(state, batches):
            for batch in batches:
                train_step(state, batch)  # state never rebound
            return state
        """,
    })
    assert ("VMT103", "pkg/loop.py") in hits


def test_vmt103_quiet_when_caller_rebinds():
    hits = rules_hit({
        "pkg/steps.py": """
        import jax

        def _step(state, batch):
            return state

        train_step = jax.jit(_step, donate_argnums=(0,))
        """,
        "pkg/loop.py": """
        from pkg.steps import train_step

        def run(state, batches):
            for batch in batches:
                state = train_step(state, batch)
            return state
        """,
    })
    assert not {r for r, _ in hits} & {"VMT103"}


# --------------------------------------------------------------- VMT110
def test_vmt110_unguarded_write_in_threaded_class():
    hits = rules_hit({
        "pkg/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def start(self):
                threading.Thread(target=self._refresh).start()

            def _refresh(self):
                self._data.clear()  # racing put(): no lock

            def put(self, key, value):
                with self._lock:
                    self._data[key] = value
        """,
    })
    assert ("VMT110", "pkg/cache.py") in hits


def test_vmt110_clean_when_every_write_is_guarded():
    hits = rules_hit({
        "pkg/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def start(self):
                threading.Thread(target=self._refresh).start()

            def _refresh(self):
                with self._lock:
                    self._data.clear()

            def put(self, key, value):
                with self._lock:
                    self._data[key] = value

            def size(self):
                return len(self._data)  # lock-free read: allowed
        """,
    })
    assert not {r for r, _ in hits} & {"VMT110"}


def test_vmt110_quiet_without_thread_witness():
    # Same unguarded write, but nothing in the project ever runs the class
    # on a thread — single-threaded use of a lock-holding class is not a
    # race.
    hits = rules_hit({
        "pkg/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def clear(self):
                self._data.clear()

            def put(self, key, value):
                with self._lock:
                    self._data[key] = value
        """,
    })
    assert not {r for r, _ in hits} & {"VMT110"}


def test_vmt110_sees_threads_started_in_another_module():
    # The thread entry lives in app.py; the racy class lives in cache.py.
    hits = rules_hit({
        "pkg/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def refresh(self):
                self._data.clear()

            def put(self, key, value):
                with self._lock:
                    self._data[key] = value
        """,
        "pkg/app.py": """
        import threading

        from pkg.cache import Cache

        def serve():
            cache = Cache()
            threading.Thread(target=cache.refresh).start()
            return cache
        """,
    })
    assert ("VMT110", "pkg/cache.py") in hits


# --------------------------------------------------------------- VMT111
def test_vmt111_unknown_axis_in_partition_spec():
    hits = rules_hit({
        "pkg/mesh.py": """
        import jax
        from jax.sharding import Mesh

        def build(devices):
            return Mesh(devices, ("dp", "tp"))
        """,
        "pkg/specs.py": """
        from jax.sharding import PartitionSpec

        KERNEL = PartitionSpec(None, "model")
        """,
    })
    assert ("VMT111", "pkg/specs.py") in hits


def test_vmt111_clean_with_declared_axes_and_without_any_mesh():
    clean = {
        "pkg/mesh.py": """
        from jax.sharding import Mesh

        def build(devices):
            return Mesh(devices, ("dp", "tp"))
        """,
        "pkg/specs.py": """
        from jax.sharding import PartitionSpec

        KERNEL = PartitionSpec(None, "tp")
        ROWS = PartitionSpec("dp")
        """,
    }
    assert not {r for r, _ in rules_hit(clean)} & {"VMT111"}
    # No Mesh anywhere → no declared axes → the rule stays silent rather
    # than flagging every spec in a repo that doesn't use meshes.
    no_mesh = {"pkg/specs.py": clean["pkg/specs.py"]}
    assert not {r for r, _ in rules_hit(no_mesh)} & {"VMT111"}


# --------------------------------------------------------------- VMT112
def test_vmt112_layer_contract_catches_lazy_import():
    contract = (("pkg.models", "pkg.serve"),)
    hits = rules_hit({
        "pkg/models/net.py": """
        def forward(x):
            from pkg.serve.metrics import record  # lazy doesn't hide it
            record(x)
            return x
        """,
        "pkg/serve/metrics.py": """
        def record(x):
            pass
        """,
    }, layers=contract)
    assert ("VMT112", "pkg/models/net.py") in hits


def test_vmt112_clean_for_allowed_direction():
    contract = (("pkg.models", "pkg.serve"),)
    hits = rules_hit({
        "pkg/models/net.py": """
        def forward(x):
            return x
        """,
        "pkg/serve/api.py": """
        from pkg.models.net import forward  # serve → models is the point
        """,
    }, layers=contract)
    assert not {r for r, _ in hits} & {"VMT112"}


# ---------------------------------------------------------------- VMT113
def test_vmt113_direct_transfer_in_hot_loop():
    """device_put inside a loop in an engine serving entry fires."""
    fs = findings({
        "pkg/engine/runtime.py": """
        import jax

        class Engine:
            def run_many(self, reqs):
                out = []
                for r in reqs:
                    out.append(jax.device_put(r))
                return out
        """,
    })
    hits = [f for f in fs if f.rule == "VMT113"]
    assert len(hits) == 1
    assert "jax.device_put" in hits[0].message
    assert "run_many" in hits[0].message


def test_vmt113_transfer_through_project_call_chain():
    """A loop calling a helper that transitively device_gets fires, with a
    witness chain naming the concrete transfer — across modules."""
    fs = findings({
        "pkg/engine/runtime.py": """
        from pkg.engine.fetch import pull

        class Engine:
            def run(self, reqs):
                out = []
                while reqs:
                    out.append(pull(reqs.pop()))
                return out
        """,
        "pkg/engine/fetch.py": """
        import jax

        def pull(x):
            return jax.device_get(x)
        """,
    })
    hits = [f for f in fs if f.rule == "VMT113"]
    assert len(hits) == 1
    assert "pkg.engine.fetch:pull" in hits[0].message
    assert "jax.device_get" in hits[0].message


def test_vmt113_quiet_outside_hot_path_and_outside_loops():
    """Same transfer shapes stay silent when not in an engine entry's loop:
    a non-engine module, a hot function without a loop, and a comprehension
    (the repo's one-fused-transfer idiom) all pass."""
    fs = findings({
        # Not an engine module: name pattern doesn't match.
        "pkg/train/loop.py": """
        import jax

        def run_many(batches):
            return [jax.device_put(b) for b in batches]
        """,
        "pkg/engine/runtime.py": """
        import jax

        class Engine:
            def run(self, req):
                # No loop: one fused transfer per forward is the design.
                return jax.device_put(req)

            def run_many(self, reqs):
                # Comprehension, not a loop: builds ONE fused device_put.
                packed = {k: v for k, v in reqs}
                return jax.device_put(packed)
        """,
    })
    assert not [f for f in fs if f.rule == "VMT113"]


def test_vmt113_hot_reachability_crosses_helpers():
    """The hot set is transitive: a helper called from run() that loops
    over transfers fires even though the helper's name matches nothing."""
    fs = findings({
        "pkg/engine/runtime.py": """
        import jax

        def _upload_rows(rows):
            out = []
            for r in rows:
                out.append(jax.device_put(r))
            return out

        def run(reqs):
            return _upload_rows(reqs)
        """,
    })
    hits = [f for f in fs if f.rule == "VMT113"]
    assert len(hits) == 1
    assert "_upload_rows" in hits[0].message or "run" in hits[0].message


def test_vmt113_own_engine_loops_are_baselined_pipelining():
    """The real engine's only VMT113 findings are run_many's deliberate
    per-chunk pipelining (dispatch + drain), each carried by a justified
    baseline entry — the rule must not regress into noise on the tree it
    polices."""
    import os

    from vilbert_multitask_tpu.analysis import baseline as bl
    from vilbert_multitask_tpu.analysis.core import analyze_file

    root = os.path.join(os.path.dirname(__file__), "..")
    fs = [f for f in analyze_file(
        os.path.join(root, "vilbert_multitask_tpu/engine/runtime.py"),
        root=root) if f.rule == "VMT113"]
    assert fs, "run_many's pipelined dispatch/drain should be visible"
    baseline = bl.load_baseline(os.path.join(root, "vmtlint_baseline.json"))
    for f in fs:
        assert f.fingerprint() in baseline, (
            f"unbaselined engine hot-loop transfer: {f.path}:{f.line} "
            f"{f.message}")


# --------------------------------------------------------------- VMT116
def test_vmt116_sleep_under_scheduler_lock():
    hits = rules_hit({
        "pkg/serve/sched.py": """
        import threading
        import time

        class Scheduler:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = []

            def start(self):
                threading.Thread(target=self.loop).start()

            def loop(self):
                with self._lock:
                    time.sleep(0.05)  # convoy: intake blocks on the lock
                    self._ready.append(1)
        """,
    })
    assert ("VMT116", "pkg/serve/sched.py") in hits


def test_vmt116_clean_when_blocking_call_outside_lock():
    hits = rules_hit({
        "pkg/serve/sched.py": """
        import threading
        import time

        class Scheduler:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = []

            def start(self):
                threading.Thread(target=self.loop).start()

            def loop(self):
                time.sleep(0.05)  # outside the critical section: fine
                with self._lock:
                    self._ready.append(1)
        """,
    })
    assert not {r for r, _ in hits} & {"VMT116"}


def test_vmt116_quiet_without_thread_witness():
    # Same sleep-under-lock shape, but nothing ever runs the class on a
    # thread — a single-threaded lock holder cannot convoy.
    hits = rules_hit({
        "pkg/serve/sched.py": """
        import threading
        import time

        class Scheduler:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = []

            def loop(self):
                with self._lock:
                    time.sleep(0.05)
                    self._ready.append(1)
        """,
    })
    assert not {r for r, _ in hits} & {"VMT116"}


def test_vmt116_fires_in_locked_only_helper():
    # The blocking call hides in a private helper the VMT110 fixed point
    # proves only ever runs with the lock held.
    hits = rules_hit({
        "pkg/serve/sched.py": """
        import sqlite3
        import threading

        class Scheduler:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = []

            def start(self):
                threading.Thread(target=self.loop).start()

            def loop(self):
                with self._lock:
                    self._ready.append(1)
                    self._persist()

            def _persist(self):
                conn = sqlite3.connect("state.db")  # I/O under the lock
                conn.close()
        """,
    })
    assert ("VMT116", "pkg/serve/sched.py") in hits


def test_vmt116_transfer_witness_through_project_call():
    # The device round trip lives in another module; the call graph's
    # transfer witness carries it back under the lock.
    hits = rules_hit({
        "pkg/serve/sched.py": """
        import threading

        from pkg.engine.fetch import fetch_rows

        class Scheduler:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = []

            def start(self):
                threading.Thread(target=self.loop).start()

            def loop(self):
                with self._lock:
                    self._ready.append(fetch_rows())
        """,
        "pkg/engine/fetch.py": """
        import jax

        def fetch_rows():
            return jax.device_get(1)
        """,
    })
    assert ("VMT116", "pkg/serve/sched.py") in hits


def test_vmt116_scoped_to_serve_plane():
    # Identical hazard outside serve/ stays quiet: the engine's serialized
    # upload under its input-cache lock is a documented, deliberate cost.
    hits = rules_hit({
        "pkg/engine/cache.py": """
        import threading
        import time

        class SlabCache:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = []

            def start(self):
                threading.Thread(target=self.insert).start()

            def insert(self):
                with self._lock:
                    time.sleep(0.01)
                    self._rows.append(1)
        """,
    })
    assert not {r for r, _ in hits} & {"VMT116"}


def test_vmt116_real_scheduler_is_clean():
    """The rule polices the module it was built for: the continuous
    batching scheduler's condvar must guard only list/stat state, never
    dispatch, I/O, or sleeps."""
    import os

    from vilbert_multitask_tpu.analysis.core import analyze_file

    root = os.path.join(os.path.dirname(__file__), "..")
    fs = [f for f in analyze_file(
        os.path.join(root, "vilbert_multitask_tpu/serve/scheduler.py"),
        root=root) if f.rule == "VMT116"]
    assert not fs, [f"{f.path}:{f.line} {f.message}" for f in fs]


# ------------------------------------------------------------------- CLI
@pytest.fixture()
def lint_repo(tmp_path, monkeypatch):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
    [tool.vmtlint]
    paths = ["pkg"]
    library_roots = ["pkg"]
    baseline = "baseline.json"
    """))
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(textwrap.dedent("""
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        return np.asarray(x)
    """))
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_cli_prune_baseline_drops_only_stale_entries(lint_repo, capsys):
    assert cli_main(["--write-baseline", "baseline.json"]) == 0
    capsys.readouterr()
    # Fix the finding: its entry is now stale and strict mode says so.
    (lint_repo / "pkg" / "bad.py").write_text("def f(x):\n    return x\n")
    assert cli_main(["--strict"]) == 1
    capsys.readouterr()
    assert cli_main(["--prune-baseline"]) == 0
    assert "pruned 1 stale baseline entry" in capsys.readouterr().err
    doc = json.loads((lint_repo / "baseline.json").read_text())
    assert doc["entries"] == []
    assert cli_main(["--strict"]) == 0
    # Nothing stale on a second prune; still exit 0 (idempotent).
    capsys.readouterr()
    assert cli_main(["--prune-baseline"]) == 0


def test_cli_prune_baseline_requires_a_baseline(tmp_path, monkeypatch,
                                               capsys):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.vmtlint]\npaths = [\"pkg\"]\n")
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "ok.py").write_text("x = 1\n")
    monkeypatch.chdir(tmp_path)
    assert cli_main(["--prune-baseline"]) == 2  # usage error, not silence
    capsys.readouterr()


def test_cli_sarif_output(lint_repo, capsys):
    assert cli_main(["--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "vmtlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"VMT101", "VMT110", "VMT112"} <= rule_ids
    results = run["results"]
    assert any(r["ruleId"] == "VMT101" for r in results)
    hit = next(r for r in results if r["ruleId"] == "VMT101")
    assert hit["locations"][0]["physicalLocation"]["artifactLocation"][
        "uri"] == "pkg/bad.py"
    assert "vmtlint/v1" in hit["partialFingerprints"]
    # Baselined findings are suppressed, not SARIF results.
    assert cli_main(["--write-baseline", "baseline.json"]) == 0
    capsys.readouterr()
    assert cli_main(["--format", "sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []
