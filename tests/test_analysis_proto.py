"""Protocol tier suite: typestate fixture pairs for VMT132-135, the
real-tree pins (worker/scheduler claim paths verify clean; chaos
coverage is total), and the protocol manifest (PROTOCOL_SURFACE.json) —
determinism, drift detection, and the byte-for-byte committed gate CI
runs via ``proto --check``.

Rule fixtures are multi-module dicts through ``analyze_project``: the
registry resolves protocol verbs against the classes that declare them,
and wrapper composition crosses files exactly like the real
worker/scheduler split does.
"""

import ast
import copy
import json
import os
import textwrap

import pytest

from vilbert_multitask_tpu.analysis import analyze_project
from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.graph import ProjectGraph
from vilbert_multitask_tpu.analysis import proto as proto_mod
from vilbert_multitask_tpu.analysis.proto import (
    build_proto_surface,
    diff_proto_surface,
    proto_flow,
    render_proto_surface,
    render_proto_surface_sarif,
)
from vilbert_multitask_tpu.analysis.protorules import FaultPointCoverage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, proto_mod.MANIFEST_NAME)


def project(sources):
    ctxs = []
    for path in sorted(sources):
        src = textwrap.dedent(sources[path])
        ctxs.append(ModuleContext(path, src, ast.parse(src)))
    graph = ProjectGraph(ctxs)
    for c in ctxs:
        c.project = graph
    return graph


def findings(sources):
    return analyze_project(
        {p: textwrap.dedent(s) for p, s in sources.items()},
        library_roots=("pkg", "vilbert_multitask_tpu"))


def rules_hit(sources):
    return {f.rule for f in findings(sources)}


def _tree_sources():
    """The exact source set the proto CLI loads: configured paths minus
    excludes — library tree plus tests/ and scripts/ (the fault-coverage
    map needs to see the FaultPlans that live in tests)."""
    from vilbert_multitask_tpu.analysis.config import load_config
    from vilbert_multitask_tpu.analysis.core import iter_python_files

    cfg, root = load_config(REPO)
    root = root or REPO
    roots = [os.path.join(root, p) for p in cfg.paths]
    out = {}
    for path in iter_python_files(
            [r for r in roots if os.path.exists(r)], exclude=cfg.exclude):
        rel = os.path.relpath(os.path.abspath(path),
                              root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            out[rel] = f.read()
    return out


@pytest.fixture(scope="module")
def repo_flow():
    return proto_flow(project(_tree_sources()))


@pytest.fixture(scope="module")
def fresh_surface(repo_flow):
    return build_proto_surface(repo_flow.project)


# The queue a worker claims from: providers for the job protocol.
_QUEUE = """
class Queue:
    def claim(self):
        return self._pop()

    def ack(self, job_id):
        self._settle(job_id, "done")

    def nack(self, job_id):
        self._settle(job_id, "retry")

    def release(self, job_id):
        self._settle(job_id, "requeue")
"""

# The pool a dispatcher checks replicas out of.
_POOL = """
class Pool:
    def checkout(self):
        return self._pick()

    def checkin(self, rep):
        self._ready.append(rep)
"""


# ----------------------------------------------------------------- VMT132
def test_vmt132_leaked_claim_on_untaken_branch():
    srcs = {"pkg/q.py": _QUEUE, "pkg/w.py": """
    class Worker:
        def bad(self):
            job = self.q.claim()
            if job is None:
                return
            if job.retryable:
                self.q.ack(job.id)
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT132"]
    assert len(fs) == 1 and "leaked claim" in fs[0].message
    # The witness chain names the claim and the leaking exit.
    assert fs[0].flows and len(fs[0].flows[0]) >= 2


def test_vmt132_every_path_terminates_is_clean():
    srcs = {"pkg/q.py": _QUEUE, "pkg/w.py": """
    class Worker:
        def good(self):
            job = self.q.claim()
            if job is None:
                return
            try:
                self.handle(job.body)
            except Exception:
                self.q.nack(job.id)
                return
            self.q.ack(job.id)
    """}
    assert "VMT132" not in rules_hit(srcs)


def test_vmt132_double_terminal_fires_with_both_witnesses():
    srcs = {"pkg/q.py": _QUEUE, "pkg/w.py": """
    class Worker:
        def twice(self):
            job = self.q.claim()
            if job is None:
                return
            self.q.ack(job.id)
            self.q.release(job.id)
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT132"]
    assert len(fs) == 1 and "double terminal" in fs[0].message
    # codeFlow: claim -> first terminal -> second terminal.
    assert len(fs[0].flows[0]) == 3


def test_vmt132_terminal_then_handler_terminal_is_not_double():
    # The terminal itself may raise mid-flight (the exception edge fires
    # from its own boundary), so a compensating terminal in the handler
    # is the CORRECT shape, not a double.
    srcs = {"pkg/q.py": _QUEUE, "pkg/w.py": """
    class Worker:
        def safe(self):
            job = self.q.claim()
            if job is None:
                return
            try:
                self.q.ack(job.id)
            except Exception:
                self.q.nack(job.id)
    """}
    assert "VMT132" not in rules_hit(srcs)


def test_vmt132_composes_through_wrappers_across_files():
    # claim behind a helper, terminal behind another: the per-function
    # summaries compose through the call graph, so the leak in `run`
    # is visible even though `run` itself names no protocol verb.
    srcs = {"pkg/q.py": _QUEUE, "pkg/claimer.py": """
    class Claimer:
        def pull(self):
            job = self.q.claim()
            return job
    """, "pkg/w.py": """
    class Worker:
        def _fail(self, job):
            self.q.nack(job.id)

        def run(self):
            job = self.claimer.pull()
            if job is None:
                return
            if job.retryable:
                self._fail(job)
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT132"]
    assert [f.path for f in fs] == ["pkg/w.py"]
    fixed = copy.deepcopy(srcs)
    fixed["pkg/w.py"] = srcs["pkg/w.py"].replace(
        "if job.retryable:\n                self._fail(job)",
        "self._fail(job)")
    assert fixed["pkg/w.py"] != srcs["pkg/w.py"]
    assert "VMT132" not in rules_hit(fixed)


def test_vmt132_escaped_handle_is_the_callees_obligation():
    # Returning or storing the claimed handle hands the terminal
    # obligation off — the path walk must not call that a leak.
    srcs = {"pkg/q.py": _QUEUE, "pkg/w.py": """
    class Worker:
        def stash(self):
            job = self.q.claim()
            if job is None:
                return None
            self._inflight[job.id] = job
            return job
    """}
    assert "VMT132" not in rules_hit(srcs)


def test_vmt132_is_library_only():
    srcs = {"pkg/q.py": _QUEUE, "tests/test_w.py": """
    def test_claim_and_drop(q):
        job = q.claim()
        assert job.body
    """}
    assert "VMT132" not in rules_hit(srcs)


# ----------------------------------------------------------------- VMT133
def test_vmt133_checkout_abandoned_on_raise():
    srcs = {"pkg/pool.py": _POOL, "pkg/d.py": """
    class Dispatcher:
        def bad(self):
            rep = self.pool.checkout()
            if self.draining:
                raise RuntimeError("drain")
            self.pool.checkin(rep)
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT133"]
    assert len(fs) == 1 and "rep" in fs[0].message
    assert fs[0].flows  # acquire -> raise witness chain


def test_vmt133_checkin_before_raise_is_clean():
    srcs = {"pkg/pool.py": _POOL, "pkg/d.py": """
    class Dispatcher:
        def good(self):
            rep = self.pool.checkout()
            try:
                out = rep.run()
            except Exception as e:
                self.pool.checkin(rep)
                raise RuntimeError("failover") from e
            self.pool.checkin(rep)
            return out
    """}
    assert "VMT133" not in rules_hit(srcs)


def test_vmt133_started_thread_abandoned_on_raise():
    srcs = {"pkg/t.py": """
    import threading

    def bad(self):
        t = threading.Thread(target=self._drain)
        t.start()
        if self.misconfigured:
            raise ValueError("bad config")
        t.join()
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT133"]
    assert len(fs) == 1 and "thread" in fs[0].message


def test_vmt133_raise_before_start_is_clean():
    srcs = {"pkg/t.py": """
    import threading

    def good(self):
        t = threading.Thread(target=self._drain)
        if self.misconfigured:
            raise ValueError("bad config")
        t.start()
        t.join()
    """}
    assert "VMT133" not in rules_hit(srcs)


def test_vmt133_bare_sqlite_connection_abandoned_on_raise():
    srcs = {"pkg/s.py": """
    import sqlite3

    def bad(path, expected):
        conn = sqlite3.connect(path)
        row = conn.execute("SELECT v FROM kv").fetchone()
        if row[0] != expected:
            raise ValueError("drifted")
        conn.close()
        return row
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT133"]
    assert len(fs) == 1 and "sqlite" in fs[0].message


def test_vmt133_with_managed_connection_is_clean():
    # `with` releases through __exit__ on every edge — never tracked.
    srcs = {"pkg/s.py": """
    import sqlite3

    def good(path, expected):
        with sqlite3.connect(path) as conn:
            row = conn.execute("SELECT v FROM kv").fetchone()
            if row[0] != expected:
                raise ValueError("drifted")
            return row
    """}
    assert "VMT133" not in rules_hit(srcs)


# ----------------------------------------------------------------- VMT134
_FAULTED = {"pkg/svc.py": """
def send(payload):
    payload = fault_point("svc.send", payload)
    return _post(payload)
"""}


def test_vmt134_uncovered_fault_site_fires():
    fs = [f for f in findings(_FAULTED) if f.rule == "VMT134"]
    assert len(fs) == 1 and "svc.send" in fs[0].message


def test_vmt134_covered_by_exact_rule_is_clean():
    srcs = dict(_FAULTED)
    srcs["tests/test_chaos.py"] = """
    def test_send_chaos(plan):
        install_plan(FaultPlan(1, [FaultRule("svc.send", "error")]))
    """
    assert "VMT134" not in rules_hit(srcs)


def test_vmt134_covered_by_prefix_rule_is_clean():
    srcs = dict(_FAULTED)
    srcs["scripts/chaos.py"] = """
    RULES = [FaultRule("svc.*", "error", rate=0.5)]
    """
    assert "VMT134" not in rules_hit(srcs)


def test_vmt134_suppressed_on_partial_scan():
    # A --changed subset cannot prove a site is covered NOWHERE.
    rule = FaultPointCoverage()
    rule.partial_scan = True
    graph = project(_FAULTED)
    ctx = graph.modules["pkg.svc"].ctx
    assert list(rule.check(ctx)) == []


# ----------------------------------------------------------------- VMT135
_STORE = """
import sqlite3

class Store:
    def boot(self):
        with sqlite3.connect(self.path) as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS jobs ("
                "id INTEGER PRIMARY KEY, "
                "status TEXT NOT NULL DEFAULT 'pending')")

    def claim(self, now):
        with sqlite3.connect(self.path) as c:
            c.execute("UPDATE jobs SET status='inflight' WHERE id=?",
                      (now,))

    def bury(self, job_id):
        with sqlite3.connect(self.path) as c:
            c.execute("UPDATE jobs SET status='dead' WHERE id=?",
                      (job_id,))
"""


def test_vmt135_drifted_status_literal_with_did_you_mean():
    srcs = {"pkg/store.py": _STORE, "pkg/push.py": """
    def frame(job):
        return {"status": "inflite", "id": job.id}
    """}
    fs = [f for f in findings(srcs) if f.rule == "VMT135"]
    assert len(fs) == 1
    assert "inflite" in fs[0].message and "'inflight'" in fs[0].message


def test_vmt135_machine_states_are_clean():
    srcs = {"pkg/store.py": _STORE, "pkg/push.py": """
    def frame(job):
        if job.status == "dead":
            return {"status": "dead"}
        return {"status": "pending"}
    """}
    assert "VMT135" not in rules_hit(srcs)


def test_vmt135_silent_without_a_recovered_machine():
    # No jobs.status machine in the project -> nothing to drift from.
    srcs = {"pkg/push.py": """
    def frame(job):
        return {"status": "whatever"}
    """}
    assert "VMT135" not in rules_hit(srcs)


# ------------------------------------------------------ the real tree
def test_repo_claim_paths_verify_clean(repo_flow):
    # The load-bearing pin: the worker and scheduler claim paths prove
    # exactly-one-terminal over every CFG path. The single accepted
    # VMT132 finding is the /worker/claim remote handoff (baselined with
    # its contract citation in vmtlint_baseline.json).
    assert [e["path"] for e in repo_flow.job_findings] == [
        "vilbert_multitask_tpu/serve/http_api.py"]
    assert repo_flow.leak_findings == []
    assert repo_flow.frame_findings == []


def test_repo_chaos_coverage_is_total(repo_flow):
    # Every fault_point in the library tree is named by some FaultRule
    # in tests/ or scripts/ — VMT134's whole point.
    assert repo_flow.fault_findings == []
    assert {fp["site"] for fp in repo_flow.fault_points} >= {
        "worker.intake", "queue.claim", "queue.publish",
        "push.publish", "remote.post", "engine.dispatch"}
    assert all(fp["covered_by"] for fp in repo_flow.fault_points)


def test_repo_worker_terminal_wrappers_compose(repo_flow):
    wrappers = {q.split(":", 1)[1]: info
                for q, info in repo_flow.summaries.items()}
    # _claim returns a fresh job handle...
    assert wrappers["ServeWorker._claim"].acquire_return[0] == "job"
    # ...and the failure paths are composed terminals for it.
    for fn in ("ServeWorker._fail_job", "ServeWorker._failover_job",
               "ServeWorker._expire_job"):
        assert wrappers[fn].terminal_params["job"][0] == "job"


def test_repo_step_batch_proof_is_exactly_one(fresh_surface):
    verdicts = {p["function"]: p["verdict"]
                for p in fresh_surface["proof"]}
    assert verdicts[
        "vilbert_multitask_tpu.serve.worker.ServeWorker.step_batch"] \
        == "exactly-one"
    assert verdicts[
        "vilbert_multitask_tpu.serve.worker.ServeWorker._claim"] \
        == "exactly-one"


def test_surface_covers_the_three_protocols(fresh_surface):
    protos = fresh_surface["protocols"]
    assert {"job", "replica", "thread", "sqlite"} <= set(protos)
    # job: declared by both the durable queue and its remote twin.
    decl = {d["method"] for d in protos["job"]["declared_by"]}
    assert {"DurableQueue.claim", "RemoteQueue.claim"} <= decl
    assert any(s["path"] == "vilbert_multitask_tpu/serve/pool.py"
               for s in protos["replica"]["acquire_sites"])
    assert protos["thread"]["acquire_sites"]


# ---------------------------------------------------------------- manifest
def test_surface_is_deterministic():
    a = render_proto_surface(build_proto_surface(project(_tree_sources())))
    b = render_proto_surface(build_proto_surface(project(_tree_sources())))
    assert a == b


def test_committed_manifest_matches_tree_byte_for_byte(fresh_surface):
    with open(MANIFEST, "r", encoding="utf-8") as f:
        committed = f.read()
    assert committed == render_proto_surface(fresh_surface), (
        "PROTOCOL_SURFACE.json drifted — regenerate with `python -m "
        "vilbert_multitask_tpu.analysis proto` and commit")


def test_diff_reports_protocol_and_proof_drift(fresh_surface):
    msgs = diff_proto_surface(None, fresh_surface)
    assert msgs and "missing" in msgs[0]
    mutated = copy.deepcopy(fresh_surface)
    del mutated["protocols"]["job"]
    assert any("`job`" in m for m in
               diff_proto_surface(mutated, fresh_surface))
    mutated = copy.deepcopy(fresh_surface)
    mutated["protocols"]["replica"]["acquire_sites"].pop()
    assert any("acquire site" in m for m in
               diff_proto_surface(mutated, fresh_surface))
    mutated = copy.deepcopy(fresh_surface)
    mutated["proof"][0]["verdict"] = "violations-everywhere"
    assert any("verdict" in m for m in
               diff_proto_surface(mutated, fresh_surface))
    # Metadata-only drift (a witness line moved) still reports.
    mutated = copy.deepcopy(fresh_surface)
    mutated["counts"]["wrappers"] += 1
    assert diff_proto_surface(mutated, fresh_surface)
    assert diff_proto_surface(fresh_surface, fresh_surface) == []


def test_sarif_rendering_carries_witness_flows(fresh_surface):
    doc = json.loads(render_proto_surface_sarif(fresh_surface))
    assert doc["version"] == "2.1.0" and "$schema" in doc
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "vmtlint-proto"
    results = run["results"]
    assert len(results) == (fresh_surface["counts"]["acquire_sites"]
                            + fresh_surface["counts"]["fault_points"])
    for r in results:
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
    assert any(r.get("codeFlows") for r in results)
    for r in results:
        for flow in r.get("codeFlows", []):
            assert flow["threadFlows"][0]["locations"]


def test_proto_check_gate_is_clean(monkeypatch):
    from vilbert_multitask_tpu.analysis.cli import main as cli_main

    monkeypatch.chdir(REPO)
    assert cli_main(["proto", "--check"]) == 0
